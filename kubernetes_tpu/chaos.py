"""Chaos/fault-injection layer: prove the control plane tolerates churn.

Kant (PAPERS.md) and SURVEY §5.3/§5.8 make failure detection/recovery a
first-class scheduler component; this module is the harness that injects
the failures the resilience machinery (utils/backoff, RemoteHub retry +
reconnect, scheduler degraded mode, leader renew-deadline) must survive.
Two injection points, both seeded-deterministic:

* ``ChaosHub`` — wraps any in-process Hub; every RPC-shaped verb (the
  hubserver CALL_METHODS surface, leases included) can be delayed, can
  fail with ``Unavailable``, and can be blacked out wholesale for a
  timed partition window. Watch registration passes through untouched —
  stream-level chaos belongs to the proxy, where a real network cut
  happens.
* ``ChaosProxy`` — an HTTP-level man-in-the-middle between a RemoteHub
  and a hubserver: injects per-call latency, 5xx error responses,
  connection aborts, mid-stream watch cuts (after N events or by rate),
  and timed partition windows during which every connection is severed.
  The client under test talks to ``proxy.address`` exactly as it would
  to the hub; nothing in the client knows chaos exists.
* ``DeviceChaos`` — accelerator-path fault injection, plugged into
  ``Scheduler.fault_injector``: raises inside the pack/launch path
  (device launch errors, forced ``CapacityError``) and NaN-poisons
  launch results (recomputing the REAL guard reduction over the
  poisoned tensors), provoking the device→host fallback ladder and the
  poison-pod quarantine.

``run_smoke()`` drives one short end-to-end scenario (scheduler +
kubemark hollow nodes through the proxy under call faults, a watch cut,
and a partition) and asserts the storm invariants: no double-bind, no
lost pod, cache–hub convergence. ``run_device_storm()`` provokes the
fallback ladder + quarantine; ``run_crash_storm()`` is the full
acceptance storm — device faults + watch cuts + leader kill +
kill-and-restart over ≥1k pods, every pod bound exactly once.
``run_gang_storm()`` kills the leader mid-gang-commit and asserts the
all-or-nothing ledger: every gang lands fully or not at all.
``bench.py --chaos-smoke`` runs all four as the red-suite gate.
"""

from __future__ import annotations

import json
import os
import random
import re as _re
import socket
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from kubernetes_tpu.hub import Unavailable
from kubernetes_tpu.hubserver import CALL_METHODS


@dataclass
class ChaosConfig:
    """Fault knobs. All injection draws from ONE seeded rng, so a given
    (seed, call sequence) replays the same fault schedule."""

    seed: int = 0
    call_error_rate: float = 0.0     # P(injected failure) per call
    call_abort_rate: float = 0.0     # proxy only: P(connection abort)
    call_latency: float = 0.0        # fixed added seconds per call
    call_latency_jitter: float = 0.0  # + uniform(0, jitter)
    watch_cut_every: int = 0         # cut after relaying N live events
                                     # (the N+1th is dropped; 0 = off)
    watch_cut_rate: float = 0.0      # P(cut) per relayed event


class _FaultClock:
    """Shared, lock-guarded fault state: config + rng + partition window
    + counters. One instance backs a ChaosHub or a ChaosProxy."""

    def __init__(self, config: ChaosConfig | None):
        self.config = config or ChaosConfig()
        self.rng = random.Random(self.config.seed)
        self.lock = threading.Lock()
        self.partition_until = 0.0
        self.stats = {"injected_errors": 0, "injected_aborts": 0,
                      "injected_cuts": 0, "partitions": 0,
                      "calls_seen": 0, "events_relayed": 0}

    def set_fault(self, **kw) -> None:
        with self.lock:
            for k, v in kw.items():
                if not hasattr(self.config, k):
                    raise AttributeError(f"unknown fault knob {k!r}")
                setattr(self.config, k, v)

    def partition_for(self, seconds: float) -> None:
        with self.lock:
            self.partition_until = time.monotonic() + seconds
            self.stats["partitions"] += 1

    def heal(self) -> None:
        with self.lock:
            self.partition_until = 0.0

    @property
    def partitioned(self) -> bool:
        with self.lock:
            return time.monotonic() < self.partition_until

    def draw(self, rate: float) -> bool:
        if rate <= 0:
            return False
        with self.lock:
            return self.rng.random() < rate

    def latency(self) -> float:
        c = self.config
        if c.call_latency <= 0 and c.call_latency_jitter <= 0:
            return 0.0
        with self.lock:
            return c.call_latency + (
                self.rng.uniform(0, c.call_latency_jitter)
                if c.call_latency_jitter > 0 else 0.0)

    def count(self, key: str, n: int = 1) -> None:
        with self.lock:
            self.stats[key] += n


# --------------------------------------------------------------------------
# ChaosHub: in-process fault injection
# --------------------------------------------------------------------------


class _ChaosLeases:
    def __init__(self, chub: "ChaosHub"):
        self._chub = chub

    def get(self, name: str):
        self._chub._maybe_fault("leases.get")
        return self._chub._inner.leases.get(name)

    def update(self, lease, expect_holder) -> bool:
        self._chub._maybe_fault("leases.update")
        return self._chub._inner.leases.update(lease, expect_holder)


class ChaosHub:
    """Wrap any Hub; RPC-shaped verbs gain injected latency, error rate,
    and partition windows. Watches and non-CALL attributes delegate."""

    def __init__(self, hub, config: ChaosConfig | None = None,
                 sleep=time.sleep):
        self._inner = hub
        self._clock = _FaultClock(config)
        self._sleep = sleep
        self.leases = _ChaosLeases(self)

    # --- chaos controls -------------------------------------------------

    def set_fault(self, **kw) -> None:
        self._clock.set_fault(**kw)

    def partition_for(self, seconds: float) -> None:
        self._clock.partition_for(seconds)

    def heal(self) -> None:
        self._clock.heal()

    def chaos_stats(self) -> dict:
        with self._clock.lock:
            return dict(self._clock.stats)

    # --- fault gate -----------------------------------------------------

    def _maybe_fault(self, method: str) -> None:
        self._clock.count("calls_seen")
        lat = self._clock.latency()
        if lat > 0:
            self._sleep(lat)
        if self._clock.partitioned:
            self._clock.count("injected_errors")
            raise Unavailable(f"chaos: partitioned ({method})")
        if self._clock.draw(self._clock.config.call_error_rate):
            self._clock.count("injected_errors")
            raise Unavailable(f"chaos: injected failure ({method})")

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name in CALL_METHODS and callable(attr):
            def faulted(*args, _m=name, _fn=attr):
                self._maybe_fault(_m)
                return _fn(*args)

            faulted.__name__ = name
            setattr(self, name, faulted)
            return faulted
        return attr


# --------------------------------------------------------------------------
# DeviceChaos: accelerator-path fault injection (Scheduler.fault_injector)
# --------------------------------------------------------------------------


@dataclass
class DeviceChaosConfig:
    """Device-path fault knobs, seeded-deterministic like ChaosConfig."""

    seed: int = 0
    launch_error_rate: float = 0.0     # P(raise at pack/launch) per batch
    capacity_error_rate: float = 0.0   # P(forced CapacityError) per batch
    nan_rate: float = 0.0              # P(NaN-poison the result) per batch
    # P(raise inside the COMMIT THREAD's device pull) per batch: the
    # pipelined scheduler's off-thread jax.device_get — the exception
    # must surface through fut.result() in _finish and take the same
    # _finish_contained fallback ladder as an inline launch fault
    commit_pull_error_rate: float = 0.0


class DeviceChaos:
    """Injects accelerator-path faults through the Scheduler's
    ``fault_injector`` seam: ``on_pack`` may raise (a device launch
    error or a forced ``CapacityError``) before the fused launch;
    ``on_result`` may NaN-poison the launch's score tensor — and
    recomputes the REAL guard reduction over the poisoned tensors, so
    the scheduler's NaN guard (not this injector) is what trips. Every
    injected fault must come out the other side of the device→host
    fallback ladder with zero daemon deaths and zero lost pods."""

    def __init__(self, config: DeviceChaosConfig | None = None):
        self.config = config or DeviceChaosConfig()
        self._rng = random.Random(self.config.seed)
        self._lock = threading.Lock()
        self.stats = {"injected_launch_errors": 0,
                      "injected_capacity_errors": 0,
                      "injected_nans": 0, "injected_pull_errors": 0,
                      "batches_seen": 0}

    def set_fault(self, **kw) -> None:
        with self._lock:
            for k, v in kw.items():
                if not hasattr(self.config, k):
                    raise AttributeError(f"unknown fault knob {k!r}")
                setattr(self.config, k, v)

    def _draw(self, rate: float) -> bool:
        if rate <= 0:
            return False
        with self._lock:
            return self._rng.random() < rate

    def on_pack(self, pods) -> None:
        with self._lock:
            self.stats["batches_seen"] += 1
        if self._draw(self.config.launch_error_rate):
            with self._lock:
                self.stats["injected_launch_errors"] += 1
            raise RuntimeError(
                f"chaos: injected device launch failure "
                f"({len(pods)}-pod batch)")
        if self._draw(self.config.capacity_error_rate):
            from kubernetes_tpu.backend.mirror import CapacityError

            with self._lock:
                self.stats["injected_capacity_errors"] += 1
            raise CapacityError("__chaos__", 2 ** 30)

    def on_commit_pull(self) -> None:
        """Runs on the COMMIT THREAD at the top of the launch pull; a
        raise here propagates through the wave's future into _finish,
        exercising exactly-once containment under threaded commit."""
        if self._draw(self.config.commit_pull_error_rate):
            with self._lock:
                self.stats["injected_pull_errors"] += 1
            raise RuntimeError("chaos: injected commit-thread pull failure")

    def on_result(self, out):
        if not self._draw(self.config.nan_rate):
            return out
        import dataclasses as _dc

        import jax.numpy as jnp

        from kubernetes_tpu.models.pipeline import _guard_reduction

        with self._lock:
            self.stats["injected_nans"] += 1
        score = jnp.full_like(out.score, float("nan"))
        return _dc.replace(out, score=score,
                           guard=_guard_reduction(score, out.free))


def make_poison_pod(name: str = "poison"):
    """A genuinely poisonous pod: its cpu request fails quantity parsing,
    so ANY batch that packs it raises — the device path faults wholesale,
    and the serial host fallback's per-pod evaluation is what isolates
    (bisects) it into quarantine while its batch peers schedule on."""
    from kubernetes_tpu.testing import MakePod

    return MakePod().name(name).req(cpu="not-a-quantity").obj()


# --------------------------------------------------------------------------
# ChaosProxy: HTTP-level fault injection between RemoteHub and hubserver
# --------------------------------------------------------------------------


class _ProxyHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "kubernetes-tpu-chaos/1"

    def log_message(self, *args) -> None:  # quiet
        pass

    @property
    def clock(self) -> _FaultClock:
        return self.server.clock          # type: ignore[attr-defined]

    @property
    def upstream(self) -> str:
        return self.server.upstream       # type: ignore[attr-defined]

    def _abort(self) -> None:
        """Sever the connection with no HTTP response — what a network
        partition looks like from the client's socket."""
        try:
            self.connection.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.close_connection = True

    def _json(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # --- /call ----------------------------------------------------------

    def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        clock = self.clock
        clock.count("calls_seen")
        lat = clock.latency()
        if lat > 0:
            time.sleep(lat)
        if clock.partitioned or clock.draw(
                clock.config.call_abort_rate):
            clock.count("injected_aborts" if not clock.partitioned
                        else "injected_errors")
            self._abort()
            return
        if clock.draw(clock.config.call_error_rate):
            clock.count("injected_errors")
            self._json(503, {"error": "ChaosInjected",
                             "message": "injected 503"})
            return
        length = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(length)
        req = urllib.request.Request(
            self.upstream + self.path, data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30.0) as resp:
                payload = resp.read()
                status = resp.status
        except urllib.error.HTTPError as e:
            payload = e.read()
            status = e.code
        except OSError:
            # upstream itself is down: same as a partition
            self._abort()
            return
        data = payload
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    # --- /watch ---------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802
        clock = self.clock
        if clock.partitioned:
            self._abort()
            return
        # the proxy is a JSON-era middlebox: it sniffs sync markers and
        # re-chunks the stream line-by-line, which would corrupt binary
        # frames. Strip the client's codec offer so upstream falls back
        # to the JSON wire — exactly the degradation the fabric codec's
        # negotiation exists to make safe (and a standing integration
        # test of it: every chaos scenario crosses a JSON-only hop).
        path = _re.sub(r"&(?:codec|fp)=[^&]*", "", self.path)
        path = _re.sub(r"\?(?:codec|fp)=[^&]*&", "?", path)
        try:
            upstream = urllib.request.urlopen(
                self.upstream + path, timeout=30.0)
        except urllib.error.HTTPError as e:
            self._json(e.code, {"error": "Upstream", "message": str(e)})
            return
        except OSError:
            self._abort()
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/jsonlines")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        relayed = 0
        synced = False
        try:
            for raw in upstream:
                if self.server.stopping:   # type: ignore[attr-defined]
                    break
                if clock.partitioned:
                    clock.count("injected_cuts")
                    break
                line = raw if raw.endswith(b"\n") else raw + b"\n"
                stripped = raw.strip()
                if stripped.startswith(b'{"synced": true'):
                    synced = True
                elif synced and stripped not in (b"", b"{}"):
                    # only LIVE events trip the cut triggers — a cut
                    # quota smaller than the replay would otherwise trap
                    # the reflector in a replay loop that never syncs.
                    # After N relayed events the N+1th is dropped and
                    # the stream cut, so that event is genuinely lost
                    # from this stream and only the reconnect's relist
                    # diff can recover it.
                    cut_after = clock.config.watch_cut_every
                    if (cut_after and relayed >= cut_after) \
                            or clock.draw(clock.config.watch_cut_rate):
                        clock.count("injected_cuts")
                        break
                    relayed += 1
                    clock.count("events_relayed")
                self.wfile.write(f"{len(line):x}\r\n".encode()
                                 + line + b"\r\n")
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError,
                ValueError):
            pass
        finally:
            try:
                upstream.close()
            except OSError:
                pass
            self._abort()


class ChaosProxy:
    """proxy = ChaosProxy(hub_server.address).start(); point a RemoteHub
    at ``proxy.address``; twist the knobs mid-flight."""

    def __init__(self, upstream: str, host: str = "127.0.0.1",
                 port: int = 0, config: ChaosConfig | None = None):
        self.clock = _FaultClock(config)
        self._httpd = ThreadingHTTPServer((host, port), _ProxyHandler)
        self._httpd.daemon_threads = True
        self._httpd.clock = self.clock         # type: ignore[attr-defined]
        self._httpd.upstream = upstream.rstrip("/")  # type: ignore
        self._httpd.stopping = False           # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    @property
    def stats(self) -> dict:
        with self.clock.lock:
            return dict(self.clock.stats)

    def set_fault(self, **kw) -> None:
        self.clock.set_fault(**kw)

    def partition_for(self, seconds: float) -> None:
        self.clock.partition_for(seconds)

    def heal(self) -> None:
        self.clock.heal()

    def start(self) -> "ChaosProxy":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="chaos-proxy")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.stopping = True            # type: ignore[attr-defined]
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)


# --------------------------------------------------------------------------
# chaos smoke scenario (bench.py --chaos-smoke's red-suite gate)
# --------------------------------------------------------------------------


def run_smoke(pods: int = 40, nodes: int = 8, seed: int = 7,
              timeout_s: float = 90.0) -> dict:
    """One short storm: scheduler + kubemark hollow nodes both talking
    through a ChaosProxy while it injects 503s, a mid-stream watch cut,
    and a partition window. Returns the invariant report; ``ok`` is True
    iff every pod bound exactly once, every binding was acked Running,
    and the cache converged against the hub."""
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.hub import Hub
    from kubernetes_tpu.hubclient import RemoteHub
    from kubernetes_tpu.hubserver import HubServer
    from kubernetes_tpu.kubemark import HollowNodes
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing import MakePod

    hub = Hub()
    server = HubServer(hub).start()
    proxy = ChaosProxy(server.address,
                       config=ChaosConfig(seed=seed)).start()
    sched_client = RemoteHub(proxy.address, timeout=10.0,
                             retry_deadline=6.0, retry_base=0.02,
                             retry_cap=0.25)
    mark_client = RemoteHub(proxy.address, timeout=10.0,
                            retry_deadline=6.0, retry_base=0.02,
                            retry_cap=0.25)
    report: dict = {"pods": pods, "nodes": nodes, "seed": seed}
    sched = None
    hollow = None
    try:
        hollow = HollowNodes(mark_client, nodes, prefix="storm")
        # the heartbeat's resync_acks is the feeder's own resilience: an
        # ack dropped by an injected fault is retried on the next beat
        hollow.start_heartbeat(0.5)
        cfg = default_config()
        cfg.batch_size = 16
        sched = Scheduler(sched_client, cfg,
                          caps=Capacities(nodes=max(16, nodes * 2),
                                          pods=max(128, pods * 2)))
        sched.start()
        for i in range(pods):
            hub.create_pod(
                MakePod().name(f"storm-{i}").req(cpu="100m").obj())
        # the storm: flaky calls, then a stream cut, then a partition
        proxy.set_fault(call_error_rate=0.30)
        time.sleep(1.5)
        proxy.set_fault(call_error_rate=0.0, watch_cut_every=5)
        time.sleep(1.0)
        proxy.set_fault(watch_cut_every=0)
        proxy.partition_for(1.5)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            bound = [p for p in hub.list_pods() if p.spec.node_name]
            if len(bound) == pods and hollow.ack_count() == pods:
                break
            time.sleep(0.2)
        proxy.heal()
        all_pods = hub.list_pods()
        bound = [p for p in all_pods if p.spec.node_name]
        running = [p for p in all_pods if p.status.phase == "Running"]
        # settle: let the reflector relist catch the cache up, then diff
        settle_end = time.monotonic() + 10.0
        problems = ["unsettled"]
        while problems and time.monotonic() < settle_end:
            time.sleep(0.5)
            problems = sched.cache.compare_with_hub(hub)
        report.update({
            "bound": len(bound), "running": len(running),
            "lost": pods - len(bound),
            "cache_vs_hub": problems,
            "hub_client": sched_client.resilience_stats(),
            "chaos": proxy.stats,
            "ok": (len(bound) == pods and len(running) == pods
                   and not problems),
        })
    finally:
        if sched is not None:
            sched.close()
        if hollow is not None:
            hollow.stop()
        sched_client.close()
        mark_client.close()
        proxy.stop()
        server.stop()
    return report


# --------------------------------------------------------------------------
# device-fault storm: the fallback ladder + quarantine under fire
# --------------------------------------------------------------------------


def run_device_storm(pods: int = 80, nodes: int = 8, seed: int = 11,
                     timeout_s: float = 90.0) -> dict:
    """Accelerator-path storm on an in-process hub: injected device
    launch errors, forced CapacityErrors, and NaN-poisoned results
    against a live drain, plus one genuinely poisonous pod. ``ok`` iff
    every healthy pod bound exactly once (the ladder kept peers
    scheduling), the poison pod was quarantined with a hub Event (never
    bound), and the daemon survived every injected fault."""
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.hub import Hub
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing import MakeNode, MakePod

    import tempfile

    hub = Hub()
    for i in range(nodes):
        hub.create_node(MakeNode().name(f"dn-{i}")
                        .capacity(cpu="64", pods="440").obj())
    cfg = default_config()
    cfg.batch_size = 16
    # every injected incident class must leave a parseable black box
    # (and a clean control run below must leave none)
    autopsy_dir = tempfile.mkdtemp(prefix="chaos-autopsy-")
    cfg.autopsy_dir = autopsy_dir
    cfg.autopsy_rate_limit_s = 2.0
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=max(16, nodes * 2),
                                                pods=max(128, pods * 2)))
    chaos = DeviceChaos(DeviceChaosConfig(seed=seed))
    sched.fault_injector = chaos
    report: dict = {"pods": pods, "nodes": nodes, "seed": seed}
    poison = make_poison_pod("poison-0")
    all_knobs = ("nan_rate", "launch_error_rate", "capacity_error_rate",
                 "commit_pull_error_rate")
    try:
        # four deterministic fault phases — every rung of the ladder is
        # provoked at least once regardless of scale — then a clean drain.
        # The poison pod lands in phase 1: its pack-time exception must
        # not eclipse phase 0's NaN injection (which needs a launch that
        # actually completes to poison its result). Phase 3 faults the
        # COMMIT THREAD's device pull: containment must be identical to
        # an inline launch fault even though the raise crosses a future.
        share = max(1, pods // 4)
        phases = ({"nan_rate": 1.0}, {"launch_error_rate": 1.0},
                  {"capacity_error_rate": 1.0},
                  {"commit_pull_error_rate": 1.0})
        for n, knobs in enumerate(phases):
            chaos.set_fault(**{k: 0.0 for k in all_knobs})
            chaos.set_fault(**knobs)
            if n == 1:
                hub.create_pod(poison)
            lo, hi = n * share, (pods if n == len(phases) - 1
                                 else (n + 1) * share)
            for i in range(lo, hi):
                hub.create_pod(
                    MakePod().name(f"dp-{i}").req(cpu="100m").obj())
            sched.run_until_idle()
            sched.run_maintenance()
        chaos.set_fault(**{k: 0.0 for k in all_knobs})
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            sched.run_until_idle()
            sched.run_maintenance()
            bound = sum(1 for p in hub.list_pods() if p.spec.node_name)
            if bound == pods and sched.stats["quarantined"] >= 1:
                break
            time.sleep(0.05)
        bound = sum(1 for p in hub.list_pods() if p.spec.node_name)
        q_events = [e for e in hub.list_events(ref_kind="Pod")
                    if e.reason == "Quarantined"]
        # autopsy gate: every injected incident class filed >=1 bundle
        # that parses strictly with the matching trigger recorded
        autopsy = audit_autopsy_bundles(
            autopsy_dir, expect_kinds=("device_fallback", "quarantine"))
        report.update({
            "bound": bound, "lost": pods - bound,
            "poison_bound": bool(
                hub.get_pod(poison.metadata.uid).spec.node_name),
            "quarantines": sched.stats["quarantined"],
            "quarantine_events": len(q_events),
            "device_fallbacks": sched.stats["device_fallbacks"],
            "device_chaos": dict(chaos.stats),
            "cache_vs_hub": sched.cache.compare_with_hub(hub),
            "autopsy": autopsy,
            "ok": (bound == pods
                   and not hub.get_pod(poison.metadata.uid).spec.node_name
                   and sched.stats["quarantined"] >= 1
                   and len(q_events) >= 1
                   and sched.stats["device_fallbacks"] > 0
                   and chaos.stats["injected_nans"] >= 1
                   and chaos.stats["injected_launch_errors"] >= 1
                   and chaos.stats["injected_capacity_errors"] >= 1
                   and chaos.stats["injected_pull_errors"] >= 1
                   and not sched.cache.compare_with_hub(hub)
                   and autopsy["ok"]),
        })
    finally:
        sched.close()
    # false-positive control: an identical (smaller) drain with NO
    # chaos attached must file ZERO bundles — breach detection that
    # fires on a healthy system is itself a defect
    report["autopsy_control"] = _autopsy_clean_control()
    report["ok"] = bool(report.get("ok")) \
        and report["autopsy_control"]["ok"]
    return report


def audit_autopsy_bundles(directory: str,
                          expect_kinds: tuple = ()) -> dict:
    """Strict-parse every bundle in ``directory`` and check each
    expected incident class filed at least one. The chaos storms' proof
    that the watchdog's black boxes actually capture what was injected
    (``telemetry autopsy show`` uses the same strict reader)."""
    from kubernetes_tpu.telemetry.autopsy import list_bundles, load_bundle

    rows = list_bundles(directory)
    torn = [r["name"] for r in rows if "error" in r]
    kinds: dict[str, int] = {}
    for r in rows:
        if "error" in r:
            continue
        # re-load through the strict reader (list already parsed once;
        # this is the same path the CLI's `show` takes)
        doc = load_bundle(os.path.join(directory, r["name"]))
        k = doc.get("trigger", {}).get("kind", "?")
        kinds[k] = kinds.get(k, 0) + 1
    missing = [k for k in expect_kinds if not kinds.get(k)]
    return {"bundles": len(rows), "torn": torn, "kinds": kinds,
            "missing": missing,
            "ok": not torn and not missing}


def _autopsy_clean_control(pods: int = 24, nodes: int = 4) -> dict:
    """A chaos-free mini-drain with the watchdog + autopsy store armed
    exactly like the storm: it must bind everything and file ZERO
    bundles (no false-positive incidents on a healthy system)."""
    import tempfile

    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.hub import Hub
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing import MakeNode, MakePod

    hub = Hub()
    for i in range(nodes):
        hub.create_node(MakeNode().name(f"cn-{i}")
                        .capacity(cpu="64", pods="440").obj())
    cfg = default_config()
    cfg.batch_size = 16
    autopsy_dir = tempfile.mkdtemp(prefix="chaos-autopsy-clean-")
    cfg.autopsy_dir = autopsy_dir
    cfg.autopsy_rate_limit_s = 0.0
    cfg.watchdog_interval_s = 0.0     # poll every maintenance tick
    sched = Scheduler(hub, cfg, caps=Capacities(nodes=max(16, nodes * 2),
                                                pods=max(64, pods * 2)))
    try:
        for i in range(pods):
            hub.create_pod(MakePod().name(f"cp-{i}")
                           .req(cpu="100m").obj())
        sched.run_until_idle()
        sched.run_maintenance()
        bound = sum(1 for p in hub.list_pods() if p.spec.node_name)
    finally:
        sched.close()
    audit = audit_autopsy_bundles(autopsy_dir)
    return {"bound": bound, "pods": pods,
            "bundles": audit["bundles"], "kinds": audit["kinds"],
            "ok": bound == pods and audit["bundles"] == 0}


# --------------------------------------------------------------------------
# crash-kill/restart storm: the full acceptance gate (ISSUE 3)
# --------------------------------------------------------------------------


def run_crash_storm(pods: int = 1000, nodes: int = 24, seed: int = 13,
                    timeout_s: float = 300.0) -> dict:
    """The acceptance storm: device faults + watch cuts + leader kill +
    kill-and-restart over >=1k pods, two elected scheduler incarnations
    each behind its own ChaosProxy. Every bind is tallied straight off
    the hub's watch stream; ``ok`` iff every healthy pod bound EXACTLY
    once (fencing + bind-once), the poison pod was quarantined with a
    hub Event, and no surviving daemon recorded a loop crash."""
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.hub import EventHandlers, Hub
    from kubernetes_tpu.hubclient import RemoteHub
    from kubernetes_tpu.hubserver import HubServer
    from kubernetes_tpu.leaderelection import LeaderElector
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing import MakeNode, MakePod

    hub = Hub()
    server = HubServer(hub).start()
    proxies: dict = {}
    clients: dict = {}
    scheds: dict = {}
    electors: dict = {}

    def spawn(ident: str) -> None:
        proxy = ChaosProxy(server.address,
                           config=ChaosConfig(seed=seed)).start()
        client = RemoteHub(proxy.address, timeout=10.0, retry_deadline=3.0,
                           retry_base=0.01, retry_cap=0.1)
        cfg = default_config()
        cfg.batch_size = 64
        sched = Scheduler(client, cfg,
                          caps=Capacities(nodes=max(32, nodes * 2),
                                          pods=2048))
        sched.fault_injector = DeviceChaos(DeviceChaosConfig(
            seed=seed, launch_error_rate=0.05, nan_rate=0.05))
        elector = LeaderElector(client.leases, ident, lease_duration=2.0,
                                renew_deadline=1.0, retry_period=0.1)
        sched.start(elector=elector)
        proxies[ident], clients[ident] = proxy, client
        scheds[ident], electors[ident] = sched, elector

    # exactly-once ledger, tallied straight off the hub's own stream
    bind_counts: dict[str, int] = {}
    block = threading.Lock()

    def on_update(old, new) -> None:
        if not old.spec.node_name and new.spec.node_name:
            with block:
                uid = new.metadata.uid
                bind_counts[uid] = bind_counts.get(uid, 0) + 1

    hub.watch_pods(EventHandlers(on_update=on_update), replay=False)
    report: dict = {"pods": pods, "nodes": nodes, "seed": seed}
    poison = make_poison_pod("poison-crash")
    try:
        for i in range(nodes):
            hub.create_node(MakeNode().name(f"cn-{i}")
                            .capacity(cpu="64", memory="256Gi",
                                      pods="440").obj())
        spawn("a")
        spawn("b")
        hub.create_pod(poison)
        for i in range(pods):
            hub.create_pod(MakePod().name(f"cp-{i}").req(cpu="50m").obj())

        def leader():
            for ident, el in electors.items():
                if el.is_leader():
                    return ident
            return None

        def bound_count() -> int:
            return sum(1 for p in hub.list_pods() if p.spec.node_name)

        # phase 1: the first leader works through watch cuts
        t0 = time.monotonic()
        while time.monotonic() - t0 < 30.0 and bound_count() < pods // 4:
            time.sleep(0.2)
        for proxy in proxies.values():
            proxy.set_fault(watch_cut_every=50)
        time.sleep(1.0)
        for proxy in proxies.values():
            proxy.set_fault(watch_cut_every=0)
        # phase 2: leader kill (zombie): partition the leader's wire; it
        # must step down by the renew deadline and the peer takes over
        # with a NEWER fencing epoch — any zombie bind surfacing later
        # is rejected Fenced, never double-placed
        victim = None
        deadline = time.monotonic() + 30.0
        while victim is None and time.monotonic() < deadline:
            victim = leader()
            time.sleep(0.05)
        report["first_leader"] = victim
        if victim is not None:
            proxies[victim].partition_for(6.0)
            others = [i for i in electors if i != victim]
            takeover = time.monotonic() + 20.0
            while time.monotonic() < takeover:
                if any(electors[i].is_leader() for i in others):
                    break
                time.sleep(0.05)
            report["failover"] = True
            # phase 3: SIGKILL-restart — tear the victim down ABRUPTLY
            # (stop flag only: no graceful drain, binder pool abandoned
            # mid-flight) and bring up a fresh incarnation that relists
            dead = scheds.pop(victim)
            electors.pop(victim)
            if dead._stop is not None:
                dead._stop.set()
            clients.pop(victim).close()
            proxies.pop(victim).stop()
            spawn(victim + "2")
        # phase 4: drain to completion under residual device faults
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if bound_count() >= pods:
                break
            time.sleep(0.3)
        bound = bound_count()
        with block:
            dup = {uid: n for uid, n in bind_counts.items() if n > 1}
        q_events = [e for e in hub.list_events(ref_kind="Pod")
                    if e.reason == "Quarantined"]
        daemon_errors = {
            ident: repr(s.daemon_error) for ident, s in scheds.items()
            if getattr(s, "daemon_error", None) is not None}
        report.update({
            "bound": bound, "lost": pods - bound,
            "duplicate_binds": dup,
            "poison_bound": bool(
                hub.get_pod(poison.metadata.uid).spec.node_name),
            "quarantine_events": len(q_events),
            "fenced_writes": sum(s.stats.get("fenced", 0)
                                 for s in scheds.values()),
            "device_fallbacks": sum(s.stats.get("device_fallbacks", 0)
                                    for s in scheds.values()),
            "daemon_errors": daemon_errors,
            "ok": (bound == pods and not dup and not daemon_errors
                   and not hub.get_pod(poison.metadata.uid).spec.node_name
                   and len(q_events) >= 1),
        })
    finally:
        for s in scheds.values():
            try:
                s.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for c in clients.values():
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        for p in proxies.values():
            try:
                p.stop()
            except Exception:  # noqa: BLE001
                pass
        server.stop()
    return report


# --------------------------------------------------------------------------
# process-level crash storm: kill -9 a shard PROCESS (ISSUE 11)
# --------------------------------------------------------------------------


def run_proc_crash_storm(pods: int = 300, nodes: int = 12,
                         seed: int = 19,
                         timeout_s: float = 240.0) -> dict:
    """The out-of-process fabric's crash storm: a scheduler (with
    leader election) driving the cluster THROUGH the stateless router,
    shards as separate OS processes, and a ``kill -9`` of a pod-shard
    process mid-storm followed by a supervisor restart that replays the
    shard's bin1 WAL onto a NEW port. ``ok`` iff every pod bound
    EXACTLY once across the process death (the exactly-once ledger,
    tallied off a watch through the router), the fencing epoch is
    MONOTONE across the restart (the shared-state shard owns it — a
    shard process dying must not reset hub-wide fencing), and a write
    fenced with a stale epoch is still rejected afterwards."""
    import tempfile

    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.fabric.supervisor import spawn_local_cluster
    from kubernetes_tpu.hub import EventHandlers, Fenced
    from kubernetes_tpu.hubclient import RemoteHub
    from kubernetes_tpu.leaderelection import LeaderElector
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing import MakeNode, MakePod

    report: dict = {"pods": pods, "nodes": nodes, "seed": seed,
                    "procs": True}
    wal_dir = tempfile.mkdtemp(prefix="proc-crash-wal-")
    cluster = spawn_local_cluster(pod_shards=2, wal_dir=wal_dir)
    client = RemoteHub(cluster.router_url, timeout=10.0,
                       retry_deadline=3.0, retry_base=0.01,
                       retry_cap=0.2)
    ledger_client = RemoteHub(cluster.router_url, timeout=10.0)
    sched = None
    try:
        for i in range(nodes):
            client.create_node(MakeNode().name(f"pn-{i}")
                               .capacity(cpu="64", memory="256Gi",
                                         pods="440").obj())
        # exactly-once ledger off the router's merged watch stream
        bind_counts: dict[str, int] = {}
        block = threading.Lock()

        def on_update(old, new) -> None:
            if not old.spec.node_name and new.spec.node_name:
                with block:
                    uid = new.metadata.uid
                    bind_counts[uid] = bind_counts.get(uid, 0) + 1

        ledger_client.watch_pods(EventHandlers(on_update=on_update),
                                 replay=False)
        cfg = default_config()
        cfg.batch_size = 64
        sched = Scheduler(client, cfg,
                          caps=Capacities(nodes=max(32, nodes * 2),
                                          pods=1024))
        elector = LeaderElector(client.leases, "proc-a",
                                lease_duration=2.0, renew_deadline=1.0,
                                retry_period=0.1)
        sched.start(elector=elector)
        for i in range(pods):
            client.create_pod(MakePod().name(f"pp-{i}")
                              .namespace(f"ns-{i % 7}")
                              .req(cpu="50m").obj())

        def bound_count() -> int:
            try:
                return sum(1 for p in ledger_client.list_pods()
                           if p.spec.node_name)
            except Exception:  # noqa: BLE001 — mid-kill window
                return -1

        # phase 1: let the storm get going
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60.0 \
                and bound_count() < pods // 4:
            time.sleep(0.2)
        epoch_before = client.leases.epoch_of("kube-scheduler")
        report["epoch_before_kill"] = epoch_before

        # phase 2: kill -9 a pod-shard process mid-storm, then restart
        victim = cluster.pod_shards[seed % len(cluster.pod_shards)]
        report["killed_shard"] = victim
        report["killed_pid"] = cluster.sup.kill_shard(victim)
        time.sleep(1.0)          # the scheduler rides out the outage
        restarted = cluster.sup.restart_shard(victim)
        report["restarted_port"] = restarted.port

        # phase 3: drain to completion across the restart
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if bound_count() >= pods:
                break
            time.sleep(0.3)
        bound = bound_count()
        epoch_after = client.leases.epoch_of("kube-scheduler")
        report["epoch_after_restart"] = epoch_after
        # a stale fencing epoch must still be rejected by the restarted
        # shard (fencing lives on the state shard, not in the WAL).
        # The probe pod carries a scheduler_name no profile owns, so
        # the live scheduler never races the check — the gate runs in
        # EVERY storm, including fully-drained successful ones.
        probe = MakePod().name("fence-probe").namespace("ns-0") \
            .scheduler_name("fence-probe-noop").obj()
        client.create_pod(probe)
        stale_fenced = False
        if epoch_after > 0:
            try:
                # positional: the /call wire carries no kwargs
                client.bind(probe, "pn-0", epoch_after - 1)
            except Fenced:
                stale_fenced = True
        try:
            client.delete_pod(probe.metadata.uid)
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass
        with block:
            dup = {uid: n for uid, n in bind_counts.items() if n > 1}
        daemon_error = getattr(sched, "daemon_error", None)
        report.update({
            "bound": bound, "lost": pods - bound,
            "duplicate_binds": dup,
            "stale_epoch_fenced": stale_fenced,
            "daemon_error": repr(daemon_error) if daemon_error
            else None,
            "client_relists":
                client.resilience_stats()["watch_relists"],
            "ok": (bound == pods and not dup
                   and epoch_after >= epoch_before >= 1
                   and stale_fenced and daemon_error is None),
        })
    finally:
        if sched is not None:
            try:
                sched.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for c in (client, ledger_client):
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        cluster.stop()
    return report


# --------------------------------------------------------------------------
# replicated-state storm: kill -9 the state LEADER mid-storm (ISSUE 13)
# --------------------------------------------------------------------------


def run_state_storm(pods: int = 300, nodes: int = 12, seed: int = 29,
                    timeout_s: float = 300.0) -> dict:
    """The replicated-state-core battery: a 3-replica state quorum
    (rv allocation, lease fencing, ring map), shards and a scheduler
    driving commits through the router, and a ``kill -9`` of the state
    LEADER mid-storm — landing mid-``rv.next`` (every commit draws a
    revision), mid-lease-renew (the elector renews continuously), and
    mid-ring-CAS (a rebalance fires concurrently with the kill).

    ``ok`` iff: a new leader is elected and the killed replica rejoins
    from its WAL; every pod binds EXACTLY once across the failover
    (watch-tallied ledger); fencing epochs are monotone and a stale
    epoch is still Fenced by the new quorum; the journal audit finds
    **no rv ever reused** (every committed revision is globally
    unique — the majority-ack-before-release invariant); the
    concurrent rebalance either completed (ring flipped exactly once)
    or rolled back (ring unchanged) with zero pods lost either way;
    and the ledger's watch healed with zero relists."""
    import tempfile

    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.fabric.cluster import RING_SLOTS, ring_slot
    from kubernetes_tpu.fabric.replica import ReplicaClient
    from kubernetes_tpu.fabric.supervisor import spawn_local_cluster
    from kubernetes_tpu.hub import Conflict, EventHandlers, Fenced
    from kubernetes_tpu.hubclient import RemoteHub
    from kubernetes_tpu.leaderelection import LeaderElector
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing import MakeNode, MakePod

    report: dict = {"pods": pods, "nodes": nodes, "seed": seed,
                    "state_replicas": 3}
    wal_dir = tempfile.mkdtemp(prefix="state-storm-wal-")
    cluster = spawn_local_cluster(pod_shards=2, wal_dir=wal_dir,
                                  state_replicas=3)
    client = RemoteHub(cluster.router_url, timeout=10.0,
                       retry_deadline=5.0, retry_base=0.01,
                       retry_cap=0.2)
    ledger_client = RemoteHub(cluster.router_url, timeout=10.0)
    state_client = ReplicaClient(cluster.state_urls)
    sched = None

    def with_retry(fn, deadline_s: float = 30.0):
        end = time.monotonic() + deadline_s
        while True:
            try:
                return fn()
            except Exception:  # noqa: BLE001 — failover window
                if time.monotonic() > end:
                    raise
                time.sleep(0.2)

    try:
        for i in range(nodes):
            client.create_node(MakeNode().name(f"sn-{i}")
                               .capacity(cpu="64", memory="256Gi",
                                         pods="440").obj())
        bind_counts: dict[str, int] = {}
        block = threading.Lock()

        def on_update(old, new) -> None:
            if not old.spec.node_name and new.spec.node_name:
                with block:
                    uid = new.metadata.uid
                    bind_counts[uid] = bind_counts.get(uid, 0) + 1

        ledger_client.watch_pods(EventHandlers(on_update=on_update),
                                 replay=False)
        cfg = default_config()
        cfg.batch_size = 64
        sched = Scheduler(client, cfg,
                          caps=Capacities(nodes=max(32, nodes * 2),
                                          pods=1024))
        elector = LeaderElector(client.leases, "state-storm-a",
                                lease_duration=2.0, renew_deadline=1.0,
                                retry_period=0.1)
        sched.start(elector=elector)
        for i in range(pods):
            with_retry(lambda i=i: client.create_pod(
                MakePod().name(f"sp-{i}").namespace(f"ns-{i % 7}")
                .req(cpu="50m").obj()))

        def bound_count() -> int:
            try:
                return sum(1 for p in ledger_client.list_pods()
                           if p.spec.node_name)
            except Exception:  # noqa: BLE001 — mid-kill window
                return -1

        # phase 1: let the storm get going (rv.next + lease-renew
        # traffic is continuous — the kill below lands mid-both)
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60.0 \
                and bound_count() < pods // 4:
            time.sleep(0.2)
        epoch_before = with_retry(
            lambda: client.leases.epoch_of("kube-scheduler"))
        report["epoch_before_kill"] = epoch_before

        # phase 2: a rebalance racing the leader kill — the in-flight
        # ring CAS must complete or roll back, never half-apply
        ring0 = with_retry(lambda: client.fabric_ring())
        slot = ring_slot("ns-0", len(ring0["slots"]) or RING_SLOTS)
        src = ring0["slots"][slot]
        dst = next(n for n in cluster.pod_shards if n != src)
        rebalance_outcome: dict = {}

        def rebalance() -> None:
            # generous timeout: mid-kill, shard commits stall on the
            # state client's redirect budget before the move proceeds
            admin = RemoteHub(cluster.router_url, timeout=90.0)
            try:
                r = admin.rebalance_segment([slot], dst)
                rebalance_outcome["result"] = "completed"
                rebalance_outcome["epoch"] = r["epoch"]
            except Conflict as e:
                rebalance_outcome["result"] = "rolled_back"
                rebalance_outcome["error"] = str(e)
            except Exception as e:  # noqa: BLE001 — quorum lost window
                # ambiguous (the answer, not the move, was lost): the
                # quorum's ring is the verdict — the same resolution
                # rebalance_segment itself applies to a lost CAS reply
                rebalance_outcome["error"] = repr(e)
                try:
                    cur = with_retry(lambda: client.fabric_ring())
                    rebalance_outcome["result"] = \
                        "completed" if cur["slots"][slot] == dst \
                        else "rolled_back"
                except Exception:  # noqa: BLE001
                    rebalance_outcome["result"] = "unavailable"
            finally:
                admin.close()

        reb_thread = threading.Thread(target=rebalance, daemon=True)

        # phase 3: kill -9 the state LEADER mid-storm
        leader = cluster.state_leader()
        report["killed_leader"] = leader
        reb_thread.start()
        time.sleep(0.05)     # let the rebalance reach its CAS window
        report["killed_pid"] = cluster.sup.kill_shard(leader)
        reb_thread.join(timeout=120.0)
        report["rebalance"] = rebalance_outcome

        # a NEW leader must be elected among the survivors
        new_leader = cluster.state_leader(timeout_s=30.0)
        report["new_leader"] = new_leader
        # the killed replica rejoins from its WAL (same port, same log)
        restarted = cluster.sup.restart_shard(leader)
        report["restarted_port"] = restarted.port

        # phase 4: drain to completion across the failover
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if bound_count() >= pods:
                break
            time.sleep(0.3)
        bound = bound_count()
        epoch_after = with_retry(
            lambda: client.leases.epoch_of("kube-scheduler"))
        report["epoch_after"] = epoch_after
        # a deposed scheduler epoch must still be Fenced by the NEW
        # quorum (fencing state survived the leader kill)
        probe = MakePod().name("fence-probe").namespace("ns-0") \
            .scheduler_name("fence-probe-noop").obj()
        with_retry(lambda: client.create_pod(probe))
        stale_fenced = False
        if epoch_after > 0:
            try:
                client.bind(probe, "sn-0", epoch_after - 1)
            except Fenced:
                stale_fenced = True
        try:
            client.delete_pod(probe.metadata.uid)
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass

        # phase 5: the journal audit — every committed revision in the
        # fabric is globally unique (no rv reused across the failover;
        # gaps are the journal's contract, reuse never is)
        changes = with_retry(
            lambda: client.list_changes(0, ("pods", "nodes")))
        rvs = [c["rv"] for c in changes.get("changes", [])]
        report["journal_events"] = len(rvs)
        report["rv_reused"] = len(rvs) - len(set(rvs))
        # ring integrity after the racing rebalance
        ring_after = with_retry(lambda: client.fabric_ring())
        if rebalance_outcome.get("result") == "completed":
            ring_ok = (ring_after["epoch"] >= ring0["epoch"] + 1
                       and ring_after["slots"][slot] == dst)
        elif rebalance_outcome.get("result") == "rolled_back":
            ring_ok = ring_after["slots"][slot] == src
        else:
            ring_ok = False
        report["ring_ok"] = ring_ok

        # replica telemetry: one leader, the restarted member back as
        # a follower, terms agreeing
        statuses = state_client.replica_status()
        report["replica_roles"] = {st.get("name", st.get("url")):
                                   st.get("role", "dead")
                                   for st in statuses}
        leaders = [st for st in statuses
                   if st.get("role") == "leader"]

        with block:
            dup = {uid: n for uid, n in bind_counts.items() if n > 1}
        daemon_error = getattr(sched, "daemon_error", None)
        relists = ledger_client.resilience_stats()["watch_relists"]
        report.update({
            "bound": bound, "lost": pods - bound,
            "duplicate_binds": dup,
            "stale_epoch_fenced": stale_fenced,
            "daemon_error": repr(daemon_error) if daemon_error
            else None,
            "client_relists": relists,
            "ok": (bound == pods and not dup
                   and epoch_after >= epoch_before >= 1
                   and stale_fenced and daemon_error is None
                   and report["rv_reused"] == 0
                   and ring_ok
                   and rebalance_outcome.get("result")
                   in ("completed", "rolled_back")
                   and len(leaders) == 1
                   and relists == 0),
        })
    finally:
        if sched is not None:
            try:
                sched.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for c in (client, ledger_client, state_client):
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        cluster.stop()
    return report


# --------------------------------------------------------------------------
# gang-atomicity storm: leader kill mid-gang-commit (ISSUE 6)
# --------------------------------------------------------------------------


def run_gang_storm(gangs: int = 10, nodes: int = 16, seed: int = 17,
                   timeout_s: float = 240.0) -> dict:
    """The gang acceptance storm: two elected schedulers behind chaos
    proxies, a population of PodGroups with mixed gang sizes, and a
    leader partition timed to land MID-gang-commit. Every bind is
    tallied off the hub's own watch stream; ``ok`` iff no pod bound
    twice (fencing + bind-once), every gang landed **fully** (the
    all-or-nothing ledger: a gang is either complete or untouched — a
    rolled-back assembly leaves zero members placed and zero leaked
    assumed pods), and no surviving daemon crashed."""
    from kubernetes_tpu.api.objects import (
        LABEL_POD_GROUP,
        LABEL_QUEUE,
        ObjectMeta,
        PodGroup,
    )
    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.hub import EventHandlers, Hub
    from kubernetes_tpu.hubclient import RemoteHub
    from kubernetes_tpu.hubserver import HubServer
    from kubernetes_tpu.leaderelection import LeaderElector
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing import MakeNode, MakePod

    hub = Hub()
    server = HubServer(hub).start()
    proxies: dict = {}
    clients: dict = {}
    scheds: dict = {}
    electors: dict = {}

    def spawn(ident: str) -> None:
        proxy = ChaosProxy(server.address,
                           config=ChaosConfig(seed=seed)).start()
        client = RemoteHub(proxy.address, timeout=10.0, retry_deadline=3.0,
                           retry_base=0.01, retry_cap=0.1)
        cfg = default_config()
        cfg.batch_size = 32
        sched = Scheduler(client, cfg,
                          caps=Capacities(nodes=max(32, nodes * 2),
                                          pods=1024))
        elector = LeaderElector(client.leases, ident, lease_duration=2.0,
                                renew_deadline=1.0, retry_period=0.1)
        sched.start(elector=elector)
        proxies[ident], clients[ident] = proxy, client
        scheds[ident], electors[ident] = sched, elector

    bind_counts: dict[str, int] = {}
    block = threading.Lock()

    def on_update(old, new) -> None:
        if not old.spec.node_name and new.spec.node_name:
            with block:
                uid = new.metadata.uid
                bind_counts[uid] = bind_counts.get(uid, 0) + 1

    hub.watch_pods(EventHandlers(on_update=on_update), replay=False)
    sizes = [2, 3, 4, 6, 8]
    report: dict = {"gangs": gangs, "nodes": nodes, "seed": seed}
    gang_of: dict[str, str] = {}        # pod uid -> gang name
    gang_size: dict[str, int] = {}
    try:
        for i in range(nodes):
            hub.create_node(MakeNode().name(f"gn-{i}")
                            .capacity(cpu="16", memory="64Gi",
                                      pods="110").obj())
        for g in range(gangs):
            size = sizes[g % len(sizes)]
            name = f"gang-{g}"
            gang_size[name] = size
            hub.create_pod_group(PodGroup(
                metadata=ObjectMeta(name=name),
                min_member=size,
                queue=f"tenant-{g % 2}",
                schedule_timeout_seconds=10.0))
        spawn("a")
        spawn("b")
        for g in range(gangs):
            name = f"gang-{g}"
            for m in range(gang_size[name]):
                pod = (MakePod().name(f"{name}-m{m}")
                       .req(cpu="200m").obj())
                pod.metadata.labels[LABEL_POD_GROUP] = name
                pod.metadata.labels[LABEL_QUEUE] = f"tenant-{g % 2}"
                gang_of[pod.metadata.uid] = name
                hub.create_pod(pod)

        total = sum(gang_size.values())

        def bound_count() -> int:
            return sum(1 for p in hub.list_pods() if p.spec.node_name)

        def leader():
            for ident, el in electors.items():
                if el.is_leader():
                    return ident
            return None

        # kill the leader the moment the FIRST gang binds start landing:
        # that partition window lands mid-commit for whatever gang is in
        # flight — its fenced stragglers must be rejected, its rollback
        # must leave no partial placement
        t0 = time.monotonic()
        while time.monotonic() - t0 < 30.0 and bound_count() < 2:
            time.sleep(0.05)
        victim = leader()
        report["first_leader"] = victim
        if victim is not None:
            proxies[victim].partition_for(6.0)
            others = [i for i in electors if i != victim]
            takeover = time.monotonic() + 20.0
            while time.monotonic() < takeover:
                if any(electors[i].is_leader() for i in others):
                    break
                time.sleep(0.05)
            report["failover"] = True
        # drain to completion: the survivor (and the healed ex-leader)
        # re-admit interrupted gangs after their permit timeouts.
        # Progress extends the deadline — a slow drain on a loaded box
        # is not an atomicity verdict; only a STALLED storm times out
        # (and then reports its partially-placed in-flight gangs)
        deadline = time.monotonic() + timeout_s
        last = -1
        while time.monotonic() < deadline:
            b = bound_count()
            if b >= total:
                break
            if b > last:
                last = b
                deadline = max(deadline, time.monotonic() + 60.0)
            time.sleep(0.25)
        report["drained"] = bound_count() >= total

        # settle: heal the proxies and let each scheduler's informer
        # confirm its in-flight assumed pods — an assumed count sampled
        # mid-fault-injection is reflector lag, not a leak (run_smoke's
        # settle discipline)
        for p in proxies.values():
            p.heal()
        settle_end = time.monotonic() + 20.0
        while time.monotonic() < settle_end:
            if all(s.cache.assumed_pod_count() == 0
                   for s in scheds.values()):
                break
            time.sleep(0.5)

        per_gang: dict[str, int] = {g: 0 for g in gang_size}
        with block:
            dup = {uid: n for uid, n in bind_counts.items() if n > 1}
        for p in hub.list_pods():
            if p.spec.node_name:
                per_gang[gang_of[p.metadata.uid]] += 1
        partial = {g: n for g, n in per_gang.items()
                   if 0 < n < gang_size[g]}
        leaked_assumed = {ident: s.cache.assumed_pod_count()
                          for ident, s in scheds.items()
                          if s.cache.assumed_pod_count()}
        daemon_errors = {
            ident: repr(s.daemon_error) for ident, s in scheds.items()
            if getattr(s, "daemon_error", None) is not None}
        report.update({
            "pods": total, "bound": bound_count(),
            "duplicate_binds": dup,
            "partial_gangs": partial,
            "complete_gangs": sum(1 for g, n in per_gang.items()
                                  if n == gang_size[g]),
            "gang_rollbacks": sum(
                s._gang.stats["rollbacks"] for s in scheds.values()),
            # the storm runs the DEVICE gang path (default config):
            # these prove the fused packer carried the commits and the
            # Permit-quorum machinery stayed the fallback
            "gang_device_launches": sum(
                s.stats.get("gang_device_launches", 0)
                for s in scheds.values()),
            "gang_device_admitted": sum(
                s._gang.stats.get("device_admitted", 0)
                for s in scheds.values()),
            "gang_fallbacks": sum(
                s.stats.get("gang_fallbacks", 0)
                for s in scheds.values()),
            "fenced_writes": sum(s.stats.get("fenced", 0)
                                 for s in scheds.values()),
            "leaked_assumed": leaked_assumed,
            "daemon_errors": daemon_errors,
            "ok": (bound_count() == total and not dup and not partial
                   and not leaked_assumed and not daemon_errors),
        })
    finally:
        for s in scheds.values():
            try:
                s.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for c in clients.values():
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        for p in proxies.values():
            try:
                p.stop()
            except Exception:  # noqa: BLE001
                pass
        server.stop()
    return report


# --------------------------------------------------------------------------
# scale-out storm: N scheduler replicas, kill -9 one mid-wave (ISSUE 16)
# --------------------------------------------------------------------------


def run_scaleout_storm(pods: int = 240, nodes: int = 12,
                       replicas: int = 4, seed: int = 23,
                       timeout_s: float = 240.0) -> dict:
    """Horizontal scale-out under fire: ``replicas`` scheduler replicas
    drain the pending-pod space through the proc fabric, each owning a
    slice of the namespace ring; one replica is torn down ABRUPTLY
    (transport severed first, so its graceful release can never reach
    the board — the in-process analog of kill -9) mid-wave. ``ok`` iff
    its slices reassign within the registry TTL, every pod still binds
    EXACTLY once fleet-wide (journal-replay audit + live watch ledger),
    the slice-fence epoch is monotone across the rebalances, and a bind
    carrying a stale slice epoch is rejected Fenced. Each replica runs
    its own autopsy store; ``ok`` also requires ≥1 strictly-parseable
    ``slice_reparent`` black-box bundle across the survivors (filed
    when a survivor adopts another replica's penned pods)."""
    import tempfile

    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.fabric.supervisor import spawn_local_cluster
    from kubernetes_tpu.hub import EventHandlers, Fenced
    from kubernetes_tpu.hubclient import RemoteHub
    from kubernetes_tpu.leaderelection import (
        SCHED_SLICE_LEASE,
        SliceManager,
        ring_slot,
    )
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing import MakeNode, MakePod, \
        audit_bind_journal

    namespaces = [f"ns-{i}" for i in range(12)]
    report: dict = {"pods": pods, "nodes": nodes, "seed": seed,
                    "replicas": replicas}
    wal_dir = tempfile.mkdtemp(prefix="scaleout-wal-")
    # one autopsy store per replica (stores own their dir's seq space):
    # after the kill, at least one survivor must file a slice_reparent
    # black box when it adopts the victim's penned pods
    autopsy_root = tempfile.mkdtemp(prefix="chaos-autopsy-scaleout-")
    cluster = spawn_local_cluster(pod_shards=2, wal_dir=wal_dir)
    admin = RemoteHub(cluster.router_url, timeout=10.0,
                      retry_deadline=3.0, retry_base=0.01,
                      retry_cap=0.2)
    scheds: dict[str, Scheduler] = {}
    clients: dict[str, RemoteHub] = {}
    managers: dict[str, SliceManager] = {}
    killed: list[Scheduler] = []
    ttl_s = 2.0

    def spawn(ident: str) -> None:
        client = RemoteHub(cluster.router_url, timeout=10.0,
                           retry_deadline=3.0, retry_base=0.01,
                           retry_cap=0.2)
        cfg = default_config()
        cfg.batch_size = 32
        cfg.autopsy_dir = os.path.join(autopsy_root, ident)
        cfg.autopsy_rate_limit_s = 1.0
        sched = Scheduler(client, cfg,
                          caps=Capacities(nodes=max(32, nodes * 2),
                                          pods=1024))
        sm = SliceManager(client, ident, heartbeat_s=0.25, ttl_s=ttl_s)
        sched.start(elector=sm)
        clients[ident], scheds[ident], managers[ident] = \
            client, sched, sm

    try:
        for i in range(nodes):
            admin.create_node(MakeNode().name(f"sn-{i}")
                              .capacity(cpu="64", memory="256Gi",
                                        pods="440").obj())
        # exactly-once ledger off the router's merged watch stream (the
        # live counterpart of the journal-replay audit below)
        bind_counts: dict[str, int] = {}
        block = threading.Lock()

        def on_update(old, new) -> None:
            if not old.spec.node_name and new.spec.node_name:
                with block:
                    uid = new.metadata.uid
                    bind_counts[uid] = bind_counts.get(uid, 0) + 1

        admin.watch_pods(EventHandlers(on_update=on_update),
                         replay=False)
        for i in range(replicas):
            spawn(f"sched-{i}")

        uids: list[str] = []

        def create_wave(lo: int, hi: int) -> None:
            for i in range(lo, hi):
                pod = MakePod().name(f"sp-{i}") \
                    .namespace(namespaces[i % len(namespaces)]) \
                    .req(cpu="50m").obj()
                uids.append(pod.metadata.uid)
                admin.create_pod(pod)

        def bound_count() -> int:
            try:
                return sum(1 for p in admin.list_pods()
                           if p.spec.node_name)
            except Exception:  # noqa: BLE001 — mid-kill window
                return -1

        # phase 1: first wave drains across the ring's settle-in
        create_wave(0, pods // 2)
        t0 = time.monotonic()
        while time.monotonic() - t0 < 60.0 \
                and bound_count() < pods // 8:
            time.sleep(0.2)
        epoch_before = admin.leases.epoch_of(SCHED_SLICE_LEASE)
        ring = admin.fabric_sched_ring()
        report["epoch_before_kill"] = epoch_before
        report["ring_epoch_before_kill"] = ring["epoch"]
        # the victim must own pending work: take the owner of the ring
        # slot a seed-picked namespace hashes into
        ns_kill = namespaces[seed % len(namespaces)]
        slots = ring["slots"]
        victim = slots[ring_slot(ns_kill, len(slots))] if slots else \
            f"sched-{seed % replicas}"
        report["victim"] = victim
        report["victim_slots"] = sum(1 for s in slots if s == victim)

        # phase 2: second wave lands, then kill -9 the victim mid-wave.
        # Transport first — its release() and heartbeats can never
        # reach the board, so recovery happens on the TTL clock alone
        create_wave(pods // 2, pods)
        dead = scheds.pop(victim)
        killed.append(dead)
        managers.pop(victim)
        clients.pop(victim).close()
        if dead._stop is not None:
            dead._stop.set()
        t_kill = time.monotonic()
        reassign_s = None
        while time.monotonic() - t_kill < ttl_s * 5 + 5.0:
            try:
                cur = admin.fabric_sched_ring()["slots"]
            except Exception:  # noqa: BLE001 — transient
                time.sleep(0.1)
                continue
            if cur and victim not in cur:
                reassign_s = time.monotonic() - t_kill
                break
            time.sleep(0.1)
        report["slice_reassign_s"] = reassign_s

        # phase 3: survivors drain everything, the victim's slices
        # included (pen adoption after the rebalance)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if bound_count() >= pods:
                break
            time.sleep(0.3)
        bound = bound_count()
        epoch_after = admin.leases.epoch_of(SCHED_SLICE_LEASE)
        report["epoch_after"] = epoch_after
        report["ring_epoch_after"] = \
            admin.fabric_sched_ring()["epoch"]

        # a bind carrying a pre-rebalance slice epoch must be rejected
        # by the fence even now (probe schedulerName: no profile owns
        # it, so no live replica races the check)
        probe = MakePod().name("fence-probe").namespace("ns-0") \
            .scheduler_name("fence-probe-noop").obj()
        admin.create_pod(probe)
        stale_fenced = False
        if epoch_after > 0:
            try:
                admin.bind(probe, "sn-0", epoch_after - 1,
                           SCHED_SLICE_LEASE)
            except Fenced:
                stale_fenced = True
        try:
            admin.delete_pod(probe.metadata.uid)
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass

        # journal-replay audit: exactly-once across ALL replicas'
        # commits, straight off the cluster's own commit record
        audit = audit_bind_journal(hub=admin, expected_uids=uids)
        with block:
            dup = {uid: n for uid, n in bind_counts.items() if n > 1}
        daemon_errors = {
            ident: repr(s.daemon_error) for ident, s in scheds.items()
            if getattr(s, "daemon_error", None) is not None}
        # black-box gate: every survivor's bundles must re-parse
        # strictly, and at least one survivor filed a slice_reparent
        # (the pen adoption of the victim's pods IS the incident)
        per_replica = {ident: audit_autopsy_bundles(
            os.path.join(autopsy_root, ident))
            for ident in scheds}
        reparent_seen = sum(
            a["kinds"].get("slice_reparent", 0)
            for a in per_replica.values())
        autopsy = {
            "per_replica": per_replica,
            "slice_reparent_bundles": reparent_seen,
            "ok": (reparent_seen >= 1
                   and all(a["ok"] for a in per_replica.values())),
        }
        report.update({
            "bound": bound, "lost": pods - bound,
            "duplicate_binds": dup,
            "audit": {k: audit[k] for k in
                      ("ok", "binds", "double_binds", "lost",
                       "too_old")},
            "stale_epoch_fenced": stale_fenced,
            "fenced_binds": sum(s.stats.get("fenced", 0)
                                for s in scheds.values()),
            "rebalances": {i: m.rebalances
                           for i, m in managers.items()},
            "daemon_errors": daemon_errors,
            "autopsy": autopsy,
            "ok": (bound == pods and not dup and audit["ok"]
                   and autopsy["ok"]
                   and reassign_s is not None
                   and reassign_s <= ttl_s * 5
                   and epoch_after >= epoch_before >= 1
                   and stale_fenced and not daemon_errors),
        })
    finally:
        for s in list(scheds.values()) + killed:
            try:
                s.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for c in clients.values():
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        try:
            admin.close()
        except Exception:  # noqa: BLE001
            pass
        cluster.stop()
    return report


def run_overload_storm(pods: int = 120, nodes: int = 8, seed: int = 31,
                       overload: int = 10,
                       timeout_s: float = 150.0) -> dict:
    """Flow control under a ~10× stampede: a flow-controlled hub serves
    a real scheduler while ``overload``× its concurrency in anonymous
    best-effort hammers plus a band of tenant hammers slam the /call
    wire. ``ok`` iff queue depths stay bounded (never past the
    configured per-level backlog bound), priority isolation holds
    (system and scheduler probe p99 inside budget while best-effort
    sheds with HONEST 429 accounting — every server-side rejection is
    observed as a typed 429 by exactly one client), every pod binds
    exactly once (journal-replay audit), and the drain is clean: no
    watch relists, no daemon error. The scheduler runs with an
    unholdable time-to-bind SLO and an autopsy store, so ``ok`` also
    requires the watchdog to have filed ≥1 strictly-parseable
    ``slo_breach`` black-box bundle during the stampede."""
    import tempfile

    from kubernetes_tpu.config.types import default_config
    from kubernetes_tpu.fabric.flowcontrol import (
        FlowController,
        LevelConfig,
    )
    from kubernetes_tpu.hub import Hub, TooManyRequests
    from kubernetes_tpu.hubclient import RemoteHub
    from kubernetes_tpu.hubserver import HubServer
    from kubernetes_tpu.ops.features import Capacities
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.testing import MakeNode, MakePod, \
        audit_bind_journal

    report: dict = {"pods": pods, "nodes": nodes, "seed": seed,
                    "overload": overload}
    hub = Hub()
    # give every verb a real service time (GIL-released sleep inside
    # the dispatched call): an in-process hub answers in microseconds,
    # so without this a seat is always free again before the next
    # request lands and admission control never sees contention
    slow_hub = ChaosHub(hub, ChaosConfig(seed=seed, call_latency=0.01))
    # a small server so the stampede actually saturates: best-effort
    # gets 1 seat and a shallow queue (shed fast, by design); the
    # binding and system levels keep their share
    flow = FlowController(total_concurrency=12, levels={
        "best-effort": LevelConfig(share=0.08, queues=2, queue_depth=4,
                                   queue_wait_s=0.05, hand_size=2)})
    server = HubServer(slow_hub, flow=flow).start()

    def client(identity=None, deadline=6.0):
        return RemoteHub(server.address, timeout=10.0,
                         retry_deadline=deadline, retry_base=0.01,
                         retry_cap=0.2, identity=identity)

    sched_client = client("scheduler-0")
    clients: list[RemoteHub] = [sched_client]
    stop_evt = threading.Event()
    threads: list[threading.Thread] = []
    lat: dict[str, list[float]] = {"system": [], "scheduler": [],
                                   "tenant": [], "best-effort": []}
    lat_lock = threading.Lock()

    def hammer(cl: RemoteHub, cls: str, fn, pause: float = 0.0):
        def loop():
            while not stop_evt.is_set():
                t0 = time.monotonic()
                try:
                    fn(cl)
                    with lat_lock:
                        lat[cls].append(time.monotonic() - t0)
                except TooManyRequests:
                    pass    # the client's throttled_429s counted it
                except Exception:  # noqa: BLE001 — teardown races
                    if stop_evt.is_set():
                        return
                if pause:
                    time.sleep(pause)
        t = threading.Thread(target=loop, daemon=True,
                             name=f"overload-{cls}")
        threads.append(t)
        t.start()

    sched = None
    try:
        for i in range(nodes):
            hub.create_node(MakeNode().name(f"on-{i}")
                            .capacity(cpu="64", memory="256Gi",
                                      pods="440").obj())
        cfg = default_config()
        cfg.batch_size = 16
        # autopsy gate: a time-to-bind SLO no stampede can hold (10ms
        # p99 under seat contention + compile warmup) so the watchdog
        # MUST file an slo_breach black box; the sustained 429s feed
        # the throttle_shed counter rule the same window
        autopsy_dir = tempfile.mkdtemp(prefix="chaos-autopsy-overload-")
        cfg.autopsy_dir = autopsy_dir
        cfg.autopsy_rate_limit_s = 2.0
        cfg.watchdog_interval_s = 1.0
        cfg.watchdog_slo = {"time_to_bind_p99_ms": 10.0}
        sched = Scheduler(sched_client, cfg,
                          caps=Capacities(nodes=max(16, nodes * 2),
                                          pods=max(256, pods * 2)))
        sched.start()
        uids: list[str] = []
        for i in range(pods):
            pod = MakePod().name(f"op-{i}").req(cpu="50m").obj()
            uids.append(pod.metadata.uid)
            hub.create_pod(pod)
        probe_uid = uids[0]

        # let the first schedule wave land before unleashing the storm:
        # the initial device-kernel compile holds the interpreter for
        # long stretches, and a probe call stalled under a compile
        # would gate on warmup, not on admission-control isolation
        warm_end = time.monotonic() + 30.0
        while time.monotonic() < warm_end:
            if any(p.spec.node_name for p in hub.list_pods()):
                break
            time.sleep(0.1)

        # the stampede: anonymous read hammers (best-effort level),
        # tenant-attributed read hammers, and the protected probes.
        # Cheap verbs on purpose — service time is the injected hold,
        # so the seat contention is real but the hammers don't also
        # starve the probes of interpreter time encoding huge LISTs
        for _ in range(overload * 2):
            cl = client(deadline=0.5)
            clients.append(cl)
            hammer(cl, "best-effort", lambda c: c.get_pod(probe_uid))
        for i in range(max(overload // 2, 3)):
            cl = client(f"team-{i % 3}", deadline=0.5)
            clients.append(cl)
            hammer(cl, "tenant", lambda c: c.list_nodes())
        sys_probe = client("system-probe", deadline=2.0)
        clients.append(sys_probe)
        hammer(sys_probe, "system",
               lambda c: c.get_pod(probe_uid), pause=0.005)
        sched_probe = client("sched-probe", deadline=2.0)
        clients.append(sched_probe)
        hammer(sched_probe, "scheduler",
               lambda c: c.get_pod(probe_uid), pause=0.005)

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if sum(1 for p in hub.list_pods()
                   if p.spec.node_name) >= pods:
                break
            time.sleep(0.2)
        # let the hammers rage a beat past the drain so the shed
        # accounting below reflects a saturated steady state
        time.sleep(1.0)
        stop_evt.set()
        for t in threads:
            t.join(timeout=5.0)

        bound = sum(1 for p in hub.list_pods() if p.spec.node_name)
        audit = audit_bind_journal(hub=hub, expected_uids=uids)
        fstats = flow.stats()["levels"]
        depths_bounded = all(
            lv["depth_peak"] <= lv["queue_depth_bound"]
            for lv in fstats.values())
        server_rejected = {
            name: lv["rejected_full"] + lv["rejected_timeout"]
            for name, lv in fstats.items()}
        client_throttled = sum(
            c.resilience_stats()["throttled_429s"] for c in clients)

        def p99(cls: str) -> float:
            with lat_lock:
                xs = sorted(lat[cls])
            if not xs:
                return -1.0
            return xs[min(len(xs) - 1, int(0.99 * len(xs)))]

        p99s = {cls: round(p99(cls), 4) for cls in lat}
        rs = sched_client.resilience_stats()
        # the watchdog must have filed at least one slo_breach black
        # box (the injected 10ms p99 limit is unholdable under the
        # stampede), and every bundle on disk must re-parse strictly
        autopsy = audit_autopsy_bundles(
            autopsy_dir, expect_kinds=("slo_breach",))
        report.update({
            "bound": bound,
            "audit": {k: audit[k] for k in
                      ("ok", "binds", "double_binds", "lost",
                       "too_old")},
            "flow": fstats,
            "server_rejected": server_rejected,
            "client_throttled_429s": client_throttled,
            "probe_p99_s": p99s,
            "calls_ok": {cls: len(v) for cls, v in lat.items()},
            "sched_watch_relists": rs["watch_relists"],
            "sched_throttled": rs["throttled_429s"],
            "daemon_error": repr(sched.daemon_error)
            if getattr(sched, "daemon_error", None) else None,
            "autopsy": autopsy,
            "ok": (bound == pods and audit["ok"]
                   and autopsy["ok"]
                   and depths_bounded
                   # best-effort sheds, with honest typed accounting:
                   # every server-side 429 reached a client as one
                   and server_rejected["best-effort"] > 0
                   and client_throttled == sum(server_rejected.values())
                   # priority isolation: the protected levels' probes
                   # stay inside their queue-wait budgets
                   and 0.0 <= p99s["system"] <= 0.5
                   and 0.0 <= p99s["scheduler"] <= 0.75
                   and rs["watch_relists"] == 0
                   and sched.daemon_error is None),
        })
    finally:
        stop_evt.set()
        if sched is not None:
            try:
                sched.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        server.stop()
    return report


def run_scenario_storm(seed: int = 7, speed: float = 3.0) -> dict:
    """Scenario battery (ISSUE 17): replay the zone-outage + recovery-
    stampede named regime, then every fuzzer-filed regression trace
    under tests/regression_traces/ — each gated on its trace-time SLO /
    ratchet gate and on journal-audit exactly-once. A filed trace
    replays at the speed its verdict was judged at (compute latency
    does not compress with speed, engineered waits do)."""
    import glob

    from kubernetes_tpu.scenario.generators import generate
    from kubernetes_tpu.scenario.replay import replay_trace
    from kubernetes_tpu.scenario.trace import load_trace

    def _summary(rep: dict, gate_key: str) -> dict:
        return {
            "name": rep["name"],
            "completed": rep["completed"],
            "audit_ok": rep["audit"]["ok"],
            f"{gate_key}_ok": rep[gate_key]["ok"],
            "breaches": rep[gate_key]["breaches"],
            "time_to_bind_p99_ms": rep["stats"]["time_to_bind_p99_ms"],
            "pacing": rep["pacing"],
            "ok": rep["completed"] and rep["audit"]["ok"]
            and rep[gate_key]["ok"],
        }

    # the named regime gates on its intent SLO
    regime_rep = replay_trace(generate("zone_outage", seed=seed),
                              speed=speed)
    report: dict = {"regime": _summary(regime_rep, "slo"),
                    "regression_traces": []}
    # filed traces gate on their RATCHET bound (they breach their
    # original slo by construction — that breach is the filed evidence)
    trace_dir = os.path.join(os.path.dirname(__file__), "..",
                             "tests", "regression_traces")
    for path in sorted(glob.glob(os.path.join(trace_dir, "*.jsonl"))):
        tr = load_trace(path)
        rep = replay_trace(
            tr, speed=float(tr.meta.get("filed_speed", speed)))
        report["regression_traces"].append(
            {"path": os.path.basename(path),
             **_summary(rep, "gate")})
    report["ok"] = report["regime"]["ok"] and all(
        r["ok"] for r in report["regression_traces"])
    return report


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description="chaos storm gate")
    ap.add_argument("--pods", type=int, default=40)
    ap.add_argument("--nodes", type=int, default=8)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--storm",
                    choices=("smoke", "device", "crash", "proc",
                             "state", "gang", "scaleout", "overload",
                             "scenario", "all"),
                    default="smoke",
                    help="which storm to run (bench.py --chaos-smoke "
                         "runs 'all')")
    args = ap.parse_args()
    if args.storm == "smoke":
        report: dict = run_smoke(pods=args.pods, nodes=args.nodes,
                                 seed=args.seed)
    elif args.storm == "device":
        report = run_device_storm(seed=args.seed)
    elif args.storm == "crash":
        report = run_crash_storm(seed=args.seed)
    elif args.storm == "proc":
        report = run_proc_crash_storm(seed=args.seed)
    elif args.storm == "state":
        report = run_state_storm(seed=args.seed)
    elif args.storm == "gang":
        report = run_gang_storm(seed=args.seed)
    elif args.storm == "scaleout":
        report = run_scaleout_storm(seed=args.seed)
    elif args.storm == "overload":
        report = run_overload_storm(seed=args.seed)
    elif args.storm == "scenario":
        report = run_scenario_storm(seed=args.seed)
    else:
        report = {
            "smoke": run_smoke(pods=args.pods, nodes=args.nodes,
                               seed=args.seed),
            "device": run_device_storm(seed=args.seed),
            "crash": run_crash_storm(seed=args.seed),
            "proc": run_proc_crash_storm(seed=args.seed),
            "state": run_state_storm(seed=args.seed),
            "gang": run_gang_storm(seed=args.seed),
            "scaleout": run_scaleout_storm(seed=args.seed),
            "overload": run_overload_storm(seed=args.seed),
            "scenario": run_scenario_storm(seed=args.seed),
        }
        report["ok"] = all(r.get("ok") for r in report.values())
    print(json.dumps(report, default=str))
    raise SystemExit(0 if report.get("ok") else 1)


if __name__ == "__main__":
    main()
