"""kubernetes_tpu — a TPU-native pod-scheduling framework.

A brand-new scheduling framework with the capabilities of the Kubernetes
kube-scheduler (reference: /root/reference, pkg/scheduler), re-designed
TPU-first:

- Host (Python + C-extension hot paths) owns API ingestion (list/watch),
  the three-tier pending queue with queueing hints, the authoritative
  generation-tracked cluster cache, preemption orchestration, binding I/O,
  metrics and config.
- Device (JAX/XLA on TPU) owns the per-cycle math: Filter predicates,
  Score, normalization, weighted aggregation and masked argmax over a dense
  ``nodes x features`` tensor resident in HBM, with pending pods batched
  along a second axis so one XLA launch schedules a whole batch
  (as-if-serial semantics via a lax.scan commit loop).

Layer map (mirrors SURVEY.md section 1, scheduler-internal layering):

    kubernetes_tpu.api        — object model (Pod/Node/...), quantities, labels
    kubernetes_tpu.utils      — interner, clock, misc
    kubernetes_tpu.backend    — cache, snapshot, node_tree, queue, heap, mirror
    kubernetes_tpu.framework  — extension points, CycleState, runtime, registry
    kubernetes_tpu.plugins    — in-tree plugins (device kernels + host logic)
    kubernetes_tpu.ops        — the JAX kernels behind the device plugins
    kubernetes_tpu.models     — the flagship batched scheduling pipeline
    kubernetes_tpu.parallel   — mesh/sharding for the node axis (ICI scale-out)
    kubernetes_tpu.config     — SchedulerConfiguration types/defaults/validation
    kubernetes_tpu.scheduler  — the Scheduler: event handlers + scheduling loop
    kubernetes_tpu.hub        — in-process API hub (list/watch/bind) for tests+bench
"""

__version__ = "0.1.0"
