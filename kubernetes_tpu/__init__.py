"""kubernetes_tpu — a TPU-native pod-scheduling framework.

A brand-new scheduling framework with the capabilities of the Kubernetes
kube-scheduler (reference: /root/reference, pkg/scheduler), re-designed
TPU-first:

- Host (Python + C-extension hot paths) owns API ingestion (list/watch),
  the three-tier pending queue with queueing hints, the authoritative
  generation-tracked cluster cache, preemption orchestration, binding I/O,
  metrics and config.
- Device (JAX/XLA on TPU) owns the per-cycle math: Filter predicates,
  Score, normalization, weighted aggregation and masked argmax over a dense
  ``nodes x features`` tensor resident in HBM, with pending pods batched
  along a second axis so one XLA launch schedules a whole batch
  (as-if-serial semantics via a lax.scan commit loop).

Layer map (mirrors SURVEY.md section 1, scheduler-internal layering):

    kubernetes_tpu.api        — object model (Pod/Node/...), quantities, labels
    kubernetes_tpu.utils      — interner, clock, misc
    kubernetes_tpu.backend    — cache, snapshot, node_tree, queue, heap, mirror
    kubernetes_tpu.framework  — extension points, CycleState, runtime, registry
    kubernetes_tpu.plugins    — in-tree plugins (device kernels + host logic)
    kubernetes_tpu.ops        — the JAX kernels behind the device plugins
    kubernetes_tpu.models     — the flagship batched scheduling pipeline
    kubernetes_tpu.parallel   — mesh/sharding for the node axis (ICI scale-out)
    kubernetes_tpu.config     — SchedulerConfiguration types/defaults/validation
    kubernetes_tpu.scheduler  — the Scheduler: event handlers + scheduling loop
    kubernetes_tpu.hub        — in-process API hub (list/watch/bind) for tests+bench
"""

__version__ = "0.1.0"

# The staged public API (the reference publishes its plugin-facing types as
# staging/src/k8s.io/kube-scheduler): everything an out-of-tree plugin,
# embedding host, or operator needs, importable from the package root.
# Heavy modules (jax-backed) load lazily so `import kubernetes_tpu` stays
# cheap for config-only consumers.

_PUBLIC = {
    # runtime surface
    "Scheduler": ("kubernetes_tpu.scheduler", "Scheduler"),
    "Hub": ("kubernetes_tpu.hub", "Hub"),
    "ServingEndpoints": ("kubernetes_tpu.serving", "ServingEndpoints"),
    "LeaderElector": ("kubernetes_tpu.leaderelection", "LeaderElector"),
    "HTTPExtender": ("kubernetes_tpu.extender", "HTTPExtender"),
    "ExtenderConfig": ("kubernetes_tpu.extender", "ExtenderConfig"),
    # configuration
    "SchedulerConfiguration": ("kubernetes_tpu.config.types",
                               "SchedulerConfiguration"),
    "SchedulerProfile": ("kubernetes_tpu.config.types", "SchedulerProfile"),
    "default_config": ("kubernetes_tpu.config.types", "default_config"),
    "load_config": ("kubernetes_tpu.config.load", "load_config"),
    "validate_config": ("kubernetes_tpu.config.validation",
                        "validate_config"),
    # plugin authoring (framework/interface.go's staged types)
    "Status": ("kubernetes_tpu.framework.interface", "Status"),
    "Code": ("kubernetes_tpu.framework.interface", "Code"),
    "ClusterEvent": ("kubernetes_tpu.framework.interface", "ClusterEvent"),
    "QueueingHint": ("kubernetes_tpu.framework.interface", "QueueingHint"),
    "PreFilterPlugin": ("kubernetes_tpu.framework.interface",
                        "PreFilterPlugin"),
    "FilterPlugin": ("kubernetes_tpu.framework.interface", "FilterPlugin"),
    "PostFilterPlugin": ("kubernetes_tpu.framework.interface",
                         "PostFilterPlugin"),
    "ScorePlugin": ("kubernetes_tpu.framework.interface", "ScorePlugin"),
    "ReservePlugin": ("kubernetes_tpu.framework.interface",
                      "ReservePlugin"),
    "PermitPlugin": ("kubernetes_tpu.framework.interface", "PermitPlugin"),
    "PreBindPlugin": ("kubernetes_tpu.framework.interface",
                      "PreBindPlugin"),
    "BindPlugin": ("kubernetes_tpu.framework.interface", "BindPlugin"),
    "PostBindPlugin": ("kubernetes_tpu.framework.interface",
                       "PostBindPlugin"),
    "PluginDescriptor": ("kubernetes_tpu.plugins.registry",
                         "PluginDescriptor"),
    "in_tree_registry": ("kubernetes_tpu.plugins.registry",
                         "in_tree_registry"),
}

__all__ = sorted(_PUBLIC) + ["api"]


def __getattr__(name: str):
    import importlib

    if name == "api":
        value = importlib.import_module("kubernetes_tpu.api")
        globals()[name] = value
        return value
    entry = _PUBLIC.get(name)
    if entry is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    mod = importlib.import_module(entry[0])
    value = getattr(mod, entry[1])
    globals()[name] = value
    return value

