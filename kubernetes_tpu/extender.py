"""HTTP scheduler extender: the out-of-process webhook, all four verbs.

From-scratch equivalent of /root/reference/pkg/scheduler/extender.go
(HTTPExtender :43, Filter :248, Prioritize :319, Bind :361,
ProcessPreemption :136, IsInterested :465) and the v1 extender API
(ExtenderArgs/ExtenderFilterResult/HostPriorityList/
ExtenderBindingArgs/ExtenderPreemptionArgs): a legacy escape hatch
predating the framework — JSON POSTs to an external service that can veto
nodes, add weighted scores, bind pods itself, and veto/trim preemption
candidates. Wired into the host side of the mixed framework: filter
verdicts AND into the device mask, scores add into the aggregate, a
binder extender replaces the default binder for its pods, and preemption
candidates pass through ProcessPreemption before selection
(framework/preemption.py call_extenders).

Objects cross the wire in this build's full-fidelity JSON schema
(utils.wire tagged dicts — the analog of the reference marshalling full
v1.Pod/v1.Node objects, extender.go:248); nodeCacheCapable extenders get
node NAMES only (extender.go:258-267).
"""

from __future__ import annotations

import json
import urllib.request
from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.api.objects import Pod
from kubernetes_tpu.utils.wire import to_wire

DEFAULT_TIMEOUT = 5.0


@dataclass
class ExtenderConfig:
    """apis/config.Extender (types.go:190+): the slice the scheduler
    consumes."""

    url_prefix: str
    filter_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    preempt_verb: str = ""
    weight: float = 1.0
    # resource names whose presence in a pod's requests makes the extender
    # interested; empty = interested in every pod (extender.go:465)
    managed_resources: list[str] = field(default_factory=list)
    # an unreachable ignorable extender is skipped; a non-ignorable one
    # fails the pod (extender.go IsIgnorable)
    ignorable: bool = False
    # nodeCacheCapable: the extender caches node objects itself, so
    # filter/prioritize payloads carry node NAMES and preemption payloads
    # carry pod-uid references instead of full objects. Defaults false
    # like the upstream ExtenderConfig field.
    node_cache_capable: bool = False
    timeout_seconds: float = DEFAULT_TIMEOUT


class ExtenderError(Exception):
    pass


def _pod_payload(pod: Pod) -> dict:
    """The FULL pod object (extender.go:248 marshals the entire v1.Pod):
    a partial payload silently breaks extenders reading nodeSelector,
    affinity, or tolerations."""
    return to_wire(pod)


class HTTPExtender:
    """One configured extender endpoint."""

    def __init__(self, cfg: ExtenderConfig):
        self.cfg = cfg

    @property
    def name(self) -> str:
        return f"Extender({self.cfg.url_prefix})"

    @property
    def is_binder(self) -> bool:
        return bool(self.cfg.bind_verb)

    @property
    def supports_preemption(self) -> bool:
        return bool(self.cfg.preempt_verb)

    def is_interested(self, pod: Pod) -> bool:
        if not self.cfg.managed_resources:
            return True
        managed = set(self.cfg.managed_resources)
        for c in pod.spec.containers + pod.spec.init_containers:
            if managed & set(c.resources.requests):
                return True
        return False

    def _post(self, verb: str, payload: dict) -> dict:
        url = self.cfg.url_prefix.rstrip("/") + "/" + verb
        data = json.dumps(payload).encode()
        req = urllib.request.Request(
            url, data=data, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(
                req, timeout=self.cfg.timeout_seconds) as resp:
            return json.loads(resp.read().decode())

    def filter(self, pod: Pod, node_names: list[str],
               nodes: Optional[list] = None
               ) -> tuple[list[str], dict[str, str]]:
        """(nodes that passed, {failed node: reason}). Raises
        ExtenderError on transport errors (caller applies ignorable).
        ``nodes`` (full objects) ride along for non-nodeCacheCapable
        extenders (extender.go:258: Nodes vs NodeNames)."""
        if not self.cfg.filter_verb:
            return node_names, {}
        try:
            payload = {"pod": _pod_payload(pod)}
            if self.cfg.node_cache_capable or nodes is None:
                payload["nodenames"] = node_names
            else:
                payload["nodes"] = [to_wire(n) for n in nodes]
            out = self._post(self.cfg.filter_verb, payload)
            if out.get("error"):
                raise ExtenderError(f"{self.name}: {out['error']}")
            passed = out.get("nodenames")
            if passed is None and out.get("nodes") is not None:
                passed = [n["metadata"]["name"] for n in out["nodes"]]
            if passed is None:
                passed = node_names
            failed = dict(out.get("failedNodes") or {})
            failed.update(out.get("failedAndUnresolvableNodes") or {})
            return list(passed), failed
        except ExtenderError:
            raise
        except Exception as e:  # noqa: BLE001 — transport OR malformed
            # response; both surface as ExtenderError so `ignorable`
            # applies instead of crashing the scheduling cycle
            raise ExtenderError(f"{self.name}: {e}") from e

    def prioritize(self, pod: Pod, node_names: list[str],
                   nodes: Optional[list] = None
                   ) -> Optional[dict[str, float]]:
        """{node: weighted score} or None without a prioritize verb."""
        if not self.cfg.prioritize_verb:
            return None
        try:
            payload = {"pod": _pod_payload(pod)}
            if self.cfg.node_cache_capable or nodes is None:
                payload["nodenames"] = node_names
            else:
                payload["nodes"] = [to_wire(n) for n in nodes]
            out = self._post(self.cfg.prioritize_verb, payload)
            return {e["host"]: float(e["score"]) * self.cfg.weight
                    for e in out or []}
        except Exception as e:  # noqa: BLE001 — transport or malformed
            raise ExtenderError(f"{self.name}: {e}") from e

    def bind(self, pod: Pod, node_name: str) -> None:
        """Delegate the binding API call (extender.go:361 Bind;
        ExtenderBindingArgs/ExtenderBindingResult). Raises ExtenderError
        on transport errors or an error result — a failed delegated bind
        fails the pod's binding cycle like a failed Binding POST."""
        try:
            out = self._post(self.cfg.bind_verb, {
                "podName": pod.metadata.name,
                "podNamespace": pod.metadata.namespace,
                "podUID": pod.metadata.uid,
                "node": node_name})
            if out and out.get("error"):
                raise ExtenderError(f"{self.name}: {out['error']}")
        except ExtenderError:
            raise
        except Exception as e:  # noqa: BLE001
            raise ExtenderError(f"{self.name}: {e}") from e

    def process_preemption(self, pod: Pod,
                           node_to_victims: dict[str, list[Pod]],
                           pdb_violations: dict[str, int]
                           ) -> dict[str, tuple[list[Pod], int]]:
        """ProcessPreemption (extender.go:136): the extender may veto
        candidate nodes (omit them) or trim their victim lists. Returns
        {node: (victims, pdb_violations)} for the surviving candidates;
        returned victim references resolve by uid against the supplied
        lists (convertToVictims, extender.go:177). nodeCacheCapable
        extenders exchange NodeNameToMetaVictims (pod uids only,
        extender.go:150); the rest get full pod objects."""
        meta = self.cfg.node_cache_capable
        if meta:
            payload = {
                "pod": _pod_payload(pod),
                "nodeNameToMetaVictims": {
                    node: {"pods": [{"uid": v.metadata.uid}
                                    for v in victims],
                           "numPDBViolations": pdb_violations.get(node, 0)}
                    for node, victims in node_to_victims.items()},
            }
        else:
            payload = {
                "pod": _pod_payload(pod),
                "nodeNameToVictims": {
                    node: {"pods": [_pod_payload(v) for v in victims],
                           "numPDBViolations": pdb_violations.get(node, 0)}
                    for node, victims in node_to_victims.items()},
            }
        try:
            out = self._post(self.cfg.preempt_verb, payload)
            result = (out.get("nodeNameToMetaVictims")
                      or out.get("nodeNameToVictims") or {})
            by_uid = {v.metadata.uid: v
                      for victims in node_to_victims.values()
                      for v in victims}
            survivors: dict[str, tuple[list[Pod], int]] = {}
            for node, entry in result.items():
                if node not in node_to_victims:
                    continue    # an extender cannot add candidates
                victims = []
                for p in entry.get("pods") or []:
                    uid = (p.get("uid")
                           or (p.get("metadata") or {}).get("uid", ""))
                    v = by_uid.get(uid)
                    if v is not None:
                        victims.append(v)
                survivors[node] = (victims,
                                   int(entry.get("numPDBViolations") or 0))
            return survivors
        except ExtenderError:
            raise
        except Exception as e:  # noqa: BLE001 — transport OR malformed
            # response; both must surface as ExtenderError so `ignorable`
            # applies in call_extenders
            raise ExtenderError(f"{self.name}: {e}") from e
