"""HTTP scheduler extender: the out-of-process Filter/Prioritize webhook.

From-scratch equivalent of /root/reference/pkg/scheduler/extender.go
(HTTPExtender :43, Filter :248, Prioritize :319, IsInterested :361) and
the v1 extender API (ExtenderArgs/ExtenderFilterResult/HostPriorityList):
a legacy escape hatch predating the framework — JSON POSTs to an external
service that can veto nodes and add weighted scores. Wired into the host
side of the mixed framework: verdicts AND into the device mask, scores
add into the aggregate.
"""

from __future__ import annotations

import json
import urllib.request
from dataclasses import dataclass, field
from typing import Optional

from kubernetes_tpu.api.objects import Pod

DEFAULT_TIMEOUT = 5.0


@dataclass
class ExtenderConfig:
    """apis/config.Extender (types.go:190+): the slice the scheduler
    consumes."""

    url_prefix: str
    filter_verb: str = ""
    prioritize_verb: str = ""
    weight: float = 1.0
    # resource names whose presence in a pod's requests makes the extender
    # interested; empty = interested in every pod (extender.go:361)
    managed_resources: list[str] = field(default_factory=list)
    # an unreachable ignorable extender is skipped; a non-ignorable one
    # fails the pod (extender.go IsIgnorable)
    ignorable: bool = False
    timeout_seconds: float = DEFAULT_TIMEOUT


class ExtenderError(Exception):
    pass


def _pod_payload(pod: Pod) -> dict:
    return {
        "metadata": {"name": pod.metadata.name,
                     "namespace": pod.metadata.namespace,
                     "uid": pod.metadata.uid,
                     "labels": dict(pod.metadata.labels)},
        "spec": {"schedulerName": pod.spec.scheduler_name,
                 "containers": [
                     {"name": c.name,
                      "resources": {"requests": dict(c.resources.requests)}}
                     for c in pod.spec.containers]},
    }


class HTTPExtender:
    """One configured extender endpoint."""

    def __init__(self, cfg: ExtenderConfig):
        self.cfg = cfg

    @property
    def name(self) -> str:
        return f"Extender({self.cfg.url_prefix})"

    def is_interested(self, pod: Pod) -> bool:
        if not self.cfg.managed_resources:
            return True
        managed = set(self.cfg.managed_resources)
        for c in pod.spec.containers + pod.spec.init_containers:
            if managed & set(c.resources.requests):
                return True
        return False

    def _post(self, verb: str, payload: dict) -> dict:
        url = self.cfg.url_prefix.rstrip("/") + "/" + verb
        data = json.dumps(payload).encode()
        req = urllib.request.Request(
            url, data=data, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(
                req, timeout=self.cfg.timeout_seconds) as resp:
            return json.loads(resp.read().decode())

    def filter(self, pod: Pod, node_names: list[str]
               ) -> tuple[list[str], dict[str, str]]:
        """(nodes that passed, {failed node: reason}). Raises
        ExtenderError on transport errors (caller applies ignorable)."""
        if not self.cfg.filter_verb:
            return node_names, {}
        try:
            out = self._post(self.cfg.filter_verb, {
                "pod": _pod_payload(pod), "nodenames": node_names})
            if out.get("error"):
                raise ExtenderError(f"{self.name}: {out['error']}")
            passed = out.get("nodenames")
            if passed is None:
                passed = node_names
            failed = dict(out.get("failedNodes") or {})
            failed.update(out.get("failedAndUnresolvableNodes") or {})
            return list(passed), failed
        except ExtenderError:
            raise
        except Exception as e:  # noqa: BLE001 — transport OR malformed
            # response; both surface as ExtenderError so `ignorable`
            # applies instead of crashing the scheduling cycle
            raise ExtenderError(f"{self.name}: {e}") from e

    def prioritize(self, pod: Pod, node_names: list[str]
                   ) -> Optional[dict[str, float]]:
        """{node: weighted score} or None without a prioritize verb."""
        if not self.cfg.prioritize_verb:
            return None
        try:
            out = self._post(self.cfg.prioritize_verb, {
                "pod": _pod_payload(pod), "nodenames": node_names})
            return {e["host"]: float(e["score"]) * self.cfg.weight
                    for e in out or []}
        except Exception as e:  # noqa: BLE001 — transport or malformed
            raise ExtenderError(f"{self.name}: {e}") from e
