"""Event journal: bounded per-kind rings + compaction + optional WAL.

The storage discipline is etcd's (reference: SharedEtcd in
test/integration/scheduler_perf/util.go): one monotonically increasing
revision space (the hub's resourceVersion counter) stamps every mutation,
the journal retains a bounded suffix of events per resource kind, and a
watch can resume from any revision that has not been compacted away.

Semantics:

* ``append(ev)`` retains ``ev`` in its kind's ring. When the ring is
  full the oldest event is dropped and that event's rv becomes the
  kind's ``compacted_rv`` — the compaction watermark.
* ``events_after(kind, since_rv)`` returns every retained event with
  ``rv > since_rv`` **iff** ``since_rv >= compacted_rv`` (the boundary
  is inclusive: a client that saw exactly the last compacted event can
  still resume). Below the watermark the gap is unrecoverable from the
  journal and :class:`RvTooOld` is raised — the caller relists.
* Revisions are global across kinds, so a kind's retained suffix is a
  COMPLETE event history for that kind above its watermark; per-kind rv
  gaps (revisions spent on other kinds) are expected and harmless.

WAL: with ``wal_path`` set, every appended event is also written as one
record and flushed, so a restarted hub can replay the file to rebuild
both its object stores and the journal rings (``replay_wal``, a lazy
record-at-a-time iterator — the file is never materialized whole).
Writes are flushed, not fsynced — the durability target is hub-process
restart, not kernel crash. A truncated final record (a write cut
mid-append) is tolerated and ignored; corruption earlier in the file
raises, because silently skipping interior history would resurrect a
hub with holes in its state.

WAL codec (``wal_codec``): ``"json"`` writes one JSON line per record
(wire-encoded objects — the original, human-greppable format);
``"bin1"`` writes 4-byte length-prefixed binary frames in the fabric's
positional codec (fabric.codec), ~6× smaller replay I/O because field
names never hit the disk. Replay SNIFFS the file's actual format (a
JSON record starts with ``{``; a bin1 frame starts with a length
prefix whose first byte is far below ``{``), so a hub reconfigured
from JSON to bin1 replays its old WAL transparently and reports
``wal_upgrade_pending`` — the hub then rewrites the file in the
configured codec on the spot (the in-place upgrade). Torn-tail
tolerance is codec-independent: a final record cut mid-write (short
line / short frame) never committed and is truncated by
``repair_wal``.

WAL compaction (``rewrite_wal``): appending forever would grow the file
linearly with total history, so the hub snapshots on boot when the
replayed history dwarfs the live object count — the WAL is atomically
rewritten as a ``{"compact": rv}`` record followed by one add-event per
live object. The compact record is etcd's compaction revision: replay
raises ``RvTooOld`` for any resume below it (``compact_floor``), because
the rewritten file no longer holds the update/delete history a resumer
from down there would need.
"""

from __future__ import annotations

import json
import os
from collections import deque
from dataclasses import dataclass
from typing import Iterator, Optional


class RvTooOld(Exception):
    """The requested resume point is unserviceable from the journal:
    either it predates the compaction watermark (the gap was dropped) or
    it lies BEYOND the hub's newest revision (the revision space was
    reset — a hub restarted without its WAL; "resuming" there would
    silently pin phantom state forever). The transport maps both to the
    apiserver's 410 Gone / "too old resource version"; clients relist."""

    def __init__(self, kind: str, since_rv: int, compacted_rv: int):
        if since_rv > compacted_rv:
            msg = (f"watch {kind}: since_rv {since_rv} is ahead of the "
                   f"hub's newest revision {compacted_rv} (revision "
                   f"space reset); relist required")
        else:
            msg = (f"watch {kind}: since_rv {since_rv} is older than "
                   f"the compaction watermark {compacted_rv}; relist "
                   f"required")
        super().__init__(msg)
        self.kind = kind
        self.since_rv = since_rv
        self.compacted_rv = compacted_rv


@dataclass(frozen=True)
class JournalEvent:
    """One committed mutation: rv is the global revision stamped by the
    hub; ``old``/``new`` carry the object before/after (None on the
    add/delete side respectively), exactly what a watch dispatches.
    ``trace`` (telemetry.trace.TraceContext, optional) is the commit's
    trace stamp — origin component, commit timestamp, relay hop count —
    carried with the event across the wire and relay tree; None on
    synthetic events (LIST replays, pre-telemetry WALs/peers).
    ``shard`` names the source SHARD PROCESS the event was committed on
    (the wire's ``sh`` tag, stamped by the fabric router): per-shard
    streams are rv-ordered but their cross-shard interleave is not, so
    resume cursors must be tracked per shard — None off a single hub,
    where one cursor is enough."""

    rv: int
    kind: str                     # watch kind, e.g. "pods"
    type: str                     # "add" | "update" | "delete"
    old: object = None
    new: object = None
    trace: object = None          # TraceContext | None
    shard: object = None          # source shard name | None


class _KindRing:
    __slots__ = ("ring", "compacted_rv")

    def __init__(self, capacity: int):
        self.ring: deque[JournalEvent] = deque(maxlen=capacity)
        self.compacted_rv = 0

    def append(self, ev: JournalEvent) -> None:
        if self.ring.maxlen and len(self.ring) == self.ring.maxlen:
            self.compacted_rv = self.ring[0].rv
        self.ring.append(ev)


class Journal:
    """Per-kind event rings sharing one revision space, plus the WAL.

    NOT self-locking: the hub appends and reads under its own lock (the
    journal is part of the same consistency domain as the stores — an
    event must land in the ring before any later revision is stamped)."""

    def __init__(self, capacity: int = 16384,
                 wal_path: Optional[str] = None,
                 wal_codec: str = "json"):
        if capacity < 1:
            raise ValueError("journal capacity must be >= 1")
        if wal_codec not in ("json", "bin1"):
            raise ValueError(f"unknown wal_codec {wal_codec!r}")
        self.capacity = capacity
        self.wal_path = wal_path
        self.wal_codec = wal_codec
        self._kinds: dict[str, _KindRing] = {}
        # the WAL's compaction revision: resume below this is impossible
        # for EVERY kind — a rewrite discarded the update/delete history
        self.compact_floor = 0
        # replay_wal bookkeeping for repair_wal's torn-tail truncation
        self._wal_good_end = 0
        self._wal_size = 0
        # the format replay actually FOUND on disk (None = empty/absent
        # file); a mismatch with wal_codec means the file predates a
        # codec switch and should be rewritten in the configured codec
        self.wal_format: Optional[str] = None
        # append handle: binary for bin1 frames, text for JSON lines
        self._wal = self._open_wal() if wal_path else None

    def _open_wal(self):
        if self.wal_codec == "bin1":
            return open(self.wal_path, "ab")
        return open(self.wal_path, "a", encoding="utf-8")

    @property
    def wal_upgrade_pending(self) -> bool:
        """True when the on-disk WAL replayed in a DIFFERENT format than
        the configured codec: the owner should rewrite it (rewrite_wal /
        the hub's boot compaction) so the file upgrades in place."""
        return (self.wal_format is not None
                and self.wal_format != self.wal_codec)

    # ------------- append / read -------------

    def append(self, ev: JournalEvent, persist: bool = True) -> None:
        ring = self._kinds.get(ev.kind)
        if ring is None:
            ring = self._kinds[ev.kind] = _KindRing(self.capacity)
        ring.append(ev)
        if self._wal is not None and persist:
            self._wal_write(self._event_record(ev))

    def wal_only(self, rec: dict) -> None:
        """Persist a CONTROL record (segment attach/detach during a
        fabric ring rebalance) to the WAL without touching the rings or
        dispatching anything: the transfer must survive a restart, but
        it is not an event — no watcher may ever see it."""
        if self._wal is not None:
            self._wal_write(rec)

    def _wal_write(self, rec: dict) -> None:
        if self.wal_codec == "bin1":
            from kubernetes_tpu.fabric import codec as binwire

            self._wal.write(binwire.frame(binwire.encode(rec)))
        else:
            self._wal.write(self._json_record(rec) + "\n")
        self._wal.flush()

    def events_after(self, kind: str, since_rv: int) -> list[JournalEvent]:
        """Every retained event of ``kind`` with rv > since_rv, oldest
        first; raises RvTooOld below the compaction watermark (ring
        wraparound or the WAL compact floor, whichever is newer). A kind
        never journaled above the floor has watermark ``compact_floor``
        (0 when no WAL rewrite ever ran): resuming at/above it is legal
        and yields nothing (there is genuinely no history to miss)."""
        wm = self.compacted_rv(kind)
        if since_rv < wm:
            raise RvTooOld(kind, since_rv, wm)
        ring = self._kinds.get(kind)
        if ring is None:
            return []
        return [e for e in ring.ring if e.rv > since_rv]

    def changes_after(self, kinds, since_rv: int) -> list[JournalEvent]:
        """Merged multi-kind resume: every retained event of ``kinds``
        with rv > since_rv in one rv-sorted list, or RvTooOld if ANY of
        the kinds cannot serve the gap (a partially-resumable answer
        would silently hide the unresumable kind's history). The drift
        sentinel's O(changes) comparer and the relay tree's downstream
        resume both read this shape."""
        evs: list[JournalEvent] = []
        for kind in kinds:
            evs.extend(self.events_after(kind, since_rv))
        evs.sort(key=lambda e: e.rv)
        return evs

    def compacted_rv(self, kind: str) -> int:
        ring = self._kinds.get(kind)
        return max(ring.compacted_rv if ring else 0, self.compact_floor)

    def stats(self) -> dict[str, dict[str, int]]:
        """{kind: {depth, compacted_rv, last_rv}} for the depth gauges."""
        return {kind: {"depth": len(r.ring),
                       "compacted_rv": self.compacted_rv(kind),
                       "last_rv": r.ring[-1].rv if r.ring else
                       self.compacted_rv(kind)}
                for kind, r in self._kinds.items()}

    # ------------- WAL replay / compaction / lifecycle -------------

    @staticmethod
    def _event_record(ev: JournalEvent) -> dict:
        """The WAL record shape, with REAL objects: the JSON writer
        wire-encodes them per line; the bin1 writer encodes the whole
        dict natively (positional structs — the replay-size win)."""
        rec = {"rv": ev.rv, "kind": ev.kind, "type": ev.type,
               "old": ev.old, "new": ev.new}
        if ev.trace is not None:
            # the commit's trace stamp persists so a restarted hub's
            # ring resumes still serve stamped events
            rec["trace"] = ev.trace
        return rec

    @staticmethod
    def _json_record(rec: dict) -> str:
        from kubernetes_tpu.utils.wire import to_wire

        return json.dumps({k: to_wire(v) for k, v in rec.items()})

    def _wal_decode(self, rec: dict, wired: bool):
        """One replayed record -> JournalEvent, control dict (yielded to
        the hub: segment attach/detach), or None (the compact record,
        consumed here). ``wired`` marks JSON records whose objects still
        need from_wire; bin1 frames decode straight to objects."""
        from kubernetes_tpu.utils.wire import from_wire

        if "compact" in rec:
            self.compact_floor = max(self.compact_floor,
                                     int(rec["compact"]))
            return None
        if "rv" not in rec:
            # a control record (segment transfer): the hub applies it
            return {k: from_wire(v) for k, v in rec.items()} \
                if wired else rec
        if wired:
            return JournalEvent(rv=rec["rv"], kind=rec["kind"],
                                type=rec["type"],
                                old=from_wire(rec.get("old")),
                                new=from_wire(rec.get("new")),
                                trace=from_wire(rec.get("trace")))
        return JournalEvent(rv=rec["rv"], kind=rec["kind"],
                            type=rec["type"], old=rec.get("old"),
                            new=rec.get("new"), trace=rec.get("trace"))

    def replay_wal(self) -> Iterator[JournalEvent]:
        """Yield the WAL's records oldest-first, lazily — one record in
        memory at a time (a long-lived WAL must not be materialized
        whole on every boot). A ``{"compact": rv}`` record (written by
        ``rewrite_wal``) raises ``compact_floor`` instead of yielding;
        control records (segment transfers) yield as dicts for the hub
        to apply. Re-seeding the rings via ``append(..., persist=False)``
        is the caller's job, alongside re-applying events to its stores.

        The on-disk FORMAT is sniffed, not assumed: a JSON line opens
        with ``{``; a bin1 frame opens with a length prefix. A WAL
        written before a codec switch replays fine and flips
        ``wal_upgrade_pending`` so the owner rewrites it.

        A torn FINAL record (unparseable, short, or missing its
        newline — the write was cut mid-append) never committed: it is
        skipped, and the byte offset of the last good record is kept so
        ``repair_wal`` can truncate the tail — appending after a
        partial record would otherwise merge two records into interior
        corruption that bricks every later boot."""
        self._wal_good_end = 0
        self._wal_size = 0
        self.wal_format = None
        if not self.wal_path or not os.path.exists(self.wal_path):
            return
        with open(self.wal_path, "rb") as f:
            first = f.read(1)
            if not first:
                return
            f.seek(0)
            self.wal_format = "json" if first == b"{" else "bin1"
            if self.wal_format == "json":
                yield from self._replay_json(f)
            else:
                yield from self._replay_bin1(f)

    def _replay_json(self, f) -> Iterator:
        pending: Optional[tuple] = None   # (text, end_offset, raw)
        pos = 0
        for raw in f:
            pos += len(raw)
            if pending is not None:
                # an interior line MUST parse: skipping one would
                # resurrect a hub with holes in its history
                ev = self._wal_decode(json.loads(pending[0]), wired=True)
                self._wal_good_end = pending[1]
                if ev is not None:
                    yield ev
            s = raw.strip()
            if s:
                pending = (s.decode("utf-8"), pos, raw)
            else:
                pending = None            # blank filler line
                self._wal_good_end = pos
        self._wal_size = pos
        if pending is not None:           # the final record
            complete = pending[2].endswith(b"\n")
            try:
                rec = json.loads(pending[0]) if complete else None
            except ValueError:
                rec = None                # torn: never committed
            if rec is not None:
                ev = self._wal_decode(rec, wired=True)
                self._wal_good_end = pending[1]
                if ev is not None:
                    yield ev

    def _replay_bin1(self, f) -> Iterator:
        from kubernetes_tpu.fabric import codec as binwire

        pos = 0
        size = os.path.getsize(self.wal_path)
        self._wal_size = size
        while True:
            hdr = f.read(4)
            if len(hdr) < 4:
                return                    # clean EOF / torn length
            n = int.from_bytes(hdr, "big")
            payload = f.read(n)
            if len(payload) < n:
                return                    # torn frame: never committed
            end = pos + 4 + n
            try:
                rec = binwire.decode(payload)
            except ValueError:
                if end >= size:
                    return                # torn final frame
                raise                     # interior corruption: loud
            pos = end
            self._wal_good_end = pos
            ev = self._wal_decode(rec, wired=False)
            if ev is not None:
                yield ev

    def repair_wal(self) -> bool:
        """Truncate the torn tail ``replay_wal`` detected (if any) so the
        next append starts on a clean line. Returns True if bytes were
        dropped. Safe with the open append handle: O_APPEND writes land
        at the post-truncation end."""
        if not self.wal_path or self._wal_good_end >= self._wal_size:
            return False
        os.truncate(self.wal_path, self._wal_good_end)
        self._wal_size = self._wal_good_end
        return True

    def rewrite_wal(self, floor_rv: int,
                    events: list[JournalEvent]) -> None:
        """Compact the WAL: atomically replace it with a compact record
        at ``floor_rv`` plus a snapshot of ``events`` (the hub's live
        objects as add-events). The FILE's history below the floor is
        gone — that is the point — so the next boot's replay raises
        ``compact_floor`` and resumes from below it relist via RvTooOld.
        The in-memory floor is deliberately NOT raised: this process's
        rings still hold the genuine history and can serve resumes the
        rewritten file no longer could."""
        if not self.wal_path:
            return
        tmp = self.wal_path + ".compact"
        if self.wal_codec == "bin1":
            from kubernetes_tpu.fabric import codec as binwire

            with open(tmp, "wb") as f:
                f.write(binwire.frame(binwire.encode(
                    {"compact": floor_rv})))
                for ev in events:
                    f.write(binwire.frame(binwire.encode(
                        self._event_record(ev))))
                f.flush()
        else:
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(json.dumps({"compact": floor_rv}) + "\n")
                for ev in events:
                    f.write(self._json_record(self._event_record(ev))
                            + "\n")
                f.flush()
        if self._wal is not None:
            self._wal.close()
        os.replace(tmp, self.wal_path)
        # the rewrite IS the in-place codec upgrade: the file is now in
        # the configured format whatever replay found
        self.wal_format = self.wal_codec
        self._wal = self._open_wal()

    def close(self) -> None:
        if self._wal is not None:
            try:
                self._wal.close()
            finally:
                self._wal = None
