"""Event journal: bounded per-kind rings + compaction + optional WAL.

The storage discipline is etcd's (reference: SharedEtcd in
test/integration/scheduler_perf/util.go): one monotonically increasing
revision space (the hub's resourceVersion counter) stamps every mutation,
the journal retains a bounded suffix of events per resource kind, and a
watch can resume from any revision that has not been compacted away.

Semantics:

* ``append(ev)`` retains ``ev`` in its kind's ring. When the ring is
  full the oldest event is dropped and that event's rv becomes the
  kind's ``compacted_rv`` — the compaction watermark.
* ``events_after(kind, since_rv)`` returns every retained event with
  ``rv > since_rv`` **iff** ``since_rv >= compacted_rv`` (the boundary
  is inclusive: a client that saw exactly the last compacted event can
  still resume). Below the watermark the gap is unrecoverable from the
  journal and :class:`RvTooOld` is raised — the caller relists.
* Revisions are global across kinds, so a kind's retained suffix is a
  COMPLETE event history for that kind above its watermark; per-kind rv
  gaps (revisions spent on other kinds) are expected and harmless.

WAL: with ``wal_path`` set, every appended event is also written as one
JSON line (wire-encoded objects) and flushed, so a restarted hub can
replay the file to rebuild both its object stores and the journal rings
(``replay_wal``, a lazy line-at-a-time iterator — the file is never
materialized whole). Writes are flushed, not fsynced — the durability
target is hub-process restart, not kernel crash. A truncated final line
(a write cut mid-append) is tolerated and ignored; corruption earlier in
the file raises, because silently skipping interior history would
resurrect a hub with holes in its state.

WAL compaction (``rewrite_wal``): appending forever would grow the file
linearly with total history, so the hub snapshots on boot when the
replayed history dwarfs the live object count — the WAL is atomically
rewritten as a ``{"compact": rv}`` record followed by one add-event per
live object. The compact record is etcd's compaction revision: replay
raises ``RvTooOld`` for any resume below it (``compact_floor``), because
the rewritten file no longer holds the update/delete history a resumer
from down there would need.
"""

from __future__ import annotations

import json
import os
from collections import deque
from dataclasses import dataclass
from typing import Iterator, Optional


class RvTooOld(Exception):
    """The requested resume point is unserviceable from the journal:
    either it predates the compaction watermark (the gap was dropped) or
    it lies BEYOND the hub's newest revision (the revision space was
    reset — a hub restarted without its WAL; "resuming" there would
    silently pin phantom state forever). The transport maps both to the
    apiserver's 410 Gone / "too old resource version"; clients relist."""

    def __init__(self, kind: str, since_rv: int, compacted_rv: int):
        if since_rv > compacted_rv:
            msg = (f"watch {kind}: since_rv {since_rv} is ahead of the "
                   f"hub's newest revision {compacted_rv} (revision "
                   f"space reset); relist required")
        else:
            msg = (f"watch {kind}: since_rv {since_rv} is older than "
                   f"the compaction watermark {compacted_rv}; relist "
                   f"required")
        super().__init__(msg)
        self.kind = kind
        self.since_rv = since_rv
        self.compacted_rv = compacted_rv


@dataclass(frozen=True)
class JournalEvent:
    """One committed mutation: rv is the global revision stamped by the
    hub; ``old``/``new`` carry the object before/after (None on the
    add/delete side respectively), exactly what a watch dispatches.
    ``trace`` (telemetry.trace.TraceContext, optional) is the commit's
    trace stamp — origin component, commit timestamp, relay hop count —
    carried with the event across the wire and relay tree; None on
    synthetic events (LIST replays, pre-telemetry WALs/peers)."""

    rv: int
    kind: str                     # watch kind, e.g. "pods"
    type: str                     # "add" | "update" | "delete"
    old: object = None
    new: object = None
    trace: object = None          # TraceContext | None


class _KindRing:
    __slots__ = ("ring", "compacted_rv")

    def __init__(self, capacity: int):
        self.ring: deque[JournalEvent] = deque(maxlen=capacity)
        self.compacted_rv = 0

    def append(self, ev: JournalEvent) -> None:
        if self.ring.maxlen and len(self.ring) == self.ring.maxlen:
            self.compacted_rv = self.ring[0].rv
        self.ring.append(ev)


class Journal:
    """Per-kind event rings sharing one revision space, plus the WAL.

    NOT self-locking: the hub appends and reads under its own lock (the
    journal is part of the same consistency domain as the stores — an
    event must land in the ring before any later revision is stamped)."""

    def __init__(self, capacity: int = 16384,
                 wal_path: Optional[str] = None):
        if capacity < 1:
            raise ValueError("journal capacity must be >= 1")
        self.capacity = capacity
        self.wal_path = wal_path
        self._kinds: dict[str, _KindRing] = {}
        # the WAL's compaction revision: resume below this is impossible
        # for EVERY kind — a rewrite discarded the update/delete history
        self.compact_floor = 0
        # replay_wal bookkeeping for repair_wal's torn-tail truncation
        self._wal_good_end = 0
        self._wal_size = 0
        self._wal = open(wal_path, "a", encoding="utf-8") \
            if wal_path else None

    # ------------- append / read -------------

    def append(self, ev: JournalEvent, persist: bool = True) -> None:
        ring = self._kinds.get(ev.kind)
        if ring is None:
            ring = self._kinds[ev.kind] = _KindRing(self.capacity)
        ring.append(ev)
        if self._wal is not None and persist:
            self._wal.write(self._wal_record(ev) + "\n")
            self._wal.flush()

    def events_after(self, kind: str, since_rv: int) -> list[JournalEvent]:
        """Every retained event of ``kind`` with rv > since_rv, oldest
        first; raises RvTooOld below the compaction watermark (ring
        wraparound or the WAL compact floor, whichever is newer). A kind
        never journaled above the floor has watermark ``compact_floor``
        (0 when no WAL rewrite ever ran): resuming at/above it is legal
        and yields nothing (there is genuinely no history to miss)."""
        wm = self.compacted_rv(kind)
        if since_rv < wm:
            raise RvTooOld(kind, since_rv, wm)
        ring = self._kinds.get(kind)
        if ring is None:
            return []
        return [e for e in ring.ring if e.rv > since_rv]

    def changes_after(self, kinds, since_rv: int) -> list[JournalEvent]:
        """Merged multi-kind resume: every retained event of ``kinds``
        with rv > since_rv in one rv-sorted list, or RvTooOld if ANY of
        the kinds cannot serve the gap (a partially-resumable answer
        would silently hide the unresumable kind's history). The drift
        sentinel's O(changes) comparer and the relay tree's downstream
        resume both read this shape."""
        evs: list[JournalEvent] = []
        for kind in kinds:
            evs.extend(self.events_after(kind, since_rv))
        evs.sort(key=lambda e: e.rv)
        return evs

    def compacted_rv(self, kind: str) -> int:
        ring = self._kinds.get(kind)
        return max(ring.compacted_rv if ring else 0, self.compact_floor)

    def stats(self) -> dict[str, dict[str, int]]:
        """{kind: {depth, compacted_rv, last_rv}} for the depth gauges."""
        return {kind: {"depth": len(r.ring),
                       "compacted_rv": self.compacted_rv(kind),
                       "last_rv": r.ring[-1].rv if r.ring else
                       self.compacted_rv(kind)}
                for kind, r in self._kinds.items()}

    # ------------- WAL replay / compaction / lifecycle -------------

    @staticmethod
    def _wal_record(ev: JournalEvent) -> str:
        from kubernetes_tpu.utils.wire import to_wire

        rec = {"rv": ev.rv, "kind": ev.kind, "type": ev.type,
               "old": to_wire(ev.old), "new": to_wire(ev.new)}
        if ev.trace is not None:
            # the commit's trace stamp persists so a restarted hub's
            # ring resumes still serve stamped events
            rec["trace"] = to_wire(ev.trace)
        return json.dumps(rec)

    def _wal_decode(self, rec: dict) -> Optional[JournalEvent]:
        from kubernetes_tpu.utils.wire import from_wire

        if "compact" in rec:
            self.compact_floor = max(self.compact_floor,
                                     int(rec["compact"]))
            return None
        return JournalEvent(rv=rec["rv"], kind=rec["kind"],
                            type=rec["type"],
                            old=from_wire(rec.get("old")),
                            new=from_wire(rec.get("new")),
                            trace=from_wire(rec.get("trace")))

    def replay_wal(self) -> Iterator[JournalEvent]:
        """Yield the WAL's events oldest-first, lazily — one line in
        memory at a time (a long-lived WAL must not be materialized
        whole on every boot). A ``{"compact": rv}`` record (written by
        ``rewrite_wal``) raises ``compact_floor`` instead of yielding.
        Re-seeding the rings via ``append(..., persist=False)`` is the
        caller's job, alongside re-applying events to its stores.

        A torn FINAL record (unparseable, or missing its newline — the
        write was cut mid-append) never committed: it is skipped, and
        the byte offset of the last good line is kept so ``repair_wal``
        can truncate the tail — appending after a partial record would
        otherwise merge two lines into interior corruption that bricks
        every later boot."""
        self._wal_good_end = 0
        self._wal_size = 0
        if not self.wal_path or not os.path.exists(self.wal_path):
            return
        with open(self.wal_path, "rb") as f:
            pending: Optional[tuple] = None   # (text, end_offset, raw)
            pos = 0
            for raw in f:
                pos += len(raw)
                if pending is not None:
                    # an interior line MUST parse: skipping one would
                    # resurrect a hub with holes in its history
                    ev = self._wal_decode(json.loads(pending[0]))
                    self._wal_good_end = pending[1]
                    if ev is not None:
                        yield ev
                s = raw.strip()
                if s:
                    pending = (s.decode("utf-8"), pos, raw)
                else:
                    pending = None            # blank filler line
                    self._wal_good_end = pos
            self._wal_size = pos
            if pending is not None:           # the final record
                complete = pending[2].endswith(b"\n")
                try:
                    rec = json.loads(pending[0]) if complete else None
                except ValueError:
                    rec = None                # torn: never committed
                if rec is not None:
                    ev = self._wal_decode(rec)
                    self._wal_good_end = pending[1]
                    if ev is not None:
                        yield ev

    def repair_wal(self) -> bool:
        """Truncate the torn tail ``replay_wal`` detected (if any) so the
        next append starts on a clean line. Returns True if bytes were
        dropped. Safe with the open append handle: O_APPEND writes land
        at the post-truncation end."""
        if not self.wal_path or self._wal_good_end >= self._wal_size:
            return False
        os.truncate(self.wal_path, self._wal_good_end)
        self._wal_size = self._wal_good_end
        return True

    def rewrite_wal(self, floor_rv: int,
                    events: list[JournalEvent]) -> None:
        """Compact the WAL: atomically replace it with a compact record
        at ``floor_rv`` plus a snapshot of ``events`` (the hub's live
        objects as add-events). The FILE's history below the floor is
        gone — that is the point — so the next boot's replay raises
        ``compact_floor`` and resumes from below it relist via RvTooOld.
        The in-memory floor is deliberately NOT raised: this process's
        rings still hold the genuine history and can serve resumes the
        rewritten file no longer could."""
        if not self.wal_path:
            return
        tmp = self.wal_path + ".compact"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps({"compact": floor_rv}) + "\n")
            for ev in events:
                f.write(self._wal_record(ev) + "\n")
            f.flush()
        if self._wal is not None:
            self._wal.close()
        os.replace(tmp, self.wal_path)
        self._wal = open(self.wal_path, "a", encoding="utf-8")

    def close(self) -> None:
        if self._wal is not None:
            try:
                self._wal.close()
            finally:
                self._wal = None
