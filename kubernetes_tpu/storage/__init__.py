"""L0 storage for the hub: event journal, compaction, WAL, watch-resume.

The etcd-analog layer under the in-memory hub (SURVEY §1 L0): every
mutation becomes a revision-stamped :class:`JournalEvent` appended to a
bounded per-kind ring (:class:`Journal`), so a watcher that lost its
stream can resume from its last-seen resourceVersion instead of
re-listing the world — the revision-resumed watch that keeps reconnects
cheap at Daemonset scale. When the requested gap has been compacted away,
:class:`RvTooOld` is the typed "410 Gone" the transport maps onto the
wire and the client reflector answers with a full relist.
"""

from kubernetes_tpu.storage.journal import (  # noqa: F401
    Journal,
    JournalEvent,
    RvTooOld,
)
