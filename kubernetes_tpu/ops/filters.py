"""Filter extension point as vmapped device predicates.

Each function evaluates one plugin's Filter for ONE pod against ALL nodes at
once — the tensorized replacement of the reference's per-node goroutine loop
``findNodesThatPassFilters`` (schedule_one.go:583-650). Returns [N] boolean
accept masks plus, where relevant, an "unresolvable" mask (the
UnschedulableAndUnresolvable distinction preemption relies on,
framework/types.go NodeToStatus).

Reference algorithms:
- NodeName:           plugins/nodename/node_name.go (spec.nodeName == node)
- NodeUnschedulable:  plugins/nodeunschedulable (spec.unschedulable unless tolerated)
- TaintToleration:    plugins/tainttoleration/taint_toleration.go:111
- NodeAffinity:       plugins/nodeaffinity/node_affinity.go:206-228
- NodePorts:          plugins/nodeports (HostPortInfo conflict, types.go:1291)
- NodeResourcesFit:   plugins/noderesources/fit.go:509-592 fitsRequest
"""

from __future__ import annotations

import jax.numpy as jnp

from kubernetes_tpu.ops import common as C
from kubernetes_tpu.ops.features import (
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
    ClusterTensors,
    PodFeatures,
)
from kubernetes_tpu.utils.interner import NONE


def node_name(ct: ClusterTensors, pod: PodFeatures) -> jnp.ndarray:
    """spec.nodeName pin; unset matches every node."""
    return (pod.node_name_id == NONE) | (ct.node_name_id == pod.node_name_id)


def node_unschedulable(ct: ClusterTensors, pod: PodFeatures,
                       unschedulable_taint_key: jnp.ndarray) -> jnp.ndarray:
    """node.spec.unschedulable rejected unless the pod tolerates the
    node.kubernetes.io/unschedulable:NoSchedule taint."""
    n = ct.unschedulable.shape[0]
    key = jnp.broadcast_to(unschedulable_taint_key, (n, 1))
    val = jnp.broadcast_to(jnp.int32(0), (n, 1))  # empty-string value id 0
    eff = jnp.broadcast_to(jnp.int32(EFFECT_NO_SCHEDULE), (n, 1))
    tolerated = C.tolerations_tolerate(
        pod.tol_valid, pod.tol_key, pod.tol_op, pod.tol_val, pod.tol_effect,
        key, val, eff)[:, 0]
    return ~ct.unschedulable | tolerated


def taint_toleration(ct: ClusterTensors, pod: PodFeatures) -> jnp.ndarray:
    """Any untolerated NoSchedule/NoExecute taint rejects the node
    (UnschedulableAndUnresolvable in the reference)."""
    tolerated = C.tolerations_tolerate(
        pod.tol_valid, pod.tol_key, pod.tol_op, pod.tol_val, pod.tol_effect,
        ct.taint_keys, ct.taint_vals, ct.taint_effects)  # [N, T]
    hard = ((ct.taint_effects == EFFECT_NO_SCHEDULE)
            | (ct.taint_effects == EFFECT_NO_EXECUTE))
    untolerated = hard & ~tolerated & (ct.taint_keys != NONE)
    return ~jnp.any(untolerated, axis=-1)


def _take_cols(table: jnp.ndarray, cols: jnp.ndarray,
               fill) -> jnp.ndarray:
    """table: [N, K]; cols: [...] i32 column indices (NONE = key unseen
    cluster-wide). Returns [N, *cols.shape] with `fill` where col is NONE.

    Node labels are columnized (one dense value column per distinct label
    key), so selector evaluation is a cheap gather over K ~ 32 columns
    instead of a [N, ..., L] pair scan — the hot-path win that makes
    affinity kernels bandwidth-bound on [N, T, E] rather than [N, T, E, L].
    """
    k = table.shape[1]
    safe = jnp.clip(cols, 0, k - 1)
    out = jnp.take(table, safe.reshape(-1), axis=1)
    out = out.reshape((table.shape[0],) + cols.shape)
    return jnp.where(cols[None] >= 0, out, fill)


def _selector_match(ct: ClusterTensors, cols, ops, is_field, vals, nums):
    """match[N, *cols.shape] for node-selector expressions.

    cols/ops/is_field/nums: [T, E]; vals: [T, E, V].
    """
    val = _take_cols(ct.label_col_vals, cols, NONE)       # [N, T, E]
    present = val != NONE

    # matchFields: the only supported key is metadata.name -> node name id
    name_val = ct.node_name_id.reshape((-1,) + (1,) * cols.ndim)  # [N, 1, 1]
    name_val = jnp.broadcast_to(name_val, val.shape)              # [N, T, E]
    val = jnp.where(is_field[None], name_val, val)
    present = jnp.where(is_field[None], True, present)

    in_vals = C.isin(val, vals[None])                    # [N, T, E]
    # Gt/Lt: numeric label value from the packed per-column table. matchFields
    # (metadata.name) Gt/Lt is not supported (invalid per reference
    # validation: matchFields only allows metadata.name with In/NotIn).
    num_val = _take_cols(ct.label_col_nums, cols, jnp.nan)
    num_ok = (~jnp.isnan(num_val) & ~jnp.isnan(nums[None]) & ~is_field[None])
    gt = num_ok & (num_val > nums[None])
    lt = num_ok & (num_val < nums[None])

    op = ops[None]
    match = jnp.where(op == OP_IN, present & in_vals,
            jnp.where(op == OP_NOT_IN, ~(present & in_vals),
            jnp.where(op == OP_EXISTS, present,
            jnp.where(op == OP_DOES_NOT_EXIST, ~present,
            jnp.where(op == OP_GT, present & gt,
            jnp.where(op == OP_LT, present & lt, False))))))
    return match  # [N, *cols.shape]


def node_affinity(ct: ClusterTensors, pod: PodFeatures,
                  full: bool = True) -> jnp.ndarray:
    """spec.nodeSelector (exact pairs, ANDed) AND required node affinity
    (OR over terms, AND within term).

    ``full=False`` (the "nodeaffinity_pin" launch feature) compiles ONLY
    the single-node pin compare: every affinity-bearing pod in the batch
    reduced to a matchFields metadata.name In [v] term (the daemonset
    shape), so the [N, T, E, V] selector kernels never materialize."""
    pin_ok = (pod.aff_pin == NONE) | (ct.node_name_id == pod.aff_pin)  # [N]
    if not full:
        return pin_ok
    # nodeSelector pairs: node's value in the pair's label column must equal
    # the pair's value (col NONE -> key on no node -> never matches)
    node_val = _take_cols(ct.label_col_vals, pod.nodesel_cols, NONE)  # [N, PL]
    used_pair = pod.nodesel_vals != NONE
    hit = node_val == pod.nodesel_vals[None]
    sel_ok = jnp.all(hit | ~used_pair[None], axis=-1)     # [N]

    match = _selector_match(ct, pod.sel_col, pod.sel_op, pod.sel_is_field,
                            pod.sel_vals, pod.sel_num)  # [N, T, E]
    used = pod.sel_op != NONE  # [T, E]
    term_ok = jnp.all(match | ~used[None], axis=-1)  # [N, T]
    term_nonempty = jnp.any(used, axis=-1)  # [T]
    term_ok = term_ok & term_nonempty[None] & pod.sel_term_valid[None]
    any_term = jnp.any(pod.sel_term_valid)
    affinity_ok = jnp.where(any_term, jnp.any(term_ok, axis=-1), True)
    return sel_ok & affinity_ok & pin_ok


def node_ports(ct: ClusterTensors, pod: PodFeatures,
               wildcard_ip: jnp.ndarray) -> jnp.ndarray:
    """No requested host port may conflict with an occupied one
    (types.go:1291 CheckConflict: wildcard IP clashes with any IP)."""
    # pod ports [HP] vs node ports [N, P]
    pp = pod.hp_port[None, None, :]       # [1, 1, HP]
    pproto = pod.hp_proto[None, None, :]
    pip = pod.hp_ip[None, None, :]
    np_ = ct.port_nums[..., None]          # [N, P, 1]
    nproto = ct.port_protos[..., None]
    nip = ct.port_ips[..., None]
    same = (pp != NONE) & (np_ == pp) & (nproto == pproto)
    ip_clash = (nip == pip) | (nip == wildcard_ip) | (pip == wildcard_ip)
    conflict = same & ip_clash
    return ~jnp.any(conflict, axis=(1, 2))


def pod_pair_port_conflict(pods: PodFeatures,
                           wildcard_ip: jnp.ndarray) -> jnp.ndarray:
    """[B, B] bool: would pods i and j conflict on host ports if co-located?
    Wildcard-IP semantics as types.go:1291 CheckConflict.

    Used by the batched commit scan to preserve as-if-serial NodePorts
    semantics inside one launch: pod j may not land on a node where an
    earlier batch pod i with a conflicting hostPort was just committed."""
    pp = pods.hp_port
    a_port = pp[:, None, :, None]
    b_port = pp[None, :, None, :]
    a_proto = pods.hp_proto[:, None, :, None]
    b_proto = pods.hp_proto[None, :, None, :]
    a_ip = pods.hp_ip[:, None, :, None]
    b_ip = pods.hp_ip[None, :, None, :]
    same = (a_port != NONE) & (a_port == b_port) & (a_proto == b_proto)
    ip_clash = (a_ip == b_ip) | (a_ip == wildcard_ip) | (b_ip == wildcard_ip)
    return jnp.any(same & ip_clash, axis=(2, 3))


def resources_fit(ct: ClusterTensors, pod: PodFeatures
                  ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """request <= free per resource column (fit.go:509-592).

    Returns (ok [N], unresolvable [N]) — unresolvable when the request
    exceeds the node's *allocatable* (no amount of preemption helps).
    """
    req = pod.req[None]                      # [1, R]
    ok = jnp.all(req <= ct.free, axis=-1)
    unresolvable = jnp.any(req > ct.allocatable, axis=-1)
    return ok, unresolvable
