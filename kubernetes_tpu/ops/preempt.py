"""Preemption dry-run as a device sweep over victim-prefix removals.

The reference dry-runs preemption per candidate node: remove all
lower-priority pods, re-run filters, then reprieve victims highest-priority
first (preemption/preemption.go:682 DryRunPreemption,
defaultpreemption/default_preemption.go:219 SelectVictimsOnNode). The
TPU-native formulation evaluates EVERY node's every victim-prefix in one
launch: the host supplies, per node, the priority-ascending victims'
cumulative freed-resource sums ``vic_cumsum [N, K+1, R]`` (k=0 means no
eviction), and the kernel returns the minimal k per node that makes the pod
fit alongside the commit-invariant static filters. Because victims are
removed in ascending-importance order, the minimal resource-feasible prefix
is exactly the reprieve loop's fixed point for resource-driven preemption.

Topology effects of victim removal (an anti-affinity term owned by a victim)
are not modeled in the sweep: the preemptor is re-scheduled through the full
pipeline after its victims exit, so an over-optimistic candidate costs one
extra cycle, never a wrong placement.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from kubernetes_tpu.models.pipeline import (
    ALL_FEATURES,
    FILTER_PLUGINS,
    NUM_FILTER_PLUGINS,
    static_filters,
)
from kubernetes_tpu.ops.features import (
    Capacities,
    ClusterBlobs,
    PodBlobs,
    unpack_cluster,
    unpack_pods,
)
from kubernetes_tpu.utils.interner import NONE


def preempt_sweep(cblobs: ClusterBlobs, pblobs: PodBlobs,
                  wk: dict[str, jnp.ndarray], vic_cumsum: jnp.ndarray,
                  caps: Capacities,
                  enabled_filters: tuple[bool, ...] | None = None
                  ) -> jnp.ndarray:
    """[N] i32: minimal victim count k (1..K) making the pod fit on each
    node; NONE where preemption cannot help (static filter fails, request
    exceeds allocatable, or even evicting every victim is not enough).

    pblobs carries ONE pod (batch axis 1); vic_cumsum [N, K+1, R] f32 is the
    cumulative freed request of the first k victims (k=0 row is zero)."""
    if enabled_filters is None:
        enabled_filters = (True,) * NUM_FILTER_PLUGINS
    ct = unpack_cluster(cblobs, caps)
    pod = jax.tree_util.tree_map(lambda x: x[0], unpack_pods(pblobs, caps))

    # the sweep runs off the hot path: evaluate every static filter (no
    # workload-activity DCE)
    masks = static_filters(ct, pod, wk, enabled_filters,
                           frozenset(ALL_FEATURES))            # [5, N]
    static_ok = jnp.all(masks, axis=0) & ct.node_valid
    unresolvable = jnp.any(pod.req[None] > ct.allocatable, axis=-1)

    # fit after evicting the first k victims, against the same effective
    # free as the pipeline's fit check (nominated reservations subtracted,
    # the pod's own nomination handed back): [N, K+1]
    own = (jnp.arange(ct.free.shape[0]) == pod.nominated_row)
    base = (ct.free - ct.nominated_req
            + jnp.where(own[:, None], pod.req[None], 0.0))
    eff = base[:, None, :] + vic_cumsum
    fit = jnp.all(pod.req[None, None] <= eff, axis=-1)
    # minimal k with a fit (k=0 would mean it already fits — the caller only
    # sweeps pods the pipeline rejected, but guard anyway)
    kmin = jnp.argmax(fit, axis=1).astype(jnp.int32)           # first True
    any_fit = jnp.any(fit, axis=1)
    ok = static_ok & ~unresolvable & any_fit
    return jnp.where(ok, kmin, jnp.int32(NONE))


@partial(jax.jit, static_argnames=("caps", "enabled_filters"))
def preempt_sweep_jit(cblobs, pblobs, wk, vic_cumsum, caps,
                      enabled_filters=None):
    return preempt_sweep(cblobs, pblobs, wk, vic_cumsum, caps,
                         enabled_filters)
