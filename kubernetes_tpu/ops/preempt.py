"""Preemption dry-run as a device sweep over victim-prefix removals.

The reference dry-runs preemption per candidate node: remove all
lower-priority pods, re-run filters, then reprieve victims highest-priority
first (preemption/preemption.go:682 DryRunPreemption,
defaultpreemption/default_preemption.go:219 SelectVictimsOnNode). The
TPU-native formulation evaluates EVERY node's every victim-prefix in one
launch: the host supplies, per node, the priority-ascending victims'
cumulative freed-resource sums ``vic_cumsum [N, K+1, R]`` (k=0 means no
eviction), and the kernel returns the minimal k per node that makes the pod
fit alongside the commit-invariant static filters. Because victims are
removed in ascending-importance order, the minimal resource-feasible prefix
is exactly the reprieve loop's fixed point for resource-driven preemption.

Topology effects of victim removal (an anti-affinity term owned by a victim)
are not modeled in the sweep: the preemptor is re-scheduled through the full
pipeline after its victims exit, so an over-optimistic candidate costs one
extra cycle, never a wrong placement.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from kubernetes_tpu.models.pipeline import (
    ALL_FEATURES,
    FILTER_PLUGINS,
    NUM_FILTER_PLUGINS,
    static_filters,
)
from kubernetes_tpu.ops.features import (
    Capacities,
    ClusterBlobs,
    PodBlobs,
    unpack_cluster,
    unpack_pods,
)
from kubernetes_tpu.utils.interner import NONE


def preempt_sweep(cblobs: ClusterBlobs, pblobs: PodBlobs,
                  wk: dict[str, jnp.ndarray], vic_cumsum: jnp.ndarray,
                  vic_cols: jnp.ndarray, caps: Capacities,
                  enabled_filters: tuple[bool, ...] | None = None,
                  free: jnp.ndarray | None = None) -> jnp.ndarray:
    """[P, N] i32: minimal victim count k (1..K) making each pod fit on
    each node; NONE where preemption cannot help (static filter fails,
    request exceeds allocatable, or even evicting every victim is not
    enough). A whole burst of preemptors sweeps in ONE launch.

    pblobs carries P pods. The freed-resource cumsum is COLUMN-SUBSET:
    ``vic_cols [C] i32`` names the resource columns any victim actually
    frees, ``vic_cumsum [N, K+1, C]`` is their cumulative freed request
    over the first k victims (k=0 row zero). Columns nobody frees are
    k-independent, so the plain fit-vs-base check covers them; this cuts
    the host->device cumsum transfer ~R/C-fold (74 -> ~4 columns on the
    PreemptionAsync shape — ~20MB to ~1MB on the tunnel). Padding entries
    of vic_cols may alias column 0: their cumsum rows are +BIG so they
    never constrain.

    ``free`` overrides the snapshot free matrix (ct.free) as the fit
    baseline: the pipelined scheduler passes its live device-resident
    chain here so a preemptor's sweep sees waves still in flight —
    without it, the sweep would nominate slots an uncommitted wave has
    already claimed and the verification launch would bounce the plan a
    cycle later."""
    if enabled_filters is None:
        enabled_filters = (True,) * NUM_FILTER_PLUGINS
    ct = unpack_cluster(cblobs, caps)
    pods = unpack_pods(pblobs, caps)       # [P, ...] — BATCHED preemptors
    # columns handled by the k-dependent check (padding double-sets col 0;
    # the real col-0 entry still constrains through the subset check)
    col_freed = jnp.zeros((ct.free.shape[1],), bool).at[vic_cols].set(True)

    def per_pod(pod):
        # the sweep runs off the hot path: evaluate every static filter
        # (no workload-activity DCE)
        masks = static_filters(ct, pod, wk, enabled_filters,
                               frozenset(ALL_FEATURES))        # [5, N]
        static_ok = jnp.all(masks, axis=0) & ct.node_valid & pod.valid
        unresolvable = jnp.any(pod.req[None] > ct.allocatable, axis=-1)
        # fit after evicting the first k victims, against the same
        # effective free as the pipeline's fit check (nominated
        # reservations subtracted, own nomination handed back): [N, K+1]
        own = (jnp.arange(ct.free.shape[0]) == pod.nominated_row)
        base_free = ct.free if free is None else free
        base = (base_free - ct.nominated_req
                + jnp.where(own[:, None], pod.req[None], 0.0))
        fit0 = pod.req[None] <= base                           # [N, R]
        ok_rest = jnp.all(fit0 | col_freed[None], axis=-1)     # [N]
        base_c = base[:, vic_cols]                             # [N, C]
        req_c = pod.req[vic_cols]                              # [C]
        eff = base_c[:, None, :] + vic_cumsum                  # [N, K+1, C]
        fit = ok_rest[:, None] & jnp.all(req_c[None, None] <= eff, axis=-1)
        # minimal k with a fit (k=0 would mean it already fits — the
        # caller only sweeps rejected pods, but guard anyway)
        kmin = jnp.argmax(fit, axis=1).astype(jnp.int32)       # first True
        any_fit = jnp.any(fit, axis=1)
        ok = static_ok & ~unresolvable & any_fit
        return jnp.where(ok, kmin, jnp.int32(NONE))

    return jax.vmap(per_pod)(pods)         # [P, N]


@partial(jax.jit, static_argnames=("caps", "enabled_filters"))
def preempt_sweep_jit(cblobs, pblobs, wk, vic_cumsum, vic_cols, caps,
                      enabled_filters=None, free=None):
    return preempt_sweep(cblobs, pblobs, wk, vic_cumsum, vic_cols, caps,
                         enabled_filters, free)


def preempt_feasible(cblobs: ClusterBlobs, pblobs: PodBlobs,
                     wk: dict[str, jnp.ndarray], caps: Capacities,
                     table_valid: jnp.ndarray, free: jnp.ndarray,
                     enable_topology: bool = True, d_cap: int | None = None,
                     enabled_filters: tuple[bool, ...] | None = None
                     ) -> jnp.ndarray:
    """[N] bool: does ONE pod pass the FULL filter set on each node, with
    ``table_valid`` masking out victim pods and ``free`` overriding the
    per-node free resources?

    This is the exact dry-run the reference runs per candidate node
    (defaultpreemption SelectVictimsOnNode :219: remove victims, re-run
    RunFilterPluginsWithNominatedPods) — evaluated for EVERY node in one
    launch. The host encodes an eviction set as (table mask, freed
    resources); topology filters (anti-affinity, required affinity, hard
    spread) see the post-eviction world because every count/presence map is
    built from the masked table.
    """
    import dataclasses as _dc

    from kubernetes_tpu.ops import topology as T

    if enabled_filters is None:
        enabled_filters = (True,) * NUM_FILTER_PLUGINS
    if d_cap is None:
        d_cap = caps.domain_cap
    ct = unpack_cluster(cblobs, caps)
    ct = _dc.replace(ct, pod_valid=ct.pod_valid & table_valid)
    pod = jax.tree_util.tree_map(lambda x: x[0], unpack_pods(pblobs, caps))
    valid = ct.node_valid
    masks = static_filters(ct, pod, wk, enabled_filters,
                           frozenset(ALL_FEATURES))
    ok = jnp.all(masks, axis=0) & valid & pod.valid
    # resource fit against the evicted free state
    if enabled_filters[FILTER_PLUGINS.index("NodeResourcesFit")]:
        own = jnp.arange(free.shape[0]) == pod.nominated_row
        eff = free - ct.nominated_req + jnp.where(own[:, None],
                                                  pod.req[None], 0.0)
        ok = ok & jnp.all(pod.req[None] <= eff, axis=-1)
    if not enable_topology:
        return ok
    tds = T.slot_topo_dom(ct)
    taint_ok, nodeaff_ok = masks[2], masks[3]
    spread_on = enabled_filters[FILTER_PLUGINS.index("PodTopologySpread")]
    ipa_on = enabled_filters[FILTER_PLUGINS.index("InterPodAffinity")]
    if spread_on:
        used_c = pod.tsc_tk != jnp.int32(-1)
        used_hard = used_c & pod.tsc_hard
        el_hard = T.spread_eligible(ct, pod, nodeaff_ok, taint_ok, used_hard)
        cnt = T.spread_cnt(ct, pod, tds, el_hard, d_cap)        # [C, D]
        exists_hard = T.spread_exists(ct, pod, el_hard, d_cap)
        min_cnt = jnp.min(jnp.where(exists_hard, cnt, jnp.inf), axis=1)
        min_cnt = jnp.where(jnp.isfinite(min_cnt), min_cnt, 0.0)
        num_domains = jnp.sum(exists_hard, axis=1)
        min_cnt = jnp.where((pod.tsc_min_domains > 0)
                            & (num_domains < pod.tsc_min_domains),
                            0.0, min_cnt)
        node_dom = T.take_cols(ct.topo_dom, pod.tsc_tk, jnp.int32(-1))
        self_m = T._tsc_self_match(pod).astype(jnp.float32)
        match_num = T.gather_rows(cnt, node_dom)                # [N, C]
        skew = match_num + self_m[None] - min_cnt[None]
        ok_c = (node_dom != jnp.int32(-1)) \
            & (skew <= pod.tsc_max_skew[None])
        ok = ok & jnp.all(ok_c | ~used_hard[None], axis=1)
    if ipa_on:
        anti_ok, present, any_match = T.inter_pod_affinity_static(
            ct, pod, tds, d_cap)
        term_used = pod.aff_tk != NONE
        node_dom3 = T.take_cols(ct.topo_dom, pod.aff_tk, NONE)
        has_lbl = node_dom3 != NONE
        term_ok = has_lbl & T.gather_rows(present, node_dom3)
        pods_exist = jnp.all(term_ok | ~term_used[None], axis=1)
        all_lbl = jnp.all(has_lbl | ~term_used[None], axis=1)
        self_ok = pod.aff_self_match & ~any_match & all_lbl
        aff_ok = jnp.where(jnp.any(term_used), pods_exist | self_ok, True)
        ok = ok & anti_ok & aff_ok
    return ok


@partial(jax.jit, static_argnames=("caps", "enable_topology", "d_cap",
                                   "enabled_filters"))
def preempt_feasible_jit(cblobs, pblobs, wk, caps, table_valid, free,
                         enable_topology=True, d_cap=None,
                         enabled_filters=None):
    return preempt_feasible(cblobs, pblobs, wk, caps, table_valid, free,
                            enable_topology, d_cap, enabled_filters)
