"""Learned scoring kernel: a small MLP over per-node score features.

The device half of the learned-scoring subsystem (kubernetes_tpu.learn):
scoring already runs as vmapped tensors inside the fused Filter/Score
launch, so the learned scorer is one more vmapped function in the same
XLA program — zero extra H2D, zero extra launches. Following "Learning
to Score" (tune the score COMBINATION instead of hand-set weights), the
feature vector is the per-node signals the hand-tuned weighted sum
already computes, so the MLP's input is free: the pipeline hands the
exact arrays it just materialized for the hand-tuned aggregate.

Feature layout (FEATURE_VERSION 1), one row per node, every entry
in [0, 1]:

    0 frac_cpu        cpu utilization fraction including this pod
    1 frac_mem        memory utilization fraction including this pod
    2 fit             NodeResourcesFit strategy score / 100
    3 balance         balanced-allocation score / 100
    4 taint           normalized taint-toleration score / 100
    5 node_affinity   normalized preferred-node-affinity score / 100
    6 image_locality  image-locality score / 100

The scorer's output is clipped to the same [0, 100] range every other
normalized plugin score lives in, then weighted into the aggregate by
``ScoreWeights.learned`` exactly like a hand-tuned term. A NaN anywhere
in the params propagates through the clip into the aggregate, where the
launch's guard reduction (pipeline._guard_reduction) trips and the
scheduler degrades that batch down the device→host fallback ladder to
hand-tuned weights — a bad checkpoint costs one batch, never a
placement.

Params are a plain pytree — ``((W0, b0), (W1, b1), ...)`` with relu
between layers and a scalar output — so swapping checkpoints of the
same architecture never recompiles (only the leaf VALUES change); a
different layer stack is a different jit signature and compiles once.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

LEARNED_FEATURES = (
    "frac_cpu",
    "frac_mem",
    "fit",
    "balance",
    "taint",
    "node_affinity",
    "image_locality",
    # v3 (ISSUE 15): the topology score terms join the feature rows —
    # normalized PodTopologySpread and InterPodAffinity (preferred +
    # hard-weight) scores, now available on BOTH commit paths since the
    # soft-topology auction computes them fused. Zero for pods/launches
    # without topology work (the learn loop sees real signal only where
    # the scheduler did).
    "spread",
    "ipa",
)
NUM_FEATURES = len(LEARNED_FEATURES)

# bumped whenever the feature layout changes; checkpoints record the
# version they were trained against and the loader rejects a mismatch
# (a scorer trained on other features would be garbage, not degraded).
# 3 = the topology/IPA columns (aligned with trace-export v3, whose
# placement rows carry these features).
FEATURE_VERSION = 3

MAX_SCORE = 100.0

# Params = tuple[tuple[Array, Array], ...]: ((W, b), ...) layer stack.


def feature_rows(frac: jnp.ndarray, fit: jnp.ndarray, bal: jnp.ndarray,
                 taint: jnp.ndarray, aff: jnp.ndarray,
                 img: jnp.ndarray, spread: jnp.ndarray | None = None,
                 ipa: jnp.ndarray | None = None) -> jnp.ndarray:
    """[N, NUM_FEATURES] feature matrix from the per-node arrays the
    pipeline already computed for the hand-tuned aggregate. ``spread``/
    ``ipa`` default to zero columns (no-topology launches)."""
    zeros = jnp.zeros_like(fit)
    spread = zeros if spread is None else spread
    ipa = zeros if ipa is None else ipa
    return jnp.stack(
        [frac[..., 0], frac[..., 1], fit / MAX_SCORE, bal / MAX_SCORE,
         taint / MAX_SCORE, aff / MAX_SCORE, img / MAX_SCORE,
         spread / MAX_SCORE, ipa / MAX_SCORE], axis=-1)


def mlp_apply(params, feats: jnp.ndarray) -> jnp.ndarray:
    """[..., F] -> [...]: the MLP forward pass (relu hidden layers,
    linear scalar head)."""
    x = feats
    last = len(params) - 1
    for i, (w, b) in enumerate(params):
        x = x @ w + b
        if i < last:
            x = jax.nn.relu(x)
    return x[..., 0]


def learned_term(params, frac: jnp.ndarray, fit: jnp.ndarray,
                 bal: jnp.ndarray, taint: jnp.ndarray, aff: jnp.ndarray,
                 img: jnp.ndarray, spread: jnp.ndarray | None = None,
                 ipa: jnp.ndarray | None = None) -> jnp.ndarray:
    """[N] learned score in [0, 100] — NaN params stay NaN through the
    clip so the launch guard owns the containment."""
    raw = mlp_apply(params, feature_rows(frac, fit, bal, taint, aff, img,
                                         spread, ipa))
    return jnp.clip(raw, 0.0, MAX_SCORE)


def hand_weight_vector():
    """The default hand-tuned score weights aligned to LEARNED_FEATURES
    order (the frac features carry weight 0) — derived from the live
    pipeline.default_weights, so the learn/ trainer's behavior-cloning
    scale and the identity-init fixture can never drift from the
    weights the scheduler actually runs. Lazy import: pipeline imports
    this module."""
    import numpy as np

    from kubernetes_tpu.models.pipeline import default_weights

    w = default_weights()
    return np.array([0.0, 0.0, float(w.resources_fit),
                     float(w.balanced_allocation),
                     float(w.taint_toleration),
                     float(w.node_affinity),
                     float(w.image_locality),
                     float(w.pod_topology_spread),
                     float(w.inter_pod_affinity)], np.float32)


def feature_row_at(row, frac: jnp.ndarray, fit: jnp.ndarray,
                   bal: jnp.ndarray, taint: jnp.ndarray, aff: jnp.ndarray,
                   img: jnp.ndarray, spread: jnp.ndarray | None = None,
                   ipa: jnp.ndarray | None = None) -> jnp.ndarray:
    """[NUM_FEATURES] feature vector of ONE node row (the commit scan
    exports the chosen node's features for the replay dataset)."""
    sp = jnp.float32(0.0) if spread is None else spread[row]
    ip = jnp.float32(0.0) if ipa is None else ipa[row]
    return jnp.stack(
        [frac[row, 0], frac[row, 1], fit[row] / MAX_SCORE,
         bal[row] / MAX_SCORE, taint[row] / MAX_SCORE,
         aff[row] / MAX_SCORE, img[row] / MAX_SCORE,
         sp / MAX_SCORE, ip / MAX_SCORE])
