"""Device kernels for gang admission: the cheap "can min_member possibly
fit" bound the GangScheduling PreFilter runs before any member burns a
scheduling cycle.

``gang_capacity`` computes, in one reduction over the mirror's free
matrix, an UPPER bound on how many identical members of the gang the
cluster can still hold: per node, the member count is the floor of
free/request minimized over the resource columns the request actually
uses (columns with zero request don't bind); the cluster capacity is the
sum over nodes. A gang whose ``min_member`` exceeds this bound cannot be
placed by ANY assignment — rejecting it here avoids reserving (and then
rolling back) members that are doomed, the device-side analog of
coscheduling's PreFilter quorum check.

The bound is optimistic on purpose (it ignores topology constraints,
taints, and per-node pod-count interactions with OTHER pods committed in
the same batch): a false "fits" costs one normal scheduling attempt; a
false "cannot fit" would wrongly starve a gang, so only the provable
case rejects.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=())
def _capacity(free: jnp.ndarray, req: jnp.ndarray) -> jnp.ndarray:
    """[N, R] free x [R] request -> scalar i32 member-capacity bound."""
    active = req > 0.0
    safe_req = jnp.where(active, req, 1.0)
    per_col = jnp.floor(jnp.maximum(free, 0.0) / safe_req)
    per_col = jnp.where(active[None, :], per_col, jnp.float32(2 ** 30))
    per_node = jnp.min(per_col, axis=1)
    # a request with NO active columns fits anywhere: cap at a big count
    any_active = jnp.any(active)
    total = jnp.sum(jnp.clip(per_node, 0.0, 2.0 ** 30))
    return jnp.where(any_active, total,
                     jnp.float32(2 ** 30)).astype(jnp.int32)


def gang_capacity(free, req) -> int:
    """Cluster-wide bound on how many ``req``-shaped members still fit
    (device reduction; one small D2H scalar pull)."""
    return int(_capacity(jnp.asarray(free, jnp.float32),
                         jnp.asarray(req, jnp.float32)))
