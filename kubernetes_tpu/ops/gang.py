"""Device kernels for gang admission: the fused gang-batch packer that
places ALL members of a PodGroup in one launch, plus the async capacity
bound the host-fallback PreFilter still consults.

``pack_gangs`` is the tentpole kernel (ISSUE 12): the batch's gang units
are packed as one ``[G, N]`` problem over the cluster mirror — one
representative pod row per gang (members of a device-packable gang are
request-identical by construction; heterogeneous gangs stay on the host
Permit path) and a ``need`` count of members to place. Per gang:

1. **Member capacity per node** — the static Filter masks (the same five
   commit-invariant plugins the main pipeline runs, via
   ``pipeline.static_filters``) AND a floored free/request division give
   ``cap_n`` = how many members node n can still hold, with nominated
   reservations subtracted exactly like the batched fit predicate.
2. **All-or-nothing feasibility reduction** — ``sum(cap_n) >= need`` is
   the gang's device verdict: every member places or none do. This
   replaces the per-member Permit round-trips with ONE verdict + one
   host commit, and it subsumes the old ``gang_capacity`` upper bound
   (``cap`` in the result is that bound, tightened by the static
   filters, fed back into the PreFilter memo so the fallback path never
   re-derives it).
3. **Topology-close packing** — nodes are filled in domain-major order
   under the packing topology key (zone; ``ct.topo_dom`` is the same
   compact domain table the spread/affinity kernels use): domains are
   ranked by member capacity DESCENDING (the packing score — the
   domain that can co-locate the most members wins), and within a
   domain the densest nodes fill first. ``spans`` reports how many
   domains the placement touched — the co-location number the
   GangTopologyPacking bench asserts on. Kant's whole-job
   topology-aware placement (PAPERS.md), expressed as a sort key
   instead of a per-member score term.

Gangs commit SEQUENTIALLY inside the launch (a lax.scan over gang rows):
gang g+1 sees g's placements in the carried free/nzr state, so one
launch admits a whole wave of gangs as-if-serial. The post-batch
``free``/``nzr`` chain to the next launch exactly like
``BatchResult.free``/``.nzr``.

``gang_capacity_device`` keeps the old optimistic bound for gangs the
packer cannot express (topology terms, heterogeneous members, claims) —
but ASYNC: it returns the device scalar, and the scheduler folds the
pull into its existing one-per-cycle ``device_get`` instead of the old
per-(sync, group) blocking pull.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

# big sentinel member-count for request columns/pods that bind nothing
_UNBOUNDED = 2.0 ** 20

# the composite node sort key packs (domain rank, density) into one i32:
# rank * _KEY_STRIDE + (_KEY_STRIDE - 1 - clipped capacity)
_KEY_STRIDE = 4096


@jax.tree_util.register_dataclass
@dataclass
class GangPackResult:
    """Per-gang outcome of one fused packing launch."""

    ok: jax.Array        # [G] bool: all-or-nothing verdict
    alloc: jax.Array     # [G, N] i32: members placed per node (0s when !ok)
    cap: jax.Array       # [G] i32: member-capacity bound over feasible nodes
    spans: jax.Array     # [G] i32: topology domains the placement touches
    free: jax.Array      # [N, R] f32: post-batch free resources (chains)
    nzr: jax.Array       # [N, 2] f32: post-batch nonzero-requested
    guard: jax.Array     # [] i32: NaN poison detector (bit 1, like pipeline)


def pack_gangs(cblobs, gblobs, wk, caps, need, tk,
               d_cap: int = 8,
               enabled_filters: tuple[bool, ...] | None = None,
               active: tuple[str, ...] | None = None,
               pfields: tuple[str, ...] | None = None,
               ptmpl=None,
               state: tuple[jnp.ndarray, jnp.ndarray] | None = None,
               own_nom: jnp.ndarray | None = None,
               ) -> GangPackResult:
    """Place every gang of the batch in one launch (module docstring).

    ``gblobs`` carries ONE representative pod row per gang ([G, ...]);
    ``need`` [G] i32 is how many members to place (0 = padding row, a
    no-op). ``tk`` (dynamic i32 scalar) is the packing topology key's
    column in ``ct.topo_dom``; -1 packs capacity-greedy with every node
    in one shared domain. ``d_cap`` (STATIC) bounds the domain space;
    the last slot is the pseudo-domain of unlabeled nodes. ``state``
    overrides free/nonzero_requested with a previous launch's chain.
    ``own_nom`` [G, N] i32 counts the gang's OWN members nominated per
    node (post-preemption retries): their reserved requests are handed
    back before the capacity division, the gang analog of the fit
    predicate's own-nomination hand-back (framework.go:989)."""
    from kubernetes_tpu.models.pipeline import (
        FILTER_PLUGINS,
        NUM_FILTER_PLUGINS,
        static_filters,
    )
    from kubernetes_tpu.ops.features import unpack_cluster, unpack_pods

    ct = unpack_cluster(cblobs, caps)
    gpods = unpack_pods(gblobs, caps, pfields, ptmpl)     # leaves [G, ...]
    free0 = ct.free if state is None else state[0]
    nzr0 = ct.nonzero_requested if state is None else state[1]
    if enabled_filters is None:
        enabled_filters = (True,) * NUM_FILTER_PLUGINS
    act = frozenset(active if active is not None else ())
    fit_on = enabled_filters[FILTER_PLUGINS.index("NodeResourcesFit")]
    valid = ct.node_valid
    n = valid.shape[0]

    def per_gang_static(pod):
        masks = static_filters(ct, pod, wk, enabled_filters, act)
        return jnp.all(masks, axis=0) & valid & pod.valid
    static_ok = jax.vmap(per_gang_static)(gpods)          # [G, N]

    # node -> packing domain: the tk column of the topology table; NONE
    # labels (and tk = -1, and ids past the bucket) collapse into the
    # last slot, the pseudo-domain of topology-less nodes
    dom_raw = ct.topo_dom[:, jnp.maximum(tk, 0)]          # [N]
    dom = jnp.where((tk >= 0) & (dom_raw >= 0) & (dom_raw < d_cap - 1),
                    dom_raw, d_cap - 1)
    arange_n = jnp.arange(n)

    if own_nom is None:
        own_nom = jnp.zeros((gpods.req.shape[0], n), jnp.int32)

    def body(carry, xs):
        free, nzr = carry
        ok_s, req, nzreq, m, onom = xs
        # member capacity per node: floored free/request over the columns
        # the request binds, nominated reservations subtracted like the
        # batched fit predicate (framework.go:989 AddPod pass) — except
        # the gang's own nominated members' reservations, handed back
        if fit_on:
            eff = jnp.maximum(
                free - ct.nominated_req
                + onom.astype(free.dtype)[:, None] * req[None, :], 0.0)
            active_col = req > 0.0
            safe_req = jnp.where(active_col, req, 1.0)
            per_col = jnp.floor(eff / safe_req)
            per_col = jnp.where(active_col[None, :], per_col,
                                jnp.float32(_UNBOUNDED))
            cap_f = jnp.min(per_col, axis=1)              # [N]
        else:
            cap_f = jnp.full((n,), jnp.float32(_UNBOUNDED))
        cap_n = jnp.where(ok_s, jnp.clip(cap_f, 0.0, _UNBOUNDED),
                          0.0).astype(jnp.int32)
        cap_total = jnp.minimum(jnp.sum(cap_n.astype(jnp.float32)),
                                2.0 ** 30).astype(jnp.int32)
        feasible = (cap_total >= m) & (m > 0)
        # domain-major greedy fill: rank domains by capacity descending
        # (the topology-close packing score), densest nodes first within
        # a domain; cumulative take fills exactly `m` members
        dcap = jax.ops.segment_sum(cap_n, dom, num_segments=d_cap)
        d_rank = jnp.argsort(jnp.argsort(-dcap))          # domain -> rank
        key = (d_rank[dom] * _KEY_STRIDE
               + (_KEY_STRIDE - 1
                  - jnp.minimum(cap_n, _KEY_STRIDE - 1)))
        order = jnp.argsort(key)                          # [N] fill order
        cap_sorted = cap_n[order]
        prefix = jnp.cumsum(cap_sorted) - cap_sorted
        take_sorted = jnp.clip(m - prefix, 0, cap_sorted)
        take = jnp.zeros((n,), jnp.int32).at[order].set(take_sorted)
        take = jnp.where(feasible, take, 0)
        # commit the whole gang into the carried usage state
        tf = take.astype(free.dtype)
        free = free - tf[:, None] * req[None, :]
        nzr = nzr + tf[:, None] * nzreq[None, :]
        used_dom = jax.ops.segment_sum((take > 0).astype(jnp.int32), dom,
                                       num_segments=d_cap)
        spans = jnp.sum((used_dom > 0).astype(jnp.int32))
        return (free, nzr), (feasible, take, cap_total, spans)

    xs = (static_ok, gpods.req, gpods.nonzero_req,
          jnp.asarray(need, jnp.int32), jnp.asarray(own_nom, jnp.int32))
    (free_out, nzr_out), (ok, alloc, cap, spans) = jax.lax.scan(
        body, (free0, nzr0), xs)
    guard = jnp.any(jnp.isnan(free_out)).astype(jnp.int32) << 1
    return GangPackResult(ok=ok, alloc=alloc, cap=cap, spans=spans,
                          free=free_out, nzr=nzr_out, guard=guard)


@partial(jax.jit, static_argnames=("caps", "d_cap", "enabled_filters",
                                   "active", "pfields"))
def pack_gangs_jit(cblobs, gblobs, wk, caps, need, tk, d_cap=8,
                   enabled_filters=None, active=None, pfields=None,
                   ptmpl=None, state=None, own_nom=None):
    return pack_gangs(cblobs, gblobs, wk, caps, need, tk, d_cap,
                      enabled_filters, active, pfields, ptmpl, state,
                      own_nom)


def pack_cache_size() -> int | None:
    """Executable-cache entries behind the gang packer (the DeviceProfiler
    folds this into ``pipeline.launch_cache_size`` so a gang-shape
    recompile is attributed, not "unattributed")."""
    size = getattr(pack_gangs_jit, "_cache_size", None)
    return None if size is None else size()


@jax.jit
def _capacity(free: jnp.ndarray, req: jnp.ndarray) -> jnp.ndarray:
    """[N, R] free x [R] request -> scalar i32 member-capacity bound."""
    active = req > 0.0
    safe_req = jnp.where(active, req, 1.0)
    per_col = jnp.floor(jnp.maximum(free, 0.0) / safe_req)
    per_col = jnp.where(active[None, :], per_col, jnp.float32(2 ** 30))
    per_node = jnp.min(per_col, axis=1)
    # a request with NO active columns fits anywhere: cap at a big count
    any_active = jnp.any(active)
    total = jnp.sum(jnp.clip(per_node, 0.0, 2.0 ** 30))
    return jnp.where(any_active, total,
                     jnp.float32(2 ** 30)).astype(jnp.int32)


def gang_capacity_device(free, req) -> jax.Array:
    """The host-fallback capacity bound (see the old ``gang_capacity``
    docstring: an optimistic upper bound on how many request-shaped
    members still fit; only provable impossibility may reject on it) —
    returned as the DEVICE scalar. Callers must NOT block on it: the
    scheduler appends it to the one-per-cycle ``device_get`` pull and
    resolves the PreFilter memo a cycle later (the optimistic cost of
    the lag is one normal scheduling attempt, which the bound's contract
    already prices in)."""
    return _capacity(jnp.asarray(free, jnp.float32),
                     jnp.asarray(req, jnp.float32))
