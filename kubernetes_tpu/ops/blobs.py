"""Blob codec: many small feature arrays <-> two dense transfer buffers.

Per-array host->device transfers cost ~5-20ms each on the TPU tunnel; a
ClusterTensors/PodFeatures pytree has ~25/~55 leaves, which would dominate the
per-cycle budget. Instead the host packs all fields of a struct into ONE f32
blob and ONE i32 blob (bools stored as i32), ships two arrays, and the jitted
pipeline unpacks them with slices/reshapes that XLA folds away.

The codec is schema-driven: field name -> (shape, kind). Schemas are derived
from Capacities so pack/unpack stay in lockstep with the dataclasses in
ops.features.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

Kind = str  # "f32" | "i32" | "bool"


@jax.tree_util.register_dataclass
@dataclass
class Blobs:
    """The two transfer buffers. Leading batch axes allowed."""

    f32: jax.Array
    i32: jax.Array


class BlobCodec:
    def __init__(self, schema: dict[str, tuple[tuple[int, ...], Kind]]):
        self.schema = schema
        self._f32_off: dict[str, tuple[int, int]] = {}
        self._i32_off: dict[str, tuple[int, int]] = {}
        f = i = 0
        for name, (shape, kind) in schema.items():
            size = math.prod(shape) if shape else 1
            if kind == "f32":
                self._f32_off[name] = (f, size)
                f += size
            else:  # i32 / bool
                self._i32_off[name] = (i, size)
                i += size
        self.f32_size = f
        self.i32_size = i

    def alloc(self, *batch: int) -> tuple[np.ndarray, np.ndarray]:
        return (np.zeros(batch + (self.f32_size,), np.float32),
                np.zeros(batch + (self.i32_size,), np.int32))

    def pack_into(self, out_f32: np.ndarray, out_i32: np.ndarray,
                  fields: dict[str, np.ndarray]) -> None:
        """Write one struct's fields into (already-allocated) blob rows.
        out_* may be views (e.g. one batch row)."""
        for name, arr in fields.items():
            shape, kind = self.schema[name]
            if kind == "f32":
                off, size = self._f32_off[name]
                out_f32[..., off:off + size] = np.asarray(arr, np.float32).reshape(
                    arr.shape[: arr.ndim - len(shape)] + (size,)) if shape else arr
            else:
                off, size = self._i32_off[name]
                flat = (np.asarray(arr, np.int32).reshape(
                    arr.shape[: arr.ndim - len(shape)] + (size,)) if shape else arr)
                out_i32[..., off:off + size] = flat

    def pack(self, fields: dict[str, np.ndarray]) -> Blobs:
        f32, i32 = self.alloc()
        self.pack_into(f32, i32, fields)
        return Blobs(f32=jnp.asarray(f32), i32=jnp.asarray(i32))

    def unpack(self, blobs: Blobs, cls=None):
        """Slice the blobs back into named arrays (inside jit: free).
        Leading batch axes of the blobs are preserved on every field."""
        out = {}
        for name, (shape, kind) in self.schema.items():
            if kind == "f32":
                off, size = self._f32_off[name]
                arr = jax.lax.slice_in_dim(blobs.f32, off, off + size, axis=-1)
            else:
                off, size = self._i32_off[name]
                arr = jax.lax.slice_in_dim(blobs.i32, off, off + size, axis=-1)
            batch = arr.shape[:-1]
            arr = arr.reshape(batch + shape) if shape else arr.reshape(batch)
            if kind == "bool":
                arr = arr != 0
            out[name] = arr
        return cls(**out) if cls is not None else out
