"""Blob codec: many small feature arrays <-> two dense transfer buffers.

Per-array host->device transfers cost ~5-20ms each on the TPU tunnel; a
ClusterTensors/PodFeatures pytree has ~25/~55 leaves, which would dominate the
per-cycle budget. Instead the host packs all fields of a struct into ONE f32
blob and ONE i32 blob (bools stored as i32), ships two arrays, and the jitted
pipeline unpacks them with slices/reshapes that XLA folds away.

The codec is schema-driven: field name -> (shape, kind). Schemas are derived
from Capacities so pack/unpack stay in lockstep with the dataclasses in
ops.features.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

Kind = str  # "f32" | "i32" | "bool"


@jax.tree_util.register_dataclass
@dataclass
class Blobs:
    """The two transfer buffers. Leading batch axes allowed."""

    f32: jax.Array
    i32: jax.Array


class BlobCodec:
    def __init__(self, schema: dict[str, tuple[tuple[int, ...], Kind]]):
        self.schema = schema
        self._subset_cache: dict[tuple, tuple] = {}
        self._f32_off: dict[str, tuple[int, int]] = {}
        self._i32_off: dict[str, tuple[int, int]] = {}
        f = i = 0
        for name, (shape, kind) in schema.items():
            size = math.prod(shape) if shape else 1
            if kind == "f32":
                self._f32_off[name] = (f, size)
                f += size
            else:  # i32 / bool
                self._i32_off[name] = (i, size)
                i += size
        self.f32_size = f
        self.i32_size = i

    def alloc(self, *batch: int) -> tuple[np.ndarray, np.ndarray]:
        return (np.zeros(batch + (self.f32_size,), np.float32),
                np.zeros(batch + (self.i32_size,), np.int32))

    def pack_into(self, out_f32: np.ndarray, out_i32: np.ndarray,
                  fields: dict[str, np.ndarray]) -> None:
        """Write one struct's fields into (already-allocated) blob rows.
        out_* may be views (e.g. one batch row)."""
        for name, arr in fields.items():
            shape, kind = self.schema[name]
            if kind == "f32":
                off, size = self._f32_off[name]
                out_f32[..., off:off + size] = np.asarray(arr, np.float32).reshape(
                    arr.shape[: arr.ndim - len(shape)] + (size,)) if shape else arr
            else:
                off, size = self._i32_off[name]
                flat = (np.asarray(arr, np.int32).reshape(
                    arr.shape[: arr.ndim - len(shape)] + (size,)) if shape else arr)
                out_i32[..., off:off + size] = flat

    def pack(self, fields: dict[str, np.ndarray]) -> Blobs:
        f32, i32 = self.alloc()
        self.pack_into(f32, i32, fields)
        return Blobs(f32=jnp.asarray(f32), i32=jnp.asarray(i32))

    # ------------- field-subset transfers -------------
    #
    # A launch only reads the fields its active features touch; shipping the
    # full schema wastes most of the host->device link (the tunnel moves
    # single-digit MB/s, and e.g. a no-affinity pod's selector arrays are
    # ~90% of its row). A subset blob packs just the named fields (schema
    # order); the device splices the rest in from a 1-row full-schema
    # template, broadcast over the batch — XLA dead-code-eliminates the
    # broadcasts nothing reads.

    def subset_layout(self, names: tuple[str, ...]):
        """(f32_offsets, i32_offsets, f32_size, i32_size) of a packed blob
        holding only `names`, laid out in schema order."""
        key = tuple(sorted(names))
        lay = self._subset_cache.get(key)
        if lay is not None:
            return lay
        unknown = [n for n in names if n not in self.schema]
        if unknown:
            # a typo'd subset name would otherwise silently ride the
            # template defaults — a silent-wrong-results failure mode
            raise KeyError(f"subset names not in schema: {unknown}")
        f_off: dict[str, tuple[int, int]] = {}
        i_off: dict[str, tuple[int, int]] = {}
        f = i = 0
        for name, (shape, kind) in self.schema.items():
            if name not in names:
                continue
            size = math.prod(shape) if shape else 1
            if kind == "f32":
                f_off[name] = (f, size)
                f += size
            else:
                i_off[name] = (i, size)
                i += size
        lay = (f_off, i_off, f, i)
        self._subset_cache[key] = lay
        return lay

    def alloc_subset(self, names: tuple[str, ...], *batch: int):
        _, _, fs, isz = self.subset_layout(names)
        return (np.zeros(batch + (fs,), np.float32),
                np.zeros(batch + (isz,), np.int32))

    def pack_into_subset(self, names: tuple[str, ...], out_f32: np.ndarray,
                         out_i32: np.ndarray,
                         fields: dict[str, np.ndarray]) -> None:
        """pack_into against a subset layout; fields outside it are skipped
        (their template defaults stand in on device)."""
        f_off, i_off, _, _ = self.subset_layout(names)
        for name, arr in fields.items():
            shape, kind = self.schema[name]
            if kind == "f32":
                if name not in f_off:
                    continue
                off, size = f_off[name]
                out_f32[..., off:off + size] = (
                    np.asarray(arr, np.float32).reshape(
                        arr.shape[: arr.ndim - len(shape)] + (size,))
                    if shape else arr)
            else:
                if name not in i_off:
                    continue
                off, size = i_off[name]
                out_i32[..., off:off + size] = (
                    np.asarray(arr, np.int32).reshape(
                        arr.shape[: arr.ndim - len(shape)] + (size,))
                    if shape else arr)

    def subset_template(self, names: tuple[str, ...], tmpl_f32: np.ndarray,
                        tmpl_i32: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Subset-layout rows sliced out of packed full-schema rows — the
        host-side base a subset batch pack starts from."""
        f_off, i_off, fs, isz = self.subset_layout(names)
        sf = np.zeros((fs,), np.float32)
        si = np.zeros((isz,), np.int32)
        for name, (off, size) in f_off.items():
            foff, _ = self._f32_off[name]
            sf[off:off + size] = tmpl_f32[foff:foff + size]
        for name, (off, size) in i_off.items():
            ioff, _ = self._i32_off[name]
            si[off:off + size] = tmpl_i32[ioff:ioff + size]
        return sf, si

    def unpack_subset(self, blobs: Blobs, names: tuple[str, ...],
                      template: Blobs, cls=None):
        """Subset blobs + a 1-row full-schema template blob for the absent
        fields, broadcast over the batch (inside jit: free)."""
        f_off, i_off, _, _ = self.subset_layout(names)
        batch = blobs.i32.shape[:-1]
        out = {}
        for name, (shape, kind) in self.schema.items():
            sub_off = f_off if kind == "f32" else i_off
            if name in sub_off:
                src = blobs.f32 if kind == "f32" else blobs.i32
                off, size = sub_off[name]
                arr = jax.lax.slice_in_dim(src, off, off + size, axis=-1)
                arr = arr.reshape(batch + shape) if shape else arr.reshape(batch)
            else:
                full_off = self._f32_off if kind == "f32" else self._i32_off
                tsrc = template.f32 if kind == "f32" else template.i32
                off, size = full_off[name]
                arr = jax.lax.slice_in_dim(tsrc, off, off + size, axis=-1)
                arr = jnp.broadcast_to(arr.reshape(shape), batch + shape)
            if kind == "bool":
                arr = arr != 0
            out[name] = arr
        return cls(**out) if cls is not None else out

    def unpack(self, blobs: Blobs, cls=None):
        """Slice the blobs back into named arrays (inside jit: free).
        Leading batch axes of the blobs are preserved on every field."""
        out = {}
        for name, (shape, kind) in self.schema.items():
            if kind == "f32":
                off, size = self._f32_off[name]
                arr = jax.lax.slice_in_dim(blobs.f32, off, off + size, axis=-1)
            else:
                off, size = self._i32_off[name]
                arr = jax.lax.slice_in_dim(blobs.i32, off, off + size, axis=-1)
            batch = arr.shape[:-1]
            arr = arr.reshape(batch + shape) if shape else arr.reshape(batch)
            if kind == "bool":
                arr = arr != 0
            out[name] = arr
        return cls(**out) if cls is not None else out
