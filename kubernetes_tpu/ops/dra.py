"""Device kernels for batched DRA allocation feasibility.

The host DRA plugin (plugins/dra.py) used to evaluate claim feasibility
per (pod, node, device) in Python — the worst host tail in the suite
(DRASteadyStateClaimTemplates at 1.12x baseline, BENCH_r06). This module
is the device half of its replacement:

- the cluster's device inventory is mirrored into dense per-node tensors
  (``dev_valid``/``dev_selbits``/``dev_in_use``, [N, D]-shaped with D a
  static per-node device bucket), maintained incrementally by
  plugins.dra.DeviceAllocatorView from the ResourceSlice watch;
- every CEL selector (DeviceClass selectors, request selectors, and the
  legacy direct ``device_class_name`` match) is pre-compiled AT WATCH
  TIME into one bit of a per-device verdict bitmask (``dev_selbits``,
  SELBIT_WORDS uint32 words = up to 256 distinct selectors): host CEL
  evaluation happens once per (selector, device) lifetime instead of
  once per (pod, node, device, cycle);
- a request then matches a device iff the request's required-bit mask is
  a subset of the device's verdict bits — a vectorized AND/compare;
- ``batch_feasible`` evaluates the whole pending batch against the whole
  node set inside the SAME jitted program as Filter/Score
  (models.pipeline.schedule_batch ANDs its [B, N] verdict into the
  feasible mask), replicating the host allocator's greedy request-order,
  device-order semantics exactly (the parity contract the allocation
  fuzz in tests/test_dra_fuzz.py enforces).

Greedy parity: the host allocator (DynamicResources.allocate_claim)
walks a pod's unallocated claims in order, each claim's requests in
order, and fills each request with the FIRST eligible free devices in
node device order. The kernel mirrors that with a per-request
cumulative-sum rank over the eligibility mask: ``pick = eligible &
(cumsum <= count)``; picked devices join a carried ``taken`` mask so the
next request sees them as gone. All-mode requests (allocation_mode All)
are feasible iff at least one eligible device remains and take ALL of
them, matching the host's ``want = len(matched)`` arithmetic.

Claims outside the device-expressible subset (matchAttribute
constraints, firstAvailable alternatives, adminAccess, non-positive
counts, selectors that fail to parse) never reach this kernel: the
builder routes their pods through the unchanged host filter path.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

# fixed selector-bitmask width: 8 uint32 words = 256 distinct compiled
# selectors. Fixed (not grown) so the kernel never recompiles as
# selectors register; the 257th distinct selector routes its claims to
# the host path instead (DeviceAllocatorView.MAX_SELECTORS).
SELBIT_WORDS = 8
MAX_SELECTORS = SELBIT_WORDS * 32

# chunk of the pod axis evaluated per lax.map step: bounds the transient
# [chunk, N, D] eligibility masks for giant drain batches
DRA_CHUNK = 256

# ``pinned`` sentinels: -1 = no allocated claim pins this pod; -2 = an
# allocated claim pins it to a node that is not (or no longer) mirrored,
# or two claims pin it to different nodes — feasible nowhere
PIN_ANY = -1
PIN_NONE = -2


@jax.tree_util.register_dataclass
@dataclass
class DraBatch:
    """One launch's DRA inputs (all dynamic args; shapes are the static
    jit key: N = mirror node capacity, D = device bucket per node,
    Q = request bucket per pod, W = SELBIT_WORDS, B = batch bucket).

    Device-side inventory (resident between launches, re-pushed only on
    slice/selector/row changes — see DeviceAllocatorView):
      dev_valid    [N, D]  bool   device exists at (node row, slot)
      dev_selbits  [N, D, W] u32  bit s set iff compiled selector s
                                  accepts the device
      dev_in_use   [N, D]  bool   allocated to some claim (ledger +
                                  assume overlay), re-packed per cycle

    Per-batch claim tensors (packed per cycle from the pods' resolved
    claims; flattened requests across each pod's unallocated claims):
      req_mask     [B, Q, W] u32  bits a device must ALL carry
      req_count    [B, Q]  i32    ExactCount want (0 = unused slot)
      req_all      [B, Q]  bool   allocation_mode All
      pinned       [B]     i32    row an allocated claim pins the pod to
                                  (PIN_ANY / PIN_NONE sentinels)
      active       [B]     bool   pod routed through the device
                                  allocator (False rows verdict True)
    """

    dev_valid: jax.Array
    dev_selbits: jax.Array
    dev_in_use: jax.Array
    req_mask: jax.Array
    req_count: jax.Array
    req_all: jax.Array
    pinned: jax.Array
    active: jax.Array


def batch_feasible(dra: DraBatch) -> jnp.ndarray:
    """[B, N] bool: can every unallocated claim of pod b be allocated on
    node n (greedy host-parity semantics), and does n satisfy the pod's
    allocated-claim pins? Inactive rows are all-True (the caller ANDs
    this into the feasible mask)."""
    free = dra.dev_valid & ~dra.dev_in_use                      # [N, D]
    n = free.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    q_cap = dra.req_mask.shape[1]

    def per_pod(mask, count, is_all, pinned, active):
        taken = jnp.zeros(free.shape, bool)                     # [N, D]
        ok = jnp.ones((n,), bool)
        for q in range(q_cap):      # static unroll: Q is a small bucket
            sel_ok = jnp.all((dra.dev_selbits & mask[q][None, None, :])
                             == mask[q][None, None, :], axis=-1)  # [N, D]
            elig = free & ~taken & sel_ok
            csum = jnp.cumsum(elig.astype(jnp.int32), axis=1)
            total = csum[:, -1]                                 # [N]
            used = (count[q] > 0) | is_all[q]
            want = jnp.where(is_all[q], 1, count[q])
            ok = ok & (~used | (total >= want))
            # greedy pick in device order (parity with the host fill's
            # first-come walk); All mode takes every eligible device
            pick = elig & (is_all[q] | (csum <= count[q]))
            taken = taken | pick
        ok = ok & jnp.where(pinned >= 0, rows == pinned,
                            pinned == PIN_ANY)
        return ok | ~active

    b = dra.req_mask.shape[0]
    tree = (dra.req_mask, dra.req_count, dra.req_all, dra.pinned,
            dra.active)
    if b <= DRA_CHUNK:
        return jax.vmap(per_pod)(*tree)
    # chunk the pod axis so the transient [chunk, N, D] masks stay small
    pad = (-b) % DRA_CHUNK
    if pad:
        tree = jax.tree.map(
            lambda x: jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1)),
            tree)
    groups = (b + pad) // DRA_CHUNK
    tree = jax.tree.map(
        lambda x: x.reshape((groups, DRA_CHUNK) + x.shape[1:]), tree)
    out = jax.lax.map(lambda t: jax.vmap(per_pod)(*t), tree)
    return out.reshape((groups * DRA_CHUNK, n))[:b]


@jax.jit
def batch_feasible_jit(dra: DraBatch) -> jnp.ndarray:
    """Standalone jitted entry (tests, the parity fuzz); production goes
    through models.pipeline.schedule_batch, which fuses batch_feasible
    into the Filter/Score launch."""
    return batch_feasible(dra)
