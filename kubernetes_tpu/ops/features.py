"""Dense tensor schema for the device-resident cluster mirror and pod batches.

This is the TPU-native replacement for the reference's per-cycle NodeInfo
snapshot (types.go:780): every string is interned to an int32 id host-side
(kubernetes_tpu.utils.interner) and every set-valued field becomes a
fixed-capacity padded array, so all Filter/Score extension points are pure
integer/float tensor ops vmappable over the node axis and batchable over the
pod axis (SURVEY.md section 7.0).

Shape/capacity notes
- All capacities are static (XLA compiles once per capacity bucket); the
  mirror grows capacities by power-of-two re-bucketing when exceeded.
- Resource units: cpu in milli-cores, memory/ephemeral-storage in MiB
  (float32 is exact for Mi-granular values up to 16 TiB), extended resources
  in raw counts. The host cache keeps exact integers; int->f32 conversion is
  monotonic, so `request <= free` compares identically to the exact-integer
  comparison whenever both sides are Mi-granular.
- `NONE` (-1) marks empty padded slots everywhere.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.utils.interner import NONE

# Packed as a required selector value for a nil LabelSelector
# (labels.Nothing() in the reference): no real id equals it, so the term
# matches no pod. Distinct from NONE (-1), which marks an unused slot.
IMPOSSIBLE = -2

# --- resource column layout ---

COL_CPU = 0       # milli-cores
COL_MEM = 1       # MiB
COL_EPH = 2       # MiB
COL_PODS = 3      # pod count
NUM_NATIVE_COLS = 4

# taint effect encoding
EFFECT_NO_SCHEDULE = 0
EFFECT_PREFER_NO_SCHEDULE = 1
EFFECT_NO_EXECUTE = 2
EFFECT_UNKNOWN = 3  # unrecognized effect string: ignored by every kernel
_EFFECTS = {"NoSchedule": EFFECT_NO_SCHEDULE,
            "PreferNoSchedule": EFFECT_PREFER_NO_SCHEDULE,
            "NoExecute": EFFECT_NO_EXECUTE}

# node-selector operator encoding
OP_IN = 0
OP_NOT_IN = 1
OP_EXISTS = 2
OP_DOES_NOT_EXIST = 3
OP_GT = 4
OP_LT = 5
OP_UNKNOWN = 6  # unrecognized operator: requirement matches nothing
_OPS = {"In": OP_IN, "NotIn": OP_NOT_IN, "Exists": OP_EXISTS,
        "DoesNotExist": OP_DOES_NOT_EXIST, "Gt": OP_GT, "Lt": OP_LT}

# toleration operator encoding
TOL_EQUAL = 0
TOL_EXISTS = 1


@dataclass(frozen=True)
class Capacities:
    """Static capacity configuration — part of the jit cache key."""

    nodes: int = 1024            # N
    ext_resources: int = 4       # extended/scalar resource columns
    label_cols: int = 32         # K: distinct node-label KEYS cluster-wide.
                                 # Labels are columnized: one dense value
                                 # column per key (TPU-native: no per-node
                                 # key-value pair scans in the kernels)
    pod_label_cols: int = 32     # Kp: distinct POD-label keys cluster-wide
                                 # (pod labels are columnized the same way for
                                 # inter-pod affinity / spread selector kernels)
    topo_cols: int = 8           # TK: topology keys in use by any pod's
                                 # (anti)affinity terms or spread constraints
    domains: int = 0             # per-topo-key compact domain-id space for
                                 # topology aggregation; 0 = same as nodes
    node_taints: int = 8         # T
    node_ports: int = 64         # P: occupied host ports per node
    node_images: int = 16        # I
    pods: int = 4096             # PT: pod-table slots (scheduled pods)
    pod_labels: int = 8          # PL
    sel_terms: int = 4           # node-selector terms per pod
    sel_exprs: int = 6           # expressions per term
    sel_vals: int = 4            # values per expression
    pref_terms: int = 8          # preferred scheduling terms
    tolerations: int = 8
    pod_ports: int = 8
    aff_terms: int = 4           # pod (anti)affinity terms per kind
    aff_ns: int = 4              # namespaces per affinity term (incl. the
                                 # pack-time namespaceSelector unroll, the
                                 # device analog of the reference's
                                 # mergeAffinityTermNamespacesIfNotEmpty,
                                 # interpodaffinity/plugin.go:123)
    aff_sel: int = 6             # selector EXPRESSIONS per affinity/spread
                                 # selector (matchLabels pairs + op-coded
                                 # matchExpressions + merged match/mismatch
                                 # LabelKeys requirements)
    aff_sel_vals: int = 4        # value ids per selector expression
    spread_constraints: int = 4
    pod_images: int = 8
    vocab: int = 65536           # interner id space mirrored to device

    @property
    def res_cols(self) -> int:
        return NUM_NATIVE_COLS + self.ext_resources

    @property
    def domain_cap(self) -> int:
        return self.domains or self.nodes


def _register(cls):
    """Register a dataclass of arrays as a JAX pytree."""
    fields = [f.name for f in dataclasses.fields(cls)]
    jax.tree_util.register_dataclass(cls, data_fields=fields, meta_fields=[])
    return cls


@_register
@dataclass
class ClusterTensors:
    """The HBM-resident cluster mirror: one row per node (+ the pod table).

    Device analog of the reference's Snapshot (snapshot.go:29); refreshed
    incrementally from the host cache's generation diff by backend.mirror.
    """

    # resources (f32): free = allocatable - requested, maintained exactly on host
    allocatable: jax.Array       # [N, R]
    free: jax.Array              # [N, R]
    nonzero_requested: jax.Array  # [N, 2] cpu/mem with 100m/200Mi defaults
    # resources reserved by nominated (preemptor) pods awaiting their victims
    # to exit — the fit check subtracts this (the device analog of
    # RunFilterPluginsWithNominatedPods' AddPod pass, runtime/framework.go:989)
    nominated_req: jax.Array     # [N, R]
    # validity + flags
    node_valid: jax.Array        # [N] bool
    unschedulable: jax.Array     # [N] bool
    node_name_id: jax.Array      # [N] i32
    # labels, columnized: one column per distinct label KEY cluster-wide.
    # label_col_vals[n, k] = value id of key k on node n (NONE if absent);
    # label_col_nums = numeric parse of the value (NaN if absent/non-int,
    # for Gt/Lt without a vocab gather).
    label_col_vals: jax.Array    # [N, K] i32
    label_col_nums: jax.Array    # [N, K] f32
    # topology domains: for each registered topology key tk, the compact
    # per-key domain id of the node's label value (NONE = label absent).
    # Two nodes are in the same topology domain under tk iff their ids match.
    # This is the scatter/gather substrate for InterPodAffinity and
    # PodTopologySpread (SURVEY.md §7.1 step 5).
    topo_dom: jax.Array          # [N, TK] i32
    # taints
    taint_keys: jax.Array        # [N, T] i32
    taint_vals: jax.Array        # [N, T] i32
    taint_effects: jax.Array     # [N, T] i32
    # occupied host ports
    port_ips: jax.Array          # [N, P] i32
    port_protos: jax.Array       # [N, P] i32
    port_nums: jax.Array         # [N, P] i32 (-1 empty)
    # images present on node
    image_ids: jax.Array         # [N, I] i32
    image_sizes: jax.Array       # [N, I] f32 MiB
    # pod table (scheduled pods, for inter-pod affinity / topology spread).
    # Labels columnized over pod-label columns [Kp]; each term group stores
    # (topo tk-index, selected namespaces + all-namespaces flag, op-coded
    # selector expressions); the preferred groups add weights. Term slots
    # with tk = NONE are unused; expression slots with op = NONE are unused.
    # Full LabelSelector semantics (framework/types.go:537 AffinityTerm):
    # matchLabels pairs pack as In exprs, matchExpressions pack op-coded
    # (In/NotIn/Exists/DoesNotExist), match/mismatchLabelKeys merge as
    # In/NotIn exprs (strategy.go applyMatchLabelKeysAndMismatchLabelKeys),
    # namespaceSelector unrolls into the ns list at pack time (empty
    # selector => ns_all).
    pod_valid: jax.Array         # [PT] bool
    pod_node: jax.Array          # [PT] i32 node row index
    pod_ns: jax.Array            # [PT] i32 namespace id
    pod_uid: jax.Array           # [PT] i32 interned pod uid (self-exclusion:
                                 # a pod never matches its own table entry)
    pod_nominated: jax.Array     # [PT] bool: nominated-not-yet-bound pod —
                                 # counts for anti-affinity, excluded from
                                 # required-affinity presence and scoring
                                 # (the dual-pass rule of framework.go:989)
    pt_label_vals: jax.Array     # [PT, Kp] i32 label value per pod-label column
    # REQUIRED anti-affinity terms (satisfyExistingPodsAntiAffinity)
    pod_anti_tk: jax.Array       # [PT, A] i32 topo-key index (-1 = unused term)
    pod_anti_ns: jax.Array       # [PT, A, NS] i32 namespace ids the term selects
    pod_anti_ns_all: jax.Array   # [PT, A] bool: empty namespaceSelector
    pod_anti_sel_cols: jax.Array  # [PT, A, MS] i32 pod-label column
    pod_anti_sel_ops: jax.Array   # [PT, A, MS] i32 op id (-1 = unused expr)
    pod_anti_sel_vals: jax.Array  # [PT, A, MS, V2] i32 value ids
    # REQUIRED affinity terms (hardPodAffinityWeight scoring)
    pod_aff_tk: jax.Array        # [PT, A] i32
    pod_aff_ns: jax.Array        # [PT, A, NS] i32
    pod_aff_ns_all: jax.Array    # [PT, A] bool
    pod_aff_sel_cols: jax.Array  # [PT, A, MS] i32
    pod_aff_sel_ops: jax.Array   # [PT, A, MS] i32
    pod_aff_sel_vals: jax.Array  # [PT, A, MS, V2] i32
    # PREFERRED affinity / anti-affinity terms (scoring)
    pod_paff_tk: jax.Array       # [PT, A] i32
    pod_paff_weight: jax.Array   # [PT, A] i32
    pod_paff_ns: jax.Array       # [PT, A, NS] i32
    pod_paff_ns_all: jax.Array   # [PT, A] bool
    pod_paff_sel_cols: jax.Array  # [PT, A, MS] i32
    pod_paff_sel_ops: jax.Array   # [PT, A, MS] i32
    pod_paff_sel_vals: jax.Array  # [PT, A, MS, V2] i32
    pod_panti_tk: jax.Array      # [PT, A] i32
    pod_panti_weight: jax.Array  # [PT, A] i32
    pod_panti_ns: jax.Array      # [PT, A, NS] i32
    pod_panti_ns_all: jax.Array  # [PT, A] bool
    pod_panti_sel_cols: jax.Array  # [PT, A, MS] i32
    pod_panti_sel_ops: jax.Array   # [PT, A, MS] i32
    pod_panti_sel_vals: jax.Array  # [PT, A, MS, V2] i32


def node_schema(caps: Capacities) -> dict[str, tuple[tuple[int, ...], str]]:
    """Per-node-row field schema for the blob codec (leading N axis implied)."""
    r = caps.res_cols
    return {
        "allocatable": ((r,), "f32"),
        "free": ((r,), "f32"),
        "nonzero_requested": ((2,), "f32"),
        "nominated_req": ((r,), "f32"),
        "label_col_nums": ((caps.label_cols,), "f32"),
        "image_sizes": ((caps.node_images,), "f32"),
        "node_valid": ((), "bool"),
        "unschedulable": ((), "bool"),
        "node_name_id": ((), "i32"),
        "label_col_vals": ((caps.label_cols,), "i32"),
        "topo_dom": ((caps.topo_cols,), "i32"),
        "taint_keys": ((caps.node_taints,), "i32"),
        "taint_vals": ((caps.node_taints,), "i32"),
        "taint_effects": ((caps.node_taints,), "i32"),
        "port_ips": ((caps.node_ports,), "i32"),
        "port_protos": ((caps.node_ports,), "i32"),
        "port_nums": ((caps.node_ports,), "i32"),
        "image_ids": ((caps.node_images,), "i32"),
    }


def pod_table_schema(caps: Capacities) -> dict[str, tuple[tuple[int, ...], str]]:
    """Per-pod-slot schema for the scheduled-pod table (leading PT axis implied)."""
    a, ns, ms, v2 = caps.aff_terms, caps.aff_ns, caps.aff_sel, caps.aff_sel_vals
    d = {
        "pod_valid": ((), "bool"),
        "pod_node": ((), "i32"),
        "pod_ns": ((), "i32"),
        "pod_uid": ((), "i32"),
        "pod_nominated": ((), "bool"),
        "pt_label_vals": ((caps.pod_label_cols,), "i32"),
    }
    for g in ("anti", "aff", "paff", "panti"):
        d[f"pod_{g}_tk"] = ((a,), "i32")
        if g in ("paff", "panti"):
            d[f"pod_{g}_weight"] = ((a,), "i32")
        d[f"pod_{g}_ns"] = ((a, ns), "i32")
        d[f"pod_{g}_ns_all"] = ((a,), "bool")
        d[f"pod_{g}_sel_cols"] = ((a, ms), "i32")
        d[f"pod_{g}_sel_ops"] = ((a, ms), "i32")
        d[f"pod_{g}_sel_vals"] = ((a, ms, v2), "i32")
    return d


def pod_schema(caps: Capacities) -> dict[str, tuple[tuple[int, ...], str]]:
    """Per-pending-pod PodFeatures schema (batch axis B implied)."""
    r = caps.res_cols
    T, E, V = caps.sel_terms, caps.sel_exprs, caps.sel_vals
    PW, TO, HP = caps.pref_terms, caps.tolerations, caps.pod_ports
    A, NS, MS, C = caps.aff_terms, caps.aff_ns, caps.aff_sel, caps.spread_constraints
    V2 = caps.aff_sel_vals
    PL, IM = caps.pod_labels, caps.pod_images
    d = {
        "req": ((r,), "f32"),
        "nonzero_req": ((2,), "f32"),
        "num_containers": ((), "f32"),
        "sel_num": ((T, E), "f32"),
        "pref_num": ((PW, E), "f32"),
        "priority": ((), "i32"),
        "ns": ((), "i32"),
        "name_id": ((), "i32"),
        "uid_id": ((), "i32"),
        "nominated_row": ((), "i32"),
        "plabel_vals": ((caps.pod_label_cols,), "i32"),
        "nodesel_cols": ((PL,), "i32"),
        "nodesel_vals": ((PL,), "i32"),
        "aff_pin": ((), "i32"),
        "sel_term_valid": ((T,), "bool"),
        "sel_col": ((T, E), "i32"),
        "sel_op": ((T, E), "i32"),
        "sel_is_field": ((T, E), "bool"),
        "sel_vals": ((T, E, V), "i32"),
        "pref_weight": ((PW,), "i32"),
        "pref_col": ((PW, E), "i32"),
        "pref_op": ((PW, E), "i32"),
        "pref_is_field": ((PW, E), "bool"),
        "pref_vals": ((PW, E, V), "i32"),
        "tol_key": ((TO,), "i32"),
        "tol_op": ((TO,), "i32"),
        "tol_val": ((TO,), "i32"),
        "tol_effect": ((TO,), "i32"),
        "tol_valid": ((TO,), "bool"),
        "hp_ip": ((HP,), "i32"),
        "hp_proto": ((HP,), "i32"),
        "hp_port": ((HP,), "i32"),
        "aff_self_match": ((), "bool"),
        "tsc_tk": ((C,), "i32"),
        "tsc_max_skew": ((C,), "i32"),
        "tsc_hard": ((C,), "bool"),
        "tsc_min_domains": ((C,), "i32"),
        "tsc_sel_cols": ((C, MS), "i32"),
        "tsc_sel_ops": ((C, MS), "i32"),
        "tsc_sel_vals": ((C, MS, V2), "i32"),
        "tsc_honor_affinity": ((C,), "bool"),
        "tsc_honor_taints": ((C,), "bool"),
        "image_ids": ((IM,), "i32"),
        "node_name_id": ((), "i32"),
        "valid": ((), "bool"),
    }
    for g in ("aff", "anti", "paff", "panti"):
        d[f"{g}_tk"] = ((A,), "i32")
        if g in ("paff", "panti"):
            d[f"{g}_weight"] = ((A,), "i32")
        d[f"{g}_ns"] = ((A, NS), "i32")
        d[f"{g}_ns_all"] = ((A,), "bool")
        d[f"{g}_sel_cols"] = ((A, MS), "i32")
        d[f"{g}_sel_ops"] = ((A, MS), "i32")
        d[f"{g}_sel_vals"] = ((A, MS, V2), "i32")
    return d


@_register
@dataclass
class PodFeatures:
    """One pending pod, fully interned/padded. Batched by stacking (axis 0)."""

    # resources
    req: jax.Array               # [R] f32
    nonzero_req: jax.Array       # [2] f32
    num_containers: jax.Array    # f32 scalar (incl. init; image-locality threshold)
    priority: jax.Array          # i32 scalar
    ns: jax.Array                # i32 scalar namespace id
    name_id: jax.Array           # i32 scalar (pod name, for debugging)
    uid_id: jax.Array            # i32 scalar interned uid (self-exclusion
                                 # vs the pod table, incl. own nomination)
    nominated_row: jax.Array     # i32 scalar: node row this pod is nominated
                                 # on (-1 none); its own reservation is added
                                 # back to free on that row
    plabel_vals: jax.Array       # [Kp] i32 own labels over pod-label columns
    # spec.nodeSelector: exact (label-column, value) pairs, ANDed; a pair on a
    # key no node carries packs col=NONE (matches nothing). Unused slots have
    # val=NONE.
    nodesel_cols: jax.Array      # [PL] i32 label-column index (-1 = key unseen)
    nodesel_vals: jax.Array      # [PL] i32 (-1 = unused slot)
    # required node affinity, PIN form: the whole required clause reduces
    # to one matchFields metadata.name In [v] term (the daemonset-controller
    # shape) — packed as the target's interned name so the filter is ONE
    # [N] compare instead of the [N, T, E, V] selector kernels (NONE = no
    # pin; the general form below then applies)
    aff_pin: jax.Array           # i32 scalar (-1 = no pin)
    # required node affinity: OR over terms, AND within term. Expressions
    # reference label COLUMNS (host-resolved); unused slots have op=NONE.
    sel_term_valid: jax.Array    # [T] bool
    sel_col: jax.Array           # [T, E] i32 (-1 = key unseen cluster-wide)
    sel_op: jax.Array            # [T, E] i32 (-1 = unused expr)
    sel_is_field: jax.Array      # [T, E] bool (metadata.name matchFields)
    sel_vals: jax.Array          # [T, E, V] i32
    sel_num: jax.Array           # [T, E] f32 (rhs for Gt/Lt)
    # preferred node affinity
    pref_weight: jax.Array       # [PW] i32 (0 = unused)
    pref_col: jax.Array          # [PW, E] i32
    pref_op: jax.Array           # [PW, E] i32
    pref_is_field: jax.Array     # [PW, E] bool
    pref_vals: jax.Array         # [PW, E, V] i32
    pref_num: jax.Array          # [PW, E] f32
    # tolerations
    tol_key: jax.Array           # [TO] i32 (-1 = unused; key NONE+valid uses empty id 0)
    tol_op: jax.Array            # [TO] i32 TOL_EQUAL/TOL_EXISTS
    tol_val: jax.Array           # [TO] i32
    tol_effect: jax.Array        # [TO] i32 (-1 = all effects)
    tol_valid: jax.Array         # [TO] bool
    # requested host ports
    hp_ip: jax.Array             # [HP] i32
    hp_proto: jax.Array          # [HP] i32
    hp_port: jax.Array           # [HP] i32 (-1 unused)
    # pod (anti)affinity terms — required and preferred, both directions.
    # *_tk is the registered topology-key index (NONE = unused term slot);
    # selectors are op-coded expressions over pod-label columns (full
    # LabelSelector semantics; op NONE = unused expr slot); namespaces are
    # an explicit id list (namespaceSelector unrolled at pack time) plus an
    # all-namespaces flag for the empty selector.
    aff_self_match: jax.Array    # bool: pod matches ALL its own required
                                 # affinity terms (first-pod-of-group rule,
                                 # filtering.go satisfyPodAffinity)
    aff_tk: jax.Array            # [A] i32 required affinity
    aff_ns: jax.Array            # [A, NS] i32
    aff_ns_all: jax.Array        # [A] bool
    aff_sel_cols: jax.Array      # [A, MS] i32
    aff_sel_ops: jax.Array       # [A, MS] i32
    aff_sel_vals: jax.Array      # [A, MS, V2] i32
    anti_tk: jax.Array           # [A] i32 required anti-affinity
    anti_ns: jax.Array           # [A, NS] i32
    anti_ns_all: jax.Array       # [A] bool
    anti_sel_cols: jax.Array     # [A, MS] i32
    anti_sel_ops: jax.Array      # [A, MS] i32
    anti_sel_vals: jax.Array     # [A, MS, V2] i32
    paff_tk: jax.Array           # [A] i32 preferred affinity
    paff_weight: jax.Array       # [A] i32
    paff_ns: jax.Array           # [A, NS] i32
    paff_ns_all: jax.Array       # [A] bool
    paff_sel_cols: jax.Array     # [A, MS] i32
    paff_sel_ops: jax.Array      # [A, MS] i32
    paff_sel_vals: jax.Array     # [A, MS, V2] i32
    panti_tk: jax.Array          # [A] i32 preferred anti-affinity
    panti_weight: jax.Array      # [A] i32
    panti_ns: jax.Array          # [A, NS] i32
    panti_ns_all: jax.Array      # [A] bool
    panti_sel_cols: jax.Array    # [A, MS] i32
    panti_sel_ops: jax.Array     # [A, MS] i32
    panti_sel_vals: jax.Array    # [A, MS, V2] i32
    # topology spread constraints
    tsc_tk: jax.Array            # [C] i32 (-1 unused)
    tsc_max_skew: jax.Array      # [C] i32
    tsc_hard: jax.Array          # [C] bool (DoNotSchedule)
    tsc_min_domains: jax.Array   # [C] i32 (0 = unset)
    tsc_sel_cols: jax.Array      # [C, MS] i32
    tsc_sel_ops: jax.Array       # [C, MS] i32
    tsc_sel_vals: jax.Array      # [C, MS, V2] i32
    tsc_honor_affinity: jax.Array  # [C] bool (nodeAffinityPolicy == Honor)
    tsc_honor_taints: jax.Array    # [C] bool (nodeTaintsPolicy == Honor)
    # images referenced by containers
    image_ids: jax.Array         # [IM] i32
    # misc
    node_name_id: jax.Array      # i32 scalar: spec.nodeName pin (-1 = unset)
    valid: jax.Array             # bool scalar: padding rows in a batch are False


@_register
@dataclass
class ClusterBlobs:
    """Transfer form of ClusterTensors: three dense buffers + vocab table."""

    node_f32: jax.Array   # [N, nf]
    node_i32: jax.Array   # [N, ni]
    pods_i32: jax.Array   # [PT, pi] (pod table has no f32 fields)


@_register
@dataclass
class PodBlobs:
    """Transfer form of a PodFeatures batch."""

    f32: jax.Array        # [B, pf]
    i32: jax.Array        # [B, pi]


def _codecs(caps: Capacities):
    from kubernetes_tpu.ops.blobs import BlobCodec

    return (BlobCodec(node_schema(caps)), BlobCodec(pod_table_schema(caps)),
            BlobCodec(pod_schema(caps)))


_codec_cache: dict[Capacities, tuple] = {}


def codecs(caps: Capacities):
    c = _codec_cache.get(caps)
    if c is None:
        c = _codec_cache[caps] = _codecs(caps)
    return c


def unpack_cluster(blobs: ClusterBlobs, caps: Capacities) -> ClusterTensors:
    """Slice the blobs into the full ClusterTensors view (inside jit: free)."""
    from kubernetes_tpu.ops.blobs import Blobs

    node_codec, table_codec, _ = codecs(caps)
    fields = node_codec.unpack(Blobs(f32=blobs.node_f32, i32=blobs.node_i32))
    empty = jnp.zeros(blobs.pods_i32.shape[:-1] + (0,), jnp.float32)
    fields.update(table_codec.unpack(Blobs(f32=empty, i32=blobs.pods_i32)))
    return ClusterTensors(**fields)


def unpack_pods(blobs: PodBlobs, caps: Capacities,
                fields: tuple[str, ...] | None = None,
                template: PodBlobs | None = None) -> PodFeatures:
    """Full-schema unpack, or — when ``fields`` is given — a subset unpack
    where absent fields broadcast from the 1-row ``template`` blob (see
    BlobCodec.unpack_subset; the transfer-thrift path)."""
    from kubernetes_tpu.ops.blobs import Blobs

    _, _, pod_codec = codecs(caps)
    if fields is None:
        return pod_codec.unpack(Blobs(f32=blobs.f32, i32=blobs.i32),
                                PodFeatures)
    return pod_codec.unpack_subset(
        Blobs(f32=blobs.f32, i32=blobs.i32), fields,
        Blobs(f32=template.f32, i32=template.i32), PodFeatures)


def effect_id(effect: str) -> int:
    """Unknown effect strings map to EFFECT_UNKNOWN: the taint filter only
    acts on NoSchedule/NoExecute, so a malformed node object degrades to
    "effect ignored" instead of killing the pack (the reference tolerates
    arbitrary effect strings)."""
    return _EFFECTS.get(effect, EFFECT_UNKNOWN)


def op_id(op: str) -> int:
    """Unknown operators map to OP_UNKNOWN, which matches nothing in
    _selector_match — the device analog of the reference's
    selector-parse-error → no-match behavior."""
    return _OPS.get(op, OP_UNKNOWN)


# nodesel/PodFeatures helpers live in backend.mirror (the packer); this module
# only defines the schema and encodings so ops/* stay free of host imports.
