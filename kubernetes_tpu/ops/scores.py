"""Score extension point as device kernels, with reference-parity normalization.

One pod against all nodes, [N] float32 raw scores; normalization helpers
mirror each plugin's NormalizeScore. The 3-stage reference pipeline
(parallel Score -> Normalize -> weighted sum, runtime/framework.go:1117-1194)
collapses into fused tensor ops here.

Reference algorithms:
- least/most allocated:   noderesources/least_allocated.go:30, most_allocated.go:30
- balanced allocation:    noderesources/balanced_allocation.go (std of fractions)
- node affinity score:    nodeaffinity (sum of matched preferred weights)
- taint toleration score: tainttoleration:146 (intolerable PreferNoSchedule count)
- image locality:         imagelocality (scaled sum of present image sizes)
"""

from __future__ import annotations

import jax.numpy as jnp

from kubernetes_tpu.ops import common as C
from kubernetes_tpu.ops.features import (
    COL_CPU,
    COL_MEM,
    EFFECT_PREFER_NO_SCHEDULE,
    ClusterTensors,
    PodFeatures,
)
from kubernetes_tpu.ops.filters import _selector_match
from kubernetes_tpu.utils.interner import NONE

MAX_NODE_SCORE = 100.0


def utilization_fractions(alloc2: jnp.ndarray, nonzero_requested: jnp.ndarray,
                          pod_nonzero_req: jnp.ndarray) -> jnp.ndarray:
    """(NonZeroRequested + pod nonzero request) / allocatable for cpu, memory.
    [N, 2], clamped to [0, 1]; allocatable 0 -> fraction 1.

    Parameterized on the live ``nonzero_requested`` so the batched commit
    scan can feed its carry instead of the static snapshot column."""
    req = nonzero_requested + pod_nonzero_req[None]
    frac = jnp.where(alloc2 > 0, req / jnp.maximum(alloc2, 1e-9), 1.0)
    return jnp.clip(frac, 0.0, 1.0)


def least_allocated_from_fractions(frac: jnp.ndarray) -> jnp.ndarray:
    """mean over {cpu, mem} of (1 - utilization) * 100 (least_allocated.go:30,
    default weights 1/1)."""
    return jnp.mean(1.0 - frac, axis=-1) * MAX_NODE_SCORE


def most_allocated_from_fractions(frac: jnp.ndarray) -> jnp.ndarray:
    """mean utilization * 100 (most_allocated.go:30): bin-packing bias."""
    return jnp.mean(frac, axis=-1) * MAX_NODE_SCORE


def requested_to_capacity_ratio_from_fractions(
        frac: jnp.ndarray, shape_x: jnp.ndarray,
        shape_y: jnp.ndarray) -> jnp.ndarray:
    """Piecewise-linear utilization -> score per resource, averaged
    (requested_to_capacity_ratio.go:60 buildRequestedToCapacityRatioScorer):
    shape_x = utilization fractions 0..1 ascending, shape_y = scores
    0..100."""
    per_res = jnp.interp(frac, shape_x, shape_y)
    return jnp.mean(per_res, axis=-1)


def fit_score_from_fractions(frac: jnp.ndarray, strategy: str,
                             shape) -> jnp.ndarray:
    """NodeResourcesFit score under the configured ScoringStrategy
    (apis/config types.go ScoringStrategyType). ``strategy`` is STATIC —
    the launch compiles exactly one scorer."""
    if strategy == "MostAllocated":
        return most_allocated_from_fractions(frac)
    if strategy == "RequestedToCapacityRatio":
        return requested_to_capacity_ratio_from_fractions(
            frac, shape[0], shape[1])
    return least_allocated_from_fractions(frac)


def balanced_allocation_from_fractions(frac: jnp.ndarray) -> jnp.ndarray:
    """(1 - std(fractions)) * 100 (balanced_allocation.go)."""
    mean = jnp.mean(frac, axis=-1, keepdims=True)
    std = jnp.sqrt(jnp.mean((frac - mean) ** 2, axis=-1))
    return (1.0 - std) * MAX_NODE_SCORE


def alloc_cpu_mem(ct: ClusterTensors) -> jnp.ndarray:
    return jnp.stack([ct.allocatable[:, COL_CPU], ct.allocatable[:, COL_MEM]],
                     axis=-1)


def _requested_fractions(ct: ClusterTensors, pod: PodFeatures) -> jnp.ndarray:
    return utilization_fractions(alloc_cpu_mem(ct), ct.nonzero_requested,
                                 pod.nonzero_req)


def least_allocated(ct: ClusterTensors, pod: PodFeatures) -> jnp.ndarray:
    return least_allocated_from_fractions(_requested_fractions(ct, pod))


def most_allocated(ct: ClusterTensors, pod: PodFeatures) -> jnp.ndarray:
    return most_allocated_from_fractions(_requested_fractions(ct, pod))


def balanced_allocation(ct: ClusterTensors, pod: PodFeatures) -> jnp.ndarray:
    return balanced_allocation_from_fractions(_requested_fractions(ct, pod))


def node_affinity_score(ct: ClusterTensors, pod: PodFeatures) -> jnp.ndarray:
    """Sum of weights of matching PreferredSchedulingTerms (raw; normalized by
    max across nodes at aggregation)."""
    match = _selector_match(ct, pod.pref_col, pod.pref_op, pod.pref_is_field,
                            pod.pref_vals, pod.pref_num)  # [N, PW, E]
    used = pod.pref_op != NONE
    term_ok = jnp.all(match | ~used[None], axis=-1)       # [N, PW]
    term_nonempty = jnp.any(used, axis=-1)                # [PW]
    active = term_nonempty[None] & (pod.pref_weight[None] != 0)
    return jnp.sum(jnp.where(term_ok & active,
                             pod.pref_weight[None].astype(jnp.float32), 0.0),
                   axis=-1)


def taint_toleration_score(ct: ClusterTensors, pod: PodFeatures) -> jnp.ndarray:
    """Raw = count of intolerable PreferNoSchedule taints (lower is better;
    inverted by normalize_inverse)."""
    tolerated = C.tolerations_tolerate(
        pod.tol_valid, pod.tol_key, pod.tol_op, pod.tol_val, pod.tol_effect,
        ct.taint_keys, ct.taint_vals, ct.taint_effects)
    soft = (ct.taint_effects == EFFECT_PREFER_NO_SCHEDULE) & (ct.taint_keys != NONE)
    return jnp.sum(soft & ~tolerated, axis=-1).astype(jnp.float32)


def image_locality(ct: ClusterTensors, pod: PodFeatures,
                   num_nodes: jnp.ndarray) -> jnp.ndarray:
    """Scaled sum of sizes of requested images already present
    (imagelocality.go): each image's size is scaled by the fraction of nodes
    having it (spread), then mapped through [23Mi, 1000Mi] -> [0, 100]."""
    # presence [N, IM]: pod image im present in node's image list
    pim = pod.image_ids[None, :, None]            # [1, IM, 1]
    nim = ct.image_ids[:, None, :]                # [N, 1, I]
    present = jnp.any((nim == pim) & (pim != NONE), axis=-1)  # [N, IM]
    sizes = jnp.max(jnp.where(nim == pim, ct.image_sizes[:, None, :], 0.0),
                    axis=-1)                       # [N, IM] MiB
    # spread: fraction of (valid) nodes having each image
    have = jnp.sum(present & ct.node_valid[:, None], axis=0).astype(jnp.float32)
    spread = have / jnp.maximum(num_nodes.astype(jnp.float32), 1.0)  # [IM]
    summed = jnp.sum(present * sizes * spread[None], axis=-1)  # [N] MiB
    # thresholds (MiB): min 23Mi; max 1000Mi scaled by total container count
    # (image_locality.go calculatePriority maxThreshold * numContainers)
    min_t = 23.0
    max_t = 1000.0 * jnp.maximum(pod.num_containers, 1.0)
    return jnp.clip((summed - min_t) / (max_t - min_t), 0.0, 1.0) * MAX_NODE_SCORE


# ---------------- normalization (per-plugin NormalizeScore) ----------------


def normalize_max(scores: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """DefaultNormalizeScore: score * 100 / max (helper.DefaultNormalizeScore)."""
    top = C.masked_max(scores, mask)
    top = jnp.where(jnp.isfinite(top) & (top > 0), top, 1.0)
    return scores * (MAX_NODE_SCORE / top)


def normalize_inverse(scores: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Reverse normalize (taint toleration): 100 * (1 - score/max)."""
    top = C.masked_max(scores, mask)
    top = jnp.where(jnp.isfinite(top) & (top > 0), top, 1.0)
    return (1.0 - scores / top) * MAX_NODE_SCORE


def normalize_maxmin(scores: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """InterPodAffinity NormalizeScore (scoring.go:258):
    100 * (score - min) / (max - min); all-equal -> 0."""
    mn = C.masked_min(scores, mask)
    mx = C.masked_max(scores, mask)
    diff = mx - mn
    ok = jnp.isfinite(diff) & (diff > 0)
    return jnp.where(ok, MAX_NODE_SCORE * (scores - mn)
                     / jnp.where(ok, diff, 1.0), 0.0)


def normalize_spread(scores: jnp.ndarray, mask: jnp.ndarray,
                     ignored: jnp.ndarray) -> jnp.ndarray:
    """PodTopologySpread NormalizeScore (scoring.go:226): lower raw count is
    better: 100 * (max + min - s) / max; max == 0 -> 100; ignored -> 0."""
    live = mask & ~ignored
    mn = C.masked_min(scores, live)
    mx = C.masked_max(scores, live)
    ok = jnp.isfinite(mx) & (mx > 0)
    out = jnp.where(ok, MAX_NODE_SCORE * (mx + mn - scores)
                    / jnp.where(ok, mx, 1.0), MAX_NODE_SCORE)
    return jnp.where(ignored, 0.0, out)
