"""Shared device-side primitives for the plugin kernels.

All functions are pure jnp ops over the explicit node axis [N]; the pod axis
is added by vmap at the model level (models.pipeline). No Python control flow
on traced values anywhere — everything is masked arithmetic, which is what
lets XLA fuse the whole Filter/Score pipeline into a handful of TPU kernels.
"""

from __future__ import annotations

import jax.numpy as jnp

from kubernetes_tpu.utils.interner import NONE


def isin(value: jnp.ndarray, candidates: jnp.ndarray) -> jnp.ndarray:
    """value: [...]; candidates: [..., V] padded with NONE. True if value
    equals any non-NONE candidate."""
    v = value[..., None]
    return jnp.any((candidates == v) & (candidates != NONE), axis=-1)


def tolerations_tolerate(
    tol_valid: jnp.ndarray, tol_key: jnp.ndarray, tol_op: jnp.ndarray,
    tol_val: jnp.ndarray, tol_effect: jnp.ndarray,
    taint_key: jnp.ndarray, taint_val: jnp.ndarray, taint_effect: jnp.ndarray,
) -> jnp.ndarray:
    """For each taint slot, is it tolerated by any toleration?

    tol_*: [TO] (pod side); taint_*: [N, T] (node side). Returns [N, T] bool.
    Semantics: v1.Toleration.ToleratesTaint (api/core/v1/toleration.go).
    """
    from kubernetes_tpu.ops.features import TOL_EXISTS

    tk = taint_key[..., None]      # [N, T, 1]
    tv = taint_val[..., None]
    te = taint_effect[..., None]
    m_effect = (tol_effect == NONE) | (tol_effect == te)
    m_key = (tol_key == NONE) | (tol_key == tk)
    m_op = (tol_op == TOL_EXISTS) | (tol_val == tv)
    m = tol_valid & m_effect & m_key & m_op
    return jnp.any(m, axis=-1)
def masked_max(x: jnp.ndarray, mask: jnp.ndarray, axis=None) -> jnp.ndarray:
    return jnp.max(jnp.where(mask, x, -jnp.inf), axis=axis)


def masked_min(x: jnp.ndarray, mask: jnp.ndarray, axis=None) -> jnp.ndarray:
    return jnp.min(jnp.where(mask, x, jnp.inf), axis=axis)


def masked_argmax_random(score: jnp.ndarray, mask: jnp.ndarray,
                         perturb: jnp.ndarray) -> jnp.ndarray:
    """Tie-broken argmax: equal top scores pick uniformly via a pre-drawn
    perturbation in [0, 1) — the device analog of selectHost's reservoir
    sampling (schedule_one.go:865)."""
    s = jnp.where(mask, score, -jnp.inf)
    top = jnp.max(s)
    tie = mask & (s == top)
    pick = jnp.argmax(jnp.where(tie, perturb, -1.0))
    return jnp.where(jnp.any(mask), pick.astype(jnp.int32), jnp.int32(-1))
