"""InterPodAffinity + PodTopologySpread as topology-domain tensor kernels.

The reference computes per-pod PreFilter state by scanning all pods on all
nodes into `(topologyKey, topologyValue) -> count` hash maps
(interpodaffinity/filtering.go:204-272, podtopologyspread/filtering.go:235+)
and then does per-node map lookups. The TPU-native formulation replaces the
hash maps with dense per-topology-key domain arrays:

- every registered topology key tk has a compact domain-id space [0, D);
  a node's domain under tk is ``ct.topo_dom[n, tk]`` (NONE = label absent);
- "existing pod p affects all nodes in its domain" becomes a scatter of
  per-(pod-slot, term) matches into a ``[TK or A or C, D]`` map;
- "node n looks up its (key, value) pair" becomes a gather of that map at
  ``topo_dom[n, tk]``.

Scatter + gather over dense domain ids is exactly the XLA-friendly shape of
the reference's two-phase build/lookup — one launch, no hashing, vmappable
over the pod batch.

Reference semantics implemented here:
- interpodaffinity/filtering.go: satisfyExistingPodsAntiAffinity (:352),
  satisfyPodAntiAffinity (:367), satisfyPodAffinity (:382) including the
  first-pod-of-a-group rule.
- interpodaffinity/scoring.go: processExistingPod (:81-123) — incoming
  preferred terms both directions, existing pods' required terms at
  hardPodAffinityWeight, existing pods' preferred terms.
- podtopologyspread/filtering.go: skew = matchNum + selfMatchNum -
  minMatchNum > maxSkew (:311), minDomains (:300), node-inclusion policies.
- podtopologyspread/scoring.go: scoreForCount (:300) with
  topologyNormalizingWeight = log(size + 2) (:292).
"""

from __future__ import annotations

import jax.numpy as jnp

from kubernetes_tpu.ops import common as C
from kubernetes_tpu.ops.features import (  # noqa: F401  (IMPOSSIBLE re-export)
    IMPOSSIBLE,
    ClusterTensors,
    PodFeatures,
)
from kubernetes_tpu.utils.interner import NONE


def take_cols(table: jnp.ndarray, cols: jnp.ndarray, fill) -> jnp.ndarray:
    """table: [R, K]; cols: [...] i32 (NONE allowed). -> [R, *cols.shape]."""
    k = table.shape[1]
    safe = jnp.clip(cols, 0, k - 1)
    out = jnp.take(table, safe.reshape(-1), axis=1)
    out = out.reshape((table.shape[0],) + cols.shape)
    return jnp.where(cols[None] >= 0, out, fill)


def slot_topo_dom(ct: ClusterTensors) -> jnp.ndarray:
    """[PT, TK]: topology domain of each table pod's node per topo key.
    Shared across the whole batch — compute once per launch."""
    tds = ct.topo_dom[jnp.maximum(ct.pod_node, 0)]
    return jnp.where(ct.pod_valid[:, None], tds, NONE)


def incoming_terms_vs_table(ct: ClusterTensors, tk: jnp.ndarray,
                            ns: jnp.ndarray, sel_cols: jnp.ndarray,
                            sel_vals: jnp.ndarray) -> jnp.ndarray:
    """[PT, A]: does table pod s satisfy the incoming pod's term a?
    (term.Matches: s.ns in term.namespaces and selector matches s's labels)"""
    ns_ok = C.isin(ct.pod_ns[:, None], ns[None])               # [PT, A]
    tv = take_cols(ct.pt_label_vals, sel_cols, NONE)           # [PT, A, MS]
    used = sel_vals != NONE
    sel_ok = jnp.all((tv == sel_vals[None]) | ~used[None], axis=-1)
    return ns_ok & sel_ok & ct.pod_valid[:, None] & (tk[None] != NONE)


def table_terms_vs_incoming(ct: ClusterTensors, grp_tk: jnp.ndarray,
                            grp_ns: jnp.ndarray, grp_cols: jnp.ndarray,
                            grp_vals: jnp.ndarray,
                            pod: PodFeatures) -> jnp.ndarray:
    """[PT, A]: does the incoming pod satisfy table pod s's term a?"""
    ns_ok = jnp.any((grp_ns == pod.ns) & (grp_ns != NONE), axis=-1)  # [PT, A]
    kp = pod.plabel_vals.shape[0]
    pv = pod.plabel_vals[jnp.clip(grp_cols, 0, kp - 1)]        # [PT, A, MS]
    pv = jnp.where(grp_cols >= 0, pv, NONE)
    sel_ok = jnp.all((pv == grp_vals) | (grp_vals == NONE), axis=-1)
    return ns_ok & sel_ok & (grp_tk != NONE) & ct.pod_valid[:, None]


def scatter_or(tk2d: jnp.ndarray, dom2d: jnp.ndarray, hit2d: jnp.ndarray,
               num_rows: int, d_cap: int) -> jnp.ndarray:
    """[num_rows, d_cap] bool: OR of hits at (row=tk2d, col=dom2d)."""
    ok = hit2d & (tk2d != NONE) & (dom2d != NONE)
    flat = jnp.clip(tk2d, 0) * d_cap + jnp.clip(dom2d, 0)
    m = jnp.zeros((num_rows * d_cap,), bool)
    m = m.at[flat.reshape(-1)].max(ok.reshape(-1))
    return m.reshape(num_rows, d_cap)


def gather_rows(m: jnp.ndarray, dom: jnp.ndarray):
    """m: [R, D]; dom: [N, R] domain per node per row -> m[r, dom[n, r]]
    masked where dom is NONE (False/0)."""
    r = m.shape[0]
    vals = m[jnp.arange(r)[None, :], jnp.clip(dom, 0)]
    zero = jnp.zeros((), m.dtype)
    return jnp.where(dom != NONE, vals, zero)


# --------------------------- InterPodAffinity ---------------------------


def inter_pod_affinity_filter(ct: ClusterTensors, pod: PodFeatures,
                              tds: jnp.ndarray, d_cap: int) -> jnp.ndarray:
    """[N] accept mask for one pod (filtering.go Filter)."""
    tk_cap = ct.topo_dom.shape[1]

    # 1. existing pods' required anti-affinity vs incoming pod
    m1 = table_terms_vs_incoming(ct, ct.pod_anti_tk, ct.pod_anti_ns,
                                 ct.pod_anti_sel_cols, ct.pod_anti_sel_vals,
                                 pod)                              # [PT, A]
    dom1 = jnp.take_along_axis(tds, jnp.clip(ct.pod_anti_tk, 0, tk_cap - 1),
                               axis=1)
    dom1 = jnp.where(ct.pod_anti_tk != NONE, dom1, NONE)
    f1 = scatter_or(ct.pod_anti_tk, dom1, m1, tk_cap, d_cap)       # [TK, D]
    fail1 = jnp.any(gather_rows(f1, ct.topo_dom), axis=1)    # [N]

    # 2. incoming pod's required anti-affinity vs existing pods
    m2 = incoming_terms_vs_table(ct, pod.anti_tk, pod.anti_ns,
                                 pod.anti_sel_cols, pod.anti_sel_vals)
    dom2 = tds[:, jnp.clip(pod.anti_tk, 0, tk_cap - 1)]            # [PT, A]
    dom2 = jnp.where(pod.anti_tk[None] != NONE, dom2, NONE)
    tk2 = jnp.broadcast_to(pod.anti_tk[None], m2.shape)
    f2 = scatter_or(tk2, dom2, m2, tk_cap, d_cap)
    fail2 = jnp.any(gather_rows(f2, ct.topo_dom), axis=1)

    # 3. incoming pod's required affinity: every term needs a matching pod
    #    in the node's domain (node must carry every term's topology label)
    a_cap = pod.aff_tk.shape[0]
    m3 = incoming_terms_vs_table(ct, pod.aff_tk, pod.aff_ns,
                                 pod.aff_sel_cols, pod.aff_sel_vals)
    dom3 = tds[:, jnp.clip(pod.aff_tk, 0, tk_cap - 1)]             # [PT, A]
    dom3 = jnp.where(pod.aff_tk[None] != NONE, dom3, NONE)
    rows3 = jnp.broadcast_to(jnp.arange(a_cap)[None], m3.shape)
    present = scatter_or(rows3, dom3, m3, a_cap, d_cap)            # [A, D]
    term_used = pod.aff_tk != NONE                                 # [A]
    node_dom = take_cols(ct.topo_dom, pod.aff_tk, NONE)            # [N, A]
    has_lbl = node_dom != NONE
    cnt_ok = gather_rows(present, node_dom)                  # [N, A]
    term_ok = has_lbl & cnt_ok
    pods_exist = jnp.all(term_ok | ~term_used[None], axis=1)       # [N]
    all_lbl = jnp.all(has_lbl | ~term_used[None], axis=1)
    # first-pod-of-a-group: no term matched ANY existing pod anywhere, the
    # pod matches its own terms, and the node has all requested topologies
    any_match = jnp.any(m3 & (dom3 != NONE) & term_used[None])
    self_ok = pod.aff_self_match & ~any_match & all_lbl
    aff_ok = jnp.where(jnp.any(term_used), pods_exist | self_ok, True)

    return ~fail1 & ~fail2 & aff_ok


def inter_pod_affinity_score(ct: ClusterTensors, pod: PodFeatures,
                             tds: jnp.ndarray, d_cap: int,
                             hard_weight: jnp.ndarray) -> jnp.ndarray:
    """[N] raw score (scoring.go processExistingPod); normalized max-min at
    aggregation (NormalizeScore :258)."""
    tk_cap = ct.topo_dom.shape[1]
    score = jnp.zeros((tk_cap * d_cap,), jnp.float32)

    def add_incoming(score, tk, ns, cols, vals, w, sign):
        m = incoming_terms_vs_table(ct, tk, ns, cols, vals)        # [PT, A]
        dom = tds[:, jnp.clip(tk, 0, tk_cap - 1)]
        ok = m & (dom != NONE) & (tk[None] != NONE)
        flat = jnp.clip(tk[None], 0) * d_cap + jnp.clip(dom, 0)
        upd = jnp.where(ok, sign * w[None].astype(jnp.float32), 0.0)
        return score.at[flat.reshape(-1)].add(upd.reshape(-1))

    def add_table(score, tk, ns, cols, vals, w, sign):
        m = table_terms_vs_incoming(ct, tk, ns, cols, vals, pod)   # [PT, A]
        dom = jnp.take_along_axis(tds, jnp.clip(tk, 0, tk_cap - 1), axis=1)
        ok = m & (dom != NONE) & (tk != NONE)
        flat = jnp.clip(tk, 0) * d_cap + jnp.clip(dom, 0)
        upd = jnp.where(ok, sign * w.astype(jnp.float32), 0.0)
        return score.at[flat.reshape(-1)].add(upd.reshape(-1))

    score = add_incoming(score, pod.paff_tk, pod.paff_ns, pod.paff_sel_cols,
                         pod.paff_sel_vals, pod.paff_weight, 1.0)
    score = add_incoming(score, pod.panti_tk, pod.panti_ns,
                         pod.panti_sel_cols, pod.panti_sel_vals,
                         pod.panti_weight, -1.0)
    hw = jnp.broadcast_to(hard_weight, ct.pod_aff_tk.shape)
    score = add_table(score, ct.pod_aff_tk, ct.pod_aff_ns,
                      ct.pod_aff_sel_cols, ct.pod_aff_sel_vals, hw, 1.0)
    score = add_table(score, ct.pod_paff_tk, ct.pod_paff_ns,
                      ct.pod_paff_sel_cols, ct.pod_paff_sel_vals,
                      ct.pod_paff_weight, 1.0)
    score = add_table(score, ct.pod_panti_tk, ct.pod_panti_ns,
                      ct.pod_panti_sel_cols, ct.pod_panti_sel_vals,
                      ct.pod_panti_weight, -1.0)

    per_tk = gather_rows(score.reshape(tk_cap, d_cap), ct.topo_dom)
    return jnp.sum(per_tk, axis=1)                                 # [N]


# --------------------------- PodTopologySpread ---------------------------


def _tsc_self_match(pod: PodFeatures) -> jnp.ndarray:
    """[C]: does the pod match its own constraint selector? (selfMatchNum)"""
    kp = pod.plabel_vals.shape[0]
    pv = pod.plabel_vals[jnp.clip(pod.tsc_sel_cols, 0, kp - 1)]    # [C, MS]
    pv = jnp.where(pod.tsc_sel_cols >= 0, pv, NONE)
    return jnp.all((pv == pod.tsc_sel_vals) | (pod.tsc_sel_vals == NONE),
                   axis=-1)


def _tsc_matches(ct: ClusterTensors, pod: PodFeatures) -> jnp.ndarray:
    """[PT, C]: table pod s matches constraint c's selector in pod's ns."""
    ns_ok = ct.pod_ns[:, None] == pod.ns                           # [PT, 1]
    tv = take_cols(ct.pt_label_vals, pod.tsc_sel_cols, NONE)       # [PT, C, MS]
    used = pod.tsc_sel_vals != NONE
    sel_ok = jnp.all((tv == pod.tsc_sel_vals[None]) | ~used[None], axis=-1)
    return sel_ok & ns_ok & ct.pod_valid[:, None] & (pod.tsc_tk[None] != NONE)


def spread_eligible(ct: ClusterTensors, pod: PodFeatures,
                    nodeaff_ok: jnp.ndarray, taint_ok: jnp.ndarray,
                    consider: jnp.ndarray) -> jnp.ndarray:
    """[N, C] node-inclusion eligibility per constraint
    (matchNodeInclusionPolicies, common.go:33-127), plus the
    requireAllTopologies rule: a node missing ANY considered constraint's
    topology label is ignored entirely (filtering.go calPreFilterState).

    ``consider`` [C] selects the constraint set: the Filter path evaluates
    only DoNotSchedule constraints, the Score path only ScheduleAnyway —
    mixing them would let a soft constraint on an unlabeled key disable
    hard filtering."""
    node_dom = take_cols(ct.topo_dom, pod.tsc_tk, NONE)            # [N, C]
    all_topo = jnp.all((node_dom != NONE) | ~consider[None], axis=1)  # [N]
    base = ct.node_valid & all_topo                                # [N]
    ok = jnp.where(pod.tsc_honor_affinity[None], nodeaff_ok[:, None], True)
    ok = ok & jnp.where(pod.tsc_honor_taints[None], taint_ok[:, None], True)
    return base[:, None] & ok & consider[None]                     # [N, C]


def spread_filter(ct: ClusterTensors, pod: PodFeatures, tds: jnp.ndarray,
                  eligible: jnp.ndarray, d_cap: int) -> jnp.ndarray:
    """[N] accept mask for DoNotSchedule constraints (filtering.go:311)."""
    tk_cap = ct.topo_dom.shape[1]
    c_cap = pod.tsc_tk.shape[0]
    # counts: matching pods on ELIGIBLE nodes, per (constraint, domain)
    m = _tsc_matches(ct, pod)                                      # [PT, C]
    m = m & eligible[jnp.maximum(ct.pod_node, 0)]                  # [PT, C]
    dom = tds[:, jnp.clip(pod.tsc_tk, 0, tk_cap - 1)]              # [PT, C]
    dom = jnp.where(pod.tsc_tk[None] != NONE, dom, NONE)
    ok = m & (dom != NONE)
    flat = jnp.broadcast_to(jnp.arange(c_cap)[None], m.shape) * d_cap \
        + jnp.clip(dom, 0)
    cnt = jnp.zeros((c_cap * d_cap,), jnp.float32)
    cnt = cnt.at[flat.reshape(-1)].add(ok.reshape(-1).astype(jnp.float32))
    cnt = cnt.reshape(c_cap, d_cap)                                # [C, D]

    node_dom = take_cols(ct.topo_dom, pod.tsc_tk, NONE)            # [N, C]
    exists = scatter_or(jnp.broadcast_to(jnp.arange(c_cap)[None],
                                         node_dom.shape),
                        node_dom, eligible, c_cap, d_cap)          # [C, D]
    num_domains = jnp.sum(exists, axis=1)                          # [C]
    min_cnt = jnp.min(jnp.where(exists, cnt, jnp.inf), axis=1)     # [C]
    min_cnt = jnp.where(jnp.isfinite(min_cnt), min_cnt, 0.0)
    # minDomains: fewer eligible domains than required -> global min is 0
    min_cnt = jnp.where((pod.tsc_min_domains > 0)
                        & (num_domains < pod.tsc_min_domains), 0.0, min_cnt)

    self_m = _tsc_self_match(pod).astype(jnp.float32)              # [C]
    match_num = gather_rows(cnt, node_dom)                   # [N, C]
    skew = match_num + self_m[None] - min_cnt[None]
    used_hard = (pod.tsc_tk != NONE) & pod.tsc_hard                # [C]
    ok_c = (node_dom != NONE) & (skew <= pod.tsc_max_skew[None])
    return jnp.all(ok_c | ~used_hard[None], axis=1)                # [N]


def spread_score(ct: ClusterTensors, pod: PodFeatures, tds: jnp.ndarray,
                 eligible: jnp.ndarray, filtered: jnp.ndarray,
                 d_cap: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Raw spread score + ignored mask (scoring.go).

    score[n] = sum over SOFT constraints of
        cnt(domain of n) * log(topoSize + 2) + (maxSkew - 1)
    where topoSize counts domains among `filtered` nodes. Lower is better —
    normalized at aggregation as 100 * (max + min - s) / max, ignored -> 0.
    """
    tk_cap = ct.topo_dom.shape[1]
    c_cap = pod.tsc_tk.shape[0]
    used_soft = (pod.tsc_tk != NONE) & ~pod.tsc_hard               # [C]

    m = _tsc_matches(ct, pod) & eligible[jnp.maximum(ct.pod_node, 0)]
    dom = tds[:, jnp.clip(pod.tsc_tk, 0, tk_cap - 1)]              # [PT, C]
    dom = jnp.where(pod.tsc_tk[None] != NONE, dom, NONE)
    ok = m & (dom != NONE)
    flat = jnp.broadcast_to(jnp.arange(c_cap)[None], m.shape) * d_cap \
        + jnp.clip(dom, 0)
    cnt = jnp.zeros((c_cap * d_cap,), jnp.float32)
    cnt = cnt.at[flat.reshape(-1)].add(ok.reshape(-1).astype(jnp.float32))
    cnt = cnt.reshape(c_cap, d_cap)

    node_dom = take_cols(ct.topo_dom, pod.tsc_tk, NONE)            # [N, C]
    has = node_dom != NONE
    ignored = jnp.any(~has & used_soft[None], axis=1)              # [N]

    exists = scatter_or(jnp.broadcast_to(jnp.arange(c_cap)[None],
                                         node_dom.shape),
                        node_dom, filtered[:, None] & ~ignored[:, None],
                        c_cap, d_cap)                              # [C, D]
    topo_size = jnp.sum(exists, axis=1).astype(jnp.float32)        # [C]
    tp_weight = jnp.log(topo_size + 2.0)

    match_num = gather_rows(cnt, node_dom)                   # [N, C]
    per_c = match_num * tp_weight[None] \
        + (pod.tsc_max_skew[None].astype(jnp.float32) - 1.0)
    per_c = jnp.where(used_soft[None] & has, per_c, 0.0)
    return jnp.sum(per_c, axis=1), ignored
