"""InterPodAffinity + PodTopologySpread as topology-domain tensor kernels.

The reference computes per-pod PreFilter state by scanning all pods on all
nodes into `(topologyKey, topologyValue) -> count` hash maps
(interpodaffinity/filtering.go:204-272, podtopologyspread/filtering.go:235+)
and then does per-node map lookups. The TPU-native formulation replaces the
hash maps with dense per-topology-key domain arrays:

- every registered topology key tk has a compact domain-id space [0, D);
  a node's domain under tk is ``ct.topo_dom[n, tk]`` (NONE = label absent);
- "existing pod p affects all nodes in its domain" becomes a scatter of
  per-(pod-slot, term) matches into a ``[TK or A or C, D]`` map;
- "node n looks up its (key, value) pair" becomes a gather of that map at
  ``topo_dom[n, tk]``.

Scatter + gather over dense domain ids is exactly the XLA-friendly shape of
the reference's two-phase build/lookup — one launch, no hashing, vmappable
over the pod batch.

Reference semantics implemented here:
- interpodaffinity/filtering.go: satisfyExistingPodsAntiAffinity (:352),
  satisfyPodAntiAffinity (:367), satisfyPodAffinity (:382) including the
  first-pod-of-a-group rule.
- interpodaffinity/scoring.go: processExistingPod (:81-123) — incoming
  preferred terms both directions, existing pods' required terms at
  hardPodAffinityWeight, existing pods' preferred terms.
- podtopologyspread/filtering.go: skew = matchNum + selfMatchNum -
  minMatchNum > maxSkew (:311), minDomains (:300), node-inclusion policies.
- podtopologyspread/scoring.go: scoreForCount (:300) with
  topologyNormalizingWeight = log(size + 2) (:292).
"""

from __future__ import annotations

import jax.numpy as jnp

from kubernetes_tpu.ops import common as C
from kubernetes_tpu.ops.features import (  # noqa: F401  (IMPOSSIBLE re-export)
    IMPOSSIBLE,
    ClusterTensors,
    PodFeatures,
)
from kubernetes_tpu.utils.interner import NONE


def take_cols(table: jnp.ndarray, cols: jnp.ndarray, fill) -> jnp.ndarray:
    """table: [R, K]; cols: [...] i32 (NONE allowed). -> [R, *cols.shape]."""
    k = table.shape[1]
    safe = jnp.clip(cols, 0, k - 1)
    out = jnp.take(table, safe.reshape(-1), axis=1)
    out = out.reshape((table.shape[0],) + cols.shape)
    return jnp.where(cols[None] >= 0, out, fill)


def slot_topo_dom(ct: ClusterTensors) -> jnp.ndarray:
    """[PT, TK]: topology domain of each table pod's node per topo key.
    Shared across the whole batch — compute once per launch."""
    tds = ct.topo_dom[jnp.maximum(ct.pod_node, 0)]
    return jnp.where(ct.pod_valid[:, None], tds, NONE)


def sel_match(ops: jnp.ndarray, vals: jnp.ndarray,
              tgt_vals: jnp.ndarray) -> jnp.ndarray:
    """Full LabelSelector match over op-coded expressions.

    ops: [..., MS] (NONE = unused slot); vals: [..., MS, V]; tgt_vals:
    [..., MS] = target's label value gathered at each expression's column
    (NONE = label absent). Semantics follow apimachinery labels.Requirement:
    In = present & value in set; NotIn = !present | value not in set;
    Exists = present; DoesNotExist = !present; unknown op matches nothing.
    Returns [...] bool: AND over used expressions."""
    from kubernetes_tpu.ops.features import (
        OP_DOES_NOT_EXIST, OP_EXISTS, OP_IN, OP_NOT_IN)

    present = tgt_vals != NONE
    inin = present & C.isin(tgt_vals, vals)
    m = jnp.where(ops == OP_IN, inin,
        jnp.where(ops == OP_NOT_IN, ~inin,
        jnp.where(ops == OP_EXISTS, present,
        jnp.where(ops == OP_DOES_NOT_EXIST, ~present, False))))
    return jnp.all(m | (ops == NONE), axis=-1)


def table_mask(ct: ClusterTensors, pod: PodFeatures,
               include_nominated: bool) -> jnp.ndarray:
    """[PT]: which table pods count for this incoming pod. Always excludes
    the pod's own entry (incl. its own nomination); nominated pods count
    only for anti-affinity constraints, not for required-affinity presence,
    scoring, or spread counts (the dual-pass rule of
    RunFilterPluginsWithNominatedPods, runtime/framework.go:989)."""
    m = ct.pod_valid & (ct.pod_uid != pod.uid_id)
    if not include_nominated:
        m = m & ~ct.pod_nominated
    return m


def incoming_terms_vs_table(ct: ClusterTensors, tbl_ok: jnp.ndarray,
                            tk: jnp.ndarray,
                            ns: jnp.ndarray, ns_all: jnp.ndarray,
                            sel_cols: jnp.ndarray, sel_ops: jnp.ndarray,
                            sel_vals: jnp.ndarray) -> jnp.ndarray:
    """[PT, A]: does table pod s satisfy the incoming pod's term a?
    (AffinityTerm.Matches: s.ns in term.namespaces (or all-ns) and the
    selector expressions match s's labels). tbl_ok: [PT] from table_mask."""
    ns_ok = C.isin(ct.pod_ns[:, None], ns[None]) | ns_all[None]  # [PT, A]
    tv = take_cols(ct.pt_label_vals, sel_cols, NONE)           # [PT, A, MS]
    sel_ok = sel_match(sel_ops[None], sel_vals[None], tv)      # [PT, A]
    return ns_ok & sel_ok & tbl_ok[:, None] & (tk[None] != NONE)


def table_terms_vs_incoming(ct: ClusterTensors, tbl_ok: jnp.ndarray,
                            grp_tk: jnp.ndarray,
                            grp_ns: jnp.ndarray, grp_ns_all: jnp.ndarray,
                            grp_cols: jnp.ndarray, grp_ops: jnp.ndarray,
                            grp_vals: jnp.ndarray,
                            pod: PodFeatures) -> jnp.ndarray:
    """[PT, A]: does the incoming pod satisfy table pod s's term a?"""
    ns_ok = (jnp.any((grp_ns == pod.ns) & (grp_ns != NONE), axis=-1)
             | grp_ns_all)                                     # [PT, A]
    kp = pod.plabel_vals.shape[0]
    pv = pod.plabel_vals[jnp.clip(grp_cols, 0, kp - 1)]        # [PT, A, MS]
    pv = jnp.where(grp_cols >= 0, pv, NONE)
    sel_ok = sel_match(grp_ops, grp_vals, pv)                  # [PT, A]
    return ns_ok & sel_ok & (grp_tk != NONE) & tbl_ok[:, None]


def scatter_or(tk2d: jnp.ndarray, dom2d: jnp.ndarray, hit2d: jnp.ndarray,
               num_rows: int, d_cap: int) -> jnp.ndarray:
    """[num_rows, d_cap] bool: OR of hits at (row=tk2d, col=dom2d)."""
    ok = hit2d & (tk2d != NONE) & (dom2d != NONE)
    flat = jnp.clip(tk2d, 0) * d_cap + jnp.clip(dom2d, 0)
    m = jnp.zeros((num_rows * d_cap,), bool)
    m = m.at[flat.reshape(-1)].max(ok.reshape(-1))
    return m.reshape(num_rows, d_cap)


def gather_rows(m: jnp.ndarray, dom: jnp.ndarray):
    """m: [R, D]; dom: [N, R] domain per node per row -> m[r, dom[n, r]]
    masked where dom is NONE (False/0)."""
    r = m.shape[0]
    vals = m[jnp.arange(r)[None, :], jnp.clip(dom, 0)]
    zero = jnp.zeros((), m.dtype)
    return jnp.where(dom != NONE, vals, zero)


# ----------------- in-batch (committed pods) machinery -----------------
#
# The batched commit scan must preserve as-if-serial semantics: pod b has to
# see pods 0..b-1's placements exactly as the serial loop's assume step
# would provide (schedule_one.go:938). For the topology plugins that means
# pairwise GROUP<->GROUP term matches are precomputed OUTSIDE the scan
# (labels and terms don't depend on placement; pods dedup into groups,
# Mirror._batch_groups), and the scan folds each commit into small node-
# space carry maps with dense compares — see pipeline.map_updates. TPU
# scatters/gathers run ~100x below bandwidth, so nothing in the per-step
# path scatters or gathers by domain.


def pair_term_match(tk: jnp.ndarray, ns: jnp.ndarray, ns_all: jnp.ndarray,
                    cols: jnp.ndarray, ops: jnp.ndarray, vals: jnp.ndarray,
                    tgt_labels: jnp.ndarray, tgt_ns: jnp.ndarray,
                    tgt_valid: jnp.ndarray) -> jnp.ndarray:
    """[Bx, A, By]: does batch pod y satisfy batch pod x's term a?

    tk [Bx, A]; ns [Bx, A, NS]; ns_all [Bx, A]; cols/ops [Bx, A, MS];
    vals [Bx, A, MS, V]; tgt_labels [By, Kp]; tgt_ns/tgt_valid [By]."""
    kp = tgt_labels.shape[1]
    pv = tgt_labels.T[jnp.clip(cols, 0, kp - 1)]       # [Bx, A, MS, By]
    pv = jnp.where(cols[..., None] >= 0, pv, NONE)
    # move By before MS so sel_match reduces over its last-but-one layout:
    # [Bx, A, By, MS] vs vals broadcast [Bx, A, 1, MS, V]
    pv = jnp.moveaxis(pv, -1, -2)                       # [Bx, A, By, MS]
    sel_ok = sel_match(ops[..., None, :], vals[..., None, :, :], pv)
    ns_ok = (jnp.any((ns[..., :, None] == tgt_ns[None, None, None, :])
                     & (ns[..., :, None] != NONE), axis=2)
             | ns_all[..., None])                       # [Bx, A, By]
    return (ns_ok & sel_ok & (tk[..., None] != NONE)
            & tgt_valid[None, None, :])


def pair_tsc_match(pods: PodFeatures) -> jnp.ndarray:
    """[Bx, C, By]: does batch pod y match batch pod x's spread constraint c?
    (same namespace + selector expressions over y's labels)"""
    kp = pods.plabel_vals.shape[1]
    pv = pods.plabel_vals.T[jnp.clip(pods.tsc_sel_cols, 0, kp - 1)]
    pv = jnp.where(pods.tsc_sel_cols[..., None] >= 0, pv, NONE)
    pv = jnp.moveaxis(pv, -1, -2)                       # [Bx, C, By, MS]
    sel_ok = sel_match(pods.tsc_sel_ops[..., None, :],
                       pods.tsc_sel_vals[..., None, :, :], pv)
    ns_ok = pods.ns[:, None, None] == pods.ns[None, None, :]
    return (sel_ok & ns_ok & (pods.tsc_tk[..., None] != NONE)
            & pods.valid[None, None, :])






# --------------------------- InterPodAffinity ---------------------------


def inter_pod_affinity_static(ct: ClusterTensors, pod: PodFeatures,
                              tds: jnp.ndarray, d_cap: int):
    """Pre-batch-table part of the Filter (filtering.go): returns
    (anti_ok [N] — rules 1+2 vs the table, present [A, D] — affinity
    presence map from the table, any_match — scalar). The commit scan layers
    in-batch deltas on top (step_terms_forbid/step_own_terms_forbid/
    step_affinity_ok)."""
    tk_cap = ct.topo_dom.shape[1]
    anti_ok_tbl = table_mask(ct, pod, include_nominated=True)
    pres_tbl = table_mask(ct, pod, include_nominated=False)

    # 1. existing pods' required anti-affinity vs incoming pod
    m1 = table_terms_vs_incoming(ct, anti_ok_tbl, ct.pod_anti_tk,
                                 ct.pod_anti_ns,
                                 ct.pod_anti_ns_all, ct.pod_anti_sel_cols,
                                 ct.pod_anti_sel_ops, ct.pod_anti_sel_vals,
                                 pod)                              # [PT, A]
    dom1 = jnp.take_along_axis(tds, jnp.clip(ct.pod_anti_tk, 0, tk_cap - 1),
                               axis=1)
    dom1 = jnp.where(ct.pod_anti_tk != NONE, dom1, NONE)
    f1 = scatter_or(ct.pod_anti_tk, dom1, m1, tk_cap, d_cap)       # [TK, D]
    fail1 = jnp.any(gather_rows(f1, ct.topo_dom), axis=1)    # [N]

    # 2. incoming pod's required anti-affinity vs existing pods
    m2 = incoming_terms_vs_table(ct, anti_ok_tbl, pod.anti_tk, pod.anti_ns,
                                 pod.anti_ns_all, pod.anti_sel_cols,
                                 pod.anti_sel_ops, pod.anti_sel_vals)
    dom2 = tds[:, jnp.clip(pod.anti_tk, 0, tk_cap - 1)]            # [PT, A]
    dom2 = jnp.where(pod.anti_tk[None] != NONE, dom2, NONE)
    tk2 = jnp.broadcast_to(pod.anti_tk[None], m2.shape)
    f2 = scatter_or(tk2, dom2, m2, tk_cap, d_cap)
    fail2 = jnp.any(gather_rows(f2, ct.topo_dom), axis=1)

    # 3. incoming pod's required affinity: every term needs a matching pod
    #    in the node's domain (node must carry every term's topology label)
    a_cap = pod.aff_tk.shape[0]
    m3 = incoming_terms_vs_table(ct, pres_tbl, pod.aff_tk, pod.aff_ns,
                                 pod.aff_ns_all, pod.aff_sel_cols,
                                 pod.aff_sel_ops, pod.aff_sel_vals)
    dom3 = tds[:, jnp.clip(pod.aff_tk, 0, tk_cap - 1)]             # [PT, A]
    dom3 = jnp.where(pod.aff_tk[None] != NONE, dom3, NONE)
    rows3 = jnp.broadcast_to(jnp.arange(a_cap)[None], m3.shape)
    present = scatter_or(rows3, dom3, m3, a_cap, d_cap)            # [A, D]
    term_used = pod.aff_tk != NONE                                 # [A]
    any_match = jnp.any(m3 & (dom3 != NONE) & term_used[None])
    return ~fail1 & ~fail2, present, any_match


def inter_pod_affinity_score(ct: ClusterTensors, pod: PodFeatures,
                             tds: jnp.ndarray, d_cap: int,
                             hard_weight: jnp.ndarray) -> jnp.ndarray:
    """[N] raw score (scoring.go processExistingPod); normalized max-min at
    aggregation (NormalizeScore :258)."""
    tk_cap = ct.topo_dom.shape[1]
    score = jnp.zeros((tk_cap * d_cap,), jnp.float32)
    tbl_ok = table_mask(ct, pod, include_nominated=False)

    def add_incoming(score, tk, ns, ns_all, cols, ops, vals, w, sign):
        m = incoming_terms_vs_table(ct, tbl_ok, tk, ns, ns_all, cols, ops,
                                    vals)
        dom = tds[:, jnp.clip(tk, 0, tk_cap - 1)]
        ok = m & (dom != NONE) & (tk[None] != NONE)
        flat = jnp.clip(tk[None], 0) * d_cap + jnp.clip(dom, 0)
        upd = jnp.where(ok, sign * w[None].astype(jnp.float32), 0.0)
        return score.at[flat.reshape(-1)].add(upd.reshape(-1))

    def add_table(score, tk, ns, ns_all, cols, ops, vals, w, sign):
        m = table_terms_vs_incoming(ct, tbl_ok, tk, ns, ns_all, cols, ops,
                                    vals, pod)
        dom = jnp.take_along_axis(tds, jnp.clip(tk, 0, tk_cap - 1), axis=1)
        ok = m & (dom != NONE) & (tk != NONE)
        flat = jnp.clip(tk, 0) * d_cap + jnp.clip(dom, 0)
        upd = jnp.where(ok, sign * w.astype(jnp.float32), 0.0)
        return score.at[flat.reshape(-1)].add(upd.reshape(-1))

    score = add_incoming(score, pod.paff_tk, pod.paff_ns, pod.paff_ns_all,
                         pod.paff_sel_cols, pod.paff_sel_ops,
                         pod.paff_sel_vals, pod.paff_weight, 1.0)
    score = add_incoming(score, pod.panti_tk, pod.panti_ns, pod.panti_ns_all,
                         pod.panti_sel_cols, pod.panti_sel_ops,
                         pod.panti_sel_vals, pod.panti_weight, -1.0)
    hw = jnp.broadcast_to(hard_weight, ct.pod_aff_tk.shape)
    score = add_table(score, ct.pod_aff_tk, ct.pod_aff_ns, ct.pod_aff_ns_all,
                      ct.pod_aff_sel_cols, ct.pod_aff_sel_ops,
                      ct.pod_aff_sel_vals, hw, 1.0)
    score = add_table(score, ct.pod_paff_tk, ct.pod_paff_ns,
                      ct.pod_paff_ns_all, ct.pod_paff_sel_cols,
                      ct.pod_paff_sel_ops, ct.pod_paff_sel_vals,
                      ct.pod_paff_weight, 1.0)
    score = add_table(score, ct.pod_panti_tk, ct.pod_panti_ns,
                      ct.pod_panti_ns_all, ct.pod_panti_sel_cols,
                      ct.pod_panti_sel_ops, ct.pod_panti_sel_vals,
                      ct.pod_panti_weight, -1.0)

    per_tk = gather_rows(score.reshape(tk_cap, d_cap), ct.topo_dom)
    return jnp.sum(per_tk, axis=1)                                 # [N]


# --------------------------- PodTopologySpread ---------------------------


def _tsc_self_match(pod: PodFeatures) -> jnp.ndarray:
    """[C]: does the pod match its own constraint selector? (selfMatchNum)"""
    kp = pod.plabel_vals.shape[0]
    pv = pod.plabel_vals[jnp.clip(pod.tsc_sel_cols, 0, kp - 1)]    # [C, MS]
    pv = jnp.where(pod.tsc_sel_cols >= 0, pv, NONE)
    return sel_match(pod.tsc_sel_ops, pod.tsc_sel_vals, pv)


def _tsc_matches(ct: ClusterTensors, pod: PodFeatures) -> jnp.ndarray:
    """[PT, C]: table pod s matches constraint c's selector in pod's ns.
    Nominated pods and the pod's own entry are excluded from spread counts
    (shouldn't double-count itself; nominated pods may never run)."""
    ns_ok = ct.pod_ns[:, None] == pod.ns                           # [PT, 1]
    tv = take_cols(ct.pt_label_vals, pod.tsc_sel_cols, NONE)       # [PT, C, MS]
    sel_ok = sel_match(pod.tsc_sel_ops[None], pod.tsc_sel_vals[None], tv)
    tbl = table_mask(ct, pod, include_nominated=False)
    return sel_ok & ns_ok & tbl[:, None] & (pod.tsc_tk[None] != NONE)


def spread_eligible(ct: ClusterTensors, pod: PodFeatures,
                    nodeaff_ok: jnp.ndarray, taint_ok: jnp.ndarray,
                    consider: jnp.ndarray) -> jnp.ndarray:
    """[N, C] node-inclusion eligibility per constraint
    (matchNodeInclusionPolicies, common.go:33-127), plus the
    requireAllTopologies rule: a node missing ANY considered constraint's
    topology label is ignored entirely (filtering.go calPreFilterState).

    ``consider`` [C] selects the constraint set: the Filter path evaluates
    only DoNotSchedule constraints, the Score path only ScheduleAnyway —
    mixing them would let a soft constraint on an unlabeled key disable
    hard filtering."""
    node_dom = take_cols(ct.topo_dom, pod.tsc_tk, NONE)            # [N, C]
    all_topo = jnp.all((node_dom != NONE) | ~consider[None], axis=1)  # [N]
    base = ct.node_valid & all_topo                                # [N]
    ok = jnp.where(pod.tsc_honor_affinity[None], nodeaff_ok[:, None], True)
    ok = ok & jnp.where(pod.tsc_honor_taints[None], taint_ok[:, None], True)
    return base[:, None] & ok & consider[None]                     # [N, C]


def spread_cnt(ct: ClusterTensors, pod: PodFeatures, tds: jnp.ndarray,
               eligible: jnp.ndarray, d_cap: int) -> jnp.ndarray:
    """[C, D] f32: matching pods per (constraint, domain), counting only
    pods on nodes eligible for that constraint (TpPairToMatchNum)."""
    tk_cap = ct.topo_dom.shape[1]
    c_cap = pod.tsc_tk.shape[0]
    m = _tsc_matches(ct, pod)                                      # [PT, C]
    m = m & eligible[jnp.maximum(ct.pod_node, 0)]                  # [PT, C]
    dom = tds[:, jnp.clip(pod.tsc_tk, 0, tk_cap - 1)]              # [PT, C]
    dom = jnp.where(pod.tsc_tk[None] != NONE, dom, NONE)
    ok = m & (dom != NONE)
    flat = jnp.broadcast_to(jnp.arange(c_cap)[None], m.shape) * d_cap \
        + jnp.clip(dom, 0)
    cnt = jnp.zeros((c_cap * d_cap,), jnp.float32)
    cnt = cnt.at[flat.reshape(-1)].add(ok.reshape(-1).astype(jnp.float32))
    return cnt.reshape(c_cap, d_cap)


def spread_exists(ct: ClusterTensors, pod: PodFeatures,
                  node_mask: jnp.ndarray, d_cap: int) -> jnp.ndarray:
    """[C, D] bool: domains present among masked-in nodes per constraint.
    node_mask: [N, C]."""
    c_cap = pod.tsc_tk.shape[0]
    node_dom = take_cols(ct.topo_dom, pod.tsc_tk, NONE)            # [N, C]
    return scatter_or(jnp.broadcast_to(jnp.arange(c_cap)[None],
                                       node_dom.shape),
                      node_dom, node_mask, c_cap, d_cap)


