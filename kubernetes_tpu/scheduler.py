"""The Scheduler: event handlers + the batched scheduling loop.

Equivalent of /root/reference/pkg/scheduler/scheduler.go (Scheduler struct,
New, Run) + eventhandlers.go:366 (addAllEventHandlers) + the hot path of
schedule_one.go — with the per-pod serial cycle replaced by the batched
device pipeline: pop a BATCH from the activeQ, refresh the incremental HBM
mirror, run ONE fused filter+score+select launch for the whole batch
(as-if-serial commit scan on device), then assume/reserve/permit/bind each
winner on host and requeue the losers with plugin-attributed diagnoses.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.api.objects import (
    LABEL_POD_GROUP,
    Node,
    Pod,
    PodCondition,
    pod_group_key,
)
from kubernetes_tpu.backend.cache import Cache
from kubernetes_tpu.backend.jobqueue import JobQueue
from kubernetes_tpu.backend.mirror import (
    MI,
    CapacityError,
    Mirror,
)
from kubernetes_tpu.backend.nominator import Nominator
from kubernetes_tpu.backend.queue import PriorityQueue, QueuedPodInfo
from kubernetes_tpu.backend.snapshot import Snapshot
from kubernetes_tpu.framework.preemption import Evaluator
from kubernetes_tpu.config.types import (
    SchedulerConfiguration,
    default_config,
)
from kubernetes_tpu.framework.cycle_state import CycleState
from kubernetes_tpu.framework.interface import (
    ActionType,
    ClusterEvent,
    EventResource,
)
from kubernetes_tpu.framework.runtime import Framework
from kubernetes_tpu.framework.interface import Code
from kubernetes_tpu.framework.waiting import WaitingPod
from kubernetes_tpu import telemetry
from kubernetes_tpu.hub import EventHandlers, Fenced, Hub, Unavailable
from kubernetes_tpu.storage import RvTooOld
from kubernetes_tpu.utils.backoff import Backoff
from kubernetes_tpu.utils.gcguard import guard as gc_guard
from kubernetes_tpu.utils.tracing import FlightRecorder, PodTimelines
from kubernetes_tpu.models.pipeline import (
    ADAPTIVE_PCT,
    ALT_NONE,
    FILTER_PLUGINS,
    BatchResult,
    extract_state_jit,
    launch_batch,
    patch_chain,
    warm_patch_chain,
)
from kubernetes_tpu.metrics import AsyncRecorder, SchedulerMetrics
from kubernetes_tpu.ops.features import COL_PODS, Capacities

logger = logging.getLogger("kubernetes_tpu.scheduler")

# a scheduling cycle slower than this logs a phase-by-phase trace
# (schedule_one.go:404's 100ms slow-attempt threshold)
SLOW_CYCLE_SECONDS = 0.1

# outstanding chained launches in run_until_idle's software pipeline: 2 =
# commit batch k-1 while launches k and k+1 queue on the device, which
# hides the device wait entirely when host commit time ~ device time
PIPELINE_DEPTH = 2

# chain-surviving churn bounds: above CHAIN_PATCH_MAX pending patches a
# full resync is cheaper than the scatter (and the pow2 patch buckets are
# pre-warmed only up to this cap — see warm_patch_chain); after
# CHAIN_DELTA_RESYNC accumulated per-pod delta applications the chain is
# resynced once for float hygiene (per-pod rounded-up f32 requests only
# ever UNDERSTATE free, but a delete re-credits at most 1 ulp more than
# the add took for non-representable quantities — bound the drift)
CHAIN_PATCH_MAX = 256
CHAIN_DELTA_RESYNC = 100_000

# poison-pod quarantine: a pod in this many faulted batches (or raising
# in its own serial host-fallback evaluation) is parked out of the
# scheduling population with escalating backoff instead of wedging peers
QUARANTINE_STRIKES = 3
QUARANTINE_BASE_S = 5.0
QUARANTINE_CAP_S = 300.0


class DeviceFault(RuntimeError):
    """The fused device launch produced untrustworthy output (guard
    reduction tripped: NaN scores or a poisoned usage state). Raised by
    ``_finish`` before any commit; contained by the fallback ladder."""

A = ActionType
R = EventResource


def _node_update_action(old: Node, new: Node) -> ActionType:
    """Which parts of the node changed (eventhandlers.go nodeSchedulingPropertiesChange)."""
    action = ActionType(0)
    if old.metadata.labels != new.metadata.labels:
        action |= A.UPDATE_NODE_LABEL
    if old.spec.taints != new.spec.taints \
            or old.spec.unschedulable != new.spec.unschedulable:
        action |= A.UPDATE_NODE_TAINT
    if old.status.allocatable != new.status.allocatable:
        action |= A.UPDATE_NODE_ALLOCATABLE
    return action or A.UPDATE_NODE_CONDITION


class Scheduler:
    def __init__(self, hub: Hub,
                 config: Optional[SchedulerConfiguration] = None,
                 caps: Optional[Capacities] = None,
                 now=time.time, registry=None, mesh=None):
        self.hub = hub
        self.config = config or default_config()
        self.now = now
        profile = self.config.profiles[0]
        self._profile_name = profile.scheduler_name
        self.cache = Cache(now=now)
        self.snapshot = Snapshot()
        self.caps = caps or Capacities(
            nodes=self.config.node_capacity,
            pods=self.config.pod_table_capacity)
        # multi-chip: a jax.sharding.Mesh with a 'nodes' axis shards the
        # resident node table row-wise (SURVEY §5.7/§5.8); every device
        # launch this scheduler makes — batched pipeline, usage chain,
        # preemption sweeps — then runs SPMD over the mesh, placements
        # bit-identical to single-device (tests/test_multichip.py).
        self.mesh = mesh
        self.mirror = Mirror(caps=self.caps, mesh=mesh)
        # fencing: set by run()/start() when an elector gates the loop;
        # every bind/status-patch then carries the elector's epoch so a
        # deposed incarnation's in-flight writes are rejected (Fenced)
        self._elector = None
        # per-binder-thread fencing context: the epoch a bind carries is
        # captured when the bind is SUBMITTED, not when it executes — a
        # deposed-then-re-elected leader must not launder a stale
        # placement through its newer epoch
        self._bind_fence = threading.local()
        # chaos seam: a DeviceChaos (kubernetes_tpu.chaos) hooks the
        # pack/launch path here to provoke the fallback ladder under test
        self.fault_injector = None
        self.nominator = Nominator()
        self.preemption = Evaluator(
            hub, lambda: self.mirror, lambda: self.caps,
            self._filters_for, self.nominator)
        from kubernetes_tpu.plugins.dra import DynamicResources
        from kubernetes_tpu.plugins.gang import GangScheduling

        self._dra = DynamicResources(hub)
        # the gang coordinator is shared across profiles like the DRA
        # manager: quorum counting must see every profile's reservations
        self._gang = GangScheduling(hub=hub,
                                    mirror_fn=lambda: self.mirror,
                                    now=now)
        # the multi-tenant job-queue layer in front of the activeQ; pods
        # without tenant/gang labels never touch it (jobqueue.active
        # gates the per-cycle release step)
        self.jobqueue = JobQueue(self.config.tenants, now=now,
                                 bound_fn=self._gang.bound_count)
        extra = {"binder": self._fenced_bind, "hub": hub,
                 "preemption_evaluator": self.preemption,
                 # shared across profiles (SharedDRAManager analog): one
                 # assume overlay must see every profile's allocations
                 "dra_shared": self._dra,
                 "gang_shared": self._gang}
        # one resolved framework per profile (profile/profile.go:47 Map);
        # frameworkForPod routes each pod by spec.schedulerName
        self.frameworks = {
            p.scheduler_name: Framework(p, registry=registry,
                                        extra_args=extra)
            for p in self.config.profiles}
        self.framework = self.frameworks[profile.scheduler_name]
        # one shared queue: QueueSort must agree across profiles (the
        # reference validates this); PreEnqueue gates run through the POD's
        # profile, queueing-hint registrations merge across profiles
        merged_hints = {}
        for fw in self.frameworks.values():
            merged_hints.update(fw.events_to_register())
        if not self.config.gate("SchedulerQueueingHints"):
            # gate off: keep the event registrations but drop the hint fns
            # — any matching event requeues (pre-hints upstream behavior)
            from kubernetes_tpu.framework.interface import (
                ClusterEventWithHint,
            )

            merged_hints = {
                name: [ClusterEventWithHint(event=r.event) for r in regs]
                for name, regs in merged_hints.items()}
        self.queue = PriorityQueue(
            less_fn=self.framework.queue_sort_less,
            sort_key_fn=self.framework.queue_sort_key,
            pre_enqueue=lambda pod: self._fw_for(
                pod).run_pre_enqueue_plugins(pod),
            queueing_hints=merged_hints,
            initial_backoff=self.config.pod_initial_backoff_seconds,
            max_backoff=self.config.pod_max_backoff_seconds,
            now=now)
        for fw in self.frameworks.values():
            self._gang.register_waiting_map(fw.waiting_pods)
        self.metrics = SchedulerMetrics(
            pending_fn=self.queue.pending_counts)
        self._gang.metrics = self.metrics
        # fenced evictions/nomination-clears: the evaluator's queued hub
        # writes carry the epoch their flush runs under, so a deposed
        # leader's backlog is rejected instead of landing after failover
        self.preemption.fencing_fn = self._fencing_args
        self.preemption.fenced_metric = (
            lambda verb: self.metrics.fenced_writes.inc(verb=verb))
        # the always-on flight recorder: every cycle's fine-grained
        # phases into a bounded ring + the phase/plugin histograms
        # (utils/tracing.FlightRecorder); per-pod lifecycle timelines
        # behind /debug/pod. flight_recorder_capacity=0 disables.
        self.flight = FlightRecorder(
            phase_hist=self.metrics.phase_duration,
            plugin_hist=self.metrics.plugin_duration,
            capacity=getattr(self.config, "flight_recorder_capacity", 256),
            export_path=getattr(self.config, "trace_export_path", None),
            export_max_bytes=getattr(self.config,
                                     "trace_export_max_bytes", 0))
        self.timelines = PodTimelines(
            capacity=getattr(self.config, "timelines_capacity", 4096),
            now=now)
        # placement FEATURE export (the replay-training substrate) is
        # opt-in on top of the export itself: phase-timing export users
        # must not pay the feature kernels + extra D2H + line growth
        self._export_feats = (self.flight.exporting and getattr(
            self.config, "trace_export_features", False))
        # placement ALTERNATIVE export (top-K candidate node scores, the
        # regret counterfactual substrate): same opt-in discipline — it
        # compiles a [B, K] top_k into every launch and rides the
        # existing per-cycle pull
        self._export_alts = (self.flight.exporting and getattr(
            self.config, "trace_export_alts", False))
        self._last_pop_s = 0.0
        if self.flight.enabled:
            for fw in self.frameworks.values():
                fw.plugin_timer = self.flight.plugin_observe
        # the device-launch profiler (telemetry.profiler): XLA compiles
        # per bucket shape, recompile attribution to re-bucket churn,
        # per-shape walltime, live HBM buffer bytes. Rides the flight
        # recorder's enable switch — one observability budget.
        self.profiler = None
        if self.flight.enabled:
            from kubernetes_tpu.telemetry.profiler import DeviceProfiler

            self.profiler = DeviceProfiler(metrics=self.metrics, now=now)
        # optional fleet collector (telemetry.fleet.FleetView) attached
        # by the operator/harness; serving exposes /debug/fleet and the
        # merged /metrics/fleet exposition when set
        self.fleet = None
        # SLO watchdog + incident autopsy (telemetry/watchdog.py,
        # telemetry/autopsy.py): breach rules polled at the end of every
        # maintenance window; containment sites raise incidents directly
        # through telemetry.incident(). The watchdog always runs (a
        # handful of comparisons per window); black-box bundle capture
        # needs config.autopsy_dir.
        from kubernetes_tpu.telemetry.watchdog import Watchdog

        self.autopsy = None
        _autopsy_dir = getattr(self.config, "autopsy_dir", None)
        if _autopsy_dir:
            from kubernetes_tpu.telemetry.autopsy import AutopsyStore

            self.autopsy = AutopsyStore(
                _autopsy_dir,
                max_bundles=getattr(self.config,
                                    "autopsy_max_bundles", 32),
                max_bytes=getattr(self.config, "autopsy_max_bytes",
                                  16 * 1024 * 1024),
                rate_limit_s=getattr(self.config,
                                     "autopsy_rate_limit_s", 30.0),
                now=now, metrics=self.metrics)
        self.watchdog = Watchdog(
            self, store=self.autopsy,
            interval_s=getattr(self.config, "watchdog_interval_s", 5.0),
            now=now)
        # gate opener of last resort: a flush that deleted nothing (empty
        # or already-gone victim sets) fires no cluster event, so the
        # evaluator re-activates those preemptors directly
        self.preemption.activate_fn = self.queue.activate
        self.recorder = AsyncRecorder(now=now)
        self.preemption.metrics = self.metrics
        # per-profile launch configuration
        self._profile_cfg = {
            name: {"filters": fw.enabled_filters(),
                   "weights": fw.score_weights(),
                   "fit": fw.fit_scoring(),
                   # the batched fit-only preemption fast path is only
                   # semantics-preserving when DefaultPreemption is the
                   # profile's ONLY PostFilter plugin
                   "batch_preempt_ok": [n for n, _ in
                                        fw.points["post_filter"]]
                   == ["DefaultPreemption"],
                   # fused device DRA allocation only applies to profiles
                   # that enable the DynamicResources filter — a profile
                   # with it disabled must keep scheduling claim pods
                   # unfiltered, exactly as the host path did
                   "dra_filter": "DynamicResources" in {
                       n for n, _ in fw.points["filter"]},
                   # the profile-gated learned scorer's checkpoint
                   # manager (plugins/learned.py); None unless the
                   # profile enables the LearnedScore plugin — the
                   # launch then compiles the MLP term out entirely
                   "learned": fw.instance("LearnedScore"),
                   # device gang packing only engages for profiles that
                   # run the GangScheduling plugin at all — without it
                   # gang labels are inert and members are plain pods
                   "gang_plugin": any(
                       n == "GangScheduling"
                       for pt in ("filter", "permit")
                       for n, _ in fw.points[pt])}
            for name, fw in self.frameworks.items()}
        # device-side gang packing (ops/gang.pack_gangs): whole PodGroups
        # placed in one fused launch; off = every gang takes the host
        # Permit-quorum path (the differential-test arm)
        self._gang_device = bool(getattr(
            self.config, "gang_device_packing", True))
        # explicit tie-break seed (config) threaded into every launch as
        # a DYNAMIC scalar: paired A/B runs share a seed so placement
        # diffs attribute to the scorer, not the coin; 0 = historical
        self._tie_seed = np.uint32(
            getattr(self.config, "tie_break_seed", 0))
        self._enabled_filters = self.framework.enabled_filters()
        from kubernetes_tpu.extender import HTTPExtender

        self._extenders = [HTTPExtender(c) for c in self.config.extenders]
        # preemption candidates pass through ProcessPreemption
        # (preemption.go:335 callExtenders)
        self.preemption.extenders_fn = lambda: self._extenders
        self._has_host_filters = any(fw.has_host_filters()
                                     for fw in self.frameworks.values())
        gates = [fw.host_gates() for fw in self.frameworks.values()]
        self._host_gates = (None if any(g is None for g in gates)
                            else [g for gs in gates for g in gs])
        self._has_host_scores = any(fw.has_host_scores()
                                    for fw in self.frameworks.values())
        sgates = [fw.host_score_gates() for fw in self.frameworks.values()]
        self._host_score_gates = (None if any(g is None for g in sgates)
                                  else [g for gs in sgates for g in gs])
        # pods popped but deferred to a later batch (host-serial volume
        # conflicts — see _defer_host_conflicts); still in-flight queue-wise
        self._deferred: list[QueuedPodInfo] = []
        self.stats = {"scheduled": 0, "unschedulable": 0, "errors": 0,
                      "batches": 0, "attempts": 0,
                      "parked_unreachable": 0, "fenced": 0,
                      "device_fallbacks": 0, "quarantined": 0,
                      "drift_repairs": 0, "drift_full_lists": 0,
                      "drift_incremental": 0,
                      "gang_device_launches": 0, "gang_fallbacks": 0,
                      "slice_rebalances": 0, "foreign_stashed": 0,
                      "foreign_adopted": 0,
                      "brownout_enters": 0, "brownout_exits": 0,
                      "chain_patches": 0, "chain_patch_rows": 0,
                      "chain_patch_fallbacks": 0}
        # horizontal scale-out: when run() is handed a SliceManager the
        # replica drains only pods whose namespace (gang: the GROUP's
        # namespace) hashes into its owned ring slots. Everything else
        # waits in the foreign pen — cheap Pod refs, no queue/cache
        # residency — until a rebalance re-homes the slice here or the
        # true owner binds it. None = single-replica mode, zero filter.
        self._slices = None
        self._slice_gen = -1
        self._foreign: dict[str, Pod] = {}
        # poison-pod quarantine: uid -> {"qp", "until", "reason"};
        # strike/quarantine counts survive release so a re-offender's
        # backoff keeps escalating
        self._quarantine: dict[str, dict] = {}
        self._fault_strikes: dict[str, int] = {}
        self._quarantine_counts: dict[str, int] = {}
        # drift sentinel cadence (0 disables); strikes gate the
        # full-rebuild last resort. _drift_rv is the journal revision
        # the last report was consistent at: steady-state passes diff
        # O(changes) after it instead of re-LISTing the cluster, and
        # fall back to the full diff only on RvTooOld (compacted gap)
        self.drift_check_interval = 30.0
        self._last_drift_check = 0.0
        self._drift_strikes = 0
        self._drift_rv: int | None = None
        # scheduler brownout (overload self-protection): a sustained run
        # of hub flow-control rejections (429s — the hub's queue-wait
        # SLO breaches surface as rejected-timeout 429s through the same
        # counter) trips a load-shedding mode: the effective batch
        # shrinks, the drift sentinel stretches its cadence, and
        # best-effort tenants park in the jobqueue. Exits after
        # brownout_clear_windows consecutive clean ~1s windows.
        self.brownout = False
        self._brownout_clean = 0
        self._brownout_throttled_seen = 0.0
        self._last_brownout_eval = 0.0
        self._drift_interval_base: float | None = None
        # degraded mode: the hub is unreachable (transport Unavailable).
        # Work parks with backoff instead of erroring; assumed pods are
        # preserved (their confirm events cannot arrive); the informer's
        # relist diff re-converges everything after reconnect.
        self._hub_down = False
        # expired assumed pods awaiting their requeue check (the hub may
        # be unreachable when they expire; see _drain_assumed_requeue)
        self._assumed_requeue: list[Pod] = []
        # device-resident (free, nonzero_requested) chain: the post-launch
        # usage state of the NEWEST dispatched launch. While no external
        # event has touched the cluster state, the next no-topology batch can
        # launch against this chain WITHOUT a host snapshot/mirror re-sync —
        # the batched analog of the cache staying hot between cycles
        # (cache.go:361 assume). Any event not caused by our own commits
        # invalidates it (set to None) and forces a full re-sync.
        self._chain: Optional[tuple] = None
        self._chain_epoch = 0
        # pipelined scheduling waves (config.pipelined_waves): chain
        # patching + off-thread commit + immediate preemptor re-dispatch.
        # Off = the strict-alternation differential arm.
        self._pipelined = bool(getattr(config, "pipelined_waves", True))
        # chain-surviving churn bookkeeping. Instead of invalidating the
        # device chain on every informer event, handlers register the
        # event's EFFECT and the next dispatch scatters it into the chain
        # (models/pipeline.patch_chain): _chain_dirty names nodes whose
        # row must be absolutely repacked from the live cache (node
        # add/update/delete — applied after in-flight waves flush, the
        # conservative form of "touched node intersects an in-flight
        # wave's packed set"); _chain_deltas accumulates commutative
        # (d_free, d_nzr) per node from foreign pod binds/deletes —
        # deltas compose with in-flight device commits in either order,
        # so they need NO flush. Both clear on invalidate and right
        # after a full mirror sync (which subsumes them).
        self._chain_dirty: set[str] = set()
        self._chain_deltas: dict[str, list[np.ndarray]] = {}
        self._chain_delta_count = 0
        self._patch_warmed = False
        # off-thread commit: wave N's blocking D2H pull runs on this
        # one-thread pool so it overlaps wave N+1's device time. The
        # commit thread does ONLY jax.device_get (+ the chaos seam) —
        # host mutation (assume/bind/queue/timeline) stays on the
        # single-mutator loop thread, preserving the _wrap threading
        # model; exceptions surface in _finish via fut.result() and ride
        # the existing _finish_contained blast-radius ladder.
        self._commit_pool: Optional[ThreadPoolExecutor] = (
            ThreadPoolExecutor(max_workers=1, thread_name_prefix="commit")
            if self._pipelined else None)
        # preemptor re-probes ride the next wave: after an eviction flush
        # fires, nominated reservations already protect the slots, so the
        # evaluator re-activates the flushed preemptors immediately
        # instead of letting them wait out backoff until victim-deletion
        # events land (framework/preemption.Evaluator.flush_evictions)
        self.preemption.activate_flushed = self._pipelined
        # preemption dry-runs read the LIVE chain when one exists: under
        # pipelining the mirror's host free matrix lags by the in-flight
        # waves, and a dry-run against it would over-evict
        self.preemption.live_free_fn = (
            lambda: self._chain[0] if (self._pipelined
                                       and self._chain is not None)
            else None)
        # percentageOfNodesToScore rotating offset, persisted across
        # launches (schedule_one.go:620 nextStartNodeIndex); device scalar
        self._pct_start = None
        # threading model: ONE mutator thread at a time. The coarse lock
        # serializes the scheduling loop against event handlers invoked from
        # foreign threads; the binder pool's own hub writes dispatch events
        # back into _deferred_events instead (processed on the loop thread),
        # so waiting on a bind future while holding the lock cannot deadlock.
        self._lock = threading.RLock()
        self._binder: Optional[ThreadPoolExecutor] = None
        self._binder_tids: set[int] = set()
        if self.config.async_binding:
            self._binder = ThreadPoolExecutor(
                max_workers=self.config.binding_workers,
                thread_name_prefix="binder",
                initializer=lambda: self._binder_tids.add(
                    threading.get_ident()))
        self._inflight_binds: list[tuple] = []
        self._bind_backlog: list[tuple] = []
        self._pod_rv: dict[str, int] = {}   # newest applied pod revision
        self._rv_tombstones: deque = deque()
        self._deferred_events: deque = deque()
        self._last_backoff_flush = 0.0
        self._last_unsched_flush = 0.0
        # mirrored-counter watermarks: external monotonic counts (hub
        # client watch resumes/relists, DRA CEL errors) flow into the
        # registry's true Counters by DELTA
        self._mirrored_counts: dict[str, float] = {}
        self._last_journal_mirror = 0.0
        self._daemon: Optional[threading.Thread] = None
        self._stop: Optional[threading.Event] = None
        self._register_handlers()

    # ------------- event handlers (eventhandlers.go:366) -------------

    def _wrap(self, fn):
        """Route events raised by the binder pool's own API writes to the
        deferred queue (replayed on the loop thread); for every other
        caller, apply inline under the scheduler lock when it's free
        and defer when it's contended. Blocking on a contended lock
        here deadlocks scale-out: two in-process replicas share one
        hub, so replica A's bind delivers this event on a thread that
        sits inside A's locked drain while OUR loop holds our lock
        delivering into A — both hands full, neither lets go. The
        deferred queue replays on the loop thread either way; per-pod
        rv dedup absorbs the cross-thread reordering this admits."""
        def handler(*args):
            if threading.get_ident() in self._binder_tids:
                self._deferred_events.append((fn, args))
                return
            if self._lock.acquire(blocking=False):
                try:
                    fn(*args)
                finally:
                    self._lock.release()
            else:
                self._deferred_events.append((fn, args))
        return handler

    def _process_deferred_events(self) -> None:
        while self._deferred_events:
            fn, args = self._deferred_events.popleft()
            fn(*args)

    def _pod_event_stale(self, pod: Pod) -> bool:
        """Hub dispatch happens outside the hub lock, so two threads'
        events for one pod can arrive out of commit order (the binder's
        deferred bind-update vs the loop's own later patch). Drop any
        event older than the newest revision already applied."""
        uid = pod.metadata.uid
        rv = pod.metadata.resource_version
        if rv <= self._pod_rv.get(uid, -1):
            return True
        self._pod_rv[uid] = rv
        return False

    def _register_handlers(self) -> None:
        w = self._wrap
        self.hub.watch_nodes(EventHandlers(
            on_add=w(self._on_node_add),
            on_update=w(self._on_node_update),
            on_delete=w(self._on_node_delete)))
        # pods ride the on_event shape: the full JournalEvent carries
        # the commit's TraceContext, which the timeline join needs (the
        # typed trio would drop it); dedup/relist-diff still apply
        # upstream on both transports
        self.hub.watch_pods(EventHandlers(
            on_event=w(self._on_pod_event)))
        self.hub.watch_namespaces(EventHandlers(
            on_add=w(self._on_ns_set),
            on_update=w(lambda old, new: self._on_ns_set(new)),
            on_delete=w(self._on_ns_delete)))
        # volume objects: pure requeue signals (no device state involved)
        self.hub.watch_pvcs(EventHandlers(
            on_add=w(lambda o: self.queue.move_all_to_active_or_backoff(
                ClusterEvent(R.PVC, A.ADD), None, o)),
            on_update=w(lambda old, new:
                        self.queue.move_all_to_active_or_backoff(
                            ClusterEvent(R.PVC, A.UPDATE), old, new))))
        self.hub.watch_resource_slices(EventHandlers(
            on_add=w(lambda o: self.queue.move_all_to_active_or_backoff(
                ClusterEvent(R.RESOURCE_SLICE, A.ADD), None, o)),
            on_delete=w(lambda o: self.queue.move_all_to_active_or_backoff(
                ClusterEvent(R.RESOURCE_SLICE, A.DELETE), o, None))))
        self.hub.watch_resource_claims(EventHandlers(
            on_add=w(lambda o: self.queue.move_all_to_active_or_backoff(
                ClusterEvent(R.RESOURCE_CLAIM, A.ADD), None, o)),
            on_update=w(lambda old, new:
                        self.queue.move_all_to_active_or_backoff(
                            ClusterEvent(R.RESOURCE_CLAIM, A.UPDATE),
                            old, new)),
            on_delete=w(lambda o: self.queue.move_all_to_active_or_backoff(
                ClusterEvent(R.RESOURCE_CLAIM, A.DELETE), o, None))))
        self.hub.watch_pvs(EventHandlers(
            on_add=w(lambda o: self.queue.move_all_to_active_or_backoff(
                ClusterEvent(R.PV, A.ADD), None, o)),
            on_update=w(lambda old, new:
                        self.queue.move_all_to_active_or_backoff(
                            ClusterEvent(R.PV, A.UPDATE), old, new))))
        self.hub.watch_csi_capacities(EventHandlers(
            on_add=w(lambda o: self.queue.move_all_to_active_or_backoff(
                ClusterEvent(R.CSI_STORAGE_CAPACITY, A.ADD), None, o)),
            on_update=w(lambda old, new:
                        self.queue.move_all_to_active_or_backoff(
                            ClusterEvent(R.CSI_STORAGE_CAPACITY, A.UPDATE),
                            old, new))))
        self.hub.watch_pod_groups(EventHandlers(
            on_add=w(lambda g: self._on_group_set(g, A.ADD)),
            on_update=w(lambda old, new: self._on_group_set(new, A.UPDATE)),
            on_delete=w(self._on_group_delete)))

    def _on_group_set(self, group, action) -> None:
        """A PodGroup arrived/changed: the job queue may now release its
        orphaned members, the gang coordinator refreshes min_member and
        timeout, and parked members get a requeue chance."""
        self.jobqueue.set_group(group)
        self._gang.set_group(group)
        self.queue.move_all_to_active_or_backoff(
            ClusterEvent(R.POD_GROUP, action), None, group)

    def _on_group_delete(self, group) -> None:
        self.jobqueue.remove_group(group.key())
        self._gang.remove_group(group.key())

    def _invalidate_chain(self) -> None:
        """Drop the device-resident usage chain and bump the epoch so a
        dispatch that raced with the invalidation (e.g. a bind failure
        drained while packing) does not re-install a stale chain. Pending
        chain patches die with the chain — the full resync that follows
        subsumes them."""
        self._chain = None
        self._chain_epoch += 1
        self._chain_dirty.clear()
        self._chain_deltas.clear()

    def _chain_note_node(self, name: str) -> None:
        """A node add/update/delete touched the cluster: instead of
        invalidating the chain, mark the node's row for an absolute
        repack from the live cache at next dispatch (chain-surviving
        churn). Falls back to whole-chain invalidation when pipelining is
        off, no chain exists, or the pending patch set outgrows the
        pre-warmed scatter buckets (a resync is cheaper then anyway)."""
        if not self._pipelined or self._chain is None:
            self._invalidate_chain()
            return
        # an absolute repack includes every pod on the node — pending
        # deltas for it are subsumed
        self._chain_deltas.pop(name, None)
        self._chain_dirty.add(name)
        if len(self._chain_dirty) + len(self._chain_deltas) \
                > CHAIN_PATCH_MAX:
            self.stats["chain_patch_fallbacks"] += 1
            self._invalidate_chain()

    def _chain_note_pod(self, pod: Pod, sign: int) -> None:
        """A FOREIGN bound pod appeared (+1) or vanished (-1): accumulate
        its request as a commutative (free, nzr) delta against its node's
        chain row. Deltas compose with in-flight waves' device commits in
        either order (the chain already carries every dispatched commit),
        so unlike node repacks they apply without a pipeline flush. Pods
        with host ports route to the absolute-repack path instead: the
        mirror's port columns must move with them, and a row repack is
        the only operation that does that."""
        if not self._pipelined or self._chain is None:
            self._invalidate_chain()
            return
        node = pod.spec.node_name
        if node in self._chain_dirty:
            return                    # repack at apply time covers it
        from kubernetes_tpu.api.resources import pod_request

        if self.mirror.batch_has_host_ports([pod]):
            self._chain_note_node(node)
            return
        try:
            row = self.mirror._res_row(pod_request(pod)).copy()
        except CapacityError:
            self._invalidate_chain()
            return
        row[COL_PODS] = 1.0
        nz = pod_request(pod, non_zero=True)
        acc = self._chain_deltas.get(node)
        if acc is None:
            acc = self._chain_deltas[node] = [
                np.zeros_like(row), np.zeros((2,), np.float32)]
        # free MOVES OPPOSITE the pod: an added pod consumes its request
        acc[0] -= np.float32(sign) * row
        acc[1] += np.float32(sign) * np.asarray(
            [nz.milli_cpu, nz.memory / MI], np.float32)
        self._chain_delta_count += 1
        if len(self._chain_dirty) + len(self._chain_deltas) \
                > CHAIN_PATCH_MAX \
                or self._chain_delta_count > CHAIN_DELTA_RESYNC:
            self.stats["chain_patch_fallbacks"] += 1
            self._chain_delta_count = 0
            self._invalidate_chain()

    def _on_ns_set(self, ns) -> None:
        self._invalidate_chain()
        self.cache.set_namespace(ns.metadata.name, ns.metadata.labels)

    def _on_ns_delete(self, ns) -> None:
        self._invalidate_chain()
        self.cache.remove_namespace(ns.metadata.name)

    def _on_node_add(self, node: Node) -> None:
        self._chain_note_node(node.metadata.name)
        self.cache.add_node(node)
        self.queue.move_all_to_active_or_backoff(
            ClusterEvent(R.NODE, A.ADD), None, node)

    def _on_node_update(self, old: Node, new: Node) -> None:
        self._chain_note_node(new.metadata.name)
        if old.metadata.name != new.metadata.name:
            self._chain_note_node(old.metadata.name)
        self.cache.update_node(old, new)
        self.queue.move_all_to_active_or_backoff(
            ClusterEvent(R.NODE, _node_update_action(old, new)), old, new)

    def _on_node_delete(self, node: Node) -> None:
        self._chain_note_node(node.metadata.name)
        self.cache.remove_node(node)
        self.queue.move_all_to_active_or_backoff(
            ClusterEvent(R.NODE, A.DELETE), node, None)

    @staticmethod
    def _terminal(pod: Pod) -> bool:
        return pod.status.phase in ("Succeeded", "Failed")

    def _filters_for(self, pod: Pod | None = None) -> tuple[bool, ...]:
        """Enabled device-filter slots for the pod's profile (the
        preemption dry-run must see the same filter set the pod's own
        scheduling cycle uses)."""
        if pod is not None:
            cfg = self._profile_cfg.get(pod.spec.scheduler_name)
            if cfg is not None:
                return cfg["filters"]
        return self._enabled_filters

    def _fw_for(self, pod: Pod) -> Framework:
        """frameworkForPod (schedule_one.go:371): by spec.schedulerName."""
        return self.frameworks.get(pod.spec.scheduler_name, self.framework)

    def _ours(self, pod: Pod) -> bool:
        return pod.spec.scheduler_name in self.frameworks

    def _owns_pod(self, pod: Pod) -> bool:
        """Scale-out slice filter: does this replica's owned ring slice
        cover the pod? Single-replica mode (no SliceManager) owns
        everything. Gang members hash by their GROUP's namespace —
        ``pod_group_key`` is ``namespace/name``, and members share the
        group's namespace — so a gang can never straddle replicas."""
        sm = self._slices
        if sm is None:
            return True
        gang = pod_group_key(pod)
        ns = (gang.split("/", 1)[0] if gang is not None
              else pod.metadata.namespace)
        return sm.owns_namespace(ns)

    def _stash_foreign(self, pod: Pod) -> None:
        """Pen a pending pod another replica owns: dropped from our
        queues (it may have been ours before a rebalance), kept as a
        bare Pod ref so a later rebalance can adopt it without a
        relist. The pen self-cleans on bind/delete events."""
        uid = pod.metadata.uid
        self._foreign[uid] = pod
        self.queue.delete(pod)
        self.nominator.delete(uid)
        if self.jobqueue.active and self.jobqueue.holds(uid):
            self.jobqueue.remove(pod)
        self.stats["foreign_stashed"] += 1

    def _quarantine_holds(self, pod: Pod) -> bool:
        """A quarantined pod must not re-enter the queue through an
        informer add/update — a controller status patch or relist replay
        would otherwise reset its escalating backoff. The release path
        re-fetches hub truth, so nothing else to track here."""
        return pod.metadata.uid in self._quarantine

    def _enqueue_fresh(self, pod: Pod) -> None:
        """Route a pending pod to its queue: tenant/gang pods go through
        the job-queue layer (DRR + quota + gang gating), everything else
        straight to the activeQ — two dict probes for plain pods."""
        if self.jobqueue.wants(pod) \
                and not self.jobqueue.was_admitted(pod.metadata.uid):
            self.jobqueue.add(pod)
        else:
            self.queue.add(pod)

    def _note_bound_pod(self, pod: Pod) -> None:
        """Bound-pod observation for the gang/tenant bookkeeping (quorum
        counting across failover, quota replay after restart)."""
        if LABEL_POD_GROUP in pod.metadata.labels:
            self._gang.note_bound(pod)
        if self.jobqueue.wants(pod):
            self.jobqueue.remove(pod)       # no longer queued here
            self.jobqueue.note_bound(pod)

    def _on_pod_event(self, ev) -> None:
        """Pod watch dispatch (JournalEvent-shaped): join the commit's
        wire trace stamp into the pod timeline, then run the typed
        handler. Events without a stamp (LIST replays, pre-telemetry
        peers) flow identically — hop data degrades, never the event."""
        if ev.type == "delete":
            self._on_pod_delete(ev.old)
            return
        if self.flight.enabled:
            self._stamp_wire_trace(ev)
        if ev.type == "add":
            self._on_pod_add(ev.new)
        else:
            self._on_pod_update(ev.old, ev.new)

    def _stamp_wire_trace(self, ev) -> None:
        """The cross-wire timeline join (telemetry.trace): ``created``
        from the pod's add commit, ``bound`` from the bind commit,
        ``acked`` from the kubelet's status-Running commit, and
        ``kubelet_recv`` from the ack's trace-baggage annotation (the
        bound event's arrival stamp after its relay hops) — one
        end-to-end hub -> relay -> scheduler -> bind -> ack timeline
        per pod, served at /debug/pod."""
        from kubernetes_tpu.telemetry.trace import (
            ACK_TRACE_ANNOTATION,
            parse_ack_trace,
        )

        pod, tr, tl = ev.new, ev.trace, self.timelines
        if not self._ours(pod):
            return
        if ev.type == "add":
            if tr is not None and not pod.spec.node_name:
                tl.wire_stamp(pod, "created", tr.ts, tr.origin, tr.hops)
            return
        old = ev.old
        if tr is not None and pod.spec.node_name \
                and (old is None or not old.spec.node_name):
            tl.wire_stamp(pod, "bound", tr.ts, tr.origin, tr.hops)
        if pod.status.phase == "Running" \
                and (old is None or old.status.phase != "Running"):
            if tr is not None:
                tl.wire_stamp(pod, "acked", tr.ts, tr.origin, tr.hops)
            baggage = pod.metadata.annotations.get(ACK_TRACE_ANNOTATION)
            if baggage:
                bt = parse_ack_trace(baggage)
                if bt is not None:
                    tl.wire_stamp(pod, "kubelet_recv", bt.ts,
                                  bt.origin, bt.hops)

    def _on_pod_add(self, pod: Pod) -> None:
        if self._pod_event_stale(pod):
            return
        if pod.spec.node_name:
            self._foreign.pop(pod.metadata.uid, None)
            if not self.cache.is_assumed_pod(pod):
                # a pod WE placed is already in the chain (its launch
                # committed it on device); only foreign binds move it
                self._chain_note_pod(pod, +1)
            self.cache.add_pod(pod)
            self._note_bound_pod(pod)
            self.queue.move_all_to_active_or_backoff(
                ClusterEvent(R.ASSIGNED_POD, A.ADD), None, pod)
        elif not self._terminal(pod) and self._ours(pod) \
                and not self._quarantine_holds(pod):
            # foreign schedulerName pods are another scheduler's business
            # (schedule_one.go:371); foreign SLICE pods belong to a peer
            # replica — penned, not queued
            if not self._owns_pod(pod):
                self._stash_foreign(pod)
                return
            # restart/replay: re-seed nominations from status so
            # reservations survive a scheduler restart
            if pod.status.nominated_node_name:
                self.nominator.add(pod, pod.status.nominated_node_name)
            if self.flight.enabled:
                self.timelines.event(pod, "enqueued")
            self._enqueue_fresh(pod)

    def _on_pod_update(self, old: Pod, new: Pod) -> None:
        if self._pod_event_stale(new):
            return
        if new.spec.node_name:
            self._foreign.pop(new.metadata.uid, None)
            if not self.cache.is_assumed_pod(new):
                if old.spec.node_name:
                    # bound-pod mutation: the chain moves by the request
                    # DIFFERENCE (labels-only updates cancel to zero)
                    self._chain_note_pod(old, -1)
                    self._chain_note_pod(new, +1)
                else:
                    self._chain_note_pod(new, +1)
            self.nominator.delete(new.metadata.uid)
            if old.spec.node_name:
                self.cache.update_pod(old, new)
                action = (A.UPDATE_POD_LABEL
                          if old.metadata.labels != new.metadata.labels
                          else A.UPDATE_POD_SCALE_DOWN)
                self.queue.move_all_to_active_or_backoff(
                    ClusterEvent(R.ASSIGNED_POD, action), old, new)
            else:
                # freshly bound (possibly by us): informer truth confirms
                self.cache.add_pod(new)
                self.queue.delete(new)
                self._note_bound_pod(new)
                self.queue.move_all_to_active_or_backoff(
                    ClusterEvent(R.ASSIGNED_POD, A.ADD), old, new)
        elif not self._terminal(new) and self._ours(new) \
                and not self._quarantine_holds(new):
            if not self._owns_pod(new):
                self._stash_foreign(new)
                return
            if new.metadata.uid in self._foreign:
                # adopted by an update that arrived after a rebalance
                # made the pod ours (label change re-hashing its gang,
                # or a pen refresh): queue it like a fresh add
                del self._foreign[new.metadata.uid]
                self.stats["foreign_adopted"] += 1
                self._enqueue_fresh(new)
                return
            self.nominator.update(new)
            if self.jobqueue.active \
                    and self.jobqueue.holds(new.metadata.uid):
                self.jobqueue.update(new)
            else:
                self.queue.update(old, new)

    def _on_pod_delete(self, pod: Pod) -> None:
        # deletes always win: tombstone at max rv so a straggling update
        # for the dead pod can't resurrect it in the cache; tombstones age
        # out of a bounded FIFO instead of a wholesale clear
        uid = pod.metadata.uid
        self._foreign.pop(uid, None)
        was_quarantined = self._quarantine.pop(uid, None) is not None
        self._fault_strikes.pop(uid, None)
        self._quarantine_counts.pop(uid, None)
        if self.jobqueue.active and self.jobqueue.wants(pod):
            # credit the tenant's quota reservation; drop queued copies
            self.jobqueue.remove(pod)
        gang = pod_group_key(pod)
        if gang is not None:
            if pod.spec.node_name:
                self._gang.note_unbound(pod)
            if was_quarantined:
                # the poisoned member is gone: the rest of the gang may
                # schedule again once NO member remains quarantined
                # (re-offense re-poisons)
                self._gang.release_poison(gang, uid)
        self._pod_rv[uid] = 2 ** 62
        self._rv_tombstones.append(uid)
        if len(self._rv_tombstones) > 50_000:
            self._pod_rv.pop(self._rv_tombstones.popleft(), None)
        # a pod parked at Permit WAIT holds an assumed reservation: free it
        # now (the reference rejects waiting pods from the delete handler)
        if pod.spec.resource_claims:
            from kubernetes_tpu.plugins.dra import release_pod_claims

            try:
                release_pod_claims(self.hub, pod)
            except Unavailable:
                # raised on the informer thread: must not kill the
                # reflector; claim reservations reconcile on relist
                self._note_hub_down()
        wp = None
        for fw in self.frameworks.values():
            wp = fw.waiting_pods.remove(uid)
            if wp is not None:
                break
        if wp is not None:
            self._fw_for(wp.qp.pod).run_unreserve_plugins(
                wp.state, wp.qp.pod, wp.node_name)
            assumed = wp.qp.pod.clone()
            assumed.spec.node_name = wp.node_name
            # guard like _undo_commit: a foreign bind may have CONFIRMED
            # this reservation through the informer before the delete
            # arrived — forget_pod would raise on a confirmed pod, and
            # the assigned-pod branch below already removes it
            if self.cache.is_assumed_pod(assumed):
                self.cache.forget_pod(assumed)
                # the reservation WAS committed on device by its launch:
                # hand the freed request back to the chain
                self._chain_note_pod(assumed, -1)
            self.queue.done(uid)
        self.nominator.delete(uid)
        if pod.spec.node_name:
            self._chain_note_pod(pod, -1)
            self.cache.remove_pod(pod)
            self.queue.move_all_to_active_or_backoff(
                ClusterEvent(R.ASSIGNED_POD, A.DELETE), pod, None)
        else:
            self.queue.delete(pod)

    # ------------- degraded mode (hub unreachable) -------------

    def hub_degraded(self) -> bool:
        """True while the hub transport is down. A RemoteHub knows its
        own state; for in-process wrappers (ChaosHub) the flag set by the
        last failed call stands until a probe succeeds."""
        connected = getattr(self.hub, "connected", None)
        if connected is not None:
            return not connected
        return self._hub_down

    def _note_hub_down(self) -> None:
        if not self._hub_down:
            logger.warning(
                "hub unreachable: entering degraded mode (parking work)")
            telemetry.incident(self, "hub_degraded",
                               reason="hub unreachable; parking work")
        self._hub_down = True

    def _park_unreachable(self, qp: QueuedPodInfo) -> None:
        """Park a pod the hub outage interrupted: error-class backoff so
        retries pace themselves, but NO condition patch (it would need
        the hub) and no error accounting — the pod did nothing wrong."""
        qp.unschedulable_plugins = set()
        qp.consecutive_errors_count += 1
        self.stats["parked_unreachable"] += 1
        self.queue.add_unschedulable_if_not_present(qp)

    def _park_batch_unreachable(self, runnable: list[QueuedPodInfo]
                                ) -> None:
        """Hub outage during pack/dispatch: park the whole batch and
        keep the loop alive. Anything _dispatch deferred came out of
        this same runnable list, so clearing _deferred cannot strand a
        pod."""
        self._note_hub_down()
        self._invalidate_chain()
        self._deferred = []
        for qp in runnable:
            self._park_unreachable(qp)

    def _fencing_args(self) -> tuple:
        """Extra positional args for fenced hub writes: (epoch,
        lease_name) while an elector gates this scheduler, () otherwise
        (single-scheduler deployments stay unfenced)."""
        el = self._elector
        return () if el is None else (el.epoch, el.lease_name)

    def _fenced_bind(self, pod: Pod, node_name: str) -> None:
        """The binder client handed to DefaultBinder: Hub.bind carrying
        our fencing epoch, so an in-flight bind submitted before we were
        deposed is rejected (Fenced) instead of double-placing the pod.
        Inside a binding cycle the epoch captured at submission wins —
        re-election must not refresh a stale decision's token."""
        fargs = getattr(self._bind_fence, "args", None)
        if fargs is None:
            fargs = self._fencing_args()
        self.hub.bind(pod, node_name, *fargs)

    def _patch_condition_best_effort(self, pod: Pod,
                                     condition: PodCondition,
                                     nominated_node: str | None = None
                                     ) -> None:
        """Condition patches are observability, not correctness: in
        degraded mode (or when fenced) they are dropped — and COUNTED,
        so operators can see lost status — not allowed to wedge the
        loop."""
        try:
            # positional: RemoteHub's RPC proxies take *args only
            self.hub.patch_pod_condition(pod, condition, nominated_node,
                                         *self._fencing_args())
        except Unavailable:
            self._note_hub_down()
            self.metrics.condition_patches_dropped.inc(
                reason="unavailable")
        except Fenced:
            self.stats["fenced"] += 1
            self.metrics.fenced_writes.inc(verb="patch_pod_condition")
            self.metrics.condition_patches_dropped.inc(reason="fenced")

    def _flush_evictions_safe(self) -> None:
        # only a flush with queued work is a measurable phase (this runs
        # every cycle; an empty flush is a couple of attribute reads)
        busy = self.preemption.has_pending()
        t0 = self.now() if busy else 0.0
        try:
            if busy:
                # evictions fire only over durably-bound state: a victim
                # whose own bind still rides the binder backlog would be
                # deleted BEFORE its bind lands, losing the pod (the
                # bind-after-delete fails and the deleted pod can't
                # requeue). The strict path orders wait-drain before
                # flush for the same reason (schedule_one_batch).
                self._drain_bind_results(wait=True)
            # the queue's coalescing window batches the wave's delete
            # events into ONE requeue pass (in-process hubs dispatch
            # them inline on this thread); the whole wave — deletes AND
            # requeue reaction — lands under the single eviction_flush
            # phase observation below, never per-delete
            with self.queue.coalescing():
                self.preemption.flush_evictions()
        except Unavailable:
            self._note_hub_down()
        finally:
            if busy:
                self.flight.observe_phase("eviction_flush",
                                          self.now() - t0)

    # ------------- fault containment (the self-healing ladder) -------------
    #
    # The ladder, top to bottom: (1) the fused device launch; (2) on any
    # device-path exception (XLA error, guard-reduction NaN, re-bucket
    # non-convergence, a plugin raising during pack) the batch degrades
    # to the serial host Filter/Score path — peers keep scheduling THIS
    # cycle, and the device path is retried fresh on the next batch;
    # (3) a pod that raises in its own serial evaluation, or keeps
    # appearing in faulted batches (QUARANTINE_STRIKES), is bisected out
    # into the quarantine set with escalating backoff, a hub Event, and
    # a metric. The daemon never dies because the accelerator path did.

    def _finish_contained(self, inflight: tuple) -> None:
        """_finish with blast-radius containment: an exception commits
        nothing further and routes the batch's still-pending pods down
        the ladder instead of escaping the loop."""
        try:
            self._finish(inflight)
        except Unavailable:
            self._park_batch_unreachable(self._still_pending(inflight[0]))
        except Exception as e:  # noqa: BLE001 — the containment seam
            self._contain_batch_fault(inflight[0], e)

    def _still_pending(self, runnable: list[QueuedPodInfo]
                       ) -> list[QueuedPodInfo]:
        """The subset of a faulted batch that no commit path has touched
        yet (a _finish that raised midway may have assumed — or even
        bound-and-confirmed — some pods already, or parked others; none
        of those may be re-driven)."""
        return [qp for qp in runnable
                if self.cache.get_pod(qp.pod) is None
                and not self.queue.is_parked(qp.uid)]

    def _contain_batch_fault(self, runnable: list[QueuedPodInfo],
                             exc: BaseException) -> None:
        """Rung 2 of the ladder: the device path failed for this batch.
        Strike every member (poison attribution), invalidate the usage
        chain, and degrade the survivors to the host path."""
        self.stats["device_fallbacks"] += 1
        self.metrics.device_fallbacks.inc()
        self._invalidate_chain()
        logger.warning(
            "device path failed for a %d-pod batch (%r); degrading to "
            "the host fallback path", len(runnable), exc)
        telemetry.incident(self, "device_fallback",
                           reason=repr(exc), pods=len(runnable))
        pending = self._still_pending(runnable)
        # pods _dispatch deferred before raising (profile split, host
        # volume conflicts) are still in flight via _deferred — the next
        # pop drives them; driving them here too would double-place
        deferred = {qp.uid for qp in self._deferred}
        pending = [qp for qp in pending if qp.uid not in deferred]
        for qp in pending:
            self._fault_strikes[qp.uid] = \
                self._fault_strikes.get(qp.uid, 0) + 1
        self._host_fallback_batch(pending)

    def _host_fallback_batch(self, qps: list[QueuedPodInfo]) -> None:
        """The degraded scheduling path: serial host-side Filter/Score
        over the snapshot (resources, taints, node selector/affinity,
        host ports, unschedulable marks, plus the host plugin filters
        and scores). Serial evaluation IS the bisection: a pod that
        raises poisons only itself and is quarantined; its batch peers
        keep scheduling. Pods needing topology kernels are parked to
        retry the device path next cycle (the host path has no fused
        affinity state)."""
        if not qps:
            return
        # drain in-flight binds BEFORE the phase clock starts: the drain
        # records its own binder_drain observation, and both phases are
        # HOST_PHASES — timing it here too would double-count the wall
        # time in host_tail_share
        try:
            self._drain_bind_results(wait=True)
        except Unavailable:
            self._park_batch_unreachable(qps)
            return
        # the fallback's serial host-path cost feeds the host_fallback
        # phase histogram: scheduler_device_fallbacks_total says how
        # OFTEN the ladder fired, this says what each firing COST
        t_fb0 = self.now()
        try:
            self._host_fallback_batch_inner(qps)
        finally:
            self.flight.observe_phase("host_fallback",
                                      self.now() - t_fb0)

    def _host_fallback_batch_inner(self, qps: list[QueuedPodInfo]) -> None:
        # the fallback evaluates on host: re-enable the host DRA filter
        # for every pod (device routing only holds for a device launch)
        self._dra.set_device_routed(())
        try:
            self.cache.update_snapshot(self.snapshot)
        except Unavailable:
            self._park_batch_unreachable(qps)
            return
        committed: dict[str, object] = {}     # node -> Resource committed
        committed_pods: dict[str, int] = {}
        for qp in qps:
            if self._fault_strikes.get(qp.uid, 0) >= QUARANTINE_STRIKES:
                self._quarantine_pod(
                    qp, f"{self._fault_strikes[qp.uid]} batch faults")
                continue
            try:
                node, plugins = self._host_place_one(qp, committed,
                                                     committed_pods)
            except Unavailable:
                self._note_hub_down()
                self._park_unreachable(qp)
                continue
            except Exception as e:  # noqa: BLE001 — the poison seam:
                # this pod's own spec/plugins raised in SERIAL evaluation,
                # so the attribution is exact — quarantine it alone
                self._quarantine_pod(qp, f"host fallback raised: {e!r}")
                continue
            if node is None:
                # rung-bottom preemption mini-path (ISSUE 15): a fully
                # device-dead scheduler must still be able to evict —
                # serial host candidate selection + the queued eviction
                # flush; the nomination rides the unschedulable park so
                # the retry (still on the host path if the device stays
                # dead) claims the vacated room
                nominated = self._host_preempt_fallback(qp, plugins)
                if nominated:
                    self.stats["preemptions"] = self.stats.get(
                        "preemptions", 0) + 1
                self._park_unschedulable(
                    qp, plugins, "host fallback: no feasible node",
                    nominated=nominated)
            elif node == "":
                # topology pod: the host path cannot evaluate it — park
                # error-class and let the next cycle retry the device path
                self._error(qp, "device path failed; topology pod awaits "
                                "device retry")
            else:
                from kubernetes_tpu.api.resources import pod_request

                r = committed.get(node)
                if r is None:
                    committed[node] = pod_request(qp.pod).clone()
                else:
                    r.add(pod_request(qp.pod))
                committed_pods[node] = committed_pods.get(node, 0) + 1
                self._fault_strikes.pop(qp.uid, None)
                self._commit(qp, node)

    def _host_place_one(self, qp: QueuedPodInfo, committed: dict,
                        committed_pods: dict
                        ) -> tuple[Optional[str], set[str]]:
        """One pod through the host predicates + scores. Returns
        (node_name, set()) on success, (None, rejecting_plugins) when
        infeasible, ("", set()) when the pod needs the device's topology
        kernels (affinity/anti-affinity/spread — not evaluable here)."""
        from kubernetes_tpu.api.labels import (
            find_untolerated_taint,
            label_selector_matches,
            pod_matches_node_selector_and_affinity,
        )
        from kubernetes_tpu.api.resources import pod_request

        pod = qp.pod
        if self.mirror.batch_has_topology([pod]):
            return "", set()
        req = pod_request(pod)
        infos = self.snapshot.node_info_list
        fw = self._fw_for(pod)
        host_mask = host_scores = None
        qp.host_reject_counts = {}
        if (self._has_host_filters or self._has_host_scores) \
                and self._host_relevant(pod):
            state = CycleState()
            host_mask, counts, early = fw.run_host_filters(state, pod,
                                                           infos)
            if counts:
                qp.host_reject_counts = counts
            if early is not None:
                return None, set(counts) or {early.plugin or "HostFilter"}
            if self._has_host_scores:
                host_scores = fw.run_host_scores(state, pod, infos)
        ports = [(p.host_ip, p.protocol, p.host_port)
                 for c in pod.spec.containers for p in c.ports
                 if p.host_port > 0]
        rejects: set[str] = set(qp.host_reject_counts)
        best = None
        best_score = float("-inf")
        for i, ni in enumerate(infos):
            node = ni.node
            if node is None:
                continue
            if host_mask is not None and not host_mask[i]:
                continue
            if node.spec.unschedulable:
                rejects.add("NodeUnschedulable")
                continue
            if not pod_matches_node_selector_and_affinity(pod, node):
                rejects.add("NodeAffinity")
                continue
            if find_untolerated_taint(node.spec.taints,
                                      pod.spec.tolerations) is not None:
                rejects.add("TaintToleration")
                continue
            if any(ni.used_ports.conflicts(*p) for p in ports):
                rejects.add("NodePorts")
                continue
            # symmetry guard: an EXISTING pod's required anti-affinity
            # must not be violated by this placement; non-hostname
            # domains span other nodes, which only the device kernels
            # track — send such pods back to the device path
            sym_block = False
            for pi in ni.pods_with_required_anti_affinity:
                for term in pi.required_anti_affinity_terms:
                    if label_selector_matches(term.label_selector,
                                              pod.metadata.labels) \
                            and pi.pod.metadata.namespace \
                            == pod.metadata.namespace:
                        if term.topology_key != "kubernetes.io/hostname":
                            return "", set()
                        sym_block = True
            if sym_block:
                rejects.add("InterPodAffinity")
                continue
            alloc = ni.allocatable
            extra = committed.get(ni.name)
            free_cpu = alloc.milli_cpu - ni.requested.milli_cpu \
                - (extra.milli_cpu if extra else 0)
            free_mem = alloc.memory - ni.requested.memory \
                - (extra.memory if extra else 0)
            free_eph = alloc.ephemeral_storage \
                - ni.requested.ephemeral_storage \
                - (extra.ephemeral_storage if extra else 0)
            n_pods = len(ni.pods) + committed_pods.get(ni.name, 0)
            if req.milli_cpu > free_cpu or req.memory > free_mem \
                    or req.ephemeral_storage > free_eph \
                    or (alloc.allowed_pod_number > 0
                        and n_pods + 1 > alloc.allowed_pod_number):
                rejects.add("NodeResourcesFit")
                continue
            if any(v > alloc.scalar.get(k, 0)
                   - ni.requested.scalar.get(k, 0)
                   - (extra.scalar.get(k, 0) if extra else 0)
                   for k, v in req.scalar.items()):
                rejects.add("NodeResourcesFit")
                continue
            # LeastAllocated over cpu+memory — the host analog of the
            # default fit scoring, enough to spread a degraded batch —
            # plus any configured host score plugins
            score = 0.0
            if alloc.milli_cpu > 0:
                score += (free_cpu - req.milli_cpu) / alloc.milli_cpu
            if alloc.memory > 0:
                score += (free_mem - req.memory) / alloc.memory
            if host_scores is not None:
                score += host_scores[i]
            if score > best_score:
                best, best_score = ni.name, score
        if best is None:
            return None, rejects or {"NodeResourcesFit"}
        return best, set()

    def _host_preempt_fallback(self, qp: QueuedPodInfo,
                               plugins: set[str]) -> Optional[str]:
        """The host fallback's preemption rung: serial candidate
        selection over the snapshot (Evaluator.host_preempt) when the
        rejection is preemption-resolvable. Returns the nominated node
        name, or None when preemption does not apply / found nothing."""
        pod = qp.pod
        if pod.priority() <= 0 \
                or pod.metadata.uid in self.preemption.preempting:
            return None
        # only fit-class rejections are resolvable by eviction; host
        # plugin rejects (volumes, claims) and pure static rejects are
        # not — matching the device path's Unresolvable discipline
        if plugins and "NodeResourcesFit" not in plugins:
            return None
        if not self._fw_for(pod).points["post_filter"]:
            return None         # profile disabled preemption
        try:
            node, _status = self.preemption.host_preempt(pod,
                                                         self.snapshot)
        except Unavailable:
            self._note_hub_down()
            return None
        except Exception as e:  # noqa: BLE001 — the mini-path must
            # never take the whole fallback batch down with it
            logger.warning("host preemption mini-path failed for %s: %r",
                           pod.key(), e)
            return None
        return node

    def _park_unschedulable(self, qp: QueuedPodInfo, plugins: set[str],
                            msg: str, nominated: Optional[str] = None
                            ) -> None:
        """Unschedulable park with plugin attribution. Full PostFilter
        preemption is a device sweep the fallback path must not re-enter;
        the host mini-path's nomination (if any) rides the condition
        patch so the preemptor's reservation survives the park."""
        if self.flight.enabled:
            self.timelines.diagnose(qp.pod, {}, qp.host_reject_counts
                                    or {p: -1 for p in plugins}, msg)
            self.timelines.event(qp.pod, "unschedulable", msg)
        qp.unschedulable_plugins = plugins or {"NodeResourcesFit"}
        qp.unschedulable_count += 1
        qp.consecutive_errors_count = 0
        self.stats["unschedulable"] += 1
        self.metrics.schedule_attempts.inc(
            result="unschedulable", profile=qp.pod.spec.scheduler_name)
        self._patch_condition_best_effort(qp.pod, PodCondition(
            type="PodScheduled", status="False", reason="Unschedulable",
            message=msg), nominated)
        if nominated:
            # park the FRESH object so the packed nominated_row sees
            # status.nominatedNodeName next attempt (same re-fetch
            # discipline as _park_failed)
            try:
                stored = self.hub.get_pod(qp.uid)
            except Unavailable:
                self._note_hub_down()
                stored = None
            if stored is not None:
                qp.pod = stored
        self.queue.add_unschedulable_if_not_present(qp)

    # ------------- poison-pod quarantine -------------

    def _quarantine_pod(self, qp: QueuedPodInfo, reason: str) -> None:
        """Park a pod that keeps faulting its batch: out of the queue,
        escalating backoff, hub Event + metric so operators see it."""
        uid = qp.uid
        n = self._quarantine_counts.get(uid, 0) + 1
        self._quarantine_counts[uid] = n
        backoff = min(QUARANTINE_CAP_S, QUARANTINE_BASE_S * (2 ** (n - 1)))
        self._quarantine[uid] = {"qp": qp, "until": self.now() + backoff,
                                 "reason": reason}
        self._fault_strikes.pop(uid, None)
        self.queue.done(uid)
        self.stats["quarantined"] += 1
        self.metrics.quarantines.inc(reason="poison")
        self.metrics.quarantined_pods.set(float(len(self._quarantine)))
        if self.flight.enabled:
            self.timelines.event(qp.pod, "quarantined",
                                 f"{backoff:.0f}s: {reason}")
        gang = pod_group_key(qp.pod)
        if gang is not None:
            # a poisoned member poisons the WHOLE gang: members reject at
            # PreFilter/Reserve and any assembling reservation rolls back
            # — a gang placed around its poisoned member would violate
            # all-or-nothing (released with this pod's quarantine)
            self._gang.poison(gang, reason, uid)
        logger.error("quarantining pod %s for %.0fs (offense %d): %s",
                     qp.pod.key(), backoff, n, reason)
        telemetry.incident(self, "quarantine", reason=reason,
                           pod=qp.pod.key(), offense=n)
        try:
            self.hub.record_event(
                "Pod", qp.pod.key(), "Quarantined",
                f"poison-pod quarantine ({backoff:.0f}s, offense {n}): "
                f"{reason}")
        except Unavailable:
            self._note_hub_down()

    def _release_quarantined(self) -> None:
        """Maintenance tick: return served-out quarantine entries to the
        queue (re-offense re-quarantines with doubled backoff)."""
        if not self._quarantine:
            self.metrics.quarantined_pods.set(0.0)
            return
        now = self.now()
        for uid, entry in list(self._quarantine.items()):
            if entry["until"] > now:
                continue
            try:
                stored = self.hub.get_pod(uid)
            except Unavailable:
                self._note_hub_down()
                continue            # retry on the next tick
            entry = self._quarantine.pop(uid)
            gang = pod_group_key(entry["qp"].pod)
            if gang is not None:
                self._gang.release_poison(gang, uid)
            if stored is not None and not stored.spec.node_name \
                    and not self._terminal(stored):
                self._enqueue_fresh(stored)
        self.metrics.quarantined_pods.set(float(len(self._quarantine)))

    def quarantined_uids(self) -> set[str]:
        """Introspection for tests/serving: pods currently quarantined."""
        return set(self._quarantine)

    # ------------- capacity re-bucketing -------------

    def _grow(self, err: CapacityError) -> None:
        """Double the exceeded capacity and rebuild the mirror (the
        re-bucketing strategy from the Mirror docstring; kernels recompile
        once per bucket)."""
        field = {"ext_resources": "ext_resources"}.get(err.field, err.field)
        if not hasattr(self.caps, field):
            raise err
        cur = getattr(self.caps, field)
        new = max(cur * 2, 8)
        while new < err.needed:
            new *= 2
        self.caps = dataclasses.replace(self.caps, **{field: new})
        prev = self.mirror
        self.mirror = Mirror(caps=self.caps, mesh=self.mesh)
        # sticky-bucket continuity: the fresh mirror keeps the old one's
        # shape high-water marks, so re-bucketing doesn't re-learn d_cap/
        # g_cap from scratch and flap the compiled programs again
        self.mirror.adopt_hysteresis(prev)
        self.snapshot = Snapshot()
        self._invalidate_chain()
        self.cache.update_snapshot(self.snapshot)
        # NO sync here: the caller's retry loop re-syncs, so a second field
        # overflowing during the rebuild raises inside the try (and grows
        # again) instead of escaping the loop from this except-handler.

    # ------------- the batched scheduling cycle -------------

    def _pop_runnable(self) -> tuple[int, list[QueuedPodInfo]]:
        """Pop up to batch_size pods and apply skipPodSchedule
        (schedule_one.go:380: deleted or already assumed). Pods deferred
        from the previous batch (host-serial volume conflicts) go first —
        they are still in flight from their original pop."""
        t_pop0 = self.now()
        deferred, self._deferred = self._deferred, []
        batch = deferred + self.queue.pop_batch(
            self._effective_batch() - len(deferred))
        runnable: list[QueuedPodInfo] = []
        for i, qp in enumerate(batch):
            try:
                stored = self.hub.get_pod(qp.uid)
            except Unavailable:
                # hub unreachable mid-pop: park the whole batch (vetted
                # pods included — their binds would only fail) and let
                # backoff pace the retry; nothing errors, nothing is lost
                self._note_hub_down()
                for rest in runnable + batch[i:]:
                    self._park_unreachable(rest)
                return len(batch), []
            if stored is None or stored.metadata.deletion_timestamp:
                self.queue.done(qp.uid)
                continue
            if self.cache.is_assumed_pod(qp.pod):
                self.queue.done(qp.uid)
                continue
            if self._fault_strikes.get(qp.uid, 0) >= QUARANTINE_STRIKES:
                # repeat offender re-entering via error backoff (e.g. a
                # pod whose reserve plugin keeps raising): bisect it out
                # before it faults another batch
                self._quarantine_pod(
                    qp, f"{self._fault_strikes[qp.uid]} batch/commit "
                        "faults")
                continue
            runnable.append(qp)
        t_pop1 = self.now()
        # consumed by _dispatch into the cycle's queue_pop phase (one
        # shared clock read stamps the whole batch's pop events)
        self._last_pop_s = t_pop1 - t_pop0
        if self.flight.enabled and runnable:
            tl = self.timelines
            for qp in runnable:
                tl.event(qp.pod, "popped", f"attempt {qp.attempts}",
                         t=t_pop1)
        return len(batch), runnable

    def _chain_eligible(self, pods: list[Pod]) -> bool:
        """Can this batch launch against the device-resident usage chain
        without a host snapshot/mirror re-sync? Requires: a live chain (no
        external event since the newest dispatch) and a launch that reads
        nothing the skipped sync would refresh — no topology kernels (pod
        table), no batch host ports (port tables), and no host-filter work
        (host plugins read the snapshot, so it must be fresh)."""
        return (self._chain is not None
                and not self.mirror.table_has_topology()
                and not self.mirror.batch_has_topology(pods)
                and not self.mirror.batch_has_host_ports(pods)
                and not (self._has_host_filters
                         and any(self._host_relevant(p) for p in pods)))

    def _apply_chain_patches(self, flush_pending=None) -> bool:
        """Fold the pending churn patches into the live device chain
        (chain-surviving churn, models/pipeline.patch_chain). Deltas
        commute with in-flight waves' device commits, so they scatter
        straight in; absolute node repacks read cache truth, so when any
        are pending the in-flight waves flush FIRST — the conservative
        form of "invalidate only when a touched node intersects an
        in-flight wave's packed set" (every in-flight wave's packed set
        came from the pre-event mirror, so a flush is the cheap safe
        answer; per-wave set intersection would save a flush only on the
        churn-while-deep-pipeline overlap, which the bench shows is
        rare). Returns False when the chain must fall back to a full
        resync (mirror capacity overflow, vanished rows, a flush fault
        that invalidated the chain) — the caller dispatches unchained."""
        if not self._chain_dirty and not self._chain_deltas:
            return True
        if self._chain is None:
            self._chain_dirty.clear()
            self._chain_deltas.clear()
            return False
        if self._chain_dirty and flush_pending is not None:
            flush_pending()
            if self._chain is None:     # a flush fault killed the chain
                self._chain_dirty.clear()
                self._chain_deltas.clear()
                return False
        # snapshot + clear AFTER the flush: events the flush delivered
        # inline (eviction deletes, binder confirms) registered more
        # patches, and this application must carry them too
        dirty = sorted(self._chain_dirty)
        deltas = [(nm, acc) for nm, acc in self._chain_deltas.items()
                  if nm not in self._chain_dirty]
        self._chain_dirty.clear()
        self._chain_deltas.clear()
        set_rows: list[tuple] = []
        add_rows: list[tuple] = []
        try:
            for name in dirty:
                patched = self.mirror.patch_node(
                    name, self.cache.node_info(name))
                if patched is not None:
                    set_rows.append(patched)
            for name, (dfree, dnzr) in deltas:
                row = self.mirror.row_of(name)
                if row < 0:
                    # a delta for a node the mirror never packed: the
                    # chain has no row to move — resync is the only
                    # consistent answer
                    self.stats["chain_patch_fallbacks"] += 1
                    self._invalidate_chain()
                    return False
                add_rows.append((row, dfree, dnzr))
        except CapacityError:
            self.stats["chain_patch_fallbacks"] += 1
            self._invalidate_chain()
            return False
        if set_rows or add_rows:
            free, nzr = self._chain
            self._chain = patch_chain(free, nzr, set_rows, add_rows)
            self.stats["chain_patches"] += 1
            self.stats["chain_patch_rows"] += len(set_rows) + len(add_rows)
        return True

    def _dispatch(self, runnable: list[QueuedPodInfo], chained: bool,
                  flush_pending=None) -> Optional[tuple]:
        """Pack + launch one batch (async dispatch; no host<->device block).
        Returns (runnable, BatchResult) or None if every pod was routed to
        the failure path during packing. ``flush_pending`` commits a
        still-in-flight previous launch before any fallback re-sync, so a
        chained dispatch that has to re-bucket never syncs a cache missing
        the previous batch's placements."""
        t_cycle0 = self.now()
        # chain-surviving churn: fold pending informer patches into the
        # live chain BEFORE this launch packs against it. On fallback
        # (patch set too large, mirror capacity overflow) the chain is
        # invalidated and this dispatch takes the full-sync path.
        t_patch = 0.0
        if chained and (self._chain_dirty or self._chain_deltas):
            t_p0 = self.now()
            if not self._apply_chain_patches(flush_pending):
                chained = False
            t_patch = self.now() - t_p0
        epoch = self._chain_epoch
        if len(self.frameworks) > 1:
            # one profile per launch: enabled filters / weights / scoring
            # strategy are per-profile launch configuration
            prof = runnable[0].pod.spec.scheduler_name
            same = [qp for qp in runnable
                    if qp.pod.spec.scheduler_name == prof]
            if len(same) != len(runnable):
                self._deferred.extend(
                    qp for qp in runnable
                    if qp.pod.spec.scheduler_name != prof)
                runnable = same
        else:
            prof = self._profile_name
        pcfg = self._profile_cfg[prof]
        if self._has_host_filters:
            runnable = self._defer_host_conflicts(runnable)
            if not runnable:
                return None
        if self.fault_injector is not None:
            # chaos seam: may raise (device launch error, forced
            # CapacityError, poison-pod exception) — contained by the
            # fallback ladder exactly like a real device fault
            self.fault_injector.on_pack([qp.pod for qp in runnable])
        self.stats["batches"] += 1
        self.stats["attempts"] += len(runnable)
        # flight recorder: this cycle's trace opens here and is recorded
        # by _finish (the dispatched tuple carries it through the
        # pipelined drain)
        tr = self.flight.begin(t_cycle0, len(runnable), chained)
        tr.add("queue_pop", self._last_pop_s)
        self._last_pop_s = 0.0
        if t_patch:
            tr.add("chain_patch", t_patch)
        state = self._chain if chained else None
        need_sync = not chained
        for attempt in range(16):  # one capacity field may grow per attempt
            try:
                if need_sync:
                    if flush_pending is not None:
                        flush_pending()
                        flush_pending = None
                    t_sync0 = self.now()
                    self.cache.update_snapshot(self.snapshot)
                    self.mirror.sync(self.snapshot)
                    # a full sync subsumes every pending chain patch:
                    # handlers mutate the cache synchronously before
                    # registering, and the sync read that cache
                    self._chain_dirty.clear()
                    self._chain_deltas.clear()
                    tr.add("snapshot_sync", self.now() - t_sync0)
                t_pack0 = self.now()
                self.mirror.set_nominated(self.nominator.by_node())
                spec = self.mirror.prepare_launch(
                    [qp.pod for qp in runnable], self.config.batch_size)
                tr.add("pack", self.now() - t_pack0)
                break
            except CapacityError as e:
                if flush_pending is not None:
                    # commit in-flight launches against the OLD mirror NOW:
                    # _grow replaces self.mirror with an empty re-bucketed
                    # one, and a later flush would resolve their node rows
                    # against it (name_of_row -> None for every row)
                    flush_pending()
                    flush_pending = None
                self._grow(e)          # invalidates the chain
                state = None
                need_sync = True
        else:
            raise RuntimeError("mirror re-bucketing did not converge")

        # learned scorer (profile-gated): poll the checkpoint's mtime at
        # snapshot-sync time — a stat when unchanged, a load + H2D push
        # when an offline trainer published a new version. Params then
        # ride this launch as one more weighted term; a reload mid-run
        # never recompiles (same architecture = same jit signature).
        learned_params = None
        mgr = pcfg["learned"]
        if mgr is not None:
            t_l0 = self.now()
            mgr.maybe_reload()
            learned_params = mgr.params()
            tr.add("learned_score", self.now() - t_l0)
            # reloads = swaps AFTER the initial load (the manager's
            # count); errors delta-mirrored like other external counts
            # the generation label rides the delta at reload time:
            # promoted-vs-manual publishes stay distinguishable in the
            # fleet scrape (generation 0 = manual)
            self._mirror_count(f"learned_reloads:{prof}", mgr.reloads,
                               self.metrics.learned_reloads,
                               profile=prof,
                               generation=str(mgr.generation))
            w = getattr(mgr, "_watcher", None)
            if w is not None:
                self._mirror_count(f"learned_errs:{prof}", w.load_errors,
                                   self.metrics.learned_load_errors,
                                   profile=prof)
            self.metrics.learned_checkpoint_version.set(
                float(mgr.version if learned_params is not None else 0),
                profile=prof)

        # batched DRA allocator: pack this batch's claim tensors and fuse
        # the device verdict into the launch (ops/dra.py + the dra arg of
        # schedule_batch). Pods whose claims sit outside the device-
        # expressible subset stay on the host filter path — applies()
        # keeps returning True for exactly those. Gated on the profile
        # actually enabling the DynamicResources filter (the batch is
        # single-profile by this point).
        if pcfg["dra_filter"] \
                and any(qp.pod.spec.resource_claims for qp in runnable):
            # claim state must be as settled as the host path saw it:
            # in-flight binding cycles write allocations (PreBind), so
            # land them before the in-use mask packs
            self._drain_bind_results(wait=True)
            t_dra0 = self.now()
            dra_batch, dra_stats = self._dra.build_device_batch(
                [qp.pod for qp in runnable], self.mirror.row_of,
                self.caps.nodes, spec.pblobs.f32.shape[0])
            t_dra1 = self.now()
            spec.dra = dra_batch
            for qp in runnable:
                if qp.pod.spec.resource_claims:
                    # stale attribution from a previous attempt must not
                    # survive into this cycle's diagnosis
                    qp.host_reject_counts = {}
            # dra_mask_compile = selector compilation + inventory
            # refresh; dra_device_eval = the per-cycle claim/in-use
            # tensor pack. Both are VIEWS (excluded from the cycle-total
            # arithmetic); the wall time itself lands in `pack`.
            tr.add("dra_mask_compile", dra_stats["compile_s"])
            tr.add("dra_device_eval",
                   (t_dra1 - t_dra0) - dra_stats["compile_s"])
            tr.add("pack", t_dra1 - t_dra0)

        # commit mode: the parallel-rounds auction whenever the launch has
        # no topology work and no batch pod carries host ports (in-batch
        # port conflicts are impossible without batch host ports; node-side
        # conflicts are in the static masks the auction honors); the exact
        # as-if-serial scan otherwise (see pipeline._rounds_commit)
        # percentageOfNodesToScore (schedule_one.go:668): when set, the
        # rotating feasible-subset selection only exists in the serial
        # scan, so the auction (which scores all nodes by design) is gated
        # off. None/100 = score everything — the TPU-native stance (SURVEY
        # §2.7 P2); an explicit 0 = the reference's adaptive percentage.
        raw = self.config.percentage_of_nodes_to_score
        pct = (0 if raw is None or raw >= 100
               else ADAPTIVE_PCT if raw == 0 else raw)
        # topology launches may join the auction when the batch's
        # topology work is SOFT-only (preferred weights / ScheduleAnyway
        # spread, ISSUE 15): soft terms are scores, so either commit
        # engine can carry them fused. Engine choice is a backend
        # heuristic like pipeline.scan_unroll: on accelerators the
        # auction's few big fused rounds beat B sequential scan steps;
        # on CPU the soft-serial scan's small per-step kernels beat the
        # auction's bandwidth-bound [B, N] rounds — measured both ways
        # on the preferred band (BENCH_r15).
        soft_auction = spec.topo_soft and jax.default_backend() != "cpu"
        use_auction = (not pct
                       and (not spec.enable_topology or soft_auction)
                       and not self.mirror.batch_has_host_ports(
                           [qp.pod for qp in runnable])
                       and pcfg["filters"][FILTER_PLUGINS.index(
                           "NodeResourcesFit")])
        host_ok = host_score = None
        if self._has_host_filters or self._has_host_scores \
                or self._extenders:
            t_host0 = self.now()
            host_ok, host_score = self._run_host_plugins(runnable)
            tr.add("host_plugins", self.now() - t_host0)
        fit_strategy, fit_shape = pcfg["fit"]
        # export-pull flags captured ONCE: the launch compiles against
        # them and the commit thread pulls against them, so they must be
        # the same observation (a rotation-disabled export mid-cycle
        # must not desync the pull list from the launch outputs)
        exporting = self.flight.exporting
        want_feats = self._export_feats and exporting
        want_alts = self._export_alts and exporting
        if state is None:
            # seed the usage chain from the freshly synced mirror so every
            # launch carries explicit state: one jit signature for chained
            # and unchained dispatches (see pipeline.extract_state_jit)
            state = extract_state_jit(spec.cblobs, self.caps)
        t_disp0 = self.now()
        out: BatchResult = launch_batch(
            spec, self.mirror.well_known(), pcfg["weights"], self.caps,
            pcfg["filters"], serial_scan=not use_auction, state=state,
            host_ok=host_ok, host_score=host_score,
            fit_strategy=fit_strategy, fit_shape=fit_shape, pct_nodes=pct,
            # seeded with a concrete 0 (not None) so every launch shares one
            # arg pytree and therefore one trace/compile
            pct_start=(self._pct_start if self._pct_start is not None
                       else np.int32(0)) if pct else None,
            learned=learned_params, tie_seed=self._tie_seed,
            # chosen-node feature rows only materialize while the
            # feature export is opted in AND the export file is still
            # open (a failed rotation disables the export; the feature
            # kernels must not keep running for output nobody pulls)
            with_feats=want_feats, with_alts=want_alts)
        if self.fault_injector is not None:
            out = self.fault_injector.on_result(out)
        if pct:
            # device-resident rotation carry; stays async (never sync'd to
            # host), consumed as the next launch's seed
            self._pct_start = out.pct_start
        # the chain advances to this launch's post-batch state UNLESS an
        # invalidation raced in while we were packing (epoch check); later
        # external events reset it via the handlers
        if epoch == self._chain_epoch:
            self._chain = (out.free, out.nzr)
            if self._pipelined and not self._patch_warmed:
                # pre-compile every patch-scatter bucket for this chain
                # shape, once per scheduler: churn patches must never
                # trigger an XLA compile mid-drain
                self._patch_warmed = True
                warm_patch_chain(out.free, out.nzr, CHAIN_PATCH_MAX)
        t_done = self.now()
        tr.add("device_dispatch", t_done - t_disp0)
        # device-launch profiler: the jit call above traced (and, on a
        # new bucket shape, COMPILED) synchronously before dispatching,
        # so reading the executable-cache size here attributes any
        # growth to exactly this launch's shape
        pshape = None
        compiled = False
        prof = self.profiler
        if prof is not None:
            from kubernetes_tpu.telemetry.profiler import (
                shape_key,
                tree_nbytes,
            )

            pshape = shape_key(
                self.caps, spec.pblobs.f32.shape[0],
                spec.enable_topology, spec.d_cap, spec.g_cap,
                not use_auction, spec.dra is not None,
                learned_params is not None, want_feats,
                alts=want_alts, soft=spec.topo_soft)
            compiled = prof.note_launch(pshape)
            if compiled or prof.launches == 1:
                # buffer footprints are bucket-static: re-measure only
                # when a compile (= a bucket/flag change) happened
                prof.note_buffers({
                    "cluster": tree_nbytes(spec.cblobs),
                    "pods": tree_nbytes(spec.pblobs),
                    "dra": tree_nbytes(spec.dra),
                    "learned": tree_nbytes(learned_params)})
        # off-thread commit: the wave's blocking D2H pull rides the
        # commit thread from HERE, so it overlaps whatever the loop (and
        # the device) does next; _finish harvests the future. The flags
        # tuple snapshots what the launch actually compiled so the pull
        # list matches its outputs.
        flags = (learned_params is not None, exporting,
                 want_feats, want_alts)
        fut = (self._commit_pool.submit(self._pull_launch, out, flags)
               if self._commit_pool is not None else None)
        return (runnable, out, t_done, t_done - t_cycle0, tr,
                flags, pshape, compiled, fut)

    # ------------- device-side gang packing (ISSUE 12) -------------
    #
    # A whole PodGroup as ONE device problem: the batch's gang units are
    # packed into a single fused launch (ops/gang.pack_gangs) — static
    # filters, member-capacity-per-node, an all-or-nothing feasibility
    # reduction, and topology-close domain packing, gangs committed
    # as-if-serial inside the launch. A unit that clears the verdict
    # commits through the fenced binder as one atomic host step
    # (reserve-all -> bind-all); the Permit quorum machinery survives
    # only as the host-fallback path for gangs the kernel cannot express
    # (topology terms, heterogeneous members, claims/volumes, active
    # nominations) and as rung 2 of the ladder on any device fault.

    def _gang_unit_fallback_reason(self, key: str,
                                   qps: list[QueuedPodInfo]
                                   ) -> Optional[str]:
        """None = the unit is device-packable; otherwise the reason it
        must take the host Permit path (the fallback metric's label)."""
        group = self._gang.group_of(key)
        if group is None:
            return "no_group"
        if self._gang._poison_reason(key) is not None:
            return "poisoned"
        pods = [qp.pod for qp in qps]
        prof = pods[0].spec.scheduler_name
        if any(p.spec.scheduler_name != prof for p in pods[1:]):
            return "profiles"
        pcfg = self._profile_cfg.get(prof)
        if pcfg is None or not pcfg.get("gang_plugin"):
            return "no_plugin"
        # every member present in THIS batch places together; the unit
        # is packable only if that completes the quorum (bound members
        # count — failover admits the tail of a half-bound gang)
        need = max(group.min_member - self._gang.bound_count(key), 0)
        if len(pods) < need:
            return "partial"
        if self.mirror.batch_has_topology(pods):
            return "topology"
        if self.mirror.batch_has_host_ports(pods):
            return "ports"
        if any(p.spec.resource_claims or p.spec.volumes for p in pods):
            return "host_filters"
        if any(ext.is_interested(p) for ext in self._extenders
               for p in pods):
            return "extender"
        if max((p.priority() for p in pods), default=0) > 0:
            # a preempting gang the packer would reject anyway (the
            # memoized capacity bound, still fresh by content token,
            # already proves < need) goes STRAIGHT to the host path —
            # paying a pack launch + pipeline flush every retry cycle
            # while victims drain is what regressed GangPreemption
            cached = self._gang._cap_cache.get(key)
            if cached is not None and cached[1] < len(pods):
                try:
                    if cached[0] == self._gang.cap_token(self.mirror,
                                                         pods[0]):
                        return "infeasible_preempting"
                except CapacityError:
                    return "capacity"
        try:
            from kubernetes_tpu.api.resources import pod_request

            row0 = self.mirror._res_row(pod_request(pods[0])).tobytes()
            if any(self.mirror._res_row(pod_request(p)).tobytes() != row0
                   for p in pods[1:]):
                # the packer places request-IDENTICAL members (one
                # representative row per gang)
                return "hetero"
        except CapacityError:
            return "capacity"   # normal path re-buckets and retries
        return None

    def _split_gang_units(self, runnable: list[QueuedPodInfo]
                          ) -> tuple[list, list[QueuedPodInfo]]:
        """Partition a popped batch into device-packable gang units and
        the rest (plain pods + fallback-path gang members)."""
        by_key: dict[str, list[QueuedPodInfo]] = {}
        for qp in runnable:
            key = pod_group_key(qp.pod)
            if key is not None:
                by_key.setdefault(key, []).append(qp)
        if not by_key:
            return [], runnable
        units: list[tuple[str, list[QueuedPodInfo]]] = []
        taken: set[str] = set()
        unit_prof = None
        for key, qps in by_key.items():
            reason = self._gang_unit_fallback_reason(key, qps)
            if reason is None:
                prof = qps[0].pod.spec.scheduler_name
                if unit_prof is None:
                    unit_prof = prof
                elif prof != unit_prof:
                    # one enabled-filter set per launch: units of another
                    # profile ride the normal path this cycle
                    reason = "profiles_mixed"
            if reason is None:
                units.append((key, qps))
                taken.update(qp.uid for qp in qps)
            else:
                self.stats["gang_fallbacks"] += 1
                self.metrics.gang_fallbacks.inc(reason=reason)
        if not units:
            return [], runnable
        return units, [qp for qp in runnable if qp.uid not in taken]

    def _schedule_gang_units(self, runnable: list[QueuedPodInfo],
                             flush_pending=None) -> list[QueuedPodInfo]:
        """Route the batch's device-packable gang units through the
        fused packer; returns what the normal path still owns. Faults
        degrade the units to the host Permit path (the ladder), never
        kill the cycle."""
        if not self._gang_device or not runnable:
            return runnable
        units, rest = self._split_gang_units(runnable)
        if not units:
            return rest
        if flush_pending is not None:
            # commit in-flight pipelined launches first: their results
            # are what the usage chain (or the re-synced mirror) must
            # already reflect, and a rollback among them invalidates it
            flush_pending()
        # fault containment is PER CHUNK: a fault in chunk k may only
        # degrade chunk k's still-uncommitted members and the chunks
        # not yet dispatched — units chunk 0 already committed are mid
        # bind and must never re-enter any scheduling path
        fallback: list[QueuedPodInfo] = []
        for i in range(0, len(units), self.GANG_PACK_BUCKET):
            chunk = units[i:i + self.GANG_PACK_BUCKET]
            later = units[i + self.GANG_PACK_BUCKET:]
            try:
                fallback.extend(self._dispatch_gang_chunk(chunk))
            except Unavailable:
                self._note_hub_down()
                self._invalidate_chain()
                chunk_qps = [qp for _key, qps in chunk for qp in qps]
                for qp in self._still_pending(chunk_qps):
                    self._park_unreachable(qp)
                for _key, qps in later:
                    for qp in qps:
                        self._park_unreachable(qp)
                return rest + fallback
            except Exception as e:  # noqa: BLE001 — containment seam:
                # the Permit-quorum path still schedules these gangs
                self.stats["device_fallbacks"] += 1
                self.metrics.device_fallbacks.inc()
                self._invalidate_chain()
                degraded = chunk + later
                logger.warning(
                    "gang device path failed for %d unit(s) (%r); "
                    "degrading to the host Permit path", len(degraded), e)
                for _key, _qps in degraded:
                    self.stats["gang_fallbacks"] += 1
                    self.metrics.gang_fallbacks.inc(reason="device_fault")
                chunk_qps = [qp for _key, qps in chunk for qp in qps]
                fallback.extend(self._still_pending(chunk_qps))
                fallback.extend(qp for _key, qps in later for qp in qps)
                return rest + fallback
        return rest + fallback

    # gang-pack launch bucket: FIXED so every wave (warmup, first storm
    # wave, tail) runs ONE compiled program per cluster shape — a
    # units-count-sized pow2 bucket put a fresh XLA compile in the first
    # measured wave of every gang bench. Wider waves chunk (the chunks
    # chain their usage state, still O(1) launches per gang).
    GANG_PACK_BUCKET = 16

    def _dispatch_gang_chunk(self, units: list) -> list[QueuedPodInfo]:
        """ONE fused packing launch for a chunk of gang units + the
        atomic host commit of every unit that cleared the verdict.
        Returns members that must fall back to the normal path (a
        preempting gang the packer found infeasible)."""
        import jax.numpy as jnp

        from kubernetes_tpu.ops.features import PodBlobs
        from kubernetes_tpu.ops.gang import pack_gangs_jit

        t0 = self.now()
        # chain-surviving churn: pending patches fold in before the pack
        # reads the chain (the caller already flushed the pipeline, so
        # no flush closure is needed for absolute repacks)
        if self._chain is not None \
                and (self._chain_dirty or self._chain_deltas):
            t_p0 = self.now()
            self._apply_chain_patches()
            self.flight.observe_phase("chain_patch", self.now() - t_p0)
        epoch = self._chain_epoch
        state = self._chain
        need_sync = state is None
        reps = [qps[0].pod for _key, qps in units]
        g_bucket = self.GANG_PACK_BUCKET
        for _attempt in range(16):
            try:
                if need_sync:
                    self.cache.update_snapshot(self.snapshot)
                    self.mirror.sync(self.snapshot)
                    self._chain_dirty.clear()
                    self._chain_deltas.clear()
                # nominated reservations must be CURRENT: the packer
                # subtracts them (and hands back each gang's own)
                self.mirror.set_nominated(self.nominator.by_node())
                feats = self.mirror.launch_features(reps)
                pfields = self.mirror.pod_fields(feats, False)
                f32, i32 = self.mirror._pack_batch_np(reps, g_bucket,
                                                      pfields)
                break
            except CapacityError as e:
                self._grow(e)
                state = None
                need_sync = True
        else:
            raise RuntimeError("mirror re-bucketing did not converge")
        if self.fault_injector is not None:
            # chaos seam: poison members / forced faults land here and
            # degrade the units to the Permit path via the caller
            self.fault_injector.on_pack(
                [qp.pod for _key, qps in units for qp in qps])
        tk, d_bucket = self.mirror.gang_pack_domain()
        need = np.zeros((g_bucket,), np.int32)
        own_nom = np.zeros((g_bucket, self.caps.nodes), np.int32)
        for i, (_key, qps) in enumerate(units):
            need[i] = len(qps)
            for qp in qps:
                nom = qp.pod.status.nominated_node_name
                if nom:
                    row = self.mirror.row_of(nom)
                    if row >= 0:
                        own_nom[i, row] += 1
        cblobs = self.mirror.to_blobs()
        if state is None:
            state = extract_state_jit(cblobs, self.caps)
        pcfg = self._profile_cfg[reps[0].spec.scheduler_name]
        out = pack_gangs_jit(
            cblobs, PodBlobs(f32=jnp.asarray(f32), i32=jnp.asarray(i32)),
            self.mirror.well_known(), self.caps, need, np.int32(tk),
            d_cap=d_bucket, enabled_filters=pcfg["filters"], active=feats,
            pfields=pfields, ptmpl=self.mirror.pod_template_blobs(),
            state=state, own_nom=jnp.asarray(own_nom))
        self.stats["gang_device_launches"] += 1
        self.metrics.gang_device_launches.inc()
        pshape = None
        prof = self.profiler
        if prof is not None:
            from kubernetes_tpu.telemetry.profiler import shape_key

            # the "gang" row of the shape key: a packer recompile (new
            # domain bucket / caps) is attributed, not "unattributed"
            pshape = shape_key(self.caps, g_bucket, False, d_bucket, 0,
                               True, False, False, False,
                               gang=g_bucket)
            prof.note_launch(pshape)
        # ONE pull for the whole wave: verdicts + placements + capacity
        # bounds + spans (+ any PreFilter capacity reductions awaiting
        # their ride — the folded gang_capacity D2H)
        cap_pulls = self._gang.take_pending_caps()
        pull = [out.ok, out.alloc, out.cap, out.spans, out.guard]
        pull.extend(arr for _key, _tok, arr in cap_pulls)
        vals = jax.device_get(tuple(pull))
        ok_arr, alloc_arr, cap_arr, spans_arr, guard = vals[:5]
        for (ckey, ctok, _arr), v in zip(cap_pulls, vals[5:]):
            self._gang.resolve_cap(ckey, ctok, int(v))
        launch_s = self.now() - t0
        self.flight.observe_phase("gang_device", launch_s)
        if prof is not None and pshape is not None:
            prof.observe_walltime(pshape, launch_s)
        if int(guard):
            raise DeviceFault(
                f"gang pack guard tripped (mask {int(guard):#x}): "
                "poisoned usage state")
        t_commit0 = self.now()
        fallback: list[QueuedPodInfo] = []
        alloc_np = np.asarray(alloc_arr)
        try:
            for i, (key, qps) in enumerate(units):
                # the packer's capacity column seeds the PreFilter memo:
                # the fallback bound never re-derives what this launch
                # already proved
                self._gang.note_device_cap(
                    key, self._gang.cap_token(self.mirror, qps[0].pod),
                    int(cap_arr[i]))
                counts = alloc_np[i]
                if bool(ok_arr[i]) and int(counts.sum()) == len(qps):
                    rows = np.repeat(np.arange(counts.shape[0]), counts)
                    names = [self.mirror.name_of_row(int(r))
                             for r in rows]
                    if any(nm is None for nm in names):
                        self.stats["gang_fallbacks"] += 1
                        self.metrics.gang_fallbacks.inc(reason="rows")
                        fallback.extend(qps)
                        continue
                    self._commit_gang_unit(key, qps, names)
                    continue
                if max((qp.pod.priority() for qp in qps), default=0) > 0:
                    # a positive-priority gang may open capacity by
                    # preempting: infeasibility is not provable — the
                    # host path's PostFilter owns it
                    self.stats["gang_fallbacks"] += 1
                    self.metrics.gang_fallbacks.inc(
                        reason="infeasible_preempting")
                    fallback.extend(qps)
                    continue
                group = self._gang.group_of(key)
                quorum = (max(group.min_member
                              - self._gang.bound_count(key), 1)
                          if group is not None else len(qps))
                if len(qps) > quorum:
                    # the packer places ALL present members or none; the
                    # Permit path can still admit the min_member quorum
                    # SUBSET when only that fits — don't park what the
                    # host path would schedule
                    self.stats["gang_fallbacks"] += 1
                    self.metrics.gang_fallbacks.inc(
                        reason="infeasible_partial")
                    fallback.extend(qps)
                    continue
                msg = (f"gang {key}: device packer found no "
                       f"all-or-nothing placement for {len(qps)} "
                       f"member(s) (capacity bound {int(cap_arr[i])})")
                for qp in qps:
                    qp.host_reject_counts = {}
                    self._park_unschedulable(qp, {"GangScheduling"}, msg)
        finally:
            self.flight.observe_phase("gang_commit",
                                      self.now() - t_commit0)
        # the chain advances to the launch's post-batch state unless a
        # rollback/park above invalidated it (epoch check, like
        # _dispatch); parked/fallback units were never debited on device
        if epoch == self._chain_epoch:
            self._chain = (out.free, out.nzr)
        return fallback

    def _commit_gang_unit(self, key: str, qps: list[QueuedPodInfo],
                          node_names: list[str]) -> None:
        """Atomic host commit of one device-placed gang: reserve EVERY
        member first; any failure rolls the whole unit back before a
        single member reaches the binder (all-or-nothing, no Permit
        round-trips — the device verdict is the quorum)."""
        fw = self._fw_for(qps[0].pod)
        reserved: list[tuple] = []
        failure = None
        fail_i = len(qps)
        for i, (qp, node) in enumerate(zip(qps, node_names)):
            fail_i = i
            pod = qp.pod
            assumed = pod.clone()
            assumed.spec.node_name = node
            self.cache.assume_pod(assumed)
            state = CycleState()
            try:
                s = fw.run_reserve_plugins(state, pod, node)
            except Unavailable as e:
                failure = (qp, state, assumed, node,
                           f"reserve: {e}", "unreachable")
                break
            except Exception as e:  # noqa: BLE001 — poison seam, like
                # _commit: strike so a repeat offender quarantines
                self._fault_strikes[qp.uid] = \
                    self._fault_strikes.get(qp.uid, 0) + 1
                failure = (qp, state, assumed, node,
                           f"reserve raised: {e!r}", "")
                break
            if not s.is_success():
                failure = (qp, state, assumed, node,
                           f"reserve: {s.message()}",
                           s.plugin if s.is_rejected() else "")
                break
            reserved.append((qp, state, assumed, node))
        if failure is not None:
            self._gang.stats["rollbacks"] += 1
            self.metrics.gang_rollbacks.inc()
            fqp, fstate, fassumed, fnode, msg, tag = failure
            peer_msg = f"gang {key} rollback: peer {fqp.pod.key()}: {msg}"
            for qp, state, assumed, node in reserved:
                self._undo_commit(
                    qp, state, assumed, node, peer_msg,
                    rejected_by=("" if tag == "unreachable"
                                 else "GangScheduling"),
                    park_unreachable=(tag == "unreachable"))
            self._undo_commit(
                fqp, fstate, fassumed, fnode, msg,
                rejected_by=(tag if tag not in ("", "unreachable")
                             else ""),
                park_unreachable=(tag == "unreachable"))
            # members AFTER the failure never reserved, but they are
            # part of the all-or-nothing unit: park them with the same
            # attribution instead of dropping them from the queue
            for qp in qps[fail_i + 1:]:
                if tag == "unreachable":
                    self._park_unreachable(qp)
                else:
                    self._park_unschedulable(qp, {"GangScheduling"},
                                             peer_msg)
            return
        # every member reserved: the device verdict IS the quorum —
        # Permit answers allow for marked uids. Permits run for the
        # WHOLE unit before any member reaches the binder: a failure
        # rolls every member back (all-or-nothing holds through the
        # permit stage too — undoing only the failing member would
        # leave its peers binding as a partial gang).
        self._gang.device_admit(key, {qp.uid for qp, *_rest in reserved})
        verdicts: list[tuple] = []
        failure = None
        try:
            for qp, state, assumed, node in reserved:
                try:
                    s, waits = fw.run_permit_plugins(state, qp.pod, node)
                except Unavailable as e:
                    failure = (qp, f"permit: {e}", "unreachable")
                    break
                except Exception as e:  # noqa: BLE001
                    self._fault_strikes[qp.uid] = \
                        self._fault_strikes.get(qp.uid, 0) + 1
                    failure = (qp, f"permit raised: {e!r}", "")
                    break
                if not s.is_success() and s.code != Code.WAIT:
                    failure = (qp, f"permit: {s.message()}",
                               s.plugin if s.is_rejected() else "")
                    break
                verdicts.append((qp, state, assumed, node, s, waits))
        finally:
            self._gang.clear_device_admit(key)
        if failure is not None:
            self._gang.stats["rollbacks"] += 1
            self.metrics.gang_rollbacks.inc()
            fqp, msg, tag = failure
            peer_msg = f"gang {key} rollback: peer {fqp.pod.key()}: {msg}"
            for qp, state, assumed, node in reserved:
                own = qp.uid == fqp.uid
                if tag == "unreachable":
                    rej = ""
                elif own:
                    rej = tag    # "" (error class) or rejecting plugin
                else:
                    rej = "GangScheduling"
                self._undo_commit(
                    qp, state, assumed, node, msg if own else peer_msg,
                    rejected_by=rej,
                    park_unreachable=(tag == "unreachable"))
            return
        for qp, state, assumed, node, s, waits in verdicts:
            if s.code == Code.WAIT:
                # another permit plugin wants the wait room: honor it
                fw.waiting_pods.add(WaitingPod(qp, node, state, waits,
                                               self.now()))
            else:
                self._start_binding(qp, state, assumed, node)
        self._gang.stats["admitted"] += 1
        self._gang.stats["device_admitted"] += 1
        self.metrics.gang_admitted.inc()

    def _host_relevant(self, pod: Pod) -> bool:
        if self._host_gates is None:
            return True
        if self._has_host_scores and (
                self._host_score_gates is None
                or any(g(pod) for g in self._host_score_gates)):
            # host scoring applies to this pod (per-plugin applies()
            # probes — a host scorer must not re-route PLAIN pods
            # through the per-node Python score loop)
            return True
        if any(ext.is_interested(pod) for ext in self._extenders):
            return True
        return any(gate(pod) for gate in self._host_gates)

    def _defer_host_conflicts(self, runnable: list[QueuedPodInfo]
                              ) -> list[QueuedPodInfo]:
        """Host plugins can't see in-batch commits (their filters run once
        per batch against the snapshot), so two pods whose host verdicts
        can influence each other — a shared write-restricted volume, a
        ReadWriteOncePod claim, an unbound PVC both want — must not share a
        batch: keep the first, defer the rest to the next batch."""
        from kubernetes_tpu.plugins.dra import dra_serial_keys
        from kubernetes_tpu.plugins.volume import host_serial_keys

        seen: set[str] = set()
        keep: list[QueuedPodInfo] = []
        for qp in runnable:
            if not qp.pod.spec.volumes \
                    and not qp.pod.spec.resource_claims:
                keep.append(qp)
                continue
            keys = (host_serial_keys(self.hub, qp.pod)
                    | dra_serial_keys(self.hub, qp.pod))
            if keys & seen:
                self._deferred.append(qp)
            else:
                seen |= keys
                keep.append(qp)
        return keep

    def _run_host_plugins(self, runnable: list[QueuedPodInfo]):
        """Host Filter (and Score) plugins per pod over the synced snapshot;
        returns (host_ok [B, N] | None, host_score [B, N] | None) aligned to
        mirror rows. Plugins PreFilter-Skip irrelevant pods, so this is a
        few dict probes per pod for volume-less workloads."""
        relevant = [
            (i, qp) for i, qp in enumerate(runnable)
            if self._host_relevant(qp.pod)]
        if not relevant:
            return None, None
        ext_names = ext_rows = None
        if self._extenders:
            ext_names = [ni.node.metadata.name
                         for ni in self.snapshot.node_info_list]
            ext_rows = {n: self.mirror.row_of(n) for n in ext_names}
        # host plugins read the HUB (claims, pod placements): every
        # outstanding binding cycle must land first or a conflict check
        # could miss a just-bound pod
        self._drain_bind_results(wait=True)
        infos = self.snapshot.node_info_list
        host_ok = None
        host_score = None
        rows = None
        b_cap = self.config.batch_size
        n_cap = self.caps.nodes

        def node_rows():
            nonlocal rows
            if rows is None:
                rows = np.array([self.mirror.row_of(ni.name)
                                 for ni in infos], np.int64)
            return rows

        for i, qp in relevant:
            qp.host_reject_counts = {}
            state = CycleState()
            fw = self._fw_for(qp.pod)
            mask, counts, early = fw.run_host_filters(state, qp.pod, infos)
            if counts:
                qp.host_reject_counts = counts
            if early is not None:
                if host_ok is None:
                    host_ok = np.ones((b_cap, n_cap), bool)
                host_ok[i, :] = False
                continue
            if mask is not None and not all(mask):
                if host_ok is None:
                    host_ok = np.ones((b_cap, n_cap), bool)
                r = node_rows()
                bad = r[~np.asarray(mask, bool)]
                host_ok[i, bad[bad >= 0]] = False
            scores = (fw.run_host_scores(state, qp.pod, infos)
                      if self._has_host_scores else None)
            if scores is not None:
                if host_score is None:
                    host_score = np.zeros((b_cap, n_cap), np.float32)
                r = node_rows()
                ok = r >= 0
                host_score[i, r[ok]] = np.asarray(scores, np.float32)[ok]
            if ext_names is not None:
                host_ok, host_score = self._run_extenders(
                    qp, i, ext_names, ext_rows, host_ok, host_score,
                    b_cap, n_cap)
        return (jnp.asarray(host_ok) if host_ok is not None else None,
                jnp.asarray(host_score) if host_score is not None else None)

    def _run_extenders(self, qp, i, names, name_row, host_ok, host_score,
                       b_cap, n_cap):
        """Legacy HTTP extenders (extender.go:248 Filter, :319
        Prioritize): verdicts AND into the host mask, weighted scores add
        into the aggregate; an unreachable ignorable extender is skipped,
        a non-ignorable one fails the pod for this cycle."""
        from kubernetes_tpu.extender import ExtenderError

        interested = [ext for ext in self._extenders
                      if ext.is_interested(qp.pod)]
        if not interested:
            return host_ok, host_score
        candidates = list(names)
        for ext in interested:
            try:
                nodes = None
                if not ext.cfg.node_cache_capable:
                    # non-nodeCacheCapable: ship full node objects
                    # (extender.go:258 Nodes vs NodeNames)
                    nodes = [info.node for name in candidates
                             if (info := self.snapshot.node_info_map.get(
                                 name)) is not None]
                passed, failed = ext.filter(qp.pod, candidates, nodes)
                scores = ext.prioritize(qp.pod, candidates, nodes)
            except ExtenderError as e:
                if ext.cfg.ignorable:
                    continue
                qp.host_reject_counts[ext.name] = len(candidates)
                if host_ok is None:
                    host_ok = np.ones((b_cap, n_cap), bool)
                host_ok[i, :] = False
                logger.warning("extender failed: %s", e)
                return host_ok, host_score
            rejected = set(failed) | (set(candidates) - set(passed))
            if rejected:
                qp.host_reject_counts[ext.name] = (
                    qp.host_reject_counts.get(ext.name, 0) + len(rejected))
                if host_ok is None:
                    host_ok = np.ones((b_cap, n_cap), bool)
                for name in rejected:
                    row = name_row.get(name, -1)
                    if row >= 0:
                        host_ok[i, row] = False
                candidates = [n for n in candidates if n not in rejected]
            if scores:
                if host_score is None:
                    host_score = np.zeros((b_cap, n_cap), np.float32)
                for name, sc in scores.items():
                    row = name_row.get(name, -1)
                    if row >= 0:
                        host_score[i, row] += sc
        return host_ok, host_score

    def _pull_launch(self, out: BatchResult, flags: tuple) -> tuple:
        """The commit-thread half of _finish: ONE blocking D2H pull of the
        launch's verdict tensors (rows + guard + the flag-gated
        learned-magnitude / export tensors — a second device_get would be
        a second full round trip). Under pipelined waves this runs on the
        commit thread so the transfer wait — the wave's actual
        serialization — overlaps the next wave's device time. It touches
        NO host state (the single-mutator invariant: assume/bind/queue
        mutation stays on the loop thread) and takes no locks; exceptions
        (including the chaos commit_pull seam) surface in _finish via
        fut.result() and ride the normal containment ladder. Returns
        (vals, t_ready, pull_s) — t_ready timestamps verdict
        availability (the honest end of the device span); pull_s is this
        thread's own wall inside the pull, booked by _finish as the
        overlapped commit_pull phase when it ran off-thread."""
        t_pull0 = self.now()
        learned_on, exporting, want_feats, want_alts = flags
        fi = self.fault_injector
        if fi is not None:
            hook = getattr(fi, "on_commit_pull", None)
            if hook is not None:
                hook()          # chaos seam: may raise
        pull = [out.node_row, out.guard]
        if learned_on:
            pull.append(out.learned_mag)
        if exporting:
            pull.append(out.score)
            if want_feats:
                pull.append(out.chosen_feat)
            if want_alts:
                pull.append(out.alt_row)
                pull.append(out.alt_score)
        vals = jax.device_get(tuple(pull))
        t_ready = self.now()
        return vals, t_ready, t_ready - t_pull0

    def _finish(self, inflight: tuple) -> None:
        """Pull one dispatched launch's results and commit/fail each pod."""
        (runnable, out, t_dispatched, pack_s, tr, flags,
         pshape, compiled, fut) = inflight
        learned_on, exporting, want_feats, want_alts = flags
        # re-attach the cycle's trace: the pipelined drain may have
        # dispatched k+1 (opening its trace) before finishing k
        self.flight.resume(tr)
        n = len(runnable)
        t0 = self.now()
        if fut is not None:
            # off-thread commit: the pull has been running on the commit
            # thread since dispatch; a commit-thread exception re-raises
            # HERE and rides the same _finish_contained blast-radius
            # ladder an inline fault would. wait_s is the loop thread's
            # ACTUAL blocked time — the wave's serial cost; the commit
            # thread's pull span (pull_s) overlapped loop-thread work.
            vals, t_ready, pull_s = fut.result()
            wait_s = max(self.now() - t0, 0.0)
        else:
            vals, t_ready, pull_s = self._pull_launch(out, flags)
            wait_s = None
        # PreFilter gang-capacity reductions cannot ride the commit
        # thread's pull (they register on the loop thread, possibly
        # after dispatch); rare — gang PreFilter only — so they get
        # their own small transfer when present
        cap_pulls = self._gang.take_pending_caps()
        if cap_pulls:
            cvals = jax.device_get(
                tuple(arr for _key, _tok, arr in cap_pulls))
            for (ckey, ctok, _arr), v in zip(cap_pulls, cvals):
                self._gang.resolve_cap(ckey, ctok, int(v))
        rows_arr, guard = vals[0], vals[1]
        k = 2
        lmag = None
        if learned_on:
            lmag = vals[k]
            k += 1
        scores_arr = feats_arr = alt_rows_arr = alt_scores_arr = None
        if exporting:
            scores_arr = vals[k]
            k += 1
            if want_feats:
                feats_arr = vals[k]
                k += 1
            if want_alts:
                alt_rows_arr = vals[k]
                alt_scores_arr = vals[k + 1]
        if int(guard):
            # the launch's own guard reduction tripped: NaN scores or a
            # poisoned usage chain — nothing below can be trusted; the
            # containment wrapper degrades this batch to the host path
            raise DeviceFault(
                f"launch guard tripped (mask {int(guard):#x}): "
                f"{'NaN scores ' if int(guard) & 1 else ''}"
                f"{'poisoned usage state' if int(guard) & 2 else ''}")
        if lmag is not None:
            # observed only AFTER the guard check: a NaN-poisoned
            # checkpoint must not corrupt the magnitude histogram's sum
            # forever (Histogram.observe accumulates the raw value)
            self.metrics.learned_magnitude.observe(float(lmag))
        rows = np.asarray(rows_arr)[:n].tolist()
        # the device span ends when the verdict pull completed (t_ready,
        # stamped by whichever thread ran it) — under pipelining the loop
        # may harvest the future long after, and that host overlap time
        # must not masquerade as device time
        launch_s = max(t_ready - t_dispatched, 0.0)
        if exporting:
            # export v2/v3 placement rows: (pod, chosen node, aggregate
            # score[, chosen-node feature vector when
            # trace_export_features][, top-K alternative node scores
            # when trace_export_alts]) — the replay dataset's substrate,
            # already pulled with rows+guard above. Failed attempts
            # export node=None (time-to-bind anchors).
            placements = []
            for i, (qp, row) in enumerate(zip(runnable, rows)):
                rec = {"pod": qp.pod.key(), "uid": qp.uid}
                if row >= 0:
                    rec["node"] = self.mirror.name_of_row(row)
                    rec["score"] = round(float(scores_arr[i]), 4)
                    if feats_arr is not None:
                        rec["feat"] = [round(float(v), 5)
                                       for v in feats_arr[i]]
                    if alt_rows_arr is not None:
                        # the chosen node's own entry RIDES ALONG when
                        # top_k surfaced it: on the auction path the
                        # alt scores are end-state attributed while
                        # "score" is the decision-round win — regret
                        # must compare chosen vs alternatives on ONE
                        # basis, so the offline consumer prefers the
                        # chosen node's in-list score as its value
                        alt = []
                        for ar, asc in zip(alt_rows_arr[i],
                                           alt_scores_arr[i]):
                            if int(ar) < 0 or float(asc) <= ALT_NONE / 2:
                                continue
                            nm = self.mirror.name_of_row(int(ar))
                            if nm:
                                alt.append([nm, round(float(asc), 4)])
                        rec["alt"] = alt
                else:
                    rec["node"] = None
                # the wire-trace stamps known at commit time (the
                # "created" hub-commit stamp and its hop count join
                # offline analysis to the cluster's commit clock; the
                # ack stamps land later via /debug/pod)
                wire = self.timelines.wire_of(qp.uid)
                if wire:
                    rec["wire"] = wire
                placements.append(rec)
            tr.placements = placements
        t1 = self.now()
        # reject attribution is only read on failure; skipping the [B, P]
        # pull when every pod placed keeps the host<->device link to one
        # tiny [B] row vector. NOTE: an on-device gather of just the
        # failed rows measured SLOWER — a gather is a compute op that
        # queues behind the already-dispatched next launch, while
        # device_get of a materialized array is a pure transfer
        fail_is = [i for i in range(n) if rows[i] < 0]
        rejects = None
        if fail_is:
            t_pull0 = self.now()
            rejects, dra_rej = jax.device_get((out.reject_counts,
                                               out.dra_reject))
            rejects = np.asarray(rejects)
            # fused DRA rejections fold into host_reject_counts so
            # diagnosis, requeue hints, and the preemption fast-path
            # gate behave exactly as they did on the host filter path
            for i in fail_is:
                c = int(dra_rej[i])
                if c:
                    runnable[i].host_reject_counts["DynamicResources"] = c
            # the rows/guard pull above is inseparable from the device
            # wait (folded into device_launch); this one is a pure
            # post-compute transfer — the honest D2H measurement
            tr.add("d2h_pull", self.now() - t_pull0)
        t_commit0 = self.now()
        for qp, row in zip(runnable, rows):
            if row >= 0:
                self._commit(qp, self.mirror.name_of_row(row))
        t_commit1 = self.now()
        tr.add("commit", t_commit1 - t_commit0)
        n_fail = len(fail_is)
        if fail_is:
            self._handle_failures([(runnable[i], rejects[i].tolist())
                                   for i in fail_is])
            tr.add("failure_handling", self.now() - t_commit1)
        commit_s = self.now() - t1
        cycle_s = pack_s + launch_s + commit_s
        if wait_s is None:
            # inline pull (pipelining off): the loop thread was blocked
            # for the whole device span — all of it is serial cost
            tr.add("device_launch", launch_s)
        else:
            # pipelined arm: only the harvest wait serialized the loop
            # thread; the commit thread's pull span is recorded as the
            # overlapped commit_pull view (excluded from totals/host-tail
            # like VIEW_PHASES) so /debug/trace keeps the attribution
            # without booking concurrent wall time as if serial
            tr.add("device_launch", wait_s)
            tr.add("commit_pull", pull_s)
        if self.profiler is not None and pshape is not None:
            self.profiler.observe_walltime(pshape, launch_s)
            if compiled:
                # attribution view: this cycle's launch walltime was
                # (mostly) an XLA compile — the stall MixedChurn's
                # re-bucketing pays, now visible per phase
                tr.add("device_compile", launch_s)
        tr.scheduled = n - n_fail
        tr.failed = n_fail
        # device occupancy: launch-in-flight fraction of this cycle's
        # wall (dispatch open -> commit done). 1.0 = the device never
        # sat idle waiting on host work — the pipelining headline.
        tr.occupancy = max(0.0, min(
            1.0, launch_s / max(self.now() - tr.start, 1e-9)))
        self.flight.record(tr)
        m = self.metrics
        m.algorithm_duration.observe(launch_s)
        m.batch_duration.observe(cycle_s)
        m.extension_point_duration.observe(pack_s, extension_point="PreFilter")
        m.extension_point_duration.observe(launch_s, extension_point="Filter")
        m.extension_point_duration.observe(commit_s, extension_point="Reserve")
        per_pod = cycle_s / max(n, 1)
        if n - n_fail:
            m.attempt_duration.observe(per_pod, n=n - n_fail,
                                       result="scheduled")
        if n_fail:
            m.attempt_duration.observe(per_pod, n=n_fail,
                                       result="unschedulable")
        if cycle_s > SLOW_CYCLE_SECONDS:
            # schedule_one.go:404's slow-attempt trace, batch-shaped
            from kubernetes_tpu.utils.tracing import Trace

            tr = Trace("schedule_cycle", pods=n,
                       scheduled=sum(1 for r in rows if r >= 0))
            tr.start -= cycle_s     # reconstruct from measured phases
            tr.steps = [("pack+host_plugins", 0.0, pack_s, 0),
                        ("device_launch", pack_s, launch_s, 0),
                        ("commit+bind", pack_s + launch_s, commit_s, 0)]
            tr.log_if_long(SLOW_CYCLE_SECONDS, logger)

    def schedule_one_batch(self) -> int:
        """Pop up to batch_size pods, run one device launch, commit results.
        Returns the number of pods attempted (0 = queue idle)."""
        with self._lock:
            self._process_deferred_events()
            self._process_waiting()
            if self.jobqueue.active:
                self.jobqueue.release(self.queue, self._effective_batch())
            popped, runnable = self._pop_runnable()
            if popped == 0:
                self._drain_bind_results(wait=True)
                self._flush_evictions_safe()
                self._process_deferred_events()
                return 0
            if runnable:
                # device-packable gang units commit through their own
                # fused launch first; the normal path keeps the rest
                runnable = self._schedule_gang_units(runnable)
            if runnable:
                try:
                    inflight = self._dispatch(
                        runnable, self._chain_eligible(
                            [qp.pod for qp in runnable]))
                except Unavailable:
                    self._park_batch_unreachable(runnable)
                    inflight = None
                except Exception as e:  # noqa: BLE001 — containment seam
                    self._contain_batch_fault(runnable, e)
                    inflight = None
                if inflight is not None:
                    self._finish_contained(inflight)
            self._drain_bind_results(wait=True)
            # async preemption: victims queued by PostFilter are evicted
            # here, OUTSIDE the cycle (prepareCandidateAsync's analog)
            self._flush_evictions_safe()
            self._process_deferred_events()
            return popped

    def _commit(self, qp: QueuedPodInfo, node_name: str) -> None:
        """assume -> reserve -> permit (schedule_one.go:142); the binding
        cycle (prebind/bind) then runs on the binder pool
        (schedule_one.go:124's per-pod goroutine) and completes via
        _drain_bind_results. A WAIT permit parks the pod in the
        waitingPodsMap with its reservation held."""
        pod = qp.pod
        assumed = pod.clone()
        assumed.spec.node_name = node_name
        if self.cache.get_pod(assumed) is not None \
                and not self.cache.is_assumed_pod(assumed):
            # the pod is already in the cache CONFIRMED: a sibling
            # replica's bind landed through our informer between the
            # pop and this commit (scale-out post-rebalance race).
            # assume_pod would raise ("already in cache") and take the
            # whole device batch down the host-fallback ladder — the
            # pod is placed and theirs; drop our attempt exactly like
            # _undo_commit's foreign-confirm path
            if self.flight.enabled:
                self.timelines.event(
                    qp.pod, "foreign_bound",
                    f"confirmed on "
                    f"{self.cache.get_pod(assumed).spec.node_name} "
                    f"by a sibling replica (pre-commit)")
            self._invalidate_chain()
            self.queue.done(qp.uid)
            return
        self.cache.assume_pod(assumed)
        state = CycleState()
        fw = self._fw_for(pod)
        # binding a pod with (anti)affinity terms makes the mirror's pod
        # table stale: the chain must not skip the sync that packs it
        if self.mirror.batch_has_topology([pod]):
            self._invalidate_chain()
        try:
            s = fw.run_reserve_plugins(state, pod, node_name)
        except Unavailable as e:
            # reserve plugins read the hub (DRA claims): an outage here
            # must not wedge the rest of the batch in-flight — undo the
            # assume and park this pod like any other unreachable write
            self._undo_commit(qp, state, assumed, node_name,
                              f"reserve: {e}", park_unreachable=True)
            return
        except Exception as e:  # noqa: BLE001 — a raising out-of-tree
            # plugin must not strand the assume (the pod would be a
            # phantom placement forever); error path + strike so a
            # repeat offender quarantines
            self._fault_strikes[qp.uid] = \
                self._fault_strikes.get(qp.uid, 0) + 1
            self._undo_commit(qp, state, assumed, node_name,
                              f"reserve raised: {e!r}")
            return
        if not s.is_success():
            # a REJECTING reserve (e.g. DRA "devices vanished" — the
            # designed same-batch capacity race) is unschedulable with
            # plugin attribution, not a scheduler error; only raising
            # plugins land on the error path
            self._undo_commit(qp, state, assumed, node_name,
                              f"reserve: {s.message()}",
                              rejected_by=(s.plugin if s.is_rejected()
                                           else ""))
            return
        try:
            s, waits = fw.run_permit_plugins(state, pod, node_name)
        except Unavailable as e:
            self._undo_commit(qp, state, assumed, node_name,
                              f"permit: {e}", park_unreachable=True)
            return
        except Exception as e:  # noqa: BLE001 — same containment as
            # reserve: undo the assume, error path, strike
            self._fault_strikes[qp.uid] = \
                self._fault_strikes.get(qp.uid, 0) + 1
            self._undo_commit(qp, state, assumed, node_name,
                              f"permit raised: {e!r}")
            return
        if s.code == Code.WAIT:
            fw.waiting_pods.add(WaitingPod(qp, node_name, state, waits,
                                           self.now()))
            return
        if not s.is_success():
            self._undo_commit(qp, state, assumed, node_name,
                              f"permit: {s.message()}",
                              rejected_by=(s.plugin if s.is_rejected()
                                           else ""))
            return
        self._start_binding(qp, state, assumed, node_name)

    def _undo_commit(self, qp: QueuedPodInfo, state: CycleState,
                     assumed: Pod, node_name: str, msg: str,
                     rejected_by: str = "",
                     park_unreachable: bool = False) -> None:
        """Unreserve + Forget, then requeue: error-class for infrastructure
        failures (schedule_one.go:337's bind-failure path), unschedulable
        with plugin attribution when a plugin REJECTED the pod (permit
        reject/timeout goes through handleSchedulingFailure as
        Unschedulable, schedule_one.go:270). ``park_unreachable`` routes a
        hub-outage failure to the degraded-mode park instead — the bind
        may or may not have landed; the informer's relist decides, and the
        hub's bind-once Conflict guarantees no double-bind either way."""
        try:
            self._fw_for(qp.pod).run_unreserve_plugins(state, qp.pod,
                                                       node_name)
        except Unavailable:
            # hub-side claim state reconciles via informer truth after
            # the outage; the local overlay cleanup below is what matters
            self._note_hub_down()
        if not self.cache.is_assumed_pod(assumed) \
                and self.cache.get_pod(assumed) is not None:
            # the pod is in the cache CONFIRMED, not assumed: another
            # actor's bind landed through our informer while this
            # attempt was failing (scale-out: a sibling replica won a
            # post-rebalance race and add_pod's informer-truth-wins
            # replaced our assumed state; our own bind then answered
            # Conflict). The pod is placed and theirs — forget_pod
            # would raise ("confirmed, cannot forget") and requeueing
            # would re-schedule a bound pod. Drop our claim instead,
            # exactly like _finish_fenced's foreign-confirm path.
            if self.flight.enabled:
                self.timelines.event(
                    qp.pod, "foreign_bound",
                    f"confirmed on "
                    f"{self.cache.get_pod(assumed).spec.node_name} "
                    f"by a sibling replica (undo-commit)")
            self._invalidate_chain()
            self.queue.done(qp.uid)
            return
        self.cache.forget_pod(assumed)
        # the device chain assumed this placement; force a re-sync
        self._invalidate_chain()
        if park_unreachable:
            self._note_hub_down()
            self._park_unreachable(qp)
            return
        if rejected_by:
            if self.flight.enabled:
                self.timelines.diagnose(qp.pod, {}, {rejected_by: -1}, msg)
                self.timelines.event(qp.pod, "unschedulable", msg)
            qp.unschedulable_plugins = {rejected_by}
            qp.unschedulable_count += 1
            qp.consecutive_errors_count = 0
            self.stats["unschedulable"] += 1
            self._patch_condition_best_effort(qp.pod, PodCondition(
                type="PodScheduled", status="False", reason="Unschedulable",
                message=msg))
            self.queue.add_unschedulable_if_not_present(qp)
        else:
            self._error(qp, msg)

    def _extenders_binding(self, pod: Pod, node_name: str):
        """First interested binder extender binds INSTEAD of the bind
        plugins (schedule_one.go:960 extendersBinding). Returns a Status
        or None when no extender claims the pod."""
        from kubernetes_tpu.extender import ExtenderError
        from kubernetes_tpu.framework.interface import Status

        for ext in self._extenders:
            if not ext.is_binder or not ext.is_interested(pod):
                continue
            try:
                ext.bind(pod, node_name)
                # the extender performed the API binding; reflect it in
                # the hub like the Binding POST would (fenced: a deposed
                # leader's delegated bind must be rejected too)
                self._fenced_bind(pod, node_name)
                return Status()
            except Unavailable:
                raise    # transport outage: degraded mode parks the pod
            except Fenced:
                raise    # deposed epoch: _bind_task tags, claim released
            except ExtenderError as e:
                return Status.error(str(e))
            except Exception as e:  # noqa: BLE001
                return Status.error(f"extender bind raised: {e!r}")
        return None

    def _bind_task(self, state: CycleState, pod: Pod, node_name: str,
                   fargs: tuple = None):
        fw = self._fw_for(pod)
        t0 = time.monotonic()
        if fargs is not None:
            # decision-time fencing token (see _fenced_bind)
            self._bind_fence.args = fargs
        try:
            s = fw.run_pre_bind_plugins(state, pod, node_name)
            if s.is_success():
                ext_s = self._extenders_binding(pod, node_name)
                s = ext_s if ext_s is not None \
                    else fw.run_bind_plugins(state, pod, node_name)
        except Unavailable as e:
            # hub outage mid-bind: tagged so _finish_binding parks the
            # pod in degraded mode instead of taking the error path
            from kubernetes_tpu.framework.interface import Status

            s = Status.error(f"hub unavailable: {e}",
                             plugin="HubUnavailable")
        except Fenced as e:
            # we were deposed while this bind was in flight: the hub
            # rejected it, the new leader owns the pod now — tagged so
            # _finish_binding releases our claim without status writes
            from kubernetes_tpu.framework.interface import Status

            s = Status.error(f"fenced: {e}", plugin="Fenced")
        except Exception as e:  # noqa: BLE001 — a raising out-of-tree
            # plugin must not poison the chunk/future (every other pod in
            # it would stay assumed forever)
            from kubernetes_tpu.framework.interface import Status

            s = Status.error(f"bind cycle raised: {e!r}")
        finally:
            self._bind_fence.args = None    # don't leak across chunks
        self.recorder.observe(self.metrics.extension_point_duration,
                              time.monotonic() - t0, extension_point="Bind")
        return s

    def _start_binding(self, qp: QueuedPodInfo, state: CycleState,
                       assumed: Pod, node_name: str) -> None:
        # the fencing token travels WITH the bind from here: the epoch
        # this placement was decided under, not whatever the elector
        # holds when the binder thread finally executes it
        fargs = self._fencing_args()
        if self._binder is None:
            self._finish_binding(qp, state, assumed, node_name,
                                 self._bind_task(state, qp.pod, node_name,
                                                 fargs))
            self._process_deferred_events()
        else:
            # per-pod futures are too fine for python threads; the backlog
            # is chunked across the pool by _submit_bind_backlog
            self._bind_backlog.append((qp, state, assumed, node_name,
                                       fargs))

    def _submit_bind_backlog(self) -> None:
        backlog, self._bind_backlog = self._bind_backlog, []
        if not backlog:
            return
        workers = max(1, self.config.binding_workers)
        chunk = max(1, -(-len(backlog) // workers))

        def run_chunk(items):
            return [self._bind_task(state, qp.pod, node_name, fargs)
                    for qp, state, assumed, node_name, fargs in items]

        for i in range(0, len(backlog), chunk):
            items = backlog[i:i + chunk]
            self._inflight_binds.append(
                (items, self._binder.submit(run_chunk, items)))

    def _drain_bind_results(self, wait: bool = False) -> None:
        """Collect finished binding cycles (all of them when ``wait``);
        the binder thread's own hub events replay here, on the loop
        thread, right after each completion."""
        self._submit_bind_backlog()
        if not self._inflight_binds:
            return
        t_drain0 = self.now()
        drained = False
        still: list[tuple] = []
        for item in self._inflight_binds:
            items, fut = item
            if wait or fut.done():
                drained = True
                for (qp, state, assumed, node_name, _fargs), s in zip(
                        items, fut.result()):
                    self._finish_binding(qp, state, assumed, node_name, s)
                self._process_deferred_events()
            else:
                still.append(item)
        self._inflight_binds = still
        if drained:
            self.flight.observe_phase("binder_drain",
                                      self.now() - t_drain0)

    def _finish_binding(self, qp: QueuedPodInfo, state: CycleState,
                        assumed: Pod, node_name: str, s) -> None:
        if not s.is_success():
            if s.plugin == "Fenced":
                self._finish_fenced(qp, state, assumed, node_name)
                return
            self._undo_commit(qp, state, assumed, node_name,
                              f"bind: {s.message()}",
                              park_unreachable=(
                                  s.plugin == "HubUnavailable"))
            return
        self.cache.finish_binding(assumed)
        self.nominator.delete(qp.uid)
        self.queue.done(qp.uid)
        self._fault_strikes.pop(qp.uid, None)
        self._fw_for(qp.pod).run_post_bind_plugins(state, qp.pod, node_name)
        qp.consecutive_errors_count = 0
        self.stats["scheduled"] += 1
        self.metrics.schedule_attempts.inc(
            result="scheduled", profile=qp.pod.spec.scheduler_name)
        self.metrics.pod_scheduling_attempts.observe(qp.attempts)
        if self.flight.enabled:
            # the reference's e2e pod_scheduling_duration_seconds: first
            # attempt -> successful bind, by attempts needed (capped so
            # the label set stays bounded)
            t_bind = self.now()
            if qp.initial_attempt_timestamp is not None:
                self.metrics.pod_e2e_duration.observe(
                    t_bind - qp.initial_attempt_timestamp,
                    attempts=str(min(qp.attempts, 16)))
            self.timelines.event(qp.pod, "bound", node_name, t=t_bind)

    def _finish_fenced(self, qp: QueuedPodInfo, state: CycleState,
                       assumed: Pod, node_name: str) -> None:
        """A deposed leader's in-flight bind was rejected by the fencing
        check: release the optimistic claim quietly. NO condition patch
        (the new leader owns the pod's status — and ours are fenced
        anyway) and no error accounting — the pod did nothing wrong. It
        parks error-class so a later re-election finds it retryable;
        the new leader's bind confirms through the informer and deletes
        it from our queue like any foreign placement."""
        self.stats["fenced"] += 1
        self.metrics.fenced_writes.inc(verb="bind")
        telemetry.incident(self, "fenced_bind",
                           reason="in-flight bind rejected by fencing "
                                  "(leadership deposed)",
                           pod=qp.pod.key(), node=node_name)
        try:
            self._fw_for(qp.pod).run_unreserve_plugins(state, qp.pod,
                                                       node_name)
        except Unavailable:
            self._note_hub_down()
        if not self.cache.is_assumed_pod(assumed):
            # the new leader's bind of this pod already CONFIRMED through
            # our informer (add_pod replaced the assumed state): the pod
            # is theirs, placed and cached — nothing to forget or requeue
            if self.flight.enabled:
                cached = self.cache.get_pod(assumed)
                self.timelines.event(
                    qp.pod, "foreign_bound",
                    f"confirmed on "
                    f"{cached.spec.node_name if cached else '?'} "
                    f"by the new leader (fenced)")
            self.queue.done(qp.uid)
            return
        self.cache.forget_pod(assumed)
        self._invalidate_chain()
        qp.unschedulable_plugins = set()
        qp.consecutive_errors_count += 1
        self.queue.add_unschedulable_if_not_present(qp)

    def _process_waiting(self) -> None:
        """Harvest the waitingPodsMap: fully-allowed pods proceed to the
        binding cycle; rejected/timed-out pods unreserve and requeue
        (waiting_pods_map.go semantics)."""
        ready: list = []
        failed: list = []
        for fw in self.frameworks.values():
            r, f = fw.waiting_pods.harvest(self.now())
            ready.extend(r)
            failed.extend(f)
        for wp in ready:
            assumed = wp.qp.pod.clone()
            assumed.spec.node_name = wp.node_name
            self._start_binding(wp.qp, wp.state, assumed, wp.node_name)
        for wp, s in failed:
            assumed = wp.qp.pod.clone()
            assumed.spec.node_name = wp.node_name
            self._undo_commit(wp.qp, wp.state, assumed, wp.node_name,
                              s.message(), rejected_by=s.plugin or "Permit")

    def _handle_failures(self, failures: list[tuple]) -> None:
        """handleSchedulingFailure (schedule_one.go:1015) for a whole
        batch: record diagnoses, run PostFilter (preemption), patch
        conditions, park. Fit-only rejections of equal priority share ONE
        batched preemption sweep (Evaluator.batch_preempt) — a churn of
        identical preemptors costs one launch, not one per pod, and burst
        members never target the same capacity."""
        fit_idx = FILTER_PLUGINS.index("NodeResourcesFit")
        prepped = []
        any_pf = False
        for qp, reject_counts in failures:
            # NOTE: auction-mode (parallel-rounds) launches attribute
            # reject_counts against END-state capacity, not the state each
            # pod was evaluated under mid-drain (_rounds_commit) — plugin
            # attribution is exact, counts are post-drain. The serial scan
            # is exact per step.
            plugins = {FILTER_PLUGINS[i]
                       for i, c in enumerate(reject_counts) if c > 0}
            plugins |= set(qp.host_reject_counts)
            if self.flight.enabled:
                # /debug/pod diagnosis: which device filter rejected how
                # many nodes (the already-pulled reject_counts), which
                # host plugin rejected (host_reject_counts)
                self.timelines.diagnose(
                    qp.pod,
                    {FILTER_PLUGINS[i]: int(c)
                     for i, c in enumerate(reject_counts) if c > 0},
                    qp.host_reject_counts,
                    "no feasible node (device launch)")
                self.timelines.event(qp.pod, "unschedulable",
                                     ",".join(sorted(plugins)))
            qp.unschedulable_plugins = plugins or {"NodeResourcesFit"}
            qp.unschedulable_count += 1
            qp.consecutive_errors_count = 0
            self.stats["unschedulable"] += 1
            self.metrics.schedule_attempts.inc(
                result="unschedulable", profile=qp.pod.spec.scheduler_name)
            has_pf = bool(self._fw_for(qp.pod).points["post_filter"])
            pcfg = self._profile_cfg.get(qp.pod.spec.scheduler_name, {})
            fit_only = (pcfg.get("batch_preempt_ok", False)
                        and not qp.host_reject_counts
                        and all(c == 0 for i, c in enumerate(reject_counts)
                                if i != fit_idx))
            any_pf = any_pf or has_pf
            prepped.append((qp, reject_counts, plugins, has_pf, fit_only))
        nominated_by_uid: dict[str, str | None] = {}
        if any_pf:
            # chained launches skip the per-batch sync; preemption reads
            # the host snapshot + mirror, so refresh (O(1) when clean)
            self.cache.update_snapshot(self.snapshot)
            self.mirror.sync(self.snapshot)
            # batched sweep for fit-only preemptors, grouped by priority
            # grouped by (priority, profile): the sweep applies ONE
            # enabled-filter set per chunk, which is per-profile state
            groups: dict[tuple, list] = {}
            for qp, _rej, _pl, has_pf, fit_only in prepped:
                if has_pf and fit_only:
                    groups.setdefault(
                        (qp.pod.priority(), qp.pod.spec.scheduler_name),
                        []).append(qp)
            for _key, qps in groups.items():
                # NOTE: deferring the sweep harvest across iterations
                # (begin here, finish next cycle) was measured ~2x SLOWER
                # on PreemptionAsync: the extra cycle of nomination latency
                # per burst outweighs the hidden device wait. Synchronous
                # begin+finish it stays.
                try:
                    results = self.preemption.batch_preempt(qps,
                                                            self.snapshot)
                except Unavailable:
                    # outage mid-sweep: no nominations this round; the
                    # parked preemptors retry after backoff
                    self._note_hub_down()
                    results = {}
                for uid, (node, _status) in results.items():
                    nominated_by_uid[uid] = node
                    if node:
                        self.stats["preemptions"] = self.stats.get(
                            "preemptions", 0) + 1
            if not self.config.gate("SchedulerAsyncPreemption"):
                # gate off: prepare candidates synchronously, inside the
                # failure handling (pre-kep-4832 behavior)
                self._flush_evictions_safe()
        for qp, reject_counts, plugins, has_pf, fit_only in prepped:
            if has_pf and not fit_only:
                state = CycleState()
                try:
                    nominated, _s = self._fw_for(
                        qp.pod).run_post_filter_plugins(
                        state, qp.pod, {"snapshot": self.snapshot,
                                        "reject_counts": reject_counts,
                                        "host_rejects":
                                            qp.host_reject_counts})
                except Unavailable:
                    self._note_hub_down()
                    nominated = None
                if nominated:
                    self.stats["preemptions"] = self.stats.get(
                        "preemptions", 0) + 1
            else:
                nominated = nominated_by_uid.get(qp.uid)
            self._park_failed(qp, plugins, nominated)

    def _park_failed(self, qp: QueuedPodInfo, plugins,
                     nominated: Optional[str]) -> None:
        """Condition patch + park (the tail of handleSchedulingFailure)."""
        self._patch_condition_best_effort(qp.pod, PodCondition(
            type="PodScheduled", status="False", reason="Unschedulable",
            message=f"rejected by {sorted(plugins)}"), nominated)
        # the patch fired while this pod was in-flight (the queue
        # ignores updates for in-flight pods), so park the FRESH
        # object — the packed nominated_row must see
        # status.nominatedNodeName next attempt
        try:
            stored = self.hub.get_pod(qp.uid)
        except Unavailable:
            self._note_hub_down()
            stored = None
        if stored is not None:
            qp.pod = stored
        self.queue.add_unschedulable_if_not_present(qp)

    def _error(self, qp: QueuedPodInfo, msg: str) -> None:
        """Error-class failure: separate backoff counter
        (types.go:394-404) so apiserver-error storms back off."""
        if self.flight.enabled:
            self.timelines.event(qp.pod, "error", msg)
        qp.consecutive_errors_count += 1
        qp.unschedulable_plugins = set()
        self.stats["errors"] += 1
        self.metrics.schedule_attempts.inc(
            result="error", profile=qp.pod.spec.scheduler_name)
        self._patch_condition_best_effort(qp.pod, PodCondition(
            type="PodScheduled", status="False", reason="SchedulerError",
            message=msg))
        self.queue.add_unschedulable_if_not_present(qp)

    # ------------- the daemon (scheduler.go Run + queue flush loops) ----

    def _sync_slices(self) -> None:
        """Converge the queues to the slice map after a rebalance: pods
        in slices we lost move to the foreign pen (the new owner's
        informer already has them), pods in slices we gained move from
        the pen into the queues. One integer compare when nothing
        changed — this runs every loop tick. The jobqueue drains by
        whole unit, so a gang mid-assembly re-homes intact."""
        sm = self._slices
        if sm is None or sm.generation == self._slice_gen:
            return
        with self._lock:
            if sm.generation == self._slice_gen:
                return
            self._slice_gen = sm.generation
            for pod in self.queue.drain_unowned(self._owns_pod):
                self._stash_foreign(pod)
            if self.jobqueue.active:
                for pod in self.jobqueue.drain_unowned(self._owns_pod):
                    self._stash_foreign(pod)
            adopted = [p for p in self._foreign.values()
                       if self._owns_pod(p)]
            n_adopted = 0
            for pod in adopted:
                del self._foreign[pod.metadata.uid]
                if pod.spec.node_name or self._terminal(pod) \
                        or self._quarantine_holds(pod):
                    continue
                self.stats["foreign_adopted"] += 1
                n_adopted += 1
                self._enqueue_fresh(pod)
            # ownership moved: any device-resident chain may reflect
            # binds we are no longer racing for — resync conservatively
            self._invalidate_chain()
            self.stats["slice_rebalances"] += 1
            if n_adopted:
                # pods re-homed here mid-flight: a peer lost its slices
                # (deposed or dead) and this replica inherited live work
                # — the scale-out incident worth a black box
                telemetry.incident(
                    self, "slice_reparent",
                    reason=f"adopted {n_adopted} pending pod(s) on "
                           f"ring generation {sm.generation}",
                    adopted=n_adopted, generation=sm.generation,
                    ring_epoch=sm.ring_epoch)

    def run_maintenance(self) -> None:
        """The background timers the reference runs as goroutines: 1s
        backoff flush, 30s unschedulable-timeout flush (5min park cap,
        scheduling_queue.go:378-386), assumed-pod expiry
        (cache.go:730 cleanupAssumedPods), permit-wait harvesting, bind
        completion, queued evictions."""
        with self._lock:
            self._process_deferred_events()
            self._sync_slices()
            now = self.now()
            if now - self._last_backoff_flush >= 1.0:
                self._last_backoff_flush = now
                self.queue.flush_backoff_completed()
            if now - self._last_unsched_flush >= 30.0:
                self._last_unsched_flush = now
                self.queue.flush_unschedulable_timeout()
                # degraded: do NOT expire assumed pods — their informer
                # confirms cannot arrive while the hub is unreachable;
                # expiring them now would forget real placements and
                # invite double scheduling the moment the hub heals.
                # watches_healthy is checked separately: RPCs can
                # succeed while every watch stream is down, and the
                # confirms ride the streams, not the calls
                if not self.hub_degraded() \
                        and getattr(self.hub, "watches_healthy", True):
                    # expiry removed these from the cache already: they
                    # MUST reach the requeue check eventually, so an
                    # outage mid-loop defers the tail instead of
                    # dropping it (_assumed_requeue drains every tick)
                    self._assumed_requeue.extend(
                        self.cache.cleanup_assumed_pods())
            self._drain_assumed_requeue()
            self._release_quarantined()
            self._process_waiting()
            self._drain_bind_results()
            self._flush_evictions_safe()
            self._process_deferred_events()
            self.recorder.flush(force=False)
            self._probe_hub()
            self._evaluate_brownout()
            self._run_drift_sentinel()
            self.metrics.cache_size.set(self.cache.pod_count(), type="pods")
            self.metrics.cache_size.set(self.cache.assumed_pod_count(),
                                        type="assumed_pods")
            self._export_resilience_metrics()
            # LAST: the watchdog reads the counters/stats everything
            # above just finished updating (self-throttled to
            # watchdog_interval_s, so most ticks cost one comparison)
            self.watchdog.poll()

    def _drain_assumed_requeue(self) -> None:
        """Requeue expired assumed pods whose hub-side object is still
        unbound; retried across ticks because the hub may vanish between
        the expiry and the check."""
        if not self._assumed_requeue:
            return
        still: list[Pod] = []
        for pod in self._assumed_requeue:
            try:
                stored = self.hub.get_pod(pod.metadata.uid)
            except Unavailable:
                self._note_hub_down()
                still.append(pod)
                continue
            if stored is not None and not stored.spec.node_name:
                self.queue.add(stored)
        self._assumed_requeue = still

    def _probe_hub(self) -> None:
        """Degraded-mode recovery probe for in-process hubs (a RemoteHub
        tracks its own transport state; its reads below double as the
        probe). One cheap read per maintenance tick."""
        if not self._hub_down:
            return
        if getattr(self.hub, "connected", None) is not None:
            # the client tracks its own transport state: probing would
            # burn a retried RPC (and the retry budget) per tick while
            # holding the scheduler lock
            self._hub_down = False
            return
        try:
            self.hub.get_pod("__degraded_probe__")
            self._hub_down = False
            logger.info("hub reachable again: leaving degraded mode")
        except Unavailable:
            pass

    def _run_drift_sentinel(self) -> None:
        """The cache comparer (backend/cache/debugger/comparer.go),
        promoted from a SIGUSR2 debug hook to a periodic sentinel: every
        ``drift_check_interval`` diff the scheduler's cache against hub
        truth and auto-repair divergence by TARGETED re-sync (only the
        drifted entries mutate — generation bumps make the incremental
        snapshot/mirror refresh pick up exactly those rows). Persistent
        drift (targeted repair not converging) escalates to the full
        mirror/snapshot rebuild as last resort. Skipped while degraded
        or with dead watch streams: everything would look drifted."""
        if self.drift_check_interval <= 0:
            return
        now = self.now()
        if now - self._last_drift_check < self.drift_check_interval:
            return
        if self.hub_degraded() \
                or not getattr(self.hub, "watches_healthy", True):
            return
        self._last_drift_check = now
        try:
            report = None
            if self._drift_rv is not None:
                # steady state: O(changes) journal diff — ZERO cluster
                # LISTs when nothing (or little) changed
                try:
                    report = self.cache.drift_report(
                        self.hub, since_rv=self._drift_rv)
                    self.stats["drift_incremental"] += 1
                except RvTooOld:
                    report = None   # compacted gap: full diff below
            if report is None:
                report = self.cache.drift_report(self.hub)
                self.stats["drift_full_lists"] += 1
        except Unavailable:
            self._note_hub_down()
            return
        rep_rv = getattr(report, "rv", None)
        self._drift_rv = rep_rv if isinstance(rep_rv, int) else None
        n = report.count()
        if n == 0:
            self._drift_strikes = 0
            return
        self._drift_strikes += 1
        self.metrics.drift_detected.inc(n)
        logger.warning("drift sentinel: %d cache-vs-hub discrepancies "
                       "(strike %d): %s", n, self._drift_strikes,
                       report.render()[:5])
        telemetry.incident(self, "drift",
                           reason=f"{n} cache-vs-hub discrepancies "
                                  f"(strike {self._drift_strikes})",
                           discrepancies=n, strike=self._drift_strikes,
                           sample=report.render()[:5])
        try:
            repaired = self.cache.repair_from_hub(self.hub, report)
        except Unavailable:
            self._note_hub_down()
            return
        self.stats["drift_repairs"] += repaired
        self.metrics.drift_repaired.inc(repaired)
        # the mirror re-packs the repaired rows from the snapshot on the
        # next unchained launch; drop the chain so one happens
        self._invalidate_chain()
        if self._drift_strikes >= 3:
            # targeted repair is not converging: rebuild the device side
            # from scratch (the mirror itself may be corrupt in ways the
            # host diff cannot see)
            logger.error("drift sentinel: persistent drift after %d "
                         "targeted repairs; rebuilding mirror + snapshot",
                         self._drift_strikes)
            self.metrics.drift_rebuilds.inc()
            telemetry.incident(
                self, "drift_rebuild",
                reason=f"persistent drift after "
                       f"{self._drift_strikes} targeted repairs",
                strikes=self._drift_strikes)
            self.mirror = Mirror(caps=self.caps, mesh=self.mesh)
            self.snapshot = Snapshot()
            self.cache.update_snapshot(self.snapshot)
            self._drift_strikes = 0

    # ------------- brownout (overload self-protection) -------------

    def _effective_batch(self) -> int:
        """Pop/release budget for this cycle: the configured batch, or
        the brownout-shrunk batch while shedding load. Launch packing
        keeps its configured capacity hints — the smaller batch pads
        down to an already-warm smaller bucket, so the shrink does not
        force recompiles."""
        cfg = self.config
        if not self.brownout:
            return cfg.batch_size
        return max(cfg.batch_size // max(cfg.brownout_batch_divisor, 1),
                   min(cfg.brownout_batch_floor, cfg.batch_size))

    def _evaluate_brownout(self) -> None:
        """Watch the hub client's 429 counter and shed our own load
        while the fabric is saturated: a scheduler that answers flow
        control by hammering full batches at full cadence converts one
        overloaded component into a fleet-wide retry storm. Evaluated
        at most once per second; enters on brownout_throttle_threshold
        throttles in a window, exits after brownout_clear_windows
        consecutive windows with zero new throttles."""
        cfg = self.config
        threshold = getattr(cfg, "brownout_throttle_threshold", 0)
        if threshold <= 0:
            return
        rs = getattr(self.hub, "resilience_stats", None)
        if rs is None:
            return      # in-process hub: no flow-controlled transport
        now = self.now()
        if now - self._last_brownout_eval < 1.0:
            return
        self._last_brownout_eval = now
        throttled = float(rs().get("throttled_429s", 0))
        delta = throttled - self._brownout_throttled_seen
        self._brownout_throttled_seen = throttled
        if not self.brownout:
            if delta >= threshold:
                self._enter_brownout(delta)
            return
        if delta > 0:
            self._brownout_clean = 0
            return
        self._brownout_clean += 1
        if self._brownout_clean >= max(cfg.brownout_clear_windows, 1):
            self._exit_brownout()

    def _enter_brownout(self, rate: float) -> None:
        cfg = self.config
        self.brownout = True
        self._brownout_clean = 0
        self.stats["brownout_enters"] += 1
        # capture the CURRENT cadence, not the constructor default:
        # tests and operators retune drift_check_interval post-init
        self._drift_interval_base = self.drift_check_interval
        if self.drift_check_interval > 0:
            self.drift_check_interval *= max(cfg.brownout_drift_stretch,
                                             1.0)
        parked: list[str] = []
        if self.jobqueue.active:
            parked = self.jobqueue.park_below(
                cfg.brownout_besteffort_weight)
        self.metrics.brownout.set(1.0)
        self.metrics.brownout_transitions.inc(phase="enter")
        logger.warning(
            "brownout ENTER: %d hub throttles in the last window "
            "(threshold %d): batch %d -> %d, drift cadence %.0fs, "
            "parked best-effort tenants %s",
            int(rate), cfg.brownout_throttle_threshold, cfg.batch_size,
            self._effective_batch(), self.drift_check_interval, parked)
        telemetry.incident(
            self, "brownout_enter",
            reason=f"{int(rate)} hub throttles in the last window "
                   f"(threshold {cfg.brownout_throttle_threshold})",
            throttles=int(rate),
            effective_batch=self._effective_batch(), parked=parked)

    def _exit_brownout(self) -> None:
        self.brownout = False
        self._brownout_clean = 0
        self.stats["brownout_exits"] += 1
        if self._drift_interval_base is not None:
            self.drift_check_interval = self._drift_interval_base
            self._drift_interval_base = None
        freed = self.jobqueue.unpark_all()
        self.metrics.brownout.set(0.0)
        self.metrics.brownout_transitions.inc(phase="exit")
        logger.info("brownout EXIT: pressure clear; batch restored to "
                    "%d, unparked tenants %s",
                    self.config.batch_size, freed)

    def brownout_state(self) -> dict:
        """The /debug/fleet brownout surface."""
        return {"active": self.brownout,
                "enters": self.stats["brownout_enters"],
                "exits": self.stats["brownout_exits"],
                "clean_windows": self._brownout_clean,
                "effective_batch": self._effective_batch(),
                "drift_check_interval": self.drift_check_interval,
                "parked_tenants": sorted(
                    getattr(self.jobqueue, "parked", ()))}

    def _export_resilience_metrics(self) -> None:
        """Mirror hub-client and chaos counters into the registry (the
        hub client and chaos layer have no registry of their own)."""
        m = self.metrics
        m.hub_degraded.set(1.0 if self.hub_degraded() else 0.0)
        m.brownout.set(1.0 if self.brownout else 0.0)
        if self._slices is not None:
            m.sched_slices_owned.set(float(len(self._slices.owned)))
            m.foreign_pending_pods.set(float(len(self._foreign)))
            self._mirror_count("slice_rebalances",
                               self.stats["slice_rebalances"],
                               m.slice_rebalances)
        rs = getattr(self.hub, "resilience_stats", None)
        if rs is not None:
            s = rs()
            m.hub_client_retries.set(float(s["retries"]))
            m.hub_client_watch_reconnects.set(
                float(s["watch_reconnects"]))
            m.hub_client_degraded_seconds.set(s["degraded_seconds"])
            self._mirror_count("watch_resumes", s.get("watch_resumes", 0),
                               m.hub_watch_resumes)
            self._mirror_count("watch_relists", s.get("watch_relists", 0),
                               m.hub_watch_relists)
            self._mirror_count("throttled_429s",
                               s.get("throttled_429s", 0),
                               m.hub_client_throttled)
            self._mirror_count("throttle_retries",
                               s.get("throttle_retries", 0),
                               m.hub_client_throttle_retries)
            for codec_name, w in s.get("wire", {}).items():
                self._mirror_count(f"wire_msgs:{codec_name}",
                                   w.get("msgs", 0),
                                   m.wire_codec_messages,
                                   codec=codec_name)
                self._mirror_count(f"wire_sent:{codec_name}",
                                   w.get("bytes_sent", 0),
                                   m.wire_codec_bytes,
                                   codec=codec_name, direction="sent")
                self._mirror_count(f"wire_recv:{codec_name}",
                                   w.get("bytes_recv", 0),
                                   m.wire_codec_bytes,
                                   codec=codec_name, direction="recv")
        for src, n in self._dra.cel_error_stats().items():
            self._mirror_count(f"cel:{src}", n, m.dra_cel_errors,
                               source=src)
        self._mirror_journal_stats()
        if self.jobqueue.active:
            for tenant, st in self.jobqueue.tenant_stats().items():
                m.tenant_queue_depth.set(float(st["depth"]),
                                         tenant=tenant)
                u = st["usage"]
                m.tenant_quota_used.set(float(u["cpu_milli"]),
                                        tenant=tenant, resource="cpu_milli")
                m.tenant_quota_used.set(float(u["memory"]),
                                        tenant=tenant, resource="memory")
                m.tenant_quota_used.set(float(u["pods"]),
                                        tenant=tenant, resource="pods")
        cs = getattr(self.hub, "chaos_stats", None)
        if cs is not None:
            for kind, v in cs().items():
                # only actual faults: calls_seen/events_relayed are
                # traffic counters, not injections
                if kind.startswith("injected_") or kind == "partitions":
                    m.chaos_injected_faults.set(float(v), kind=kind)

    def _mirror_count(self, key: str, current: float, counter,
                      **labels) -> None:
        """Advance a registry Counter by the delta of an externally-owned
        monotonic count (mirrored gauges would break rate() on restart)."""
        prev = self._mirrored_counts.get(key, 0.0)
        if current > prev:
            counter.inc(current - prev, **labels)
            self._mirrored_counts[key] = current

    def _mirror_journal_stats(self) -> None:
        """Journal depth/watermark gauges, throttled: for a RemoteHub
        this is an RPC, and the maintenance tick runs every loop."""
        now = self.now()
        if now - self._last_journal_mirror < 10.0:
            return
        self._last_journal_mirror = now
        js_fn = getattr(self.hub, "get_journal_stats", None)
        if js_fn is None or self.hub_degraded():
            return
        try:
            js = js_fn()
        except Unavailable:
            return
        for kind, st in js.get("kinds", {}).items():
            self.metrics.hub_journal_depth.set(
                float(st["depth"]), kind=kind)
            self.metrics.hub_journal_compacted_rv.set(
                float(st["compacted_rv"]), kind=kind)
        # a sharded hub (fabric.sharded.ShardedHub) reports per-shard
        # journal state alongside the merged per-kind view
        for shard, st in js.get("shards", {}).items():
            self.metrics.hub_shard_depth.set(
                float(st["depth"]), shard=shard)
            self.metrics.hub_shard_compacted_rv.set(
                float(st["compacted_rv"]), shard=shard)
            self._mirror_count(f"shard_commits:{shard}",
                               st.get("commits", 0),
                               self.metrics.hub_shard_commits,
                               shard=shard)

    def run(self, stop: threading.Event, idle_sleep: float = 0.02,
            elector=None) -> None:
        """Blocking daemon loop (scheduler.go:452 Run): maintenance timers
        + scheduling cycles until ``stop`` is set. With an ``elector``
        (leaderelection.LeaderElector) the loop only schedules while
        holding the lease (server.go:284-317); a non-leader keeps its
        informer state warm but mutates nothing. Exceptions are logged and
        retained (daemon_error); the loop backs off with decorrelated
        jitter (a persistent error must not busy-spin the keep-alive)
        and keeps serving."""
        self.daemon_error: Optional[BaseException] = None
        self._elector = elector
        # a SliceManager is the scale-out elector: leadership over a
        # SLICE of the pending-pod space instead of the whole ring
        self._slices = (elector if getattr(elector, "is_slice_manager",
                                           False) else None)

        def tick_gate() -> bool:
            ok = elector.tick()
            if ok and self._slices is not None:
                self._sync_slices()
            return ok

        crash_bo = Backoff(base=0.5, cap=30.0)
        try:
            while not stop.is_set():
                if elector is not None and not tick_gate():
                    stop.wait(min(elector.retry_period, 0.5))
                    continue
                try:
                    self.run_maintenance()
                    # the drain renews the lease every batch and aborts the
                    # moment leadership is lost (the reference renews on a
                    # background goroutine; a long drain must not outlive
                    # the lease while still binding pods)
                    on_step = (None if elector is None
                               else (lambda: not tick_gate()))
                    if self.run_until_idle(on_step=on_step) == 0:
                        stop.wait(idle_sleep)
                    crash_bo.reset()
                except Exception as e:  # noqa: BLE001 — keep daemon alive
                    logger.exception("scheduling loop error: %s", e)
                    self.daemon_error = e
                    self.metrics.cycle_crashes.inc()
                    stop.wait(crash_bo.next())
        finally:
            if elector is not None:
                elector.release()

    def start(self, elector=None) -> None:
        """Run the daemon on its own thread (tests/embedding)."""
        if self._daemon is not None:
            return
        self._stop = threading.Event()
        self._daemon = threading.Thread(
            target=self.run, args=(self._stop,),
            kwargs={"elector": elector}, daemon=True,
            name="kubernetes-tpu-scheduler")
        self._daemon.start()

    def stop(self) -> None:
        if self._daemon is None:
            return
        self._stop.set()
        self._daemon.join(timeout=30)
        self._daemon = None
        self._stop = None

    def close(self) -> None:
        """Stop the daemon (if running) and release the binder pool's
        worker threads. The scheduler is unusable afterwards."""
        self.stop()
        if self._binder is not None:
            self._drain_bind_results(wait=True)
            self._process_deferred_events()
            self._binder.shutdown(wait=True)
            self._binder = None
        if self._commit_pool is not None:
            self._commit_pool.shutdown(wait=True)
            self._commit_pool = None
        self.flight.close()

    # ------------- driving -------------

    def run_until_idle(self, max_batches: int = 1000,
                       on_step=None) -> int:
        """Drain the activeQ (tests/bench); returns pods attempted.

        Pipelined: while launch k computes on device, batch k+1 is popped,
        packed, and dispatched against the device-resident usage chain
        (BatchResult.free/.nzr); batch k's host-side commits then overlap
        launch k+1's device time. Falls back to strict launch->commit
        alternation whenever the next batch cannot chain (topology or host
        ports in play, or an external event invalidated the chain).

        ``on_step`` (if given) runs once per loop iteration before the pop —
        the perf harness injects churn pods through it
        (scheduler_perf.go:819 churnOp). A truthy return stops the drain
        (pending work is still committed): with a churn feed the queue may
        never go idle, so the harness signals "measured phase done" here."""
        with self._lock, gc_guard:
            return self._run_until_idle_locked(max_batches, on_step)

    def _run_until_idle_locked(self, max_batches, on_step) -> int:
        total = 0
        # up to PIPELINE_DEPTH launches in flight: chained launches queue
        # back-to-back on the device, so blocking on the OLDEST one after
        # dispatching the newest gives the device a whole iteration of
        # host-side commit work as head start (the batched analog of the
        # reference's scheduling/binding goroutine overlap, P3)
        pending: deque[tuple] = deque()

        def flush_all() -> None:
            while pending:
                self._finish_contained(pending.popleft())

        def flush_to(depth: int) -> None:
            while len(pending) > depth:
                self._finish_contained(pending.popleft())

        for _ in range(max_batches):
            self._process_deferred_events()
            self._process_waiting()
            self._drain_bind_results()
            # the 1s backoff flush must tick DURING a busy drain too (the
            # reference runs it as a goroutine): under continuous load the
            # idle branch never runs and backoff pods would starve
            now = self.now()
            if now - self._last_backoff_flush >= 1.0:
                self._last_backoff_flush = now
                self.queue.flush_backoff_completed()
                # once-a-second young-gen sweep keeps deferred cyclic
                # garbage bounded during long drains (see utils.gcguard)
                gc_guard.idle_sweep()
            if on_step is not None and on_step():
                break
            if self.jobqueue.active:
                # admit tenant/gang work by DRR + quota before the pop
                self.jobqueue.release(self.queue, self._effective_batch())
            popped, runnable = self._pop_runnable()
            if popped == 0:
                flush_all()
                # the flush may have completed a gang quorum (Permit
                # allowed the waiting peers): harvest them into the
                # binding cycle BEFORE deciding the queue is idle, or a
                # drain ends with allowed pods stranded in the wait room
                self._process_waiting()
                if self._pipelined:
                    # the flush may also have planned evictions (the
                    # failed wave's PostFilter ran in _finish): fire them
                    # NOW so the activated preemptor rides the next wave
                    # of this same drain instead of waiting out a backoff
                    # into the next one (its nominated reservation holds
                    # the freed slot either way)
                    self._flush_evictions_safe()
                self.queue.flush_backoff_completed()
                # a drained wait room or a churn event may have refilled
                # the job queue mid-iteration
                if self.jobqueue.active:
                    self.jobqueue.release(self.queue,
                                          self._effective_batch())
                popped, runnable = self._pop_runnable()
                if popped == 0:
                    break
            total += popped
            nxt = None
            if runnable:
                # gang units first: their fused launch chains the usage
                # state the normal launch then builds on
                runnable = self._schedule_gang_units(
                    runnable, flush_pending=flush_all)
            if runnable:
                chained = self._chain_eligible([qp.pod for qp in runnable])
                # a non-chainable batch does NOT drain the pipeline here:
                # _dispatch's own need_sync path flushes lazily (through
                # flush_pending) right before the snapshot sync, so the
                # in-flight waves keep their device head start and
                # pipelining resumes at full depth after the host-path
                # batch commits
                try:
                    nxt = self._dispatch(runnable, chained,
                                         flush_pending=flush_all)
                except Unavailable:
                    self._park_batch_unreachable(runnable)
                    nxt = None
                except Exception as e:  # noqa: BLE001 — containment seam:
                    # commit what was already in flight first (their
                    # launches predate the fault), then degrade this batch
                    flush_all()
                    self._contain_batch_fault(runnable, e)
                    nxt = None
                if nxt is not None:
                    pending.append(nxt)
                    # pipeline-depth observability: how many waves were
                    # in flight right after this dispatch (tr is tuple
                    # element 4) — the stall detector for satellite runs
                    nxt[4].depth = len(pending)
            # keep up to PIPELINE_DEPTH launches outstanding: batch k-1 is
            # committed only after k AND k+1 are queued, so the device gets
            # a full iteration (dispatch + commit) of head start. The
            # off-arm (pipelined_waves=False) commits every wave before
            # the next dispatch — strict launch->commit alternation.
            flush_to(PIPELINE_DEPTH if self._pipelined else 0)
            if nxt is not None and pending and pending[-1] is nxt:
                # settle the recorded depth to the post-trim count (the
                # ring keeps the live trace object): a full pipeline
                # reads PIPELINE_DEPTH, a stalled one 1. Waves the trim
                # itself committed (the off arm) keep their dispatch-time
                # depth of 1.
                nxt[4].depth = len(pending)
            # async preemption evictions run between cycles (kep 4832)
            self._flush_evictions_safe()
        flush_all()
        self._drain_bind_results(wait=True)
        self._flush_evictions_safe()
        self._process_deferred_events()
        self.recorder.flush()
        return total
