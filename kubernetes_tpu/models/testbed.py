"""Synthetic cluster/workload builders for benchmarks and compile checks.

The analog of the reference's scheduler_perf node/pod creation strategies
(test/integration/scheduler_perf/scheduler_perf.go createNodes/createPods
with allocatable strategies): deterministic, parameterized clusters packed
through the real Cache → Snapshot → Mirror path so benchmarks exercise the
production packing code, not a shortcut.
"""

from __future__ import annotations

from kubernetes_tpu.api.objects import (
    Container,
    ContainerImage,
    LABEL_HOSTNAME,
    LABEL_ZONE,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequirements,
)
from kubernetes_tpu.backend.cache import Cache
from kubernetes_tpu.backend.mirror import Mirror
from kubernetes_tpu.backend.snapshot import Snapshot
from kubernetes_tpu.ops.features import Capacities


def make_node(i: int, zones: int = 8, cpu_milli: int = 32000,
              mem_mi: int = 131072) -> Node:
    name = f"node-{i}"
    return Node(
        metadata=ObjectMeta(name=name, labels={
            LABEL_HOSTNAME: name,
            LABEL_ZONE: f"zone-{i % zones}",
        }),
        spec=NodeSpec(),
        status=NodeStatus(
            allocatable={
                "cpu": f"{cpu_milli}m",
                "memory": f"{mem_mi}Mi",
                "ephemeral-storage": "100Gi",
                "pods": "110",
            },
            images=[ContainerImage(names=[f"img-{i % 16}"],
                                   size_bytes=(50 + i % 200) * 1024 * 1024)],
        ),
    )


def make_pod(i: int, cpu: str = "100m", mem: str = "128Mi") -> Pod:
    return Pod(
        metadata=ObjectMeta(name=f"pod-{i}", labels={"app": f"app-{i % 10}"}),
        spec=PodSpec(containers=[Container(
            name="c",
            image=f"img-{i % 16}",
            resources=ResourceRequirements(
                requests={"cpu": cpu, "memory": mem}),
        )]),
    )


def build_cluster(num_nodes: int, caps: Capacities | None = None,
                  zones: int = 8) -> tuple[Cache, Snapshot, Mirror]:
    """Cache + snapshot + synced mirror for a synthetic cluster."""
    if caps is None:
        n = 64
        while n < num_nodes:
            n *= 2
        caps = Capacities(nodes=n)
    cache = Cache()
    for i in range(num_nodes):
        cache.add_node(make_node(i, zones=zones))
    snap = Snapshot()
    cache.update_snapshot(snap)
    mirror = Mirror(caps=caps)
    mirror.sync(snap)
    return cache, snap, mirror
