"""The flagship model: one XLA launch schedules a whole batch of pods.

This replaces the reference's serial per-pod hot path — ``schedulingCycle`` →
``findNodesThatPassFilters`` (goroutine fan-out over nodes,
schedule_one.go:583-650) → ``prioritizeNodes`` (3-stage score pipeline,
runtime/framework.go:1117-1194) → ``selectHost`` (schedule_one.go:865) →
``assume`` (schedule_one.go:938) — with a single jitted program in two
phases:

1. **Parallel phase** (vmap over the pod batch): every Filter and raw Score
   whose result cannot be changed by in-batch placements — taints, node
   affinity/selectors, host ports, unschedulable, image locality — is
   evaluated for ALL (pod, node) pairs at once. This is where the FLOPs
   are, and it is embarrassingly parallel over both axes.
2. **Commit scan** (lax.scan over pods): a deliberately tiny sequential
   pass that re-evaluates only what a previous pod's commit can invalidate
   — the resource fit predicate and the utilization scores — then
   normalizes, aggregates, argmaxes, and commits the winner's resources to
   the scan carry. Pod b+1 therefore sees pod b's placement exactly as the
   serial loop's assume step would provide ("as-if-serial").

The node axis is the sharding axis: under a ``jax.sharding.Mesh`` the
per-node work is data-parallel; argmax and normalization reductions become
XLA collectives over ICI (SURVEY.md §5.8).

Filter order follows the reference's default plugin order
(apis/config/v1/default_plugins.go:30-58); a node's rejection is attributed
to its FIRST failing plugin, mirroring RunFilterPlugins' short-circuit
(runtime/framework.go:877-922) so Diagnosis/FitError parity holds.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops import common as C
from kubernetes_tpu.ops import filters as FL
from kubernetes_tpu.ops import learned as LN
from kubernetes_tpu.ops import scores as SC
from kubernetes_tpu.ops import topology as T
from kubernetes_tpu.utils.interner import NONE
from kubernetes_tpu.ops.features import (
    Capacities,
    ClusterBlobs,
    ClusterTensors,
    PodBlobs,
    PodFeatures,
    unpack_cluster,
    unpack_pods,
)

# --- filter plugin order (first-fail attribution; default_plugins.go) ---

FILTER_PLUGINS = (
    "NodeUnschedulable",
    "NodeName",
    "TaintToleration",
    "NodeAffinity",
    "NodePorts",
    "NodeResourcesFit",
    "PodTopologySpread",
    "InterPodAffinity",
)
NUM_FILTER_PLUGINS = len(FILTER_PLUGINS)

# --- score plugin set with default weights (default_plugins.go:30-58) ---

SCORE_PLUGINS = (
    "TaintToleration",            # w=3, inverse-normalized
    "NodeAffinity",               # w=2, max-normalized
    "NodeResourcesFit",           # w=1, least-allocated 0..100
    "NodeResourcesBalancedAllocation",  # w=1, 0..100
    "ImageLocality",              # w=1, 0..100
    "PodTopologySpread",          # w=2, spread-normalized
    "InterPodAffinity",           # w=2, max-min-normalized
    "LearnedScore",               # w=0 by default (profile-gated MLP term)
)

# default HardPodAffinityWeight (apis/config/v1/defaults.go)
HARD_POD_AFFINITY_WEIGHT = 1.0

# phase-1 (parallel Filter/Score) sub-batch size: bounds the transient
# [chunk, selector-capacity, N] gather footprint for giant drain batches
PHASE1_CHUNK = 1024

# top-K alternative-candidate export (with_alts; export v3): how many
# runner-up (node, score) pairs each placement row carries — the
# counterfactual substrate behind per-placement regret (learn/regret.py).
# Small and static: a [B, K] top_k fused into the launch, K-1 extra rows
# per exported placement.
ALT_K = 4
# alt_score padding sentinel for infeasible/absent candidate slots;
# aggregate scores are bounded (a few hundred), so anything below
# ALT_NONE/2 is "no candidate" on the host side
ALT_NONE = -1e9

# commit-scan unroll factor (see the lax.scan call): amortizes per-iteration
# dispatch overhead, which dominates the topology scan at these shapes.
# 16 on TPU (+15-25% on the topology workloads); 4 on CPU, where the only
# effect of a bigger body is slower XLA:CPU compiles. Resolved LAZILY at
# first trace via the real backend (no JAX init at import);
# KUBERNETES_TPU_SCAN_UNROLL overrides (>=1).
import os as _os

_SCAN_UNROLL = None


def scan_unroll() -> int:
    global _SCAN_UNROLL
    if _SCAN_UNROLL is None:
        try:
            n = int(_os.environ.get("KUBERNETES_TPU_SCAN_UNROLL", "0"))
        except ValueError:
            n = 0
        if n <= 0:
            n = 4 if jax.default_backend() == "cpu" else 16
        _SCAN_UNROLL = max(1, n)
    return _SCAN_UNROLL


# auction-round unroll factor (see _rounds_commit): how many K-accept
# rounds one while_loop iteration fuses. The loop condition is
# data-dependent, so every iteration costs a device round trip on the
# progress flag; fusing U rounds into the body cuts that U-fold while
# lax.cond skips the work of rounds past convergence (the body is
# idempotent at its fixed point, so an extra executed round is a no-op).
# Auctions converge in a handful of rounds, so a small U covers most
# drains in ONE iteration. Resolved lazily like scan_unroll;
# KUBERNETES_TPU_AUCTION_UNROLL overrides (>=1).
_AUCTION_UNROLL = None


def auction_unroll() -> int:
    global _AUCTION_UNROLL
    if _AUCTION_UNROLL is None:
        try:
            n = int(_os.environ.get("KUBERNETES_TPU_AUCTION_UNROLL", "0"))
        except ValueError:
            n = 0
        if n <= 0:
            n = 4
        _AUCTION_UNROLL = max(1, n)
    return _AUCTION_UNROLL

# minFeasibleNodesToFind (schedule_one.go:39-45): below this cluster-wide
# feasible count the percentageOfNodesToScore early-exit never truncates
MIN_FEASIBLE_NODES_TO_FIND = 100

# pct_nodes sentinel: config percentageOfNodesToScore == 0, meaning the
# reference's ADAPTIVE percentage (50 - nodes/125, min 5) rather than a
# fixed one. Unset (None) stays "score everything" — the TPU-native default.
ADAPTIVE_PCT = -1


@jax.tree_util.register_dataclass
@dataclass
class ScoreWeights:
    """Per-plugin score weights (scorePluginWeight, runtime/framework.go:57).
    A dynamic arg — changing weights does not recompile."""

    taint_toleration: jax.Array
    node_affinity: jax.Array
    resources_fit: jax.Array
    balanced_allocation: jax.Array
    image_locality: jax.Array
    pod_topology_spread: jax.Array
    inter_pod_affinity: jax.Array
    # the learned MLP term (ops/learned.py); 0 unless a profile enables
    # the LearnedScore plugin, so the default aggregate is unchanged
    learned: jax.Array


def default_weights() -> ScoreWeights:
    return ScoreWeights(
        taint_toleration=jnp.float32(3.0),
        node_affinity=jnp.float32(2.0),
        resources_fit=jnp.float32(1.0),
        balanced_allocation=jnp.float32(1.0),
        image_locality=jnp.float32(1.0),
        pod_topology_spread=jnp.float32(2.0),
        inter_pod_affinity=jnp.float32(2.0),
        learned=jnp.float32(0.0),
    )


DEFAULT_WEIGHTS = default_weights


@jax.tree_util.register_dataclass
@dataclass
class BatchResult:
    """Per-pod outcome of one batched launch.

    ``free``/``nzr`` are the post-batch cluster usage state ([N, R] and
    [N, 2]): the device-resident "assume" ledger. Feeding them to the next
    launch's ``state`` arg chains batches without a host->device mirror
    re-sync round trip in between (the batched analog of the assume step
    keeping the cache hot between cycles, cache.go:361)."""

    node_row: jax.Array        # [B] i32: chosen node row, -1 = unschedulable
    score: jax.Array           # [B] f32: winning aggregate score
    feasible_count: jax.Array  # [B] i32: nodes passing all filters
    reject_counts: jax.Array   # [B, P] i32: nodes rejected per plugin (first-fail)
    unresolvable_count: jax.Array  # [B] i32: nodes where fit can never succeed
    free: jax.Array            # [N, R] f32: post-batch free resources
    nzr: jax.Array             # [N, 2] f32: post-batch nonzero-requested
    # [] i32: post-batch rotating visit offset (nextStartNodeIndex,
    # schedule_one.go:620). Feed to the next launch's ``pct_start`` so the
    # percentageOfNodesToScore window keeps rotating ACROSS batches, not
    # just within one. Always a concrete scalar (0 when the knob is off) so
    # the pytree structure is launch-config independent.
    pct_start: jax.Array
    # [] i32 guard bitmask, the device-side poison detector: bit 0 = NaN
    # in the winning scores, bit 1 = NaN in the post-batch free state
    # (which would poison the usage chain and every chained launch after
    # it). A cheap reduction computed on device; the scheduler pulls it
    # with node_row and degrades the batch to the host path when set.
    guard: jax.Array
    # [B] i32: nodes rejected by the fused DRA device allocator (first-
    # fail after the static filters; zeros when the launch carried no
    # DraBatch). Pulled only on failure — the scheduler folds it into
    # the pod's host_reject_counts under "DynamicResources" so diagnosis
    # and requeue hints match the host filter path exactly.
    dra_reject: jax.Array
    # [] f32: mean |weighted learned-score term| over feasible (pod,
    # node) pairs this launch (0.0 when the launch carried no learned
    # params). Pulled only when the learned scorer is active — feeds the
    # scheduler_learned_score_magnitude histogram.
    learned_mag: jax.Array
    # [B, ops.learned.NUM_FEATURES] f32: the CHOSEN node's learned-score
    # feature row per pod (zeros unless the launch was compiled
    # with_feats — the flight-recorder export's replay-dataset rows).
    chosen_feat: jax.Array
    # [B, ALT_K] i32 / f32: the top-K candidate node rows and their
    # aggregate scores per pod (-1 / ALT_NONE padding unless the launch
    # was compiled with_alts — the export v3 counterfactual substrate
    # behind per-placement regret). The chosen node itself rides along
    # (it is top-1 in the common case); the offline consumer filters it.
    alt_row: jax.Array
    alt_score: jax.Array


# workload-activity flags (STATIC, host-derived per launch by
# Mirror.launch_features): a feature absent from both the batch and the
# cluster mirror compiles to an all-pass mask / zero score — XLA dead-code-
# eliminates the whole kernel. The device analog of PreFilter returning
# Skip for a pod that doesn't use the plugin (framework/interface.go:518).
ALL_FEATURES = ("nodeaffinity", "taints", "ports", "images")
# "nodeaffinity_pin" is the cheap sibling of "nodeaffinity": every
# affinity-bearing pod in the batch reduced to a matchFields
# metadata.name In [v] pin (the daemonset-controller shape), so only the
# [N] pin compare compiles — never the [N, T, E, V] selector kernels or
# the preferred-term scorer (pins carry no preferred terms).


def _guard_reduction(scores: jnp.ndarray, free: jnp.ndarray) -> jnp.ndarray:
    """BatchResult.guard: NaN poison detector, fused into the launch.
    Bit 0 = NaN in the winning scores (placements untrustworthy), bit 1 =
    NaN in the post-batch free state (the usage chain is poisoned)."""
    return (jnp.any(jnp.isnan(scores)).astype(jnp.int32)
            | (jnp.any(jnp.isnan(free)).astype(jnp.int32) << 1))


def static_filters(ct: ClusterTensors, pod: PodFeatures,
                   wk: dict[str, jnp.ndarray],
                   enabled: tuple[bool, ...],
                   active: frozenset[str]) -> jnp.ndarray:
    """Commit-invariant Filter plugins for one pod over all nodes: [5, N]
    masks in FILTER_PLUGINS order (the rest run in the commit scan).
    ``enabled`` (static, from the framework's resolved config) replaces a
    disabled plugin's mask with all-True — XLA dead-code-eliminates it;
    ``active`` does the same for features the workload doesn't use."""
    fns = (
        lambda: FL.node_unschedulable(ct, pod, wk["unschedulable_taint_key"]),
        lambda: FL.node_name(ct, pod),
        lambda: (FL.taint_toleration(ct, pod)
                 if "taints" in active else None),
        lambda: (FL.node_affinity(ct, pod, full="nodeaffinity" in active)
                 if ("nodeaffinity" in active
                     or "nodeaffinity_pin" in active) else None),
        lambda: (FL.node_ports(ct, pod, wk["wildcard_ip"])
                 if "ports" in active else None),
    )
    n = ct.node_valid.shape[0]
    masks = []
    for i, fn in enumerate(fns):
        m = fn() if enabled[i] else None
        masks.append(m if m is not None else jnp.ones((n,), bool))
    return jnp.stack(masks)


def tie_perturb(b, n: int, seed=None) -> jnp.ndarray:
    """[n] pseudo-random f32 in [0,1) keyed by (pod index b, node index):
    the stateless device analog of selectHost's reservoir sampling
    (schedule_one.go:865) — equal-score nodes pick uniformly instead of
    hotspotting the lowest row. Cheap integer hash; fuses, no RNG state.

    ``seed`` (config tie_break_seed, a DYNAMIC scalar — changing it never
    recompiles) mixes an explicit stream into the hash so paired A/B runs
    are tie-break-deterministic and score diffs attribute to the scorer,
    not the coin. Seed 0 (and None) is the identity xor: the default
    launch stays bit-identical to the historical unseeded hash."""
    x = jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(2654435761)
    x = x ^ (jnp.asarray(b).astype(jnp.uint32) * jnp.uint32(40503))
    if seed is not None:
        x = x ^ (jnp.asarray(seed).astype(jnp.uint32)
                 * jnp.uint32(2654435761))
    x = (x ^ (x >> 15)) * jnp.uint32(2246822519)
    x = x ^ (x >> 13)
    return (x >> 8).astype(jnp.float32) / jnp.float32(1 << 24)


@dataclass
class _SoftTopo:
    """Everything the auction needs to score SOFT topology terms (preferred
    pod (anti)affinity + ScheduleAnyway spread) without the serial scan.

    Soft terms never change FEASIBILITY, so a batch whose only topology
    work is soft keeps the auction's round structure: the static (table)
    part of each score is per-GROUP phase-1 work, and the in-batch part is
    recomputed per round from the placed set with dense domain
    scatters/gathers — "the same gathers with a weight multiply" as the
    hard-constraint machinery, fused into the same launch."""

    gid: jax.Array          # [B] group id per pod
    ipa_ok_g: jax.Array     # [G, N] static InterPodAffinity mask (the
                            # table's required anti-affinity vs each group;
                            # all-True when the ipa filter is disabled)
    ipa_raw_g: jax.Array    # [G, N] static ipa score (table terms both
                            # directions incl. hardPodAffinityWeight)
    match_static_g: jax.Array  # [G, N, C] static soft-spread match counts
    tpw_g: jax.Array        # [G, C] topology normalizing weight log(size+2)
    used_soft_g: jax.Array  # [G, C] soft (ScheduleAnyway) constraint slots
    dom_ok_g: jax.Array     # [G, N, C] node carries the constraint's key
    ign_g: jax.Array        # [G, N] node ignored for spread scoring
    has_soft_g: jax.Array   # [G] any soft constraint
    skew_g: jax.Array       # [G, C] maxSkew per constraint
    el_node_g: jax.Array    # [G, N, C] in-batch eligibility of a node as a
                            # commit target for the group's constraints
    # per-own-term domain columns: node n's domain under term (g, a)'s key
    nd_paff: jax.Array      # [N, G, A] i32 (NONE = key absent)
    nd_panti: jax.Array     # [N, G, A]
    nd_tsc: jax.Array       # [N, G, C]
    paff_tk_g: jax.Array    # [G, A]
    panti_tk_g: jax.Array   # [G, A]
    tsc_tk_g: jax.Array     # [G, C]
    paff_w_g: jax.Array     # [G, A] f32
    panti_w_g: jax.Array    # [G, A] f32
    M_paff_gg: jax.Array    # [G, A, G] pairwise group term matches
    M_panti_gg: jax.Array   # [G, A, G]
    M_tsc_gg: jax.Array     # [G, C, G]
    topo_dom: jax.Array     # [N, TK]
    d_cap: int = 0


def _soft_statics(ct, pods, pods_rep, gid, g_cap, d_cap, tds, wk,
                  enabled_filters, act, ipa_on, chunked_vmap):
    """Per-GROUP static halves of the soft topology scores (the auction's
    phase-1b): the table's contribution to each group's ipa mask/score and
    soft-spread counts — placement-independent, computed once per launch."""
    valid = ct.node_valid

    def per_group_soft(pod: PodFeatures):
        masks = static_filters(ct, pod, wk, enabled_filters, act)
        g_static_ok = jnp.all(masks, axis=0) & valid & pod.valid
        taint_ok, nodeaff_ok = masks[2], masks[3]
        used_c = pod.tsc_tk != jnp.int32(-1)
        used_soft = used_c & ~pod.tsc_hard
        el_soft = T.spread_eligible(ct, pod, nodeaff_ok, taint_ok,
                                    used_soft)
        cnt = T.spread_cnt(ct, pod, tds, el_soft, d_cap)         # [C, D]
        node_dom = T.take_cols(ct.topo_dom, pod.tsc_tk, jnp.int32(-1))
        ign = jnp.any((node_dom == jnp.int32(-1))
                      & used_soft[None], axis=1)                 # [N]
        exists_score = T.spread_exists(
            ct, pod, (g_static_ok & ~ign)[:, None] & used_soft[None],
            d_cap)
        tpw = jnp.log(jnp.sum(exists_score, axis=1)
                      .astype(jnp.float32) + 2.0)                # [C]
        match_static = T.gather_rows(cnt, node_dom)              # [N, C]
        # in-batch commit-target eligibility (policies + key presence);
        # soft-only batches have no hard constraints to honor
        pol = (jnp.where(pod.tsc_honor_affinity[None],
                         (nodeaff_ok & valid)[:, None], True)
               & jnp.where(pod.tsc_honor_taints[None],
                           (taint_ok & valid)[:, None], True))   # [N, C]
        dom_ok = node_dom != jnp.int32(-1)                       # [N, C]
        all_s = jnp.all(dom_ok | ~used_soft[None], axis=1)       # [N]
        el_node = pol & all_s[:, None] & dom_ok & used_soft[None]
        anti_ok, _pres, _any = T.inter_pod_affinity_static(
            ct, pod, tds, d_cap)
        ipa_raw = T.inter_pod_affinity_score(
            ct, pod, tds, d_cap, jnp.float32(HARD_POD_AFFINITY_WEIGHT))
        return (anti_ok, ipa_raw, match_static, tpw, used_soft,
                dom_ok, ign, jnp.any(used_soft), el_node)

    (anti_g, ipa_raw_g, match_g, tpw_g, soft_g, dom_ok_g, ign_g,
     has_soft_g, el_node_g) = chunked_vmap(per_group_soft, pods_rep, g_cap)
    if not ipa_on:
        anti_g = jnp.ones_like(anti_g)
    tk_cap = ct.topo_dom.shape[1]

    def nd_of(tk_g):
        # [N, G, A]: node n's domain under term (g, a)'s topology key
        nd = ct.topo_dom[:, jnp.clip(tk_g, 0, tk_cap - 1)]
        return jnp.where(tk_g[None] != NONE, nd, NONE)

    M_paff_gg = T.pair_term_match(
        pods_rep.paff_tk, pods_rep.paff_ns, pods_rep.paff_ns_all,
        pods_rep.paff_sel_cols, pods_rep.paff_sel_ops,
        pods_rep.paff_sel_vals, pods_rep.plabel_vals, pods_rep.ns,
        pods_rep.valid)
    M_panti_gg = T.pair_term_match(
        pods_rep.panti_tk, pods_rep.panti_ns, pods_rep.panti_ns_all,
        pods_rep.panti_sel_cols, pods_rep.panti_sel_ops,
        pods_rep.panti_sel_vals, pods_rep.plabel_vals, pods_rep.ns,
        pods_rep.valid)
    M_tsc_gg = T.pair_tsc_match(pods_rep)
    return _SoftTopo(
        gid=gid, ipa_ok_g=anti_g, ipa_raw_g=ipa_raw_g,
        match_static_g=match_g, tpw_g=tpw_g, used_soft_g=soft_g,
        dom_ok_g=dom_ok_g, ign_g=ign_g, has_soft_g=has_soft_g,
        skew_g=pods_rep.tsc_max_skew.astype(jnp.float32),
        el_node_g=el_node_g,
        nd_paff=nd_of(pods_rep.paff_tk), nd_panti=nd_of(pods_rep.panti_tk),
        nd_tsc=nd_of(pods_rep.tsc_tk),
        paff_tk_g=pods_rep.paff_tk, panti_tk_g=pods_rep.panti_tk,
        tsc_tk_g=pods_rep.tsc_tk,
        paff_w_g=pods_rep.paff_weight.astype(jnp.float32),
        panti_w_g=pods_rep.panti_weight.astype(jnp.float32),
        M_paff_gg=M_paff_gg, M_panti_gg=M_panti_gg, M_tsc_gg=M_tsc_gg,
        topo_dom=ct.topo_dom, d_cap=d_cap)


def _soft_scores(soft: _SoftTopo, placed, gid_oh):
    """[G, N] live soft scores (static + in-batch halves) for the current
    placed set: the auction-round analog of the scan's map_updates +
    queries, recomputed from scratch each round via domain scatter/gather
    (placed sets are small and rounds are few — no carry maps needed)."""
    d_cap = soft.d_cap
    n_cap = soft.topo_dom.shape[0]
    ok = placed >= 0                                             # [B]
    r = jnp.clip(placed, 0, n_cap - 1)
    dom_rows = jnp.where(ok[:, None], soft.topo_dom[r], NONE)    # [B, TK]
    tk_cap = soft.topo_dom.shape[1]

    def committed_dom(tk_g):
        # [B, G, A]: committed pod y's domain under term (g, a)'s key
        dy = dom_rows[:, jnp.clip(tk_g, 0, tk_cap - 1)]
        return jnp.where(tk_g[None] != NONE, dy, NONE)

    def pair_delta(tk_g, nd, M_gg, w_g):
        """[G, N] weighted same-domain score mass from placed pods, both
        directions of the preferred terms (scoring.go processExistingPod's
        incoming-vs-existing and existing-vs-incoming soft halves).

        Domain ids are validity-checked against d_cap: the padding group's
        zeroed term rows reference arbitrary topology keys whose domain
        space can exceed the launch's bucket, and an out-of-range gather
        index fills NaN — which a zero weight does NOT neutralize."""
        G, A = tk_g.shape
        dy = committed_dom(tk_g)                                 # [B, G, A]
        dy_t = jnp.moveaxis(dy, 0, -1)                           # [G, A, B]
        dv = (dy_t >= 0) & (dy_t < d_cap) & ok[None, None, :]
        flat = (jnp.arange(G)[:, None, None] * (A * d_cap)
                + jnp.arange(A)[None, :, None] * d_cap
                + jnp.clip(dy_t, 0, d_cap - 1))
        # b-side: x's own term a matches committed pod y
        Mg = M_gg[:, :, :] @ gid_oh.T                            # [G, A, B]
        P_b = jnp.zeros((G * A * d_cap,), jnp.float32).at[
            flat.reshape(-1)].add(
                jnp.where(dv, Mg, 0.0).reshape(-1))
        P_b = P_b.reshape(G, A, d_cap)
        # j-side: committed pod y's own term a matches group g2
        own = jnp.moveaxis(gid_oh, 0, -1)                        # [G, B]
        P_j = jnp.zeros((G * A * d_cap,), jnp.float32).at[
            flat.reshape(-1)].add(
                jnp.where(dv, own[:, None, :], 0.0).reshape(-1))
        P_j = P_j.reshape(G, A, d_cap)
        nd_g = jnp.moveaxis(nd, 0, -1)                           # [G, A, N]
        nd_ok = (nd_g >= 0) & (nd_g < d_cap)
        idx = jnp.clip(nd_g, 0, d_cap - 1)
        gath_b = jnp.take_along_axis(P_b, idx.reshape(G, A, -1),
                                     axis=2).reshape(nd_g.shape)
        gath_j = jnp.take_along_axis(P_j, idx.reshape(G, A, -1),
                                     axis=2).reshape(nd_g.shape)
        delta_b = jnp.sum(jnp.where(nd_ok, gath_b, 0.0)
                          * w_g[:, :, None], axis=1)             # [G, N]
        delta_j = jnp.einsum("gah,gan->hn", soft_mul(M_gg, w_g),
                             jnp.where(nd_ok, gath_j, 0.0))
        return delta_b + delta_j

    def soft_mul(M_gg, w_g):
        return M_gg.astype(jnp.float32) * w_g[:, :, None]

    ipa_delta = (pair_delta(soft.paff_tk_g, soft.nd_paff,
                            soft.M_paff_gg.astype(jnp.float32),
                            soft.paff_w_g)
                 - pair_delta(soft.panti_tk_g, soft.nd_panti,
                              soft.M_panti_gg.astype(jnp.float32),
                              soft.panti_w_g))
    ipa_live = soft.ipa_raw_g + ipa_delta                        # [G, N]

    # soft spread: in-batch match-count deltas per (group, constraint)
    G, C = soft.tsc_tk_g.shape
    dy = committed_dom(soft.tsc_tk_g)                            # [B, G, C]
    dy_t = jnp.moveaxis(dy, 0, -1)                               # [G, C, B]
    el_y = jnp.moveaxis(soft.el_node_g[:, r, :], 1, -1)          # [G, C, B]
    Mg = soft.M_tsc_gg.astype(jnp.float32) @ gid_oh.T            # [G, C, B]
    val = jnp.where((dy_t >= 0) & (dy_t < d_cap) & ok[None, None, :],
                    Mg * el_y.astype(jnp.float32), 0.0)
    flat = (jnp.arange(G)[:, None, None] * (C * d_cap)
            + jnp.arange(C)[None, :, None] * d_cap
            + jnp.clip(dy_t, 0, d_cap - 1))
    P_t = jnp.zeros((G * C * d_cap,), jnp.float32).at[
        flat.reshape(-1)].add(val.reshape(-1)).reshape(G, C, d_cap)
    nd_t = jnp.moveaxis(soft.nd_tsc, 0, -1)                      # [G, C, N]
    gath_t = jnp.take_along_axis(
        P_t, jnp.clip(nd_t, 0, d_cap - 1).reshape(G, C, -1),
        axis=2).reshape(nd_t.shape)
    match = (jnp.moveaxis(soft.match_static_g, 1, -1)
             + jnp.where((nd_t >= 0) & (nd_t < d_cap), gath_t, 0.0))
    per_c = match * soft.tpw_g[:, :, None] \
        + (soft.skew_g[:, :, None] - 1.0)
    per_c = jnp.where(soft.used_soft_g[:, :, None]
                      & jnp.moveaxis(soft.dom_ok_g, 1, -1), per_c, 0.0)
    sp_r = jnp.where(soft.ign_g, 0.0, jnp.sum(per_c, axis=1))    # [G, N]
    return ipa_live, sp_r


def _rounds_commit(ct, pods, static_ok, static_rejects, taint_raw, aff_raw,
                   img, unres, weights, free0, nzr0, host_score=None,
                   fit_strategy="LeastAllocated", fit_shape=None,
                   dra_reject=None, learned=None, tie_seed=None,
                   with_feats=False, with_alts=False, soft=None,
                   unroll=None):
    """Parallel auction replacing the per-pod commit scan when the batch has
    no topology constraints and no host ports: every round, all unplaced
    pods score+argmax in parallel; per node, up to K pods are accepted in
    BATCH INDEX order while their cumulative requests fit (the
    as-if-serial feasibility invariant — no node is ever overcommitted
    relative to the serial order); losers re-score against the updated
    cluster next round. K = ceil(B / valid nodes): 1 on clusters at least
    batch-sized (the historical one-accept-per-node behavior, bit
    identical), proportionally higher when the batch outnumbers the
    nodes — a 1024-pod batch over 200 nodes converges in ~2 rounds
    instead of the ~B/N rounds one-accept-per-node starves through,
    while ties still spread (K tracks the per-node share a balanced
    placement would take anyway).

    Placement CHOICES may differ from the serial scan (a pod scores against
    round-start state, not the exact post-predecessor state) but every
    placement satisfies the same constraints the serial loop enforces. The
    scan path remains the exact-parity mode for topology/port batches.

    Wall-clock: O(rounds) of [B, N] work instead of B sequential steps —
    rounds ≈ a few with random tie-breaking. This is what makes the batched
    design faster than the reference's per-pod loop on TPU: the MXU-friendly
    [B, N] score matrix replaces B round trips through tiny kernels."""
    B, N = static_ok.shape
    alloc2 = SC.alloc_cpu_mem(ct)
    own = jnp.arange(N)[None, :] == pods.nominated_row[:, None]    # [B, N]
    perturb = jax.vmap(lambda u: tie_perturb(u, N, tie_seed))(pods.uid_id)
    idx_b = jnp.arange(B)
    # soft-topology mode: the static ipa mask (the table's required
    # anti-affinity vs each group) joins the feasible set; the soft score
    # halves join the round totals below. Soft terms never constrain, so
    # the auction's round structure is unchanged.
    if soft is not None:
        ipa_mask = soft.ipa_ok_g[soft.gid]                         # [B, N]
        gid_oh = (soft.gid[:, None]
                  == jnp.arange(soft.ipa_ok_g.shape[0])[None, :]
                  ).astype(jnp.float32) * pods.valid[:, None]      # [B, G]
        ign_b = soft.ign_g[soft.gid]                               # [B, N]
        soft_b = soft.has_soft_g[soft.gid]                         # [B]
    else:
        ipa_mask = None
    # STATIC gate for the K-accept rounds: only a batch that outnumbers
    # the node bucket can need K > 1, and the cumulative-fit cumsums are
    # [B, N]-sized work the big-cluster shapes must not pay — at B <= N
    # the historical one-accept-per-node program compiles, bit identical
    multi_accept = B > N
    # per-node acceptance budget per round (see docstring): the share a
    # balanced placement would put on one node anyway (valid pods over
    # valid nodes — padding rows place nothing)
    k_accept = jnp.ceil(
        jnp.sum(pods.valid).astype(jnp.float32) / jnp.maximum(
            jnp.sum(ct.node_valid).astype(jnp.float32), 1.0)
    ).astype(jnp.int32) if multi_accept else None

    def eff_all(free):
        """[B, N, R] per-pod effective free rows (nominated reservations
        subtracted, the pod's OWN nomination handed back)."""
        return (free[None] - ct.nominated_req[None]
                + jnp.where(own[..., None], pods.req[:, None, :], 0.0))

    def fit_all(free):
        return jnp.all(pods.req[:, None, :] <= eff_all(free), axis=-1)

    def per_pod_scores(nzr, nzreq, t_raw, a_raw, feas):
        """One pod's normalized per-plugin score arrays against ``nzr``
        (shared by the round totals and the learned-feature export)."""
        frac = SC.utilization_fractions(alloc2, nzr, nzreq)
        least = SC.fit_score_from_fractions(frac, fit_strategy, fit_shape)
        bal = SC.balanced_allocation_from_fractions(frac)
        taint = SC.normalize_inverse(t_raw, feas)
        aff = SC.normalize_max(a_raw, feas)
        return frac, least, bal, taint, aff

    def totals(nzr, feasible, sp_b=None, ipa_b=None):
        def per_pod(nzreq, t_raw, a_raw, im, feas, *topo):
            frac, least, bal, taint, aff = per_pod_scores(
                nzr, nzreq, t_raw, a_raw, feas)
            total = (weights.taint_toleration * taint
                     + weights.node_affinity * aff
                     + weights.resources_fit * least
                     + weights.balanced_allocation * bal
                     + weights.image_locality * im)
            sp_n = ipa_n = None
            if topo:
                # soft-topology mode: normalize + weight the live soft
                # halves per pod, exactly like the serial scan's step
                sp_row, ipa_row, ign_row, softp = topo
                ipa_n = SC.normalize_maxmin(ipa_row, feas)
                sp_n = jnp.where(softp,
                                 SC.normalize_spread(sp_row, feas,
                                                     ign_row), 0.0)
                total = (total + weights.pod_topology_spread * sp_n
                         + weights.inter_pod_affinity * ipa_n)
            if learned is not None:
                total = total + weights.learned * LN.learned_term(
                    learned, frac, least, bal, taint, aff, im, sp_n,
                    ipa_n)
            return total
        args = (pods.nonzero_req, taint_raw, aff_raw, img, feasible)
        if sp_b is not None:
            args = args + (sp_b, ipa_b, ign_b, soft_b)
        out = jax.vmap(per_pod)(*args)
        return out if host_score is None else out + host_score

    def cond(state):
        _free, _nzr, _placed, _win, progress = state
        return progress

    def body(state):
        free, nzr, placed, win, _ = state
        eff = eff_all(free)                                        # [B, N, R]
        fit = jnp.all(pods.req[:, None, :] <= eff, axis=-1)
        feasible = static_ok & fit & (placed < 0)[:, None]
        if ipa_mask is not None:
            feasible = feasible & ipa_mask
        if soft is not None:
            # live soft topology scores against the ROUND-START placed
            # set (the auction's state discipline, same as utilization)
            ipa_live_g, sp_r_g = _soft_scores(soft, placed, gid_oh)
            total = totals(nzr, feasible, sp_b=sp_r_g[soft.gid],
                           ipa_b=ipa_live_g[soft.gid])
        else:
            total = totals(nzr, feasible)
        choice = jax.vmap(C.masked_argmax_random)(total, feasible, perturb)
        # per-node acceptance: up to k_accept pods per node per round,
        # in batch index order, while their CUMULATIVE requests keep
        # fitting the pod's own effective free row (exact as-if-serial
        # feasibility); colliding losers re-score against the updated
        # cluster next round, so utilization scores steer them away from
        # just-filled nodes and the final balance tracks the serial
        # loop's. Everything is dense [B, N] reductions / cumsums /
        # one-hot matmuls — no scatters, which TPU would serialize per
        # update.
        chosen = choice[:, None] == jnp.arange(N)[None, :]         # [B, N]
        if multi_accept:
            rank = jnp.cumsum(chosen.astype(jnp.int32), axis=0) - 1
            take = chosen & (rank < k_accept)
            cum_ok = jnp.ones((B, N), bool)
            for r in range(pods.req.shape[1]):     # static R unroll
                cr = jnp.cumsum(jnp.where(take, pods.req[:, r:r + 1],
                                          0.0), axis=0)
                cum_ok &= cr <= eff[:, :, r]
            acc_cell = take & cum_ok
            accept = (choice >= 0) & jnp.take_along_axis(
                acc_cell, jnp.clip(choice, 0, N - 1)[:, None],
                axis=1)[:, 0]
        else:
            # one accept per node per round: first chooser in batch
            # index order (the historical program; K would be 1 anyway)
            cand_idx = jnp.where(chosen, idx_b[:, None], B)
            first_idx = jnp.min(cand_idx, axis=0)                  # [N]
            accept = ((choice >= 0)
                      & (jnp.take(first_idx, jnp.clip(choice, 0, N - 1))
                         == idx_b))                                # [B]
        onehot = (accept[:, None] & chosen).astype(free.dtype)     # [B, N]
        free = free - onehot.T @ pods.req                          # [N, R]
        nzr = nzr + onehot.T @ pods.nonzero_req                    # [N, 2]
        placed = jnp.where(accept, choice, placed)
        win_now = jnp.take_along_axis(
            total, jnp.clip(choice, 0, N - 1)[:, None], axis=1)[:, 0]
        win = jnp.where(accept, win_now, win)
        return free, nzr, placed, win, jnp.any(accept)

    init = (free0, nzr0, jnp.full((B,), -1, jnp.int32),
            jnp.zeros((B,), jnp.float32), jnp.bool_(True))
    # fused multi-round body: the while condition is data-dependent, so
    # every loop iteration costs a host<->device round trip on the
    # progress flag. Running `unroll` rounds per iteration cuts that
    # U-fold with fixed shapes (no recompiles). Rounds past convergence
    # are skipped by lax.cond on the progress flag — and even an executed
    # extra round is a no-op, because at the fixed point the feasible set
    # admits no accept (the body is idempotent), so the final state is
    # bit-identical to the one-round-per-iteration program.
    unroll = auction_unroll() if unroll is None else max(1, int(unroll))
    if unroll == 1:
        fused = body
    else:
        def fused(state):
            state = body(state)
            for _ in range(unroll - 1):
                state = jax.lax.cond(state[4], body, lambda s: s, state)
            return state
    free, nzr, placed, win, _ = jax.lax.while_loop(cond, fused, init)

    # diagnostics from the final state (unplaced pods' reject attribution)
    fit = fit_all(free)
    zeros = jnp.zeros((B,), jnp.int32)
    if ipa_mask is not None:
        feas = jnp.sum(static_ok & fit & ipa_mask,
                       axis=1).astype(jnp.int32)
        ipa_rejects = jnp.sum(static_ok & fit & ~ipa_mask,
                              axis=1).astype(jnp.int32)
    else:
        feas = jnp.sum(static_ok & fit, axis=1).astype(jnp.int32)
        ipa_rejects = zeros
    fit_rejects = jnp.sum(static_ok & ~fit, axis=1).astype(jnp.int32)
    reject_counts = jnp.concatenate(
        [static_rejects, fit_rejects[:, None], zeros[:, None],
         ipa_rejects[:, None]], axis=1)
    # learned-score magnitude + chosen-node feature export, attributed
    # against the END state like the reject diagnostics above (the
    # per-round states the losers scored against are gone)
    learned_mag = jnp.float32(0.0)
    chosen_feat = jnp.zeros((B, LN.NUM_FEATURES), jnp.float32)
    alt_row = jnp.full((B, ALT_K), -1, jnp.int32)
    alt_score = jnp.full((B, ALT_K), ALT_NONE, jnp.float32)
    if learned is not None or with_feats or with_alts:
        ok_end = static_ok & fit       # end-state feasible, like rejects
        if ipa_mask is not None:
            ok_end = ok_end & ipa_mask
        rows_c = jnp.clip(placed, 0, N - 1)
        chosen_oh = ((rows_c[:, None] == jnp.arange(N)[None, :])
                     & (placed >= 0)[:, None])                # [B, N]
        # the chosen node joins its own candidate/normalization mask
        # even when end-state fit excludes it (it WAS feasible when it
        # won)
        cand = ok_end | chosen_oh
        if soft is not None:
            # end-state soft halves ride the export totals (and, via
            # LN.feature_rows' spread/ipa columns, the feature export)
            # exactly like the reject diagnostics above
            ipa_end_g, sp_end_g = _soft_scores(soft, placed, gid_oh)
            ipa_end_b = ipa_end_g[soft.gid]
            sp_end_b = sp_end_g[soft.gid]
        else:
            ipa_end_b = jnp.zeros((B, N), jnp.float32)
            sp_end_b = jnp.zeros((B, N), jnp.float32)
            ign_b = jnp.ones((B, N), bool)
            soft_b = jnp.zeros((B,), bool)

        def pod_eval(nzreq, t_raw, a_raw, im, feas_row, own_row,
                     ipa_row, sp_row, ign_row, softp):
            # ONE evaluation feeds every export tail (features, the
            # fused learned term, the alt totals) — like the serial
            # scan deriving all three from one per-step state. The
            # pod's OWN committed usage is subtracted first:
            # utilization_fractions re-adds the request, so feeding
            # end-state nzr directly would double-count the pod on its
            # chosen node — skewing the exported training distribution
            # away from inference AND deflating exactly the chosen
            # basis regret compares against the runner-ups
            nzr_i = nzr - own_row[:, None] * nzreq[None, :]
            frac, least, bal, taint, aff = per_pod_scores(
                nzr_i, nzreq, t_raw, a_raw, feas_row)
            ipa_n = SC.normalize_maxmin(ipa_row, feas_row)
            sp_n = jnp.where(softp,
                             SC.normalize_spread(sp_row, feas_row,
                                                 ign_row), 0.0)
            feats_row = LN.feature_rows(frac, least, bal, taint, aff,
                                        im, sp_n, ipa_n)     # [N, F]
            lterm_row = (jnp.clip(LN.mlp_apply(learned, feats_row),
                                  0.0, LN.MAX_SCORE)
                         if learned is not None
                         else jnp.zeros_like(least))          # [N]
            total = (weights.taint_toleration * taint
                     + weights.node_affinity * aff
                     + weights.resources_fit * least
                     + weights.balanced_allocation * bal
                     + weights.image_locality * im
                     + weights.pod_topology_spread * sp_n
                     + weights.inter_pod_affinity * ipa_n
                     + weights.learned * lterm_row)
            return feats_row, lterm_row, total
        # unused outputs are DCE'd per compiled flag combination
        feats, lterm, tot = jax.vmap(pod_eval)(
            pods.nonzero_req, taint_raw, aff_raw, img, cand,
            chosen_oh.astype(nzr.dtype),
            ipa_end_b, sp_end_b, ign_b, soft_b)
        if learned is not None:
            # same feasible-pair definition as the serial path's live
            # mask (modulo end-state attribution): one histogram, one
            # metric meaning across commit paths
            n_ok = jnp.maximum(jnp.sum(ok_end), 1)
            learned_mag = (jnp.sum(jnp.where(
                ok_end, jnp.abs(weights.learned * lterm), 0.0))
                / n_ok.astype(jnp.float32))
        if with_feats:
            chosen_feat = jnp.take_along_axis(
                feats, rows_c[:, None, None], axis=1)[:, 0, :]
        if with_alts:
            # top-K candidate nodes + aggregate scores, attributed
            # against the END state like the feature/reject
            # diagnostics above (the per-round states the losers
            # scored against are gone); the chosen node rides the
            # candidate set so its score is comparable to its
            # runners-up on ONE basis
            if host_score is not None:
                tot = tot + host_score
            masked = jnp.where(cand, tot, ALT_NONE)
            k = min(ALT_K, N)
            a_s, a_r = jax.lax.top_k(masked, k)
            a_r = jnp.where(a_s > ALT_NONE * 0.5,
                            a_r.astype(jnp.int32), -1)
            alt_score = alt_score.at[:, :k].set(a_s)
            alt_row = alt_row.at[:, :k].set(a_r)
    return BatchResult(node_row=placed, score=win, feasible_count=feas,
                       reject_counts=reject_counts,
                       unresolvable_count=unres, free=free, nzr=nzr,
                       pct_start=jnp.int32(0),
                       guard=_guard_reduction(win, free),
                       dra_reject=(jnp.zeros((B,), jnp.int32)
                                   if dra_reject is None else dra_reject),
                       learned_mag=learned_mag, chosen_feat=chosen_feat,
                       alt_row=alt_row, alt_score=alt_score)


def schedule_batch(cblobs: ClusterBlobs, pblobs: PodBlobs,
                   wk: dict[str, jnp.ndarray], weights: ScoreWeights,
                   caps: Capacities, enable_topology: bool = True,
                   d_cap: int | None = None,
                   enabled_filters: tuple[bool, ...] | None = None,
                   serial_scan: bool = True,
                   state: tuple[jnp.ndarray, jnp.ndarray] | None = None,
                   active: tuple[str, ...] | None = None,
                   pfields: tuple[str, ...] | None = None,
                   ptmpl: PodBlobs | None = None,
                   gid: jnp.ndarray | None = None,
                   rep: jnp.ndarray | None = None,
                   g_cap: int = 0,
                   host_ok: jnp.ndarray | None = None,
                   host_score: jnp.ndarray | None = None,
                   fit_strategy: str = "LeastAllocated",
                   fit_shape=None,
                   pct_nodes: int = 0,
                   pct_start: jnp.ndarray | None = None,
                   dra=None,
                   learned=None,
                   tie_seed=None,
                   with_feats: bool = False,
                   with_alts: bool = False,
                   topo_soft: bool = False,
                   auction_unroll: int | None = None,
                   ) -> BatchResult:
    """Schedule a whole pod batch in one launch, as-if-serial (see module
    docstring for the two-phase structure).

    ``topo_soft`` (STATIC): the batch's topology work is SOFT-only (no
    required terms, no DoNotSchedule spread — LaunchSpec.topo_soft). The
    serial scan then compiles the reduced soft program: only the
    weighted-score carries (wscore_n + node-space spread counts) survive
    — the hard-constraint carry maps (forbid/presence/domain-count
    tensors, the ones that scale with d_cap) are provably neutral for a
    soft-only batch and compile out. Same placements, bit-identical
    scores, a fraction of the per-step kernels. The auction path uses it
    to fuse the soft-score terms (_soft_statics/_soft_scores).

    ``enable_topology`` and ``d_cap`` are STATIC, host-derived launch args —
    the device analog of PreFilter returning Skip (framework/interface.go):
    a batch with no (anti)affinity terms or spread constraints compiles to a
    program with the topology kernels dead-code-eliminated, and ``d_cap``
    bounds the domain scatter space to the batch's actually-used topology
    keys (Mirror.domain_bucket) instead of the worst-case node count.

    ``serial_scan=False`` (STATIC) selects the parallel-rounds auction
    (_rounds_commit) instead of the exact-parity commit scan. Only valid
    when the launch has no topology work and no batch pod carries host
    ports — the host gates this (Scheduler/bench), mirroring the
    reference's own "skip what the pod doesn't use" PreFilter returns.

    ``state`` optionally overrides the cluster's (free, nonzero_requested)
    usage tensors with the previous launch's BatchResult.free/.nzr — the
    device-resident chain that lets a multi-batch drain run without host
    mirror re-syncs in between.

    ``gid``/``rep``/``g_cap`` (Mirror._batch_groups) dedup the batch into
    TOPOLOGY GROUPS: pods whose packed rows differ only in identity fields
    compute identical topology statics and pairwise term matches, so both
    the phase-1 statics and the commit scan's in-batch maps are computed per
    GROUP, not per pod. Real workloads are deployment-shaped (few distinct
    specs per batch), which turns the former per-pod scatter storm — TPU
    scatters run ~100x below bandwidth — into a handful of small dense
    updates. g_cap is a static pow2 bucket; a fully heterogeneous batch
    (g_cap == B) is still exact, just back to per-pod cost.

    ``host_ok``/``host_score`` ([B, N] bool / f32) carry HOST plugin
    verdicts (volume family, custom plugins): the host filter mask is ANDed
    into every pod's feasible set, the host score added to the aggregate —
    the mixed host/device framework's seam (runtime.run_host_filters).

    ``dra`` (an ops.dra.DraBatch, or None for launches without device-
    routed claim pods) fuses the batched DRA allocator into this same
    program: claim feasibility for every (pod, node) pair is one more
    vmapped predicate ANDed into the feasible mask, and the per-pod
    rejected-node count lands in BatchResult.dra_reject.

    ``learned`` (an ops.learned params pytree, or None) fuses the
    profile-gated MLP scorer into the aggregate as one more weighted
    term (weights.learned); a NaN-poisoned checkpoint trips the guard
    reduction like any other device fault. ``tie_seed`` (dynamic scalar)
    keys the tie-break hash for A/B-deterministic paired runs; seed
    0/None is the historical hash. ``with_feats`` (STATIC) additionally
    materializes each pod's chosen-node feature row in
    BatchResult.chosen_feat — the flight-recorder export's replay rows;
    off, the field is zeros and the feature kernels are DCE'd.
    ``with_alts`` (STATIC) materializes the top-ALT_K candidate node
    rows + aggregate scores per pod in BatchResult.alt_row/.alt_score —
    the export v3 counterfactual substrate behind per-placement regret
    (learn/regret.py); off, the fields are padding and the top_k is
    DCE'd."""
    ct = unpack_cluster(cblobs, caps)
    pods = unpack_pods(pblobs, caps, pfields, ptmpl)  # leaves [B, ...]
    free0 = ct.free if state is None else state[0]
    nzr0 = ct.nonzero_requested if state is None else state[1]
    act = frozenset(ALL_FEATURES if active is None else active)
    num_valid = jnp.sum(ct.node_valid)
    valid = ct.node_valid
    if d_cap is None:
        d_cap = caps.domain_cap
    if enabled_filters is None:
        enabled_filters = (True,) * NUM_FILTER_PLUGINS
    fit_on = enabled_filters[FILTER_PLUGINS.index("NodeResourcesFit")]
    spread_on = (enable_topology
                 and enabled_filters[FILTER_PLUGINS.index("PodTopologySpread")])
    ipa_on = (enable_topology
              and enabled_filters[FILTER_PLUGINS.index("InterPodAffinity")])
    tds = T.slot_topo_dom(ct)  # [PT, TK], shared across the batch
    if enable_topology and gid is None:
        # direct callers without host grouping: every pod its own group.
        # NOTE: at large B this materializes O(B*N)-sized scan-carry maps —
        # production callers go through Mirror.prepare_launch, whose host
        # dedup keeps g_cap at the number of DISTINCT pod specs
        nb = pblobs.f32.shape[0]
        gid = jnp.arange(nb, dtype=jnp.int32)
        rep = jnp.arange(nb, dtype=jnp.int32)
        g_cap = nb

    # ---- phase 1: parallel over the batch (per-pod base statics) ----
    def per_pod(pod: PodFeatures):
        masks = static_filters(ct, pod, wk, enabled_filters, act)  # [5, N]
        static_ok = jnp.all(masks, axis=0) & valid & pod.valid  # [N]
        # first-fail attribution among the static plugins
        prev_ok = jnp.cumprod(
            jnp.concatenate([jnp.ones((1, masks.shape[1]), masks.dtype),
                             masks[:-1]], axis=0), axis=0).astype(bool)
        first_fail = prev_ok & ~masks & valid[None]
        static_rejects = jnp.sum(first_fail, axis=1).astype(jnp.int32)  # [P-1]
        # raw commit-invariant scores (inactive feature -> zero, DCE'd)
        n = valid.shape[0]
        zeros_n = jnp.zeros((n,), jnp.float32)
        taint_raw = (SC.taint_toleration_score(ct, pod)
                     if "taints" in act else zeros_n)           # [N]
        aff_raw = (SC.node_affinity_score(ct, pod)
                   if "nodeaffinity" in act else zeros_n)       # [N]
        img = (SC.image_locality(ct, pod, num_valid)
               if "images" in act else zeros_n)                 # [N]
        # fit can never succeed: request exceeds allocatable (Unresolvable)
        unresolvable = jnp.any(pod.req[None] > ct.allocatable, axis=-1)
        unres_count = jnp.sum(unresolvable & valid).astype(jnp.int32)
        return (static_ok, static_rejects, taint_raw, aff_raw, img,
                unres_count)

    def chunked_vmap(fn, tree, n_rows):
        """vmap chunked through lax.map so giant batches stay inside HBM —
        per-chunk peak is what a PHASE1_CHUNK-sized batch needs."""
        if n_rows <= PHASE1_CHUNK:
            return jax.vmap(fn)(tree)
        pad = (-n_rows) % PHASE1_CHUNK
        tree_p = tree if pad == 0 else jax.tree.map(
            lambda x: jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1)), tree)
        groups = (n_rows + pad) // PHASE1_CHUNK
        tree_g = jax.tree.map(
            lambda x: x.reshape((groups, PHASE1_CHUNK) + x.shape[1:]), tree_p)
        outs = jax.lax.map(lambda p: jax.vmap(fn)(p), tree_g)
        return jax.tree.map(
            lambda x: x.reshape((groups * PHASE1_CHUNK,)
                                + x.shape[2:])[:n_rows], outs)

    B_all = pblobs.f32.shape[0]
    if gid is not None and rep.shape[0] < B_all:
        # phase-1 dedup: statics are identity-free, so compute them per
        # GROUP representative and gather back to pods — deployment-shaped
        # batches (few distinct specs) collapse the [B, N] phase-1 work to
        # [G, N] (Mirror.prepare_launch attaches groups for no-topology
        # launches too when the batch is homogeneous enough). Degenerate
        # per-pod groupings (rep as wide as the batch) skip the detour —
        # the two full-batch gathers would only add HBM traffic.
        pods_rep_p1 = jax.tree.map(lambda x: x[rep], pods)
        outs_g = chunked_vmap(per_pod, pods_rep_p1, rep.shape[0])
        outs = jax.tree.map(lambda x: x[gid], outs_g)
    else:
        outs = chunked_vmap(per_pod, pods, B_all)
    (static_ok, static_rejects, taint_raw, aff_raw, img, unres) = outs
    if dra is not None:
        # fused batched DRA allocator (ops/dra.py): claim feasibility
        # for all (pod, node) pairs in this same launch. First-fail
        # attribution after the static filters; host_ok rejects stay
        # host-attributed like before.
        from kubernetes_tpu.ops.dra import batch_feasible

        dra_ok = batch_feasible(dra)                            # [B, N]
        dra_reject = jnp.sum(static_ok & ~dra_ok, axis=1).astype(jnp.int32)
        static_ok = static_ok & dra_ok
    else:
        dra_reject = jnp.zeros((B_all,), jnp.int32)
    if host_ok is not None:
        # host Filter verdicts AND in here; host rejects are attributed by
        # the Scheduler from its own counts (they never reach reject_counts)
        static_ok = static_ok & host_ok
    if not serial_scan:
        if pct_nodes:
            raise ValueError(
                "percentageOfNodesToScore truncation only exists in the "
                "serial scan; gate the auction off when the knob is set")
        soft = None
        if enable_topology:
            if not topo_soft:
                raise ValueError(
                    "auction commit requires a no-topology or soft-only "
                    "topology launch; required terms / DoNotSchedule "
                    "spread need the serial as-if-serial commit scan")
            # SOFT-ONLY topology launch (the caller gates this on the
            # batch carrying no required terms and no DoNotSchedule
            # spread): preferred (anti)affinity weights and ScheduleAnyway
            # spread are SCORES, not constraints, so the auction's round
            # structure holds — the table halves are per-group statics,
            # the in-batch halves recompute per round (_soft_scores)
            pods_rep = jax.tree.map(lambda x: x[rep], pods)
            soft = _soft_statics(ct, pods, pods_rep, gid, g_cap, d_cap,
                                 tds, wk, enabled_filters, act, ipa_on,
                                 chunked_vmap)
        return _rounds_commit(ct, pods, static_ok, static_rejects, taint_raw,
                              aff_raw, img, unres, weights, free0, nzr0,
                              host_score, fit_strategy, fit_shape,
                              dra_reject, learned, tie_seed, with_feats,
                              with_alts, soft=soft, unroll=auction_unroll)
    soft_st = None
    if enable_topology and topo_soft:
        # ---- phase 1b (SOFT): the reduced per-group statics — exactly
        # what the soft scores need; none of the hard-constraint maps
        pods_rep = jax.tree.map(lambda x: x[rep], pods)
        soft_st = _soft_statics(ct, pods, pods_rep, gid, g_cap, d_cap,
                                tds, wk, enabled_filters, act, ipa_on,
                                chunked_vmap)
    if enable_topology and not topo_soft:
        # ---- phase 1b: topology statics per GROUP (representatives) ----
        pods_rep = jax.tree.map(lambda x: x[rep], pods)  # leaves [G, ...]

        def per_group(pod: PodFeatures):
            masks = static_filters(ct, pod, wk, enabled_filters, act)
            g_static_ok = jnp.all(masks, axis=0) & valid & pod.valid
            taint_ok, nodeaff_ok = masks[2], masks[3]
            used_c = pod.tsc_tk != jnp.int32(-1)
            used_hard = used_c & pod.tsc_hard
            used_soft = used_c & ~pod.tsc_hard
            el_hard = T.spread_eligible(ct, pod, nodeaff_ok, taint_ok,
                                        used_hard)
            el_soft = T.spread_eligible(ct, pod, nodeaff_ok, taint_ok,
                                        used_soft)
            el_mixed = jnp.where(pod.tsc_hard[None], el_hard, el_soft)
            cnt = T.spread_cnt(ct, pod, tds, el_mixed, d_cap)      # [C, D]
            exists_hard = T.spread_exists(ct, pod, el_hard, d_cap)  # [C, D]
            node_dom = T.take_cols(ct.topo_dom, pod.tsc_tk, jnp.int32(-1))
            spread_ignored = jnp.any((node_dom == jnp.int32(-1))
                                     & used_soft[None], axis=1)     # [N]
            # topoSize over (approximately) filtered nodes: static filters
            # only, matching PreScore's filteredNodes modulo in-batch effects
            exists_score = T.spread_exists(
                ct, pod,
                (g_static_ok & ~spread_ignored)[:, None] & used_soft[None],
                d_cap)
            tp_weight = jnp.log(jnp.sum(exists_score, axis=1)
                                .astype(jnp.float32) + 2.0)         # [C]
            tsc_self = T._tsc_self_match(pod).astype(jnp.float32)   # [C]
            ipa_anti_ok, aff_present, aff_any = T.inter_pod_affinity_static(
                ct, pod, tds, d_cap)
            ipa_raw = T.inter_pod_affinity_score(
                ct, pod, tds, d_cap, jnp.float32(HARD_POD_AFFINITY_WEIGHT))
            has_soft = jnp.any(used_soft)
            # in-batch spread eligibility of ANY node as a commit target for
            # this group's constraints (policies + topology-label presence;
            # the commit scan gathers it at each committed node)
            pol = (jnp.where(pod.tsc_honor_affinity[None],
                             (nodeaff_ok & valid)[:, None], True)
                   & jnp.where(pod.tsc_honor_taints[None],
                               (taint_ok & valid)[:, None], True))  # [N, C]
            dom_ok = node_dom != jnp.int32(-1)                      # [N, C]
            all_h = jnp.all(dom_ok | ~used_hard[None], axis=1)      # [N]
            all_s = jnp.all(dom_ok | ~used_soft[None], axis=1)      # [N]
            el_node = (pol & jnp.where(used_hard[None], all_h[:, None],
                                       all_s[:, None]) & used_c[None])
            # node-space statics so the commit scan never gathers by domain:
            # required-affinity term satisfaction from the PRE-batch table,
            # spread match counts at each node's domain, domain presence
            aff_node_dom = T.take_cols(ct.topo_dom, pod.aff_tk, NONE)  # [N, A]
            has_lbl = aff_node_dom != NONE
            term_static = has_lbl & T.gather_rows(aff_present, aff_node_dom)
            match_static = T.gather_rows(cnt, node_dom)              # [N, C]
            num_domains = jnp.sum(exists_hard, axis=1)               # [C]
            return (cnt, exists_hard, spread_ignored, tp_weight, tsc_self,
                    ipa_anti_ok, aff_any, ipa_raw, has_soft,
                    el_node, term_static, has_lbl, match_static, dom_ok,
                    num_domains)

        (cnt_g, exists_hard_g, ign_g, tpw_g, self_g, ipa_anti_g,
         aff_any_g, ipa_raw_g, has_soft_g, el_node_g, term_static_g,
         has_lbl_g, match_static_g, dom_ok_g,
         num_domains_g) = chunked_vmap(per_group, pods_rep, g_cap)
        # [N, G, C] so the scan dynamic-slices a committed node's row
        el_node_nr = jnp.transpose(el_node_g, (1, 0, 2))
        # group-level term tables (the scan indexes these by group id)
        anti_tk_g = pods_rep.anti_tk                        # [G, A]
        aff_tk_g = pods_rep.aff_tk
        paff_tk_g = pods_rep.paff_tk
        panti_tk_g = pods_rep.panti_tk
        paff_w_g = pods_rep.paff_weight.astype(jnp.float32)
        panti_w_g = pods_rep.panti_weight.astype(jnp.float32)
        tsc_tk_g = pods_rep.tsc_tk                          # [G, C]
        tsc_hard_g = pods_rep.tsc_hard
        tsc_skew_g = pods_rep.tsc_max_skew
        tsc_mind_g = pods_rep.tsc_min_domains
        aff_self_g = pods_rep.aff_self_match                # [G]
        # pairwise GROUP<->GROUP term matches (placement-independent)
        M_anti_gg = T.pair_term_match(
            pods_rep.anti_tk, pods_rep.anti_ns, pods_rep.anti_ns_all,
            pods_rep.anti_sel_cols, pods_rep.anti_sel_ops,
            pods_rep.anti_sel_vals, pods_rep.plabel_vals, pods_rep.ns,
            pods_rep.valid)                                 # [G, A, G]
        M_aff_gg = T.pair_term_match(
            pods_rep.aff_tk, pods_rep.aff_ns, pods_rep.aff_ns_all,
            pods_rep.aff_sel_cols, pods_rep.aff_sel_ops,
            pods_rep.aff_sel_vals, pods_rep.plabel_vals, pods_rep.ns,
            pods_rep.valid)
        M_paff_gg = T.pair_term_match(
            pods_rep.paff_tk, pods_rep.paff_ns, pods_rep.paff_ns_all,
            pods_rep.paff_sel_cols, pods_rep.paff_sel_ops,
            pods_rep.paff_sel_vals, pods_rep.plabel_vals, pods_rep.ns,
            pods_rep.valid)
        M_panti_gg = T.pair_term_match(
            pods_rep.panti_tk, pods_rep.panti_ns, pods_rep.panti_ns_all,
            pods_rep.panti_sel_cols, pods_rep.panti_sel_ops,
            pods_rep.panti_sel_vals, pods_rep.plabel_vals, pods_rep.ns,
            pods_rep.valid)
        M_tsc_gg = T.pair_tsc_match(pods_rep)               # [G, C, G]

    # ---- phase 2: sequential commit scan (tiny per-step work) ----
    alloc2 = SC.alloc_cpu_mem(ct)                               # [N, 2]
    B = pblobs.f32.shape[0]
    # per-pod tie perturbation keyed by uid: equal-score nodes pick
    # uniformly instead of hotspotting the lowest row (selectHost's
    # reservoir sample, schedule_one.go:865)
    perturb_rows = jax.vmap(
        lambda u: tie_perturb(u, cblobs.node_f32.shape[0],
                              tie_seed))(pods.uid_id)
    # pairwise hostPort conflicts: pod j can't join a node where an earlier
    # conflicting batch pod was committed (as-if-serial NodePorts)
    port_conf = (FL.pod_pair_port_conflict(pods, wk["wildcard_ip"])
                 if "ports" in act
                 else jnp.zeros((B, B), bool))                  # [B, B]

    topo_dom = ct.topo_dom
    tk_cap = topo_dom.shape[1]

    def queries(g, forbid1_n, map2_n, pres_n, any3, wscore_n, cntmap,
                cnt_match_n):
        """Per-step topology verdicts for a group-g pod from the carry maps
        (committed pods 0..b-1 already folded in). Node-space maps make
        every query a dynamic-slice by group id — no device gathers."""
        fail1 = forbid1_n[g]                                      # [N]
        fail2 = map2_n[g]                                         # [N]
        # required affinity incl. committed pods (step_affinity_ok)
        term_used = aff_tk_g[g] != NONE                           # [A]
        term_ok = term_static_g[g] | pres_n[g].T                  # [N, A]
        pods_exist = jnp.all(term_ok | ~term_used[None], axis=1)
        all_lbl = jnp.all(has_lbl_g[g] | ~term_used[None], axis=1)
        any_match = aff_any_g[g] | any3[g]
        self_ok = aff_self_g[g] & ~any_match & all_lbl
        aff_ok = jnp.where(jnp.any(term_used), pods_exist | self_ok, True)
        ipa_ok = ipa_anti_g[g] & ~fail1 & ~fail2 & aff_ok
        # spread with live counts (step_spread semantics, gather-free:
        # domain-space counts feed the min, node-space counts the match)
        used = tsc_tk_g[g] != NONE                                # [C]
        used_hard = used & tsc_hard_g[g]
        used_soft = used & ~tsc_hard_g[g]
        cnt_live = cnt_g[g] + cntmap[g]                           # [C, D]
        exists = exists_hard_g[g]
        min_cnt = jnp.min(jnp.where(exists, cnt_live, jnp.inf), axis=1)
        min_cnt = jnp.where(jnp.isfinite(min_cnt), min_cnt, 0.0)
        min_cnt = jnp.where((tsc_mind_g[g] > 0)
                            & (num_domains_g[g] < tsc_mind_g[g]),
                            0.0, min_cnt)                         # [C]
        match_num = match_static_g[g] + cnt_match_n[g].T          # [N, C]
        skew = match_num + self_g[g][None] - min_cnt[None]
        ok_c = dom_ok_g[g] & (skew <= tsc_skew_g[g][None])
        sp_ok = jnp.all(ok_c | ~used_hard[None], axis=1)          # [N]
        per_c = match_num * tpw_g[g][None] \
            + (tsc_skew_g[g][None].astype(jnp.float32) - 1.0)
        per_c = jnp.where(used_soft[None] & dom_ok_g[g], per_c, 0.0)
        sp_r = jnp.where(ign_g[g], 0.0, jnp.sum(per_c, axis=1))
        # ipa score with committed-pod weighted deltas
        ipa_live = ipa_raw_g[g] + wscore_n[g]
        return ipa_ok, sp_ok, sp_r, ipa_live

    arange_tk_f = jnp.arange(tk_cap)
    arange_d = jnp.arange(d_cap)

    def tk_onehot(tk):
        """[..., TK] f32 one-hot of term keys (NONE -> zero row): turns every
        per-step key lookup into a tiny matmul instead of a device gather."""
        return ((tk[..., None] == arange_tk_f) & (tk[..., None] != NONE)
                ).astype(jnp.float32)

    if enable_topology and topo_soft:
        # soft-scan one-hots + the per-commit update (the soft subset of
        # map_updates: weighted paff/panti score deltas + node-space
        # spread match counts; everything else is neutral for a
        # soft-only batch and never compiles)
        oh_paff_soft = tk_onehot(soft_st.paff_tk_g)
        oh_panti_soft = tk_onehot(soft_st.panti_tk_g)
        oh_tsc_soft = tk_onehot(soft_st.tsc_tk_g)
        el_node_soft_nr = jnp.transpose(soft_st.el_node_g, (1, 0, 2))
        M_paff_soft = soft_st.M_paff_gg.astype(jnp.float32)
        M_panti_soft = soft_st.M_panti_gg.astype(jnp.float32)
        paff_w_soft = soft_st.paff_w_g
        panti_w_soft = soft_st.panti_w_g

        def soft_map_updates(g, r, do, wscore_n, cnt_match_n):
            dom_row = topo_dom[r]                              # [TK]
            same_dom = ((topo_dom == dom_row[None])
                        & (dom_row[None] != NONE)
                        & do).astype(jnp.float32)              # [N, TK]
            j_side = ((same_dom @ oh_paff_soft[g].T)
                      @ (M_paff_soft[g] * paff_w_soft[g][:, None])
                      - (same_dom @ oh_panti_soft[g].T)
                      @ (M_panti_soft[g]
                         * panti_w_soft[g][:, None]))          # [N, G]
            nd_gb_paff = jnp.einsum("nt,gat->nga", same_dom,
                                    oh_paff_soft)
            nd_gb_panti = jnp.einsum("nt,gat->nga", same_dom,
                                     oh_panti_soft)
            b_side = (jnp.einsum("nga,ga->gn", nd_gb_paff,
                                 M_paff_soft[:, :, g] * paff_w_soft)
                      - jnp.einsum("nga,ga->gn", nd_gb_panti,
                                   M_panti_soft[:, :, g]
                                   * panti_w_soft))
            wscore_n = wscore_n + j_side.T + b_side
            el_r = el_node_soft_nr[r]                          # [G, C]
            hits_c = soft_st.M_tsc_gg[:, :, g] & el_r
            nd_gb_tsc = jnp.einsum("nt,gct->ngc", same_dom,
                                   oh_tsc_soft)
            cnt_match_n = cnt_match_n + jnp.einsum(
                "ngc,gc->gcn", nd_gb_tsc,
                hits_c.astype(jnp.float32))
            return wscore_n, cnt_match_n
    if enable_topology and not topo_soft:
        oh_anti_own = tk_onehot(anti_tk_g)  # [G, A, TK] (each group's terms)
        oh_aff_own = tk_onehot(aff_tk_g)
        oh_paff_own = tk_onehot(paff_tk_g)
        oh_panti_own = tk_onehot(panti_tk_g)
        oh_tsc_own = tk_onehot(tsc_tk_g)    # [G, C, TK]

    def map_updates(g, r, do, forbid1_n, map2_n, pres_n, any3, wscore_n,
                    cntmap, cnt_match_n):
        """Fold ONE commit (group-g pod on node row r) into the carry maps.
        Everything is dense compares / tiny matmuls against the committed
        node's domain row — no scatters, no gathers (TPU runs both ~100x
        below bandwidth)."""
        dom_row = topo_dom[r]                                     # [TK]
        # same_dom[n, t]: node n shares the committed node's domain under
        # topology key t (the ONE [N, TK] compare all updates contract with)
        same_dom = ((topo_dom == dom_row[None]) & (dom_row[None] != NONE)
                    & do).astype(jnp.float32)                     # [N, TK]
        dom_row_f = dom_row.astype(jnp.float32)
        nonef = jnp.float32(NONE)

        # j-side (committed pod's own terms, keys [A]): [N, A] same-domain
        oh_j_anti = oh_anti_own[g]                                # [A, TK]
        oh_j_aff = oh_aff_own[g]
        oh_j_paff = oh_paff_own[g]
        oh_j_panti = oh_panti_own[g]
        nd_j_anti = same_dom @ oh_j_anti.T                        # [N, A]
        nd_j_aff = same_dom @ oh_j_aff.T
        # forbid1_n: j's anti terms forbid same-domain nodes for groups they
        # match
        m1 = M_anti_gg[g].astype(jnp.float32)                     # [A, G]
        forbid1_n = forbid1_n | ((nd_j_anti @ m1).T > 0)          # [G, N]
        # b-side (each group's own terms vs the committed pod)
        nd_gb_anti = jnp.einsum("nt,gat->nga", same_dom, oh_anti_own)
        m2 = M_anti_gg[:, :, g].astype(jnp.float32)               # [G, A]
        map2_n = map2_n | (jnp.einsum("nga,ga->gn", nd_gb_anti, m2) > 0)
        nd_gb_aff = jnp.einsum("nt,gat->nga", same_dom, oh_aff_own)
        m3 = M_aff_gg[:, :, g]                                    # [G, A]
        pres_n = pres_n | (jnp.einsum("nga,ga->gan", nd_gb_aff,
                                      m3.astype(jnp.float32)) > 0)
        d3 = oh_aff_own @ dom_row_f                               # [G, A]
        dv3 = (d3 != nonef) & (jnp.sum(oh_aff_own, -1) > 0)
        any3 = any3 | (jnp.any(m3 & dv3, axis=1) & do)
        # weighted ipa score deltas (scoring.go processExistingPod, all five
        # directions of the old per-step scatter groups)
        hw = jnp.full(aff_tk_g.shape[1], HARD_POD_AFFINITY_WEIGHT,
                      jnp.float32)
        j_side = (nd_j_aff @ (M_aff_gg[g].astype(jnp.float32) * hw[:, None])
                  + (same_dom @ oh_j_paff.T)
                  @ (M_paff_gg[g].astype(jnp.float32)
                     * paff_w_g[g][:, None])
                  - (same_dom @ oh_j_panti.T)
                  @ (M_panti_gg[g].astype(jnp.float32)
                     * panti_w_g[g][:, None]))                    # [N, G]
        nd_gb_paff = jnp.einsum("nt,gat->nga", same_dom, oh_paff_own)
        nd_gb_panti = jnp.einsum("nt,gat->nga", same_dom, oh_panti_own)
        b_side = (jnp.einsum("nga,ga->gn", nd_gb_paff,
                             M_paff_gg[:, :, g] * paff_w_g)
                  - jnp.einsum("nga,ga->gn", nd_gb_panti,
                               M_panti_gg[:, :, g] * panti_w_g))
        wscore_n = wscore_n + j_side.T + b_side
        # spread counts: domain-space (for the min) + node-space (for match)
        el_r = el_node_nr[r]                                      # [G, C]
        hits_c = M_tsc_gg[:, :, g] & el_r                         # [G, C]
        d_c = oh_tsc_own @ dom_row_f                              # [G, C]
        dv_c = hits_c & (d_c != nonef) & (jnp.sum(oh_tsc_own, -1) > 0) & do
        cntmap = cntmap + (dv_c[..., None]
                           & (d_c[..., None] == arange_d)
                           ).astype(jnp.float32)                  # [G, C, D]
        nd_gb_tsc = jnp.einsum("nt,gct->ngc", same_dom, oh_tsc_own)
        cnt_match_n = cnt_match_n + jnp.einsum(
            "ngc,gc->gcn", nd_gb_tsc, hits_c.astype(jnp.float32))
        return forbid1_n, map2_n, pres_n, any3, wscore_n, cntmap, cnt_match_n

    def body(carry, xs):
        if pct_nodes:
            carry, start = carry[:-1], carry[-1]
        if enable_topology and topo_soft:
            # soft scan: the only live topology state is the weighted
            # score carry + node-space spread counts; feasibility is the
            # STATIC table mask (in-batch commits cannot constrain)
            (free, nzr, committed_rows, wscore_n, cnt_match_n) = carry
            (b, ok_s, t_raw, a_raw, im, req, nzreq, ptb, g) = xs
            ipa_ok = soft_st.ipa_ok_g[g]
            sp_ok = jnp.ones_like(ok_s)
            used_soft = soft_st.used_soft_g[g]
            match_num = (soft_st.match_static_g[g]
                         + cnt_match_n[g].T)                   # [N, C]
            per_c = (match_num * soft_st.tpw_g[g][None]
                     + (soft_st.skew_g[g][None] - 1.0))
            per_c = jnp.where(used_soft[None] & soft_st.dom_ok_g[g],
                              per_c, 0.0)
            sp_r = jnp.where(soft_st.ign_g[g], 0.0,
                             jnp.sum(per_c, axis=1))
            ipa_live = soft_st.ipa_raw_g[g] + wscore_n[g]
            ign_b = soft_st.ign_g[g]
            soft_b = soft_st.has_soft_g[g]
        elif enable_topology:
            (free, nzr, committed_rows, forbid1_n, map2_n, pres_n, any3,
             wscore_n, cntmap, cnt_match_n) = carry
            (b, ok_s, t_raw, a_raw, im, req, nzreq, ptb, g) = xs
            ipa_ok, sp_ok, sp_r, ipa_live = queries(
                g, forbid1_n, map2_n, pres_n, any3, wscore_n, cntmap,
                cnt_match_n)
            if not spread_on:   # filter disabled by config (score may stay)
                sp_ok = jnp.ones_like(sp_ok)
            if not ipa_on:
                ipa_ok = jnp.ones_like(sp_ok)
            ign_b = ign_g[g]
            soft_b = has_soft_g[g]
        else:
            (free, nzr, committed_rows) = carry
            (b, ok_s, t_raw, a_raw, im, req, nzreq, ptb) = xs
            ones = jnp.ones_like(ok_s)
            sp_ok = ipa_ok = ones
            sp_r = ipa_live = jnp.zeros_like(t_raw)
            ign_b = ~ones
            soft_b = jnp.bool_(False)
        if fit_on:
            # nominated preemptors reserve their requests on their nominated
            # node (framework.go:989 AddPod pass); a pod's OWN nomination is
            # handed back so it can claim the room its victims vacated
            own = (jnp.arange(free.shape[0]) == pods.nominated_row[b])
            eff = free - ct.nominated_req + jnp.where(own[:, None], req[None],
                                                      0.0)
            fit_ok = jnp.all(req[None] <= eff, axis=-1)         # [N]
        else:
            fit_ok = jnp.ones(free.shape[0], bool)
        # nodes holding an earlier batch commit that clashes on hostPort
        clash = port_conf[b] & (committed_rows >= 0)            # [B]
        forbidden = jnp.zeros_like(fit_ok).at[
            jnp.maximum(committed_rows, 0)].max(clash)          # [N]
        ports_ok = ~forbidden
        feasible = ok_s & ports_ok & fit_ok & sp_ok & ipa_ok
        if pct_nodes:
            # percentageOfNodesToScore early-exit parity
            # (schedule_one.go:668-694): visit nodes in rotating order from
            # `start`, stop once k feasible are found, score only those.
            # Unnecessary for TPU throughput (all nodes are scored in one
            # launch regardless) but preserves the reference's node-subset
            # SELECTION semantics when the knob is set. reject_counts stay
            # full-cluster (better diagnostics than the reference's
            # partial-visit counts; documented divergence). Padding rows are
            # never feasible, so they only inflate `processed` bookkeeping.
            n_total = feasible.shape[0]
            nv = num_valid.astype(jnp.int32)
            if pct_nodes == ADAPTIVE_PCT:
                # explicit 0 in config = the reference's adaptive formula
                # (numFeasibleNodesToFind, schedule_one.go:668-694):
                # pct = 50 - nodes/125, floored at 5
                eff = jnp.maximum(jnp.int32(5), 50 - nv // 125)
            else:
                eff = jnp.int32(pct_nodes)
            k_find = jnp.maximum(
                jnp.int32(MIN_FEASIBLE_NODES_TO_FIND), (nv * eff) // 100)
            rolled = jnp.roll(feasible, -start)
            csum = jnp.cumsum(rolled.astype(jnp.int32))
            feasible = jnp.roll(rolled & (csum <= k_find), start)
            found_k = csum[-1] >= k_find
            kth = jnp.argmax(csum >= k_find).astype(jnp.int32)
            processed = jnp.where(found_k, kth + 1, n_total)
            # Advance in row space, then SNAP to the next valid row so
            # nextStartNodeIndex never dwells on padding/hole regions —
            # matching the reference's rotation cadence over real nodes
            # (schedule_one.go:620) while row layout may have holes.
            start = (start + processed) % n_total
            start = (start + jnp.argmax(jnp.roll(valid, -start))) % n_total
        frac = SC.utilization_fractions(alloc2, nzr, nzreq)
        least = SC.fit_score_from_fractions(frac, fit_strategy, fit_shape)
        bal = SC.balanced_allocation_from_fractions(frac)
        taint = SC.normalize_inverse(t_raw, feasible)
        aff = SC.normalize_max(a_raw, feasible)
        ipa = SC.normalize_maxmin(ipa_live, feasible)
        spread = jnp.where(soft_b,
                           SC.normalize_spread(sp_r, feasible, ign_b), 0.0)
        total = (weights.taint_toleration * taint
                 + weights.node_affinity * aff
                 + weights.resources_fit * least
                 + weights.balanced_allocation * bal
                 + weights.image_locality * im
                 + weights.pod_topology_spread * spread
                 + weights.inter_pod_affinity * ipa)
        if learned is not None:
            # the fused MLP term, against the SAME live per-step state
            # the hand-tuned terms see (as-if-serial holds for it too)
            lterm = weights.learned * LN.learned_term(
                learned, frac, least, bal, taint, aff, im, spread, ipa)
            total = total + lterm
            lmag_step = (jnp.sum(jnp.where(feasible, jnp.abs(lterm), 0.0))
                         / jnp.maximum(jnp.sum(feasible), 1)
                         .astype(jnp.float32))
        if host_score is not None:
            total = total + host_score[b]
        row = C.masked_argmax_random(total, feasible, ptb)
        # commit the winner (the "assume"): free -= request, nonzero += request
        do = row >= 0
        r = jnp.maximum(row, 0)
        free = free.at[r].add(jnp.where(do, -req, 0.0))
        nzr = nzr.at[r].add(jnp.where(do, nzreq, 0.0))
        committed_rows = committed_rows.at[b].set(row)
        # first-fail order: NodePorts (in-batch), Fit, Spread, InterPod
        ok_ports = ok_s & ports_ok
        ok_fit = ok_ports & fit_ok
        ok_sp = ok_fit & sp_ok
        port_rejects = jnp.sum(ok_s & ~ports_ok).astype(jnp.int32)
        fit_rejects = jnp.sum(ok_ports & ~fit_ok).astype(jnp.int32)
        sp_rejects = jnp.sum(ok_fit & ~sp_ok).astype(jnp.int32)
        ipa_rejects = jnp.sum(ok_sp & ~ipa_ok).astype(jnp.int32)
        win = jnp.where(do, total[r], 0.0)
        if enable_topology and topo_soft:
            wscore_n, cnt_match_n = soft_map_updates(
                g, r, do, wscore_n, cnt_match_n)
            out_carry = (free, nzr, committed_rows, wscore_n,
                         cnt_match_n)
        elif enable_topology:
            (forbid1_n, map2_n, pres_n, any3, wscore_n, cntmap,
             cnt_match_n) = map_updates(
                g, r, do, forbid1_n, map2_n, pres_n, any3, wscore_n,
                cntmap, cnt_match_n)
            out_carry = (free, nzr, committed_rows, forbid1_n, map2_n,
                         pres_n, any3, wscore_n, cntmap, cnt_match_n)
        else:
            out_carry = (free, nzr, committed_rows)
        if pct_nodes:
            out_carry = out_carry + (start,)
        ys = (row, win, jnp.sum(feasible).astype(jnp.int32),
              port_rejects, fit_rejects, sp_rejects, ipa_rejects)
        if learned is not None:
            ys = ys + (lmag_step,)
        if with_feats:
            ys = ys + (LN.feature_row_at(r, frac, least, bal, taint, aff,
                                         im, spread, ipa),)
        if with_alts:
            # top-K candidates against the pod's LIVE per-step state —
            # exactly the alternatives this pod could have taken at its
            # decision time (the serial path's as-if-serial
            # counterfactual; top_k breaks ties by row index, so the
            # tie-perturbed winner need not be slot 0 — the offline
            # consumer treats its entry as the chosen value's basis
            # wherever it lands)
            masked_t = jnp.where(feasible, total, ALT_NONE)
            k_alt = min(ALT_K, masked_t.shape[0])
            a_s, a_r = jax.lax.top_k(masked_t, k_alt)
            if k_alt < ALT_K:
                a_s = jnp.concatenate(
                    [a_s, jnp.full((ALT_K - k_alt,), ALT_NONE,
                                   jnp.float32)])
                a_r = jnp.concatenate(
                    [a_r, jnp.full((ALT_K - k_alt,), -1, a_r.dtype)])
            a_r = jnp.where(a_s > ALT_NONE * 0.5,
                            a_r.astype(jnp.int32), -1)
            ys = ys + (a_r, a_s)
        return out_carry, ys

    xs = (jnp.arange(B), static_ok, taint_raw, aff_raw, img,
          pods.req, pods.nonzero_req, perturb_rows)
    init = (free0, nzr0, jnp.full((B,), -1, jnp.int32))
    if enable_topology and topo_soft:
        xs = xs + (gid,)
        n_cap = free0.shape[0]
        C_cap = soft_st.tsc_tk_g.shape[1]
        init = init + (
            jnp.zeros((g_cap, n_cap), jnp.float32),       # wscore_n
            jnp.zeros((g_cap, C_cap, n_cap), jnp.float32),   # cnt_match_n
        )
    elif enable_topology:
        xs = xs + (gid,)
        A_cap = anti_tk_g.shape[1]
        C_cap = tsc_tk_g.shape[1]
        n_cap = free0.shape[0]
        init = init + (
            jnp.zeros((g_cap, n_cap), bool),              # forbid1_n
            jnp.zeros((g_cap, n_cap), bool),              # map2_n (own anti)
            jnp.zeros((g_cap, A_cap, n_cap), bool),       # pres_n (affinity)
            jnp.zeros((g_cap,), bool),                    # any3
            jnp.zeros((g_cap, n_cap), jnp.float32),       # wscore_n
            jnp.zeros((g_cap, C_cap, d_cap), jnp.float32),   # cntmap
            jnp.zeros((g_cap, C_cap, n_cap), jnp.float32),   # cnt_match_n
        )
    if pct_nodes:
        # rotating nextStartNodeIndex, seeded from the previous launch's
        # BatchResult.pct_start so rotation persists ACROSS batches
        init = init + (jnp.int32(0) if pct_start is None
                       else jnp.asarray(pct_start, jnp.int32),)
    # unroll: the body is many small fused kernels; per-iteration dispatch
    # overhead (not FLOPs) is a real cost at these shapes, so unrolling
    # amortizes it
    (carry_out, ys_out) = jax.lax.scan(body, init, xs,
                                       unroll=scan_unroll())
    (rows, win_scores, feas, port_rejects, fit_rejects, sp_rejects,
     ipa_rejects) = ys_out[:7]
    extra = list(ys_out[7:])
    learned_mag = jnp.float32(0.0)
    if learned is not None:
        lmags = extra.pop(0)                                      # [B]
        n_valid = jnp.maximum(jnp.sum(pods.valid), 1)
        learned_mag = (jnp.sum(jnp.where(pods.valid, lmags, 0.0))
                       / n_valid.astype(jnp.float32))
    chosen_feat = (extra.pop(0) if with_feats
                   else jnp.zeros((B, LN.NUM_FEATURES), jnp.float32))
    if with_alts:
        alt_row = extra.pop(0)                                 # [B, K]
        alt_score = extra.pop(0)
    else:
        alt_row = jnp.full((B, ALT_K), -1, jnp.int32)
        alt_score = jnp.full((B, ALT_K), ALT_NONE, jnp.float32)
    free_out, nzr_out = carry_out[0], carry_out[1]
    start_out = carry_out[-1] if pct_nodes else jnp.int32(0)

    ports_idx = FILTER_PLUGINS.index("NodePorts")
    static_rejects = static_rejects.at[:, ports_idx].add(port_rejects)
    reject_counts = jnp.concatenate(
        [static_rejects, fit_rejects[:, None], sp_rejects[:, None],
         ipa_rejects[:, None]], axis=1)
    return BatchResult(node_row=rows, score=win_scores, feasible_count=feas,
                       reject_counts=reject_counts, unresolvable_count=unres,
                       free=free_out, nzr=nzr_out, pct_start=start_out,
                       guard=_guard_reduction(win_scores, free_out),
                       dra_reject=dra_reject, learned_mag=learned_mag,
                       chosen_feat=chosen_feat,
                       alt_row=alt_row, alt_score=alt_score)


@partial(jax.jit, static_argnames=("caps", "enable_topology", "d_cap",
                                   "enabled_filters", "serial_scan",
                                   "active", "pfields", "g_cap",
                                   "fit_strategy", "pct_nodes",
                                   "with_feats", "with_alts",
                                   "topo_soft", "auction_unroll"))
def schedule_batch_jit(cblobs, pblobs, wk, weights, caps,
                       enable_topology=True, d_cap=None,
                       enabled_filters=None, serial_scan=True, state=None,
                       active=None, pfields=None, ptmpl=None,
                       gid=None, rep=None, g_cap=0, host_ok=None,
                       host_score=None, fit_strategy="LeastAllocated",
                       fit_shape=None, pct_nodes=0, pct_start=None,
                       dra=None, learned=None, tie_seed=None,
                       with_feats=False, with_alts=False,
                       topo_soft=False, auction_unroll=None):
    return schedule_batch(cblobs, pblobs, wk, weights, caps,
                          enable_topology, d_cap, enabled_filters,
                          serial_scan, state, active, pfields, ptmpl,
                          gid, rep, g_cap, host_ok, host_score,
                          fit_strategy, fit_shape, pct_nodes, pct_start,
                          dra, learned, tie_seed, with_feats, with_alts,
                          topo_soft, auction_unroll)


@partial(jax.jit, static_argnames=("caps",))
def extract_state_jit(cblobs, caps):
    """(free, nonzero_requested) of a cluster blob — the seed for the
    device-resident usage chain. The Scheduler feeds this to every
    UNCHAINED launch so chained and unchained dispatches share one
    schedule_batch_jit signature (state always present): the warmup pass
    then compiles the exact program the full-scale drain runs, instead of
    a fresh multi-second XLA compile appearing mid-phase the first time a
    drain chains two batches."""
    ct = unpack_cluster(cblobs, caps)
    return ct.free, ct.nonzero_requested


@jax.jit
def _chain_set_rows_jit(free, nzr, idx, free_rows, nzr_rows):
    return free.at[idx].set(free_rows), nzr.at[idx].set(nzr_rows)


@jax.jit
def _chain_add_rows_jit(free, nzr, idx, free_rows, nzr_rows):
    return free.at[idx].add(free_rows), nzr.at[idx].add(nzr_rows)


def patch_chain(free, nzr, set_rows=(), add_rows=()):
    """Scatter node-row patches into the device-resident (free, nzr) usage
    chain IN PLACE of a full snapshot resync — the device half of
    chain-surviving churn. This generalizes the gang packer's free/nzr
    chunk-chaining protocol (ops.gang.pack_gangs ``state=``): the chain is
    the single mutable device truth between launches, and everyone who
    learns something about a node — a committed chunk, an informer event —
    folds it in rather than rebuilding the world.

    ``set_rows`` carries absolute repacks (node add/update/remove):
    ``(row, free_row [R], nzr_row [2])`` tuples whose rows REPLACE the
    chain's. ``add_rows`` carries commutative usage deltas (foreign pod
    bind/delete): ``(row, dfree [R], dnzr [2])`` tuples ADDED to the
    chain's rows, so they compose with in-flight waves' device commits in
    either order. Row lists are padded host-side to the next power of two
    (sets duplicate their last entry — idempotent; adds pad zero rows —
    identity) so launch shapes stay in a tiny bucket family and a drain
    never recompiles on patch count. Donation is deliberately off: the
    input chain may still be referenced by an in-flight wave's pending
    tuple. Returns the patched (free, nzr)."""
    import numpy as _np

    def _pad(rows, dup):
        k = len(rows)
        cap = 1
        while cap < k:
            cap *= 2
        idx = _np.empty((cap,), _np.int32)
        fr = _np.zeros((cap, free.shape[1]), _np.float32)
        nz = _np.zeros((cap, nzr.shape[1]), _np.float32)
        for i, (r, f, n) in enumerate(rows):
            idx[i] = r
            fr[i] = f
            nz[i] = n
        for i in range(k, cap):
            idx[i] = rows[-1][0]
            if dup:
                fr[i] = rows[-1][1]
                nz[i] = rows[-1][2]
        return idx, fr, nz
    if set_rows:
        free, nzr = _chain_set_rows_jit(free, nzr, *_pad(set_rows, True))
    if add_rows:
        free, nzr = _chain_add_rows_jit(free, nzr, *_pad(add_rows, False))
    return free, nzr


def warm_patch_chain(free, nzr, max_bucket: int = 256) -> None:
    """Pre-compile every patch-scatter bucket the scheduler can ever
    launch against this chain shape (pow2 buckets up to the scheduler's
    patch cap, beyond which it falls back to a full resync). Called once
    per chain shape at first install so churn patches never trigger an
    XLA compile mid-drain — the patch kernels ride launch_cache_size, so
    the bench's flat-cache assertion would catch a miss here."""
    import numpy as _np

    cap = 1
    while cap <= max_bucket:
        idx = _np.zeros((cap,), _np.int32)
        fr = _np.zeros((cap, free.shape[1]), _np.float32)
        nz = _np.zeros((cap, nzr.shape[1]), _np.float32)
        a = _chain_set_rows_jit(free, nzr, idx, fr, nz)
        b = _chain_add_rows_jit(free, nzr, idx, fr, nz)
        jax.block_until_ready((a, b))
        cap *= 2


def launch_cache_size() -> int | None:
    """Executable-cache entries behind the fused launch (schedule_batch_jit
    plus the state-extraction seed): the DeviceProfiler reads this after
    each dispatch — growth means a real XLA compile happened while
    tracing that launch. None when this jax build doesn't expose the
    introspection hook (the profiler then skips compile counting)."""
    # the gang packer's jit rides the same cache accounting so a
    # gang-shape recompile is attributed to its launch (imported lazily:
    # ops.gang traces against this module's static_filters)
    from kubernetes_tpu.ops.gang import pack_gangs_jit

    total = 0
    for fn in (schedule_batch_jit, extract_state_jit, pack_gangs_jit,
               _chain_set_rows_jit, _chain_add_rows_jit):
        size = getattr(fn, "_cache_size", None)
        if size is None:
            return None
        total += size()
    return total


def launch_batch(spec, wk, weights, caps, enabled_filters=None,
                 serial_scan=True, state=None, host_ok=None,
                 host_score=None, fit_strategy="LeastAllocated",
                 fit_shape=None, pct_nodes=0, pct_start=None,
                 learned=None, tie_seed=None,
                 with_feats=False, with_alts=False) -> BatchResult:
    """schedule_batch_jit driven by a Mirror.prepare_launch LaunchSpec."""
    return schedule_batch_jit(
        spec.cblobs, spec.pblobs, wk, weights, caps,
        spec.enable_topology, spec.d_cap, enabled_filters,
        serial_scan=serial_scan, state=state, active=spec.active,
        pfields=spec.pfields, ptmpl=spec.ptmpl,
        gid=spec.gid, rep=spec.rep, g_cap=spec.g_cap,
        host_ok=host_ok, host_score=host_score,
        fit_strategy=fit_strategy, fit_shape=fit_shape,
        pct_nodes=pct_nodes, pct_start=pct_start, dra=spec.dra,
        learned=learned, tie_seed=tie_seed, with_feats=with_feats,
        with_alts=with_alts, topo_soft=spec.topo_soft)
