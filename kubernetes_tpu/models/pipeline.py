"""The flagship model: one XLA launch schedules a whole batch of pods.

This replaces the reference's serial per-pod hot path — ``schedulingCycle`` →
``findNodesThatPassFilters`` (goroutine fan-out over nodes,
schedule_one.go:583-650) → ``prioritizeNodes`` (3-stage score pipeline,
runtime/framework.go:1117-1194) → ``selectHost`` (schedule_one.go:865) →
``assume`` (schedule_one.go:938) — with a single jitted program in two
phases:

1. **Parallel phase** (vmap over the pod batch): every Filter and raw Score
   whose result cannot be changed by in-batch placements — taints, node
   affinity/selectors, host ports, unschedulable, image locality — is
   evaluated for ALL (pod, node) pairs at once. This is where the FLOPs
   are, and it is embarrassingly parallel over both axes.
2. **Commit scan** (lax.scan over pods): a deliberately tiny sequential
   pass that re-evaluates only what a previous pod's commit can invalidate
   — the resource fit predicate and the utilization scores — then
   normalizes, aggregates, argmaxes, and commits the winner's resources to
   the scan carry. Pod b+1 therefore sees pod b's placement exactly as the
   serial loop's assume step would provide ("as-if-serial").

The node axis is the sharding axis: under a ``jax.sharding.Mesh`` the
per-node work is data-parallel; argmax and normalization reductions become
XLA collectives over ICI (SURVEY.md §5.8).

Filter order follows the reference's default plugin order
(apis/config/v1/default_plugins.go:30-58); a node's rejection is attributed
to its FIRST failing plugin, mirroring RunFilterPlugins' short-circuit
(runtime/framework.go:877-922) so Diagnosis/FitError parity holds.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops import common as C
from kubernetes_tpu.ops import filters as FL
from kubernetes_tpu.ops import scores as SC
from kubernetes_tpu.ops import topology as T
from kubernetes_tpu.ops.features import (
    Capacities,
    ClusterBlobs,
    ClusterTensors,
    PodBlobs,
    PodFeatures,
    unpack_cluster,
    unpack_pods,
)

# --- filter plugin order (first-fail attribution; default_plugins.go) ---

FILTER_PLUGINS = (
    "NodeUnschedulable",
    "NodeName",
    "TaintToleration",
    "NodeAffinity",
    "NodePorts",
    "NodeResourcesFit",
    "PodTopologySpread",
    "InterPodAffinity",
)
NUM_FILTER_PLUGINS = len(FILTER_PLUGINS)

# --- score plugin set with default weights (default_plugins.go:30-58) ---

SCORE_PLUGINS = (
    "TaintToleration",            # w=3, inverse-normalized
    "NodeAffinity",               # w=2, max-normalized
    "NodeResourcesFit",           # w=1, least-allocated 0..100
    "NodeResourcesBalancedAllocation",  # w=1, 0..100
    "ImageLocality",              # w=1, 0..100
    "PodTopologySpread",          # w=2, spread-normalized
    "InterPodAffinity",           # w=2, max-min-normalized
)

# default HardPodAffinityWeight (apis/config/v1/defaults.go)
HARD_POD_AFFINITY_WEIGHT = 1.0


@jax.tree_util.register_dataclass
@dataclass
class ScoreWeights:
    """Per-plugin score weights (scorePluginWeight, runtime/framework.go:57).
    A dynamic arg — changing weights does not recompile."""

    taint_toleration: jax.Array
    node_affinity: jax.Array
    resources_fit: jax.Array
    balanced_allocation: jax.Array
    image_locality: jax.Array
    pod_topology_spread: jax.Array
    inter_pod_affinity: jax.Array


def default_weights() -> ScoreWeights:
    return ScoreWeights(
        taint_toleration=jnp.float32(3.0),
        node_affinity=jnp.float32(2.0),
        resources_fit=jnp.float32(1.0),
        balanced_allocation=jnp.float32(1.0),
        image_locality=jnp.float32(1.0),
        pod_topology_spread=jnp.float32(2.0),
        inter_pod_affinity=jnp.float32(2.0),
    )


DEFAULT_WEIGHTS = default_weights


@jax.tree_util.register_dataclass
@dataclass
class BatchResult:
    """Per-pod outcome of one batched launch."""

    node_row: jax.Array        # [B] i32: chosen node row, -1 = unschedulable
    score: jax.Array           # [B] f32: winning aggregate score
    feasible_count: jax.Array  # [B] i32: nodes passing all filters
    reject_counts: jax.Array   # [B, P] i32: nodes rejected per plugin (first-fail)
    unresolvable_count: jax.Array  # [B] i32: nodes where fit can never succeed


def static_filters(ct: ClusterTensors, pod: PodFeatures,
                   wk: dict[str, jnp.ndarray]) -> jnp.ndarray:
    """Commit-invariant Filter plugins for one pod over all nodes: [P-1, N]
    masks in FILTER_PLUGINS order (NodeResourcesFit runs in the commit scan).
    """
    return jnp.stack([
        FL.node_unschedulable(ct, pod, wk["unschedulable_taint_key"]),
        FL.node_name(ct, pod),
        FL.taint_toleration(ct, pod),
        FL.node_affinity(ct, pod),
        FL.node_ports(ct, pod, wk["wildcard_ip"]),
    ])


def schedule_batch(cblobs: ClusterBlobs, pblobs: PodBlobs,
                   wk: dict[str, jnp.ndarray], weights: ScoreWeights,
                   caps: Capacities, enable_topology: bool = True,
                   d_cap: int | None = None) -> BatchResult:
    """Schedule a whole pod batch in one launch, as-if-serial (see module
    docstring for the two-phase structure).

    ``enable_topology`` and ``d_cap`` are STATIC, host-derived launch args —
    the device analog of PreFilter returning Skip (framework/interface.go):
    a batch with no (anti)affinity terms or spread constraints compiles to a
    program with the topology kernels dead-code-eliminated, and ``d_cap``
    bounds the domain scatter space to the batch's actually-used topology
    keys (Mirror.domain_bucket) instead of the worst-case node count."""
    ct = unpack_cluster(cblobs, caps)
    pods = unpack_pods(pblobs, caps)  # leaves [B, ...]
    num_valid = jnp.sum(ct.node_valid)
    valid = ct.node_valid
    if d_cap is None:
        d_cap = caps.domain_cap
    tds = T.slot_topo_dom(ct)  # [PT, TK], shared across the batch

    # ---- phase 1: parallel over the batch ----
    def per_pod(pod: PodFeatures):
        masks = static_filters(ct, pod, wk)                    # [P-1, N]
        static_ok = jnp.all(masks, axis=0) & valid & pod.valid  # [N]
        # first-fail attribution among the static plugins
        prev_ok = jnp.cumprod(
            jnp.concatenate([jnp.ones((1, masks.shape[1]), masks.dtype),
                             masks[:-1]], axis=0), axis=0).astype(bool)
        first_fail = prev_ok & ~masks & valid[None]
        static_rejects = jnp.sum(first_fail, axis=1).astype(jnp.int32)  # [P-1]
        # raw commit-invariant scores
        taint_raw = SC.taint_toleration_score(ct, pod)         # [N]
        aff_raw = SC.node_affinity_score(ct, pod)              # [N]
        img = SC.image_locality(ct, pod, num_valid)            # [N]
        if enable_topology:
            # topology plugins (commit-invariant vs the pre-batch pod table;
            # in-batch commit effects are layered on in the commit scan)
            taint_ok, nodeaff_ok = masks[2], masks[3]
            used_c = pod.tsc_tk != jnp.int32(-1)
            el_hard = T.spread_eligible(ct, pod, nodeaff_ok, taint_ok,
                                        used_c & pod.tsc_hard)
            el_soft = T.spread_eligible(ct, pod, nodeaff_ok, taint_ok,
                                        used_c & ~pod.tsc_hard)
            m_spread = T.spread_filter(ct, pod, tds, el_hard, d_cap)   # [N]
            m_ipa = T.inter_pod_affinity_filter(ct, pod, tds, d_cap)   # [N]
            ipa_raw = T.inter_pod_affinity_score(
                ct, pod, tds, d_cap, jnp.float32(HARD_POD_AFFINITY_WEIGHT))
            spread_raw, spread_ignored = T.spread_score(
                ct, pod, tds, el_soft, static_ok & m_spread & m_ipa, d_cap)
            has_soft = jnp.any(used_c & ~pod.tsc_hard)
        else:
            ones = jnp.ones_like(static_ok)
            zeros = jnp.zeros_like(taint_raw)
            m_spread = m_ipa = ones
            ipa_raw = spread_raw = zeros
            spread_ignored = ~ones
            has_soft = jnp.bool_(False)
        # fit can never succeed: request exceeds allocatable (Unresolvable)
        unresolvable = jnp.any(pod.req[None] > ct.allocatable, axis=-1)
        unres_count = jnp.sum(unresolvable & valid).astype(jnp.int32)
        return (static_ok, static_rejects, taint_raw, aff_raw, img,
                m_spread, m_ipa, ipa_raw, spread_raw, spread_ignored,
                has_soft, unres_count)

    (static_ok, static_rejects, taint_raw, aff_raw, img, m_spread, m_ipa,
     ipa_raw, spread_raw, spread_ignored, has_soft, unres) = jax.vmap(
        per_pod)(pods)

    # ---- phase 2: sequential commit scan (tiny per-step work) ----
    alloc2 = SC.alloc_cpu_mem(ct)                               # [N, 2]
    B = pblobs.f32.shape[0]
    # pairwise hostPort conflicts: pod j can't join a node where an earlier
    # conflicting batch pod was committed (as-if-serial NodePorts)
    port_conf = FL.pod_pair_port_conflict(pods, wk["wildcard_ip"])  # [B, B]

    def body(carry, xs):
        free, nzr, committed_rows = carry
        (b, ok_s, t_raw, a_raw, im, sp_ok, ipa_ok, ipa_r, sp_r, sp_ign,
         soft, req, nzreq) = xs
        fit_ok = jnp.all(req[None] <= free, axis=-1)            # [N]
        # nodes holding an earlier batch commit that clashes on hostPort
        clash = port_conf[b] & (committed_rows >= 0)            # [B]
        forbidden = jnp.zeros_like(fit_ok).at[
            jnp.maximum(committed_rows, 0)].max(clash)          # [N]
        ports_ok = ~forbidden
        feasible = ok_s & ports_ok & fit_ok & sp_ok & ipa_ok
        frac = SC.utilization_fractions(alloc2, nzr, nzreq)
        least = SC.least_allocated_from_fractions(frac)
        bal = SC.balanced_allocation_from_fractions(frac)
        taint = SC.normalize_inverse(t_raw, feasible)
        aff = SC.normalize_max(a_raw, feasible)
        ipa = SC.normalize_maxmin(ipa_r, feasible)
        spread = jnp.where(soft, SC.normalize_spread(sp_r, feasible, sp_ign),
                           0.0)
        total = (weights.taint_toleration * taint
                 + weights.node_affinity * aff
                 + weights.resources_fit * least
                 + weights.balanced_allocation * bal
                 + weights.image_locality * im
                 + weights.pod_topology_spread * spread
                 + weights.inter_pod_affinity * ipa)
        row = C.masked_argmax_first(total, feasible)
        # commit the winner (the "assume"): free -= request, nonzero += request
        do = row >= 0
        r = jnp.maximum(row, 0)
        free = free.at[r].add(jnp.where(do, -req, 0.0))
        nzr = nzr.at[r].add(jnp.where(do, nzreq, 0.0))
        committed_rows = committed_rows.at[b].set(row)
        # first-fail order: NodePorts (in-batch), Fit, Spread, InterPod
        ok_ports = ok_s & ports_ok
        ok_fit = ok_ports & fit_ok
        ok_sp = ok_fit & sp_ok
        port_rejects = jnp.sum(ok_s & ~ports_ok).astype(jnp.int32)
        fit_rejects = jnp.sum(ok_ports & ~fit_ok).astype(jnp.int32)
        sp_rejects = jnp.sum(ok_fit & ~sp_ok).astype(jnp.int32)
        ipa_rejects = jnp.sum(ok_sp & ~ipa_ok).astype(jnp.int32)
        win = jnp.where(do, total[r], 0.0)
        return (free, nzr, committed_rows), (
            row, win, jnp.sum(feasible).astype(jnp.int32),
            port_rejects, fit_rejects, sp_rejects, ipa_rejects)

    xs = (jnp.arange(B), static_ok, taint_raw, aff_raw, img, m_spread, m_ipa,
          ipa_raw, spread_raw, spread_ignored, has_soft,
          pods.req, pods.nonzero_req)
    init = (ct.free, ct.nonzero_requested, jnp.full((B,), -1, jnp.int32))
    _, (rows, win_scores, feas, port_rejects, fit_rejects, sp_rejects,
        ipa_rejects) = jax.lax.scan(body, init, xs)

    ports_idx = FILTER_PLUGINS.index("NodePorts")
    static_rejects = static_rejects.at[:, ports_idx].add(port_rejects)
    reject_counts = jnp.concatenate(
        [static_rejects, fit_rejects[:, None], sp_rejects[:, None],
         ipa_rejects[:, None]], axis=1)
    return BatchResult(node_row=rows, score=win_scores, feasible_count=feas,
                       reject_counts=reject_counts, unresolvable_count=unres)


@partial(jax.jit, static_argnames=("caps", "enable_topology", "d_cap"))
def schedule_batch_jit(cblobs, pblobs, wk, weights, caps,
                       enable_topology=True, d_cap=None):
    return schedule_batch(cblobs, pblobs, wk, weights, caps,
                          enable_topology, d_cap)
