from kubernetes_tpu.models.pipeline import (  # noqa: F401
    BatchResult,
    DEFAULT_WEIGHTS,
    ScoreWeights,
    schedule_batch,
    schedule_batch_jit,
)
