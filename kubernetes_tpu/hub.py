"""In-process API hub: the storage/watch/bind surface the scheduler talks to.

The functional stand-in for the reference's apiserver+etcd+client-go stack
(SURVEY.md §5.8): typed object store with resourceVersion bumps, LIST +
WATCH-style event delivery to registered handlers (the informer contract,
client-go tools/cache), the Binding subresource
(pkg/registry/core/pod/rest/subresources.go semantics: set spec.nodeName),
and pod status patches. Real-cluster integration would implement this same
interface over HTTPS list/watch; tests and benchmarks run against this hub
exactly like the reference's integration tests run against an in-process
apiserver (test/integration/util/util.go:86 StartScheduler).
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from kubernetes_tpu.api.objects import (
    Namespace,
    Node,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodCondition,
    PodDisruptionBudget,
    PriorityClass,
    ResourceClaim,
    ResourceSlice,
    StorageClass,
)


@dataclass
class EventHandlers:
    """cache.ResourceEventHandler equivalent."""

    on_add: Optional[Callable] = None
    on_update: Optional[Callable] = None       # (old, new)
    on_delete: Optional[Callable] = None


class Conflict(Exception):
    """resourceVersion conflict (optimistic concurrency)."""


class NotFound(Exception):
    pass


class Unavailable(Exception):
    """Transport-level failure: the hub exists but could not be reached
    (connection refused/reset, timeout, 5xx gateway, partition). The
    scheduler treats this as a degraded-mode signal — park and retry —
    never as a verdict about the object."""


class _Store:
    def __init__(self, kind: str):
        self.kind = kind
        self.objects: dict[str, object] = {}   # uid -> object
        self.handlers: list[EventHandlers] = []


class Hub:
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._rv = itertools.count(1)
        self._nodes = _Store("Node")
        self._pods = _Store("Pod")
        self._priority_classes = _Store("PriorityClass")
        self._namespaces = _Store("Namespace")
        self._pdbs = _Store("PodDisruptionBudget")
        self._pvcs = _Store("PersistentVolumeClaim")
        self._pvs = _Store("PersistentVolume")
        self._storage_classes = _Store("StorageClass")
        self._pvc_by_key: dict[str, str] = {}   # "ns/name" -> uid
        self._pv_by_name: dict[str, str] = {}   # name -> uid
        self._sc_by_name: dict[str, str] = {}
        self._node_by_name: dict[str, str] = {}
        self._claims = _Store("ResourceClaim")
        from kubernetes_tpu.leaderelection import LeaseStore

        self.leases = LeaseStore()
        self._slices = _Store("ResourceSlice")
        self._claim_by_key: dict[str, str] = {}
        self._claim_templates = _Store("ResourceClaimTemplate")
        self._template_by_key: dict[str, str] = {}
        self._device_classes = _Store("DeviceClass")
        self._device_class_by_name: dict[str, str] = {}
        self._csi_capacities = _Store("CSIStorageCapacity")

    # ------------- watch registration -------------

    def watch_nodes(self, h: EventHandlers, replay: bool = True) -> None:
        with self._lock:
            self._nodes.handlers.append(h)
            if replay and h.on_add:
                for o in list(self._nodes.objects.values()):
                    h.on_add(o)

    def watch_pods(self, h: EventHandlers, replay: bool = True) -> None:
        with self._lock:
            self._pods.handlers.append(h)
            if replay and h.on_add:
                for o in list(self._pods.objects.values()):
                    h.on_add(o)

    def unwatch(self, h: EventHandlers) -> None:
        """Deregister a handler from every store (watch-stream teardown —
        the transport layer's connection close)."""
        with self._lock:
            for store in (self._nodes, self._pods, self._namespaces,
                          self._pdbs, self._pvcs, self._pvs, self._claims,
                          self._slices, self._priority_classes,
                          self._storage_classes, self._claim_templates,
                          self._device_classes, self._csi_capacities):
                try:
                    store.handlers.remove(h)
                except ValueError:
                    pass

    @staticmethod
    def _dispatch(store: _Store, kind: str, old, new) -> None:
        """Deliver one event. NEVER called holding the hub lock: handlers
        take their own locks (the scheduler's loop lock), and a watcher
        blocked there must not hold up other API callers — the cycle
        hub-lock -> handler-lock -> (binder) -> hub-lock would deadlock."""
        for h in list(store.handlers):
            if kind == "add" and h.on_add:
                h.on_add(new)
            elif kind == "update" and h.on_update:
                h.on_update(old, new)
            elif kind == "delete" and h.on_delete:
                h.on_delete(old)

    # ------------- generic CRUD -------------

    def _create(self, store: _Store, obj) -> None:
        with self._lock:
            uid = obj.metadata.uid
            if uid in store.objects:
                raise Conflict(f"{store.kind} {uid} already exists")
            obj.metadata.resource_version = next(self._rv)
            store.objects[uid] = obj
        self._dispatch(store, "add", None, obj)

    def _update(self, store: _Store, obj) -> None:
        with self._lock:
            uid = obj.metadata.uid
            old = store.objects.get(uid)
            if old is None:
                raise NotFound(f"{store.kind} {uid}")
            obj.metadata.resource_version = next(self._rv)
            store.objects[uid] = obj
        self._dispatch(store, "update", old, obj)

    def _delete(self, store: _Store, uid: str) -> None:
        with self._lock:
            old = store.objects.pop(uid, None)
            if old is None:
                raise NotFound(f"{store.kind} {uid}")
        self._dispatch(store, "delete", old, None)

    # ------------- nodes -------------

    def create_node(self, node: Node) -> None:
        with self._lock:
            self._node_by_name[node.metadata.name] = node.metadata.uid
        self._create(self._nodes, node)

    def update_node(self, node: Node) -> None:
        self._update(self._nodes, node)

    def delete_node(self, uid: str) -> None:
        with self._lock:
            old = self._nodes.objects.get(uid)
            if old is not None:
                self._node_by_name.pop(old.metadata.name, None)
        self._delete(self._nodes, uid)

    def get_node(self, name: str) -> Optional[Node]:
        with self._lock:
            uid = self._node_by_name.get(name)
            return self._nodes.objects.get(uid) if uid else None

    def list_nodes(self) -> list[Node]:
        with self._lock:
            return list(self._nodes.objects.values())

    # ------------- pods -------------

    def create_pod(self, pod: Pod) -> None:
        self._create(self._pods, pod)

    def update_pod(self, pod: Pod) -> None:
        self._update(self._pods, pod)

    def delete_pod(self, uid: str) -> None:
        self._delete(self._pods, uid)

    def get_pod(self, uid: str) -> Optional[Pod]:
        with self._lock:
            return self._pods.objects.get(uid)

    def list_pods(self) -> list[Pod]:
        with self._lock:
            return list(self._pods.objects.values())

    # ------------- the scheduler's write paths -------------

    def _swap_pod(self, old: Pod, new: Pod) -> None:
        """Commit a prepared pod revision under the lock, dispatch outside."""
        new.metadata.resource_version = next(self._rv)
        self._pods.objects[new.metadata.uid] = new

    def bind(self, pod: Pod, node_name: str) -> None:
        """The Binding subresource: sets spec.nodeName exactly once
        (defaultbinder POST target). Conflict if already bound."""
        with self._lock:
            stored = self._pods.objects.get(pod.metadata.uid)
            if stored is None:
                raise NotFound(f"pod {pod.key()}")
            if stored.spec.node_name:
                raise Conflict(f"pod {pod.key()} already bound to "
                               f"{stored.spec.node_name}")
            new = stored.clone()
            new.spec.node_name = node_name
            self._swap_pod(stored, new)
        self._dispatch(self._pods, "update", stored, new)

    def patch_pod_condition(self, pod: Pod, condition: PodCondition,
                            nominated_node: str | None = None) -> None:
        """util.PatchPodStatus equivalent (schedule_one.go:1092)."""
        with self._lock:
            stored = self._pods.objects.get(pod.metadata.uid)
            if stored is None:
                return
            new = stored.clone()
            new.status.conditions = [
                c for c in new.status.conditions if c.type != condition.type
            ] + [condition]
            if nominated_node is not None:
                new.status.nominated_node_name = nominated_node
            self._swap_pod(stored, new)
        self._dispatch(self._pods, "update", stored, new)

    def set_pod_claim_statuses(self, uid: str,
                               statuses: dict[str, str]) -> None:
        """Record generated-claim names on pod.status.resourceClaimStatuses
        (the resourceclaim controller's status patch)."""
        with self._lock:
            stored = self._pods.objects.get(uid)
            if stored is None:
                return
            new = stored.clone()
            new.status.resource_claim_statuses = dict(statuses)
            self._swap_pod(stored, new)
        self._dispatch(self._pods, "update", stored, new)

    def clear_nominated_node(self, uid: str) -> None:
        """Clear status.nominatedNodeName (preemption.go prepareCandidate
        clears lower nominations via API so they re-evaluate)."""
        with self._lock:
            stored = self._pods.objects.get(uid)
            if stored is None or not stored.status.nominated_node_name:
                return
            new = stored.clone()
            new.status.nominated_node_name = ""
            self._swap_pod(stored, new)
        self._dispatch(self._pods, "update", stored, new)

    # ------------- namespaces -------------

    def watch_namespaces(self, h: EventHandlers, replay: bool = True) -> None:
        with self._lock:
            self._namespaces.handlers.append(h)
            if replay and h.on_add:
                for o in list(self._namespaces.objects.values()):
                    h.on_add(o)

    def create_namespace(self, ns: Namespace) -> None:
        self._create(self._namespaces, ns)

    def update_namespace(self, ns: Namespace) -> None:
        self._update(self._namespaces, ns)

    def delete_namespace(self, uid: str) -> None:
        self._delete(self._namespaces, uid)

    def list_namespaces(self) -> list[Namespace]:
        with self._lock:
            return list(self._namespaces.objects.values())

    # ------------- pod disruption budgets -------------

    def create_pdb(self, pdb: PodDisruptionBudget) -> None:
        self._create(self._pdbs, pdb)

    def update_pdb(self, pdb: PodDisruptionBudget) -> None:
        self._update(self._pdbs, pdb)

    def delete_pdb(self, uid: str) -> None:
        self._delete(self._pdbs, uid)

    def list_pdbs(self) -> list[PodDisruptionBudget]:
        with self._lock:
            return list(self._pdbs.objects.values())

    # ------------- volumes (PVC / PV / StorageClass) -------------

    def watch_pvcs(self, h: EventHandlers, replay: bool = True) -> None:
        with self._lock:
            self._pvcs.handlers.append(h)
            if replay and h.on_add:
                for o in list(self._pvcs.objects.values()):
                    h.on_add(o)

    def watch_pvs(self, h: EventHandlers, replay: bool = True) -> None:
        with self._lock:
            self._pvs.handlers.append(h)
            if replay and h.on_add:
                for o in list(self._pvs.objects.values()):
                    h.on_add(o)

    def create_pvc(self, pvc: PersistentVolumeClaim) -> None:
        with self._lock:
            self._pvc_by_key[pvc.key()] = pvc.metadata.uid
        self._create(self._pvcs, pvc)

    def update_pvc(self, pvc: PersistentVolumeClaim) -> None:
        self._update(self._pvcs, pvc)

    def delete_pvc(self, uid: str) -> None:
        with self._lock:
            old = self._pvcs.objects.get(uid)
            if old is not None:
                self._pvc_by_key.pop(old.key(), None)
        self._delete(self._pvcs, uid)

    def get_pvc(self, namespace: str, name: str
                ) -> Optional[PersistentVolumeClaim]:
        with self._lock:
            uid = self._pvc_by_key.get(f"{namespace}/{name}")
            return self._pvcs.objects.get(uid) if uid else None

    def list_pvcs(self) -> list[PersistentVolumeClaim]:
        with self._lock:
            return list(self._pvcs.objects.values())

    def create_pv(self, pv: PersistentVolume) -> None:
        with self._lock:
            self._pv_by_name[pv.metadata.name] = pv.metadata.uid
        self._create(self._pvs, pv)

    def update_pv(self, pv: PersistentVolume) -> None:
        self._update(self._pvs, pv)

    def delete_pv(self, uid: str) -> None:
        with self._lock:
            old = self._pvs.objects.get(uid)
            if old is not None:
                self._pv_by_name.pop(old.metadata.name, None)
        self._delete(self._pvs, uid)

    def get_pv(self, name: str) -> Optional[PersistentVolume]:
        with self._lock:
            uid = self._pv_by_name.get(name)
            return self._pvs.objects.get(uid) if uid else None

    def list_pvs(self) -> list[PersistentVolume]:
        with self._lock:
            return list(self._pvs.objects.values())

    def create_storage_class(self, sc: StorageClass) -> None:
        with self._lock:
            self._sc_by_name[sc.metadata.name] = sc.metadata.uid
        self._create(self._storage_classes, sc)

    def get_storage_class(self, name: str) -> Optional[StorageClass]:
        with self._lock:
            uid = self._sc_by_name.get(name)
            return self._storage_classes.objects.get(uid) if uid else None

    # ------------- dynamic resource allocation -------------

    def watch_resource_claims(self, h: EventHandlers,
                              replay: bool = True) -> None:
        with self._lock:
            self._claims.handlers.append(h)
            if replay and h.on_add:
                for o in list(self._claims.objects.values()):
                    h.on_add(o)

    def create_resource_claim(self, claim: ResourceClaim) -> None:
        with self._lock:
            self._claim_by_key[claim.key()] = claim.metadata.uid
        self._create(self._claims, claim)

    def update_resource_claim(self, claim: ResourceClaim) -> None:
        self._update(self._claims, claim)

    def delete_resource_claim(self, uid: str) -> None:
        with self._lock:
            old = self._claims.objects.get(uid)
            if old is not None:
                self._claim_by_key.pop(old.key(), None)
        self._delete(self._claims, uid)

    def get_resource_claim(self, namespace: str, name: str
                           ) -> Optional[ResourceClaim]:
        with self._lock:
            uid = self._claim_by_key.get(f"{namespace}/{name}")
            return self._claims.objects.get(uid) if uid else None

    def list_resource_claims(self) -> list[ResourceClaim]:
        with self._lock:
            return list(self._claims.objects.values())

    def watch_resource_slices(self, h: EventHandlers,
                              replay: bool = True) -> None:
        with self._lock:
            self._slices.handlers.append(h)
            if replay and h.on_add:
                for o in list(self._slices.objects.values()):
                    h.on_add(o)

    def create_resource_slice(self, sl: ResourceSlice) -> None:
        self._create(self._slices, sl)

    def delete_resource_slice(self, uid: str) -> None:
        self._delete(self._slices, uid)

    def list_resource_slices(self) -> list[ResourceSlice]:
        with self._lock:
            return list(self._slices.objects.values())

    def watch_resource_claim_templates(self, h: EventHandlers,
                                       replay: bool = True) -> None:
        with self._lock:
            self._claim_templates.handlers.append(h)
            if replay and h.on_add:
                for o in list(self._claim_templates.objects.values()):
                    h.on_add(o)

    def create_resource_claim_template(self, t) -> None:
        with self._lock:
            self._template_by_key[t.key()] = t.metadata.uid
        self._create(self._claim_templates, t)

    def get_resource_claim_template(self, namespace: str, name: str):
        with self._lock:
            uid = self._template_by_key.get(f"{namespace}/{name}")
            return self._claim_templates.objects.get(uid) if uid else None

    def watch_csi_capacities(self, h: EventHandlers,
                             replay: bool = True) -> None:
        with self._lock:
            self._csi_capacities.handlers.append(h)
            if replay and h.on_add:
                for o in list(self._csi_capacities.objects.values()):
                    h.on_add(o)

    def create_csi_capacity(self, c) -> None:
        self._create(self._csi_capacities, c)

    def update_csi_capacity(self, c) -> None:
        self._update(self._csi_capacities, c)

    def list_csi_capacities(self) -> list:
        with self._lock:
            return list(self._csi_capacities.objects.values())

    def create_device_class(self, dc) -> None:
        with self._lock:
            self._device_class_by_name[dc.metadata.name] = dc.metadata.uid
        self._create(self._device_classes, dc)

    def get_device_class(self, name: str):
        with self._lock:
            uid = self._device_class_by_name.get(name)
            return self._device_classes.objects.get(uid) if uid else None

    def list_device_classes(self) -> list:
        with self._lock:
            return list(self._device_classes.objects.values())

    # ------------- priority classes -------------

    def create_priority_class(self, pc: PriorityClass) -> None:
        self._create(self._priority_classes, pc)

    def list_priority_classes(self) -> list[PriorityClass]:
        with self._lock:
            return list(self._priority_classes.objects.values())
