"""In-process API hub: the storage/watch/bind surface the scheduler talks to.

The functional stand-in for the reference's apiserver+etcd+client-go stack
(SURVEY.md §5.8): typed object store with resourceVersion bumps, LIST +
WATCH-style event delivery to registered handlers (the informer contract,
client-go tools/cache), the Binding subresource
(pkg/registry/core/pod/rest/subresources.go semantics: set spec.nodeName),
and pod status patches. Real-cluster integration would implement this same
interface over HTTPS list/watch; tests and benchmarks run against this hub
exactly like the reference's integration tests run against an in-process
apiserver (test/integration/util/util.go:86 StartScheduler).

L0 storage (kubernetes_tpu.storage): every mutation commits a
revision-stamped event to an etcd-analog journal — a bounded per-kind
ring with a compaction watermark, optionally WAL-backed so a restarted
hub replays its state from disk. ``watch_*(h, since_rv=N)`` resumes a
watch by replaying journal events after N instead of re-listing the
world; a resume point older than the watermark raises
:class:`storage.RvTooOld` (the apiserver's 410 "too old resource
version"), telling the caller to relist. Delete events consume a
revision of their own (etcd stamps deletions), carried by the event —
the tombstoned object keeps the rv it died with.
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from kubernetes_tpu.api.objects import (
    Event,
    Namespace,
    Node,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodCondition,
    PodDisruptionBudget,
    PodGroup,
    PriorityClass,
    ResourceClaim,
    ResourceSlice,
    StorageClass,
)
from kubernetes_tpu.storage import Journal, JournalEvent, RvTooOld  # noqa: F401  (re-exported: transport + tests import RvTooOld from here)
from kubernetes_tpu.telemetry.trace import new_context


@dataclass
class EventHandlers:
    """cache.ResourceEventHandler equivalent. ``on_event``, when set,
    receives the full :class:`JournalEvent` (rv included) INSTEAD of the
    typed callbacks — the transport layer uses it to put revisions on
    the wire, and the watch relay tree (fabric.relay) uses it to keep
    its ring journal; informer-style consumers keep the typed trio.

    ``on_sync(rv, relisted)`` fires on RemoteHub streams at each sync
    marker: ``relisted`` is True when the connection replayed a full
    LIST (first connect or a 410 fallback) rather than a journal
    resume — the relay resets its ring there, because its event
    continuity broke. The in-process hub never reconnects, so it never
    calls it."""

    on_add: Optional[Callable] = None
    on_update: Optional[Callable] = None       # (old, new)
    on_delete: Optional[Callable] = None
    on_event: Optional[Callable] = None        # (JournalEvent)
    on_sync: Optional[Callable] = None         # (rv, relisted: bool)


def _deliver(h: EventHandlers, ev: JournalEvent) -> None:
    if h.on_event is not None:
        h.on_event(ev)
        return
    if ev.type == "add":
        if h.on_add:
            h.on_add(ev.new)
    elif ev.type == "update":
        if h.on_update:
            h.on_update(ev.old, ev.new)
    elif ev.type == "delete":
        if h.on_delete:
            h.on_delete(ev.old)


class Conflict(Exception):
    """resourceVersion conflict (optimistic concurrency)."""


class NotFound(Exception):
    pass


class Unavailable(Exception):
    """Transport-level failure: the hub exists but could not be reached
    (connection refused/reset, timeout, 5xx gateway, partition). The
    scheduler treats this as a degraded-mode signal — park and retry —
    never as a verdict about the object."""


class Fenced(Exception):
    """Write carried a fencing epoch older than the newest leadership
    acquisition: the caller was deposed while the write was in flight.
    The write did NOT land; the new leader owns the object now."""


class NotLeader(Exception):
    """A replicated-state verb reached a replica that is not the
    leader (or a leader that lost its quorum lease). Carries the
    redirect hint — ``leader_url`` (None while an election is running)
    and the replica's current ``term`` — encoded into the message so
    the hint survives the /call wire's {error, message} envelope; the
    single-arg constructor re-parses it on the client side."""

    _HINT = re.compile(r"\[leader=(?P<url>[^ \]]*) term=(?P<term>\d+)\]")

    def __init__(self, message: str = "", leader_url=None, term=None):
        if leader_url is not None or term is not None:
            message = (f"{message} [leader={leader_url or ''} "
                       f"term={term or 0}]")
        else:
            m = self._HINT.search(message)
            if m is not None:
                leader_url = m.group("url") or None
                term = int(m.group("term"))
        super().__init__(message)
        self.leader_url = leader_url or None
        self.term = term or 0


class StaleRing(Exception):
    """A pod write landed on a shard that no longer (or does not yet)
    own the namespace's ring slot — the caller routed on a stale ring
    epoch, usually mid-rebalance. The write did NOT land; the caller
    re-reads the ring and retries against the current owner, so a
    migrate window can never silently commit onto (and then drop with)
    a deposed segment owner."""


class TooManyRequests(Exception):
    """Flow control rejected the request: the caller's priority level
    is at its concurrency share and its fair queue is full (or the
    queue-wait deadline passed). HTTP 429 + Retry-After on the wire.
    The request did NOT run. Carries ``retry_after`` (seconds, the
    server's honest backoff hint) encoded into the message so it
    survives the /call wire's {error, message} envelope, exactly like
    NotLeader's redirect hint; the single-arg constructor re-parses it
    client-side."""

    _HINT = re.compile(r"\[retry-after=(?P<s>[0-9.]+)s\]")

    def __init__(self, message: str = "", retry_after=None):
        if retry_after is not None:
            message = f"{message} [retry-after={retry_after:.3f}s]"
        else:
            m = self._HINT.search(message)
            if m is not None:
                retry_after = float(m.group("s"))
        super().__init__(message)
        self.retry_after = retry_after or 0.0


def _by_name(obj) -> str:
    return obj.metadata.name


def _by_key(obj) -> str:
    return obj.key()


class _Store:
    def __init__(self, kind: str, watch_kind: str,
                 index_key: Optional[Callable] = None):
        self.kind = kind
        self.watch_kind = watch_kind
        self.objects: dict[str, object] = {}   # uid -> object
        self.handlers: list[EventHandlers] = []
        self.index_key = index_key             # secondary index key fn
        self.index: dict[str, str] = {}        # key -> uid

    def index_add(self, obj) -> None:
        if self.index_key is not None:
            self.index[self.index_key(obj)] = obj.metadata.uid

    def index_remove(self, obj) -> None:
        if self.index_key is not None:
            self.index.pop(self.index_key(obj), None)

    def by_index(self, key: str):
        uid = self.index.get(key)
        return self.objects.get(uid) if uid else None


class Hub:
    # the commit trace stamp's origin component; fabric shards override
    # with their shard name (telemetry.trace.TraceContext.origin)
    origin = "hub"

    def __init__(self, journal_capacity: int = 16384,
                 wal_path: str | None = None,
                 wal_codec: str = "json") -> None:
        self._lock = threading.RLock()
        self._last_rv = 0
        self._nodes = _Store("Node", "nodes", _by_name)
        self._pods = _Store("Pod", "pods")
        self._priority_classes = _Store("PriorityClass", "priority_classes")
        self._namespaces = _Store("Namespace", "namespaces")
        self._pdbs = _Store("PodDisruptionBudget", "pdbs")
        self._pvcs = _Store("PersistentVolumeClaim", "pvcs", _by_key)
        self._pvs = _Store("PersistentVolume", "pvs", _by_name)
        self._storage_classes = _Store("StorageClass", "storage_classes",
                                       _by_name)
        self._claims = _Store("ResourceClaim", "resource_claims", _by_key)
        self._slices = _Store("ResourceSlice", "resource_slices")
        self._claim_templates = _Store("ResourceClaimTemplate",
                                       "resource_claim_templates", _by_key)
        self._device_classes = _Store("DeviceClass", "device_classes",
                                      _by_name)
        self._csi_capacities = _Store("CSIStorageCapacity",
                                      "csi_capacities")
        # gang scheduling: PodGroup declares min_member + tenant queue
        self._pod_groups = _Store("PodGroup", "pod_groups", _by_key)
        # core/v1 Event analog, deduped by (ref, reason) with a count
        # bump — how controllers surface object-level failures (e.g. a
        # DeviceClass whose CEL selector does not compile)
        self._events = _Store("Event", "events",
                              lambda e: f"{e.ref_kind}/{e.ref_key}"
                                        f":{e.reason}")
        self._stores: dict[str, _Store] = {
            s.watch_kind: s for s in (
                self._nodes, self._pods, self._priority_classes,
                self._namespaces, self._pdbs, self._pvcs, self._pvs,
                self._storage_classes, self._claims, self._slices,
                self._claim_templates, self._device_classes,
                self._csi_capacities, self._pod_groups, self._events)}
        # ring-slot write fencing (fabric migrate windows): slot ->
        # "frozen" (export in flight: the copy left, the ring hasn't
        # flipped) or "gone" (the ring assigns the slot elsewhere). A
        # pod write into a marked slot answers StaleRing so the caller
        # re-resolves the ring and retries the true owner — a second
        # router routing on a stale ring epoch can never commit onto a
        # segment that is about to be (or was) dropped. Checked under
        # the hub lock, atomically with the commit.
        self._slot_marks: dict[int, str] = {}
        self._slot_mark_ts: dict[int, float] = {}
        self._mark_ring_size = 0
        self.journal = Journal(capacity=journal_capacity,
                               wal_path=wal_path, wal_codec=wal_codec)
        if wal_path:
            self._replay_wal()
        from kubernetes_tpu.leaderelection import LeaseStore, SliceBoard

        # leases are deliberately NOT journaled: leadership is ephemeral
        # by contract (a restarted hub must force re-election, not
        # resurrect a stale holder)
        self.leases = LeaseStore()
        # scheduler-replica registry + pending-pod slice ring (same
        # ephemerality argument: a restarted hub forces a re-register +
        # rebalance, not a resurrected stale slice map)
        self.slices = SliceBoard()

    # ------------- revision space / journal -------------

    def _next_rv(self) -> int:
        self._last_rv += 1
        return self._last_rv

    @property
    def current_rv(self) -> int:
        with self._lock:
            return self._last_rv

    def _newest_rv(self) -> int:
        """The newest revision that exists anywhere in this hub's
        revision space. For a standalone hub that is its own counter; a
        fabric shard (fabric.sharded._ShardHub) overrides it to the
        SHARED allocator's value, because resume points and sync
        markers travel between shards through their clients."""
        return self._last_rv

    def _commit(self, store: _Store, etype: str, old, new) -> JournalEvent:
        """Stamp one revision, journal the event (WAL included). Caller
        holds the lock and has already mutated ``store.objects`` — the
        journal append must land before any later revision is stamped,
        so ring suffixes stay complete per kind. Every commit also gets
        a telemetry trace stamp (origin component + commit timestamp +
        hop count 0) that rides the event across the wire and relay
        tree (telemetry.trace)."""
        rv = self._next_rv()
        if new is not None:
            new.metadata.resource_version = rv
        ev = JournalEvent(rv=rv, kind=store.watch_kind, type=etype,
                          old=old, new=new,
                          trace=new_context(self.origin))
        self.journal.append(ev)
        return ev

    def _replay_wal(self) -> None:
        """Rebuild stores + journal rings from the WAL (hub restart).
        Events re-apply in commit order with their original revisions;
        nothing dispatches — there are no watchers yet. When the
        replayed history dwarfs the live object count, the WAL is
        compacted on the spot (snapshot rewrite) so it cannot grow
        without bound across restart cycles."""
        max_rv = 0
        n_events = 0
        for ev in self.journal.replay_wal():
            if isinstance(ev, dict):
                # control record: a fabric ring-rebalance segment
                # transfer — applied to the store, never journaled or
                # dispatched (no watcher ever saw the move as events)
                self._apply_xfer(ev)
                continue
            store = self._stores.get(ev.kind)
            if store is not None:
                if ev.type == "delete":
                    old = store.objects.pop(ev.old.metadata.uid, None)
                    if old is not None:
                        store.index_remove(old)
                else:
                    store.objects[ev.new.metadata.uid] = ev.new
                    store.index_add(ev.new)
            self.journal.append(ev, persist=False)
            max_rv = max(max_rv, ev.rv)
            n_events += 1
        # a torn tail (write cut mid-append) must be truncated BEFORE
        # this hub's first append merges into it
        self.journal.repair_wal()
        # a WAL rewrite may have compacted past the last surviving event
        self._last_rv = max(max_rv, self.journal.compact_floor)
        live = sum(len(s.objects) for s in self._stores.values())
        if self.journal.wal_upgrade_pending \
                or n_events > max(64, 2 * live):
            # boot compaction doubles as the in-place WAL codec upgrade:
            # a JSON-era file replayed under wal_codec="bin1" (or vice
            # versa) is rewritten in the configured format right here
            self._compact_wal()

    def _apply_xfer(self, rec: dict) -> None:
        """Replay one segment-transfer control record (fabric ring
        rebalance): 'attach' re-inserts transferred pods with their
        original revisions, 'detach' removes exported ones."""
        if rec.get("xfer") == "attach":
            for pod in rec.get("pods", []):
                self._pods.objects[pod.metadata.uid] = pod
                self._pods.index_add(pod)
        elif rec.get("xfer") == "detach":
            for uid in rec.get("uids", []):
                old = self._pods.objects.pop(uid, None)
                if old is not None:
                    self._pods.index_remove(old)

    def _compact_wal(self) -> None:
        """Snapshot-rewrite the WAL: one add-event per live object,
        behind a compact record at the current revision. The in-memory
        rings keep this boot's full replayed history — the floor only
        governs what the NEXT restart (and resumes across it) can see."""
        events = [JournalEvent(rv=o.metadata.resource_version,
                               kind=s.watch_kind, type="add", new=o)
                  for s in self._stores.values()
                  for o in s.objects.values()]
        events.sort(key=lambda e: e.rv)
        self.journal.rewrite_wal(self._last_rv, events)

    def list_changes(self, since_rv: int,
                     kinds: tuple = ("pods", "nodes")) -> dict:
        """Incremental LIST: every journal event of ``kinds`` after
        ``since_rv``, rv-sorted, plus the revision the answer is
        consistent at — the O(changes) read the drift sentinel diffs
        against instead of re-LISTing the cluster. An unserviceable
        resume point (compacted, or from another revision space)
        answers ``{"too_old": True}`` with the watermark INSTEAD of
        raising: the verdict must survive the /call wire, where mapped
        exceptions reconstruct poorly, and the caller's answer (fall
        back to a full LIST) is the same either way."""
        with self._lock:
            rv = self._newest_rv()
            if since_rv > rv:
                return {"too_old": True, "compacted_rv": rv, "rv": rv}
            try:
                events = self.journal.changes_after(kinds, since_rv)
            except RvTooOld as e:
                return {"too_old": True,
                        "compacted_rv": e.compacted_rv, "rv": rv}
            return {"too_old": False, "rv": rv,
                    "changes": [{"rv": ev.rv, "kind": ev.kind,
                                 "type": ev.type,
                                 "obj": ev.new if ev.new is not None
                                 else ev.old}
                                for ev in events]}

    def shard_map(self) -> dict:
        """kind -> owning shard, the /debug/fabric topology surface. A
        single hub is one shard ("hub") for every kind; the fabric's
        ShardedHub overrides with its real layout."""
        with self._lock:
            return {kind: "hub" for kind in self._stores}

    def get_journal_stats(self) -> dict:
        """Journal depth/watermark per kind (the hub_journal_* gauges),
        plus per-kind watcher counts — the fabric smoke's per-shard
        socket accounting reads these off a shard process's /metrics."""
        with self._lock:
            return {"rv": self._last_rv,
                    "capacity": self.journal.capacity,
                    "wal": bool(self.journal.wal_path),
                    "wal_codec": self.journal.wal_codec,
                    "kinds": self.journal.stats(),
                    "watchers": {k: len(s.handlers)
                                 for k, s in self._stores.items()
                                 if s.handlers}}

    # ------------- segment transfer (fabric ring rebalance) -------------
    #
    # Moving a crc32-ring segment between shard PROCESSES must be
    # invisible in the event stream: no watcher may see a delete+add
    # storm for pods that merely changed owners. These verbs therefore
    # bypass _commit entirely — the store mutates, a WAL control record
    # persists the transfer for restart replay, and the journal RINGS
    # keep the pods' real history so resumes spanning the move still
    # serve (the router merges the old shard's pre-move suffix with the
    # new shard's post-move one; the shared rv space makes both sides of
    # the cut comparable).

    @staticmethod
    def _segment_slot(namespace: str, ring_size: int) -> int:
        # THE ring mapping (fabric.cluster.ring_slot), deferred import:
        # routers and shard processes must agree byte-for-byte on
        # namespace -> slot, so there is exactly one implementation
        from kubernetes_tpu.fabric.cluster import ring_slot

        return ring_slot(namespace, ring_size)

    # an abandoned freeze (the rebalancer died with the CAS outcome
    # unknown) is healed by the registration heartbeat: set_ring_view
    # clears frozen marks older than this once the authoritative ring
    # re-confirms ownership — a live migrate takes milliseconds
    FROZEN_TTL_S = 30.0

    def _mark_slots(self, slots, ring_size: int, mark: str) -> None:
        """Caller holds the lock."""
        self._mark_ring_size = ring_size
        now = time.monotonic()
        for s in slots:
            self._slot_marks[int(s)] = mark
            self._slot_mark_ts[int(s)] = now

    def _clear_slots(self, slots) -> None:
        for s in slots:
            self._slot_marks.pop(int(s), None)
            self._slot_mark_ts.pop(int(s), None)

    def export_segment(self, slots: list, ring_size: int) -> list:
        """Copy (NOT remove) every pod whose namespace hashes into
        ``slots``: the rebalance copies to the target shard first so a
        concurrent LIST never finds the segment in neither shard —
        duplicates during the overlap window are deduped by every
        client's uid+rv discipline. The slots FREEZE under the same
        lock acquisition as the copy: any write that passed the guard
        first is in the copy; any write after answers StaleRing until
        the ring flips (retry lands on the new owner) or the export
        aborts (retry lands back here)."""
        want = set(slots)
        with self._lock:
            self._mark_slots(want, ring_size, "frozen")
            return [p for p in self._pods.objects.values()
                    if self._segment_slot(p.metadata.namespace,
                                          ring_size) in want]

    def abort_export(self, slots: list, ring_size: int) -> int:
        """Roll back an export whose rebalance lost the ring CAS:
        unfreeze the slots so parked writers land here again."""
        with self._lock:
            thawed = sum(1 for s in slots
                         if self._slot_marks.get(int(s)) == "frozen")
            self._clear_slots([s for s in slots
                               if self._slot_marks.get(int(s))
                               == "frozen"])
            return thawed

    def import_segment(self, pods: list, slots: list | None = None,
                       ring_size: int | None = None) -> int:
        """Adopt transferred pods with their ORIGINAL uids and
        revisions — no events, no new rvs; a WAL attach record makes
        the adoption survive a restart. ``slots`` (when given) are
        un-marked here: the target owns them the moment the ring flips,
        and a post-flip write must not bounce off a stale 'gone'."""
        with self._lock:
            if slots is not None and ring_size is not None:
                self._mark_ring_size = ring_size
                self._clear_slots(slots)
            fresh = []
            for pod in pods:
                if pod.metadata.uid not in self._pods.objects:
                    fresh.append(pod)
                self._pods.objects[pod.metadata.uid] = pod
                self._pods.index_add(pod)
            if fresh:
                self.journal.wal_only({"xfer": "attach", "pods": fresh})
            return len(fresh)

    def drop_segment(self, slots: list, ring_size: int) -> int:
        """Release an exported segment after the ring flipped: remove
        the pods silently (WAL detach record; journal rings untouched so
        pre-move resumes still serve). The slots stay fenced ('gone'):
        a straggler routing on the pre-flip ring is redirected, never
        committed into the dropped segment."""
        want = set(slots)
        with self._lock:
            doomed = [p for p in self._pods.objects.values()
                      if self._segment_slot(p.metadata.namespace,
                                            ring_size) in want]
            for p in doomed:
                self._pods.objects.pop(p.metadata.uid, None)
                self._pods.index_remove(p)
            if doomed:
                self.journal.wal_only(
                    {"xfer": "detach",
                     "uids": [p.metadata.uid for p in doomed]})
            self._mark_slots(want, ring_size, "gone")
            return len(doomed)

    def set_ring_view(self, owned_slots: list, ring_size: int) -> None:
        """Refresh this shard's slot fencing from the authoritative
        ring (registration response / heartbeat): non-owned slots mark
        'gone', owned slots clear 'gone'. A 'frozen' mark survives
        unless stale past FROZEN_TTL_S — the heartbeat must not thaw a
        live export window, but must heal one abandoned by a crashed
        rebalancer."""
        owned = set(int(s) for s in owned_slots)
        with self._lock:
            self._mark_ring_size = ring_size
            now = time.monotonic()
            for s in range(ring_size):
                mark = self._slot_marks.get(s)
                if s in owned:
                    if mark == "gone" or (
                            mark == "frozen"
                            and now - self._slot_mark_ts.get(s, now)
                            > self.FROZEN_TTL_S):
                        self._clear_slots([s])
                elif mark != "frozen":
                    self._slot_marks[s] = "gone"
                    self._slot_mark_ts[s] = now

    def reconcile_ring(self, owned_slots: list, ring_size: int) -> int:
        """Startup janitor for a shard process: drop any pod whose slot
        the current ring assigns elsewhere (and fence those slots).
        Heals the killed-mid-rebalance case — a shard that died between
        the copy and the drop restarts, replays its WAL (resurrecting
        its stale copy), then reconciles against the authoritative
        ring."""
        owned = set(owned_slots)
        stray = [s for s in range(ring_size) if s not in owned]
        dropped = self.drop_segment(stray, ring_size) if stray else 0
        self.set_ring_view(owned_slots, ring_size)
        return dropped

    def close(self) -> None:
        """Release the WAL file handle (no-op for memory-only hubs)."""
        self.journal.close()

    # ------------- watch registration -------------

    def _watch_store(self, store: _Store, h: EventHandlers,
                     replay: bool = True,
                     since_rv: int | None = None) -> int:
        """Register ``h`` and replay under the lock (a consistent LIST /
        journal suffix: replayed deliveries land before any live event).
        ``since_rv`` switches replay to watch-resume — journal events
        after since_rv instead of synthetic adds of the world — raising
        RvTooOld (BEFORE registering) when the gap was compacted.
        Returns the current global revision (the wire's sync marker)."""
        with self._lock:
            if since_rv is not None:
                if since_rv > self._newest_rv():
                    # a resume point from a FUTURE revision means the
                    # client watched a different revision space (a hub
                    # reborn without its WAL): "no events" here would be
                    # a lie that pins phantom state in the client forever
                    raise RvTooOld(store.watch_kind, since_rv,
                                   self._newest_rv())
                events = self.journal.events_after(store.watch_kind,
                                                   since_rv)
                store.handlers.append(h)
                for ev in events:
                    _deliver(h, ev)
            else:
                store.handlers.append(h)
                if replay:
                    for o in list(store.objects.values()):
                        _deliver(h, JournalEvent(
                            rv=o.metadata.resource_version,
                            kind=store.watch_kind, type="add", new=o))
            return self._newest_rv()

    def watch_nodes(self, h: EventHandlers, replay: bool = True,
                    since_rv: int | None = None) -> int:
        return self._watch_store(self._nodes, h, replay, since_rv)

    def watch_pods(self, h: EventHandlers, replay: bool = True,
                   since_rv: int | None = None) -> int:
        return self._watch_store(self._pods, h, replay, since_rv)

    def watch_namespaces(self, h: EventHandlers, replay: bool = True,
                         since_rv: int | None = None) -> int:
        return self._watch_store(self._namespaces, h, replay, since_rv)

    def watch_pvcs(self, h: EventHandlers, replay: bool = True,
                   since_rv: int | None = None) -> int:
        return self._watch_store(self._pvcs, h, replay, since_rv)

    def watch_pvs(self, h: EventHandlers, replay: bool = True,
                  since_rv: int | None = None) -> int:
        return self._watch_store(self._pvs, h, replay, since_rv)

    def watch_resource_claims(self, h: EventHandlers, replay: bool = True,
                              since_rv: int | None = None) -> int:
        return self._watch_store(self._claims, h, replay, since_rv)

    def watch_resource_slices(self, h: EventHandlers, replay: bool = True,
                              since_rv: int | None = None) -> int:
        return self._watch_store(self._slices, h, replay, since_rv)

    def watch_resource_claim_templates(self, h: EventHandlers,
                                       replay: bool = True,
                                       since_rv: int | None = None) -> int:
        return self._watch_store(self._claim_templates, h, replay,
                                 since_rv)

    def watch_csi_capacities(self, h: EventHandlers, replay: bool = True,
                             since_rv: int | None = None) -> int:
        return self._watch_store(self._csi_capacities, h, replay,
                                 since_rv)

    def watch_pod_groups(self, h: EventHandlers, replay: bool = True,
                         since_rv: int | None = None) -> int:
        return self._watch_store(self._pod_groups, h, replay, since_rv)

    def unwatch(self, h: EventHandlers) -> None:
        """Deregister a handler from every store (watch-stream teardown —
        the transport layer's connection close)."""
        with self._lock:
            for store in self._stores.values():
                try:
                    store.handlers.remove(h)
                except ValueError:
                    pass

    @staticmethod
    def _dispatch(store: _Store, ev: JournalEvent) -> None:
        """Deliver one event. NEVER called holding the hub lock: handlers
        take their own locks (the scheduler's loop lock), and a watcher
        blocked there must not hold up other API callers — the cycle
        hub-lock -> handler-lock -> (binder) -> hub-lock would deadlock."""
        for h in list(store.handlers):
            _deliver(h, ev)

    # ------------- generic CRUD -------------

    def _create(self, store: _Store, obj) -> None:
        with self._lock:
            if store.watch_kind == "pods":
                self._guard_pod_write(obj.metadata.namespace)
            uid = obj.metadata.uid
            if uid in store.objects:
                raise Conflict(f"{store.kind} {uid} already exists")
            store.objects[uid] = obj
            store.index_add(obj)
            ev = self._commit(store, "add", None, obj)
        self._dispatch(store, ev)

    def _update(self, store: _Store, obj) -> None:
        with self._lock:
            if store.watch_kind == "pods":
                self._guard_pod_write(obj.metadata.namespace)
            uid = obj.metadata.uid
            old = store.objects.get(uid)
            if old is None:
                raise NotFound(f"{store.kind} {uid}")
            store.objects[uid] = obj
            store.index_add(obj)
            ev = self._commit(store, "update", old, obj)
        self._dispatch(store, ev)

    def _delete(self, store: _Store, uid: str) -> None:
        with self._lock:
            ev = self._delete_locked(store, uid)
        self._dispatch(store, ev)

    def _delete_locked(self, store: _Store, uid: str) -> JournalEvent:
        old = store.objects.pop(uid, None)
        if old is None:
            raise NotFound(f"{store.kind} {uid}")
        store.index_remove(old)
        return self._commit(store, "delete", old, None)

    # ------------- nodes -------------

    def create_node(self, node: Node) -> None:
        self._create(self._nodes, node)

    def update_node(self, node: Node) -> None:
        self._update(self._nodes, node)

    def delete_node(self, uid: str) -> None:
        self._delete(self._nodes, uid)

    def get_node(self, name: str) -> Optional[Node]:
        with self._lock:
            return self._nodes.by_index(name)

    def list_nodes(self) -> list[Node]:
        with self._lock:
            return list(self._nodes.objects.values())

    # ------------- pods -------------

    def create_pod(self, pod: Pod) -> None:
        self._create(self._pods, pod)

    def update_pod(self, pod: Pod) -> None:
        self._update(self._pods, pod)

    def delete_pod(self, uid: str, epoch: int | None = None,
                   lease_name: str = "kube-scheduler") -> None:
        """Pod deletion, optionally fenced: preemption evictions carry the
        evicting scheduler's epoch so a deposed leader's QUEUED evictions
        cannot land after failover (the eviction analog of bind fencing —
        the new leader may have re-planned around those victims). The
        fence check and the delete share ONE lock acquisition, like bind
        — a gap between them would let a deposition land in the window."""
        with self._lock:
            self._check_fence("delete_pod", epoch, lease_name)
            stored = self._pods.objects.get(uid)
            if stored is not None:
                # a delete landing on a frozen/deposed segment copy
                # would be undone when the true owner's copy survives
                self._guard_pod_write(stored.metadata.namespace)
            ev = self._delete_locked(self._pods, uid)
        self._dispatch(self._pods, ev)

    def delete_pods(self, uids: list[str], epoch: int | None = None,
                    lease_name: str = "kube-scheduler") -> list[str]:
        """Batched eviction wave (ISSUE 15): fence-checked ONCE, every
        delete committed under one lock acquisition, events dispatched in
        commit order afterwards — the multi-delete analog of delete_pod
        for preemption flushes that used to dribble one RPC per victim.
        Already-gone uids are skipped (evictions tolerate them — and that
        makes a retried wave idempotent); returns the uids actually
        deleted, so the caller can tell which candidates produced a
        deletion event."""
        evs = []
        done: list[str] = []
        try:
            with self._lock:
                self._check_fence("delete_pod", epoch, lease_name)
                for uid in uids:
                    stored = self._pods.objects.get(uid)
                    if stored is None:
                        continue
                    self._guard_pod_write(stored.metadata.namespace)
                    evs.append(self._delete_locked(self._pods, uid))
                    done.append(uid)
        finally:
            # a StaleRing raised mid-wave must not strand already-
            # committed deletes undispatched
            for ev in evs:
                self._dispatch(self._pods, ev)
        return done

    def get_pod(self, uid: str) -> Optional[Pod]:
        with self._lock:
            return self._pods.objects.get(uid)

    def list_pods(self) -> list[Pod]:
        with self._lock:
            return list(self._pods.objects.values())

    # ------------- the scheduler's write paths -------------

    def _swap_pod(self, old: Pod, new: Pod) -> JournalEvent:
        """Commit a prepared pod revision under the lock, dispatch outside."""
        self._pods.objects[new.metadata.uid] = new
        return self._commit(self._pods, "update", old, new)

    def _guard_pod_write(self, namespace: str) -> None:
        """Reject (StaleRing) a pod write whose ring slot this hub has
        frozen (segment export in flight) or handed away (the ring
        assigns it elsewhere). Caller holds the lock — the verdict is
        atomic with the commit, so a write racing an export either
        commits BEFORE the copy (and is included in it) or is sent back
        to re-resolve; it can never land in the copied-but-not-dropped
        window and be silently discarded with the segment."""
        if not self._slot_marks:
            return
        slot = self._segment_slot(namespace, self._mark_ring_size)
        mark = self._slot_marks.get(slot)
        if mark is not None:
            raise StaleRing(
                f"pod write for namespace {namespace!r}: ring slot "
                f"{slot} is {mark} on this shard (mid-migrate or stale "
                f"ring); re-resolve the ring and retry the owner")

    def _check_fence(self, verb: str, epoch: int | None,
                     lease_name: str) -> None:
        """Reject a fenced write whose epoch predates the newest
        leadership acquisition (the etcd/Chubby sequencer check). A None
        epoch is an unfenced caller (no elector — single-scheduler
        deployments, tests) and passes."""
        if epoch is None:
            return
        cur = self.leases.epoch_of(lease_name)
        if epoch < cur:
            raise Fenced(f"{verb} from deposed epoch {epoch} "
                         f"(current {cur}, lease {lease_name!r})")

    def bind(self, pod: Pod, node_name: str, epoch: int | None = None,
             lease_name: str = "kube-scheduler") -> None:
        """The Binding subresource: sets spec.nodeName exactly once
        (defaultbinder POST target). Conflict if already bound; Fenced
        if ``epoch`` predates the newest leadership acquisition (an old
        leader's async binder pool must never double-place a pod after
        failover)."""
        with self._lock:
            self._check_fence("bind", epoch, lease_name)
            self._guard_pod_write(pod.metadata.namespace)
            stored = self._pods.objects.get(pod.metadata.uid)
            if stored is None:
                raise NotFound(f"pod {pod.key()}")
            if stored.spec.node_name:
                raise Conflict(f"pod {pod.key()} already bound to "
                               f"{stored.spec.node_name}")
            new = stored.clone()
            new.spec.node_name = node_name
            ev = self._swap_pod(stored, new)
        self._dispatch(self._pods, ev)

    # ------------- scheduler scale-out: slice registry + ring -------------
    # The same verbs the fabric's StateCore serves, so a SliceManager
    # works identically against an in-process hub (tests, single-box
    # multi-replica runs) and the replicated control plane.

    def fabric_register_scheduler(self, name: str, url: str = "",
                                  pid: int | None = None) -> dict:
        return self.slices.register(name, url, pid)

    def fabric_unregister_scheduler(self, name: str) -> dict:
        return self.slices.unregister(name)

    def fabric_schedulers(self) -> dict:
        return self.slices.schedulers()

    def fabric_sched_ring(self) -> dict:
        return self.slices.ring()

    def fabric_set_sched_ring(self, ring: dict, expect_epoch: int) -> bool:
        return self.slices.set_ring(ring, expect_epoch)

    def patch_pod_condition(self, pod: Pod, condition: PodCondition,
                            nominated_node: str | None = None,
                            epoch: int | None = None,
                            lease_name: str = "kube-scheduler") -> None:
        """util.PatchPodStatus equivalent (schedule_one.go:1092); fenced
        like bind — a deposed leader must not overwrite the new leader's
        status writes."""
        with self._lock:
            self._check_fence("patch_pod_condition", epoch, lease_name)
            self._guard_pod_write(pod.metadata.namespace)
            stored = self._pods.objects.get(pod.metadata.uid)
            if stored is None:
                return
            new = stored.clone()
            new.status.conditions = [
                c for c in new.status.conditions if c.type != condition.type
            ] + [condition]
            if nominated_node is not None:
                new.status.nominated_node_name = nominated_node
            ev = self._swap_pod(stored, new)
        self._dispatch(self._pods, ev)

    def set_pod_claim_statuses(self, uid: str,
                               statuses: dict[str, str]) -> None:
        """Record generated-claim names on pod.status.resourceClaimStatuses
        (the resourceclaim controller's status patch)."""
        with self._lock:
            stored = self._pods.objects.get(uid)
            if stored is None:
                return
            self._guard_pod_write(stored.metadata.namespace)
            new = stored.clone()
            new.status.resource_claim_statuses = dict(statuses)
            ev = self._swap_pod(stored, new)
        self._dispatch(self._pods, ev)

    def clear_nominated_node(self, uid: str, epoch: int | None = None,
                             lease_name: str = "kube-scheduler") -> None:
        """Clear status.nominatedNodeName (preemption.go prepareCandidate
        clears lower nominations via API so they re-evaluate); fenced like
        bind — a deposed leader's queued nomination clears must not undo
        the new leader's reservations."""
        with self._lock:
            self._check_fence("clear_nominated_node", epoch, lease_name)
            stored = self._pods.objects.get(uid)
            if stored is None or not stored.status.nominated_node_name:
                return
            self._guard_pod_write(stored.metadata.namespace)
            new = stored.clone()
            new.status.nominated_node_name = ""
            ev = self._swap_pod(stored, new)
        self._dispatch(self._pods, ev)

    # ------------- namespaces -------------

    def create_namespace(self, ns: Namespace) -> None:
        self._create(self._namespaces, ns)

    def update_namespace(self, ns: Namespace) -> None:
        self._update(self._namespaces, ns)

    def delete_namespace(self, uid: str) -> None:
        self._delete(self._namespaces, uid)

    def list_namespaces(self) -> list[Namespace]:
        with self._lock:
            return list(self._namespaces.objects.values())

    # ------------- pod disruption budgets -------------

    def create_pdb(self, pdb: PodDisruptionBudget) -> None:
        self._create(self._pdbs, pdb)

    def update_pdb(self, pdb: PodDisruptionBudget) -> None:
        self._update(self._pdbs, pdb)

    def delete_pdb(self, uid: str) -> None:
        self._delete(self._pdbs, uid)

    def list_pdbs(self) -> list[PodDisruptionBudget]:
        with self._lock:
            return list(self._pdbs.objects.values())

    # ------------- volumes (PVC / PV / StorageClass) -------------

    def create_pvc(self, pvc: PersistentVolumeClaim) -> None:
        self._create(self._pvcs, pvc)

    def update_pvc(self, pvc: PersistentVolumeClaim) -> None:
        self._update(self._pvcs, pvc)

    def delete_pvc(self, uid: str) -> None:
        self._delete(self._pvcs, uid)

    def get_pvc(self, namespace: str, name: str
                ) -> Optional[PersistentVolumeClaim]:
        with self._lock:
            return self._pvcs.by_index(f"{namespace}/{name}")

    def list_pvcs(self) -> list[PersistentVolumeClaim]:
        with self._lock:
            return list(self._pvcs.objects.values())

    def create_pv(self, pv: PersistentVolume) -> None:
        self._create(self._pvs, pv)

    def update_pv(self, pv: PersistentVolume) -> None:
        self._update(self._pvs, pv)

    def delete_pv(self, uid: str) -> None:
        self._delete(self._pvs, uid)

    def get_pv(self, name: str) -> Optional[PersistentVolume]:
        with self._lock:
            return self._pvs.by_index(name)

    def list_pvs(self) -> list[PersistentVolume]:
        with self._lock:
            return list(self._pvs.objects.values())

    def create_storage_class(self, sc: StorageClass) -> None:
        self._create(self._storage_classes, sc)

    def get_storage_class(self, name: str) -> Optional[StorageClass]:
        with self._lock:
            return self._storage_classes.by_index(name)

    # ------------- dynamic resource allocation -------------

    def create_resource_claim(self, claim: ResourceClaim) -> None:
        self._create(self._claims, claim)

    def update_resource_claim(self, claim: ResourceClaim) -> None:
        self._update(self._claims, claim)

    def delete_resource_claim(self, uid: str) -> None:
        self._delete(self._claims, uid)

    def get_resource_claim(self, namespace: str, name: str
                           ) -> Optional[ResourceClaim]:
        with self._lock:
            return self._claims.by_index(f"{namespace}/{name}")

    def list_resource_claims(self) -> list[ResourceClaim]:
        with self._lock:
            return list(self._claims.objects.values())

    def create_resource_slice(self, sl: ResourceSlice) -> None:
        self._create(self._slices, sl)

    def delete_resource_slice(self, uid: str) -> None:
        self._delete(self._slices, uid)

    def list_resource_slices(self) -> list[ResourceSlice]:
        with self._lock:
            return list(self._slices.objects.values())

    def create_resource_claim_template(self, t) -> None:
        self._create(self._claim_templates, t)

    def get_resource_claim_template(self, namespace: str, name: str):
        with self._lock:
            return self._claim_templates.by_index(f"{namespace}/{name}")

    def create_csi_capacity(self, c) -> None:
        self._create(self._csi_capacities, c)

    def update_csi_capacity(self, c) -> None:
        self._update(self._csi_capacities, c)

    def list_csi_capacities(self) -> list:
        with self._lock:
            return list(self._csi_capacities.objects.values())

    def create_device_class(self, dc) -> None:
        self._create(self._device_classes, dc)

    def get_device_class(self, name: str):
        with self._lock:
            return self._device_classes.by_index(name)

    def list_device_classes(self) -> list:
        with self._lock:
            return list(self._device_classes.objects.values())

    # ------------- pod groups (gang scheduling) -------------

    def create_pod_group(self, pg: PodGroup) -> None:
        self._create(self._pod_groups, pg)

    def update_pod_group(self, pg: PodGroup) -> None:
        self._update(self._pod_groups, pg)

    def delete_pod_group(self, uid: str) -> None:
        self._delete(self._pod_groups, uid)

    def get_pod_group(self, namespace: str, name: str
                      ) -> Optional[PodGroup]:
        with self._lock:
            return self._pod_groups.by_index(f"{namespace}/{name}")

    def list_pod_groups(self) -> list[PodGroup]:
        with self._lock:
            return list(self._pod_groups.objects.values())

    # ------------- priority classes -------------

    def create_priority_class(self, pc: PriorityClass) -> None:
        self._create(self._priority_classes, pc)

    def list_priority_classes(self) -> list[PriorityClass]:
        with self._lock:
            return list(self._priority_classes.objects.values())

    # ------------- events (core/v1 Event analog) -------------

    def record_event(self, ref_kind: str, ref_key: str, reason: str,
                     message: str) -> None:
        """Record an object-level failure/notice, deduped by
        (ref, reason): a repeat bumps ``count`` and refreshes the
        message (the reference's event aggregation), so a hot loop
        hitting the same broken object cannot flood the store."""
        with self._lock:
            key = f"{ref_kind}/{ref_key}:{reason}"
            old = self._events.by_index(key)
            if old is not None:
                new = Event(metadata=ObjectMeta(
                                name=old.metadata.name,
                                uid=old.metadata.uid),
                            ref_kind=ref_kind, ref_key=ref_key,
                            reason=reason, message=message,
                            count=old.count + 1)
                self._events.objects[new.metadata.uid] = new
                ev = self._commit(self._events, "update", old, new)
            else:
                obj = Event(metadata=ObjectMeta(
                                name=f"{ref_kind.lower()}-{reason.lower()}"
                                     f"-{self._last_rv + 1}"),
                            ref_kind=ref_kind, ref_key=ref_key,
                            reason=reason, message=message)
                self._events.objects[obj.metadata.uid] = obj
                self._events.index_add(obj)
                ev = self._commit(self._events, "add", None, obj)
        self._dispatch(self._events, ev)

    def list_events(self, ref_kind: str | None = None,
                    ref_key: str | None = None) -> list[Event]:
        with self._lock:
            out = list(self._events.objects.values())
        if ref_kind is not None:
            out = [e for e in out if e.ref_kind == ref_kind]
        if ref_key is not None:
            out = [e for e in out if e.ref_key == ref_key]
        return out
