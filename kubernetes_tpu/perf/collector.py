"""Throughput + latency collection for the perf harness.

Equivalent of the reference's throughput collector
(test/integration/scheduler_perf/util.go:442-630): scheduled-pod counts
are bucketed into 1-second windows from the start of the measured phase;
the summary reports the overall average (pods scheduled / elapsed) plus
percentiles over the per-window samples, matching how scheduler_perf's
`SchedulingThroughput` metric items are computed.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field


def percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile (the reference reports p50/90/95/99 via its
    metrics histograms; nearest-rank over raw samples is the exact analog
    for the harness's window samples)."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   math.ceil(q / 100.0 * len(sorted_vals)) - 1))
    return sorted_vals[k]


@dataclass
class ThroughputSummary:
    pods_scheduled: int
    elapsed_s: float
    pods_per_sec: float          # overall average over the measured phase
    windows: list[int] = field(default_factory=list)   # per-1s-window counts
    p50: float = 0.0             # percentiles over window samples (pods/s)
    p90: float = 0.0
    p95: float = 0.0
    p99: float = 0.0

    def to_dict(self) -> dict:
        return {
            "pods_scheduled": self.pods_scheduled,
            "elapsed_s": round(self.elapsed_s, 3),
            "pods_per_sec": round(self.pods_per_sec, 1),
            "windows": len(self.windows),
            "p50": round(self.p50, 1),
            "p90": round(self.p90, 1),
            "p95": round(self.p95, 1),
            "p99": round(self.p99, 1),
        }


class ThroughputCollector:
    """Observes bind timestamps for a measured pod set.

    The harness registers a hub pod watch; on each update where a measured
    pod gains spec.nodeName the bind time is recorded (the same signal the
    reference collector reads from the informer: a pod with a non-empty
    NodeName counts as scheduled, util.go:560).
    """

    def __init__(self, measured_uids: set[str], now) -> None:
        self._measured = measured_uids
        self._now = now
        self._times: dict[str, float] = {}   # uid -> bind time (first only)
        self.start: float | None = None

    def begin(self) -> None:
        self.start = self._now()

    # hub watch callbacks -------------------------------------------------

    def on_update(self, old, new) -> None:
        if (new.spec.node_name and new.metadata.uid in self._measured
                and new.metadata.uid not in self._times):
            self._times[new.metadata.uid] = self._now()

    def on_add(self, pod) -> None:
        if (pod.spec.node_name and pod.metadata.uid in self._measured
                and pod.metadata.uid not in self._times):
            self._times[pod.metadata.uid] = self._now()

    # results -------------------------------------------------------------

    def scheduled_count(self) -> int:
        return len(self._times)

    def done(self) -> bool:
        return len(self._times) == len(self._measured)

    def summarize(self, end: float | None = None) -> ThroughputSummary:
        assert self.start is not None, "begin() not called"
        end = end if end is not None else (
            max(self._times.values()) if self._times else self.start)
        elapsed = max(end - self.start, 1e-9)
        n = len(self._times)
        # 1s windows from phase start (util.go:560: one sample per COMPLETED
        # second — a partial tail window would read as a spuriously low
        # pods/s sample, so it's excluded from the percentile samples)
        full = int(elapsed)
        num_windows = max(1, math.ceil(elapsed))
        counts = [0] * num_windows
        for t in self._times.values():
            w = min(int(t - self.start), num_windows - 1)
            counts[w] += 1
        if full >= 1:
            samples = sorted(float(c) for c in counts[:full])
        else:
            samples = [n / elapsed]   # sub-second run: one avg sample
        return ThroughputSummary(
            pods_scheduled=n,
            elapsed_s=elapsed,
            pods_per_sec=n / elapsed,
            windows=counts,
            p50=percentile(samples, 50),
            p90=percentile(samples, 90),
            p95=percentile(samples, 95),
            p99=percentile(samples, 99),
        )
