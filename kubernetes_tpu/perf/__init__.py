"""scheduler_perf-equivalent benchmark harness (SURVEY §2.8).

TPU-native port of /root/reference/test/integration/scheduler_perf: an op
DSL (createNodes/createNamespaces/createPods/churn/barrier), a 1s-window
throughput collector with percentiles, per-workload thresholds, and the
BASELINE workload definitions — all driven through the production
Scheduler + Hub path (pods created via hub.create_pod, bindings observed
from the hub's watch stream, exactly how the reference harness observes
them via the informer).
"""

from kubernetes_tpu.perf.collector import ThroughputCollector
from kubernetes_tpu.perf.harness import (
    Barrier,
    Churn,
    CreateNamespaces,
    CreateNodes,
    CreatePods,
    Workload,
    run_workload,
)

__all__ = [
    "Barrier",
    "Churn",
    "CreateNamespaces",
    "CreateNodes",
    "CreatePods",
    "ThroughputCollector",
    "Workload",
    "run_workload",
]
