"""Run ONE perf workload in a fresh process and print its result as JSON.

`python -m kubernetes_tpu.perf.run_one <workload_fn> [--scale X]
 [--profile] [--recorder off]`

--profile includes the flight recorder's per-phase/per-plugin breakdown
in the JSON result (bench.py --profile consumes it); --recorder off
disables the always-on recorder (flight_recorder_capacity=0) for the
--trace-overhead on/off comparison.

The bench driver (bench.py) shells out here per workload — the same
isolation the reference harness gets from one integration-test process
per workload. Process isolation matters empirically: in-process
back-to-back workloads interfere (device-memory/executable-cache
pressure from earlier workloads shows up as multi-second stalls in later
measured phases), while solo runs are clean and reproducible. The
on-disk XLA compile cache keeps each fresh process warm.
"""

from __future__ import annotations

import json
import os
import sys


def main() -> None:
    name = sys.argv[1]
    scale = 1.0
    if "--scale" in sys.argv:
        scale = float(sys.argv[sys.argv.index("--scale") + 1])
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from kubernetes_tpu.utils import jaxsetup

    jaxsetup.setup(os.path.join(repo, ".jax_cache"))
    import time

    from kubernetes_tpu.perf import workloads as W
    from kubernetes_tpu.perf.harness import run_workload

    factory = getattr(W, name)
    profile = "--profile" in sys.argv
    config = None
    if "--recorder" in sys.argv:
        idx = sys.argv.index("--recorder")
        mode = sys.argv[idx + 1] if idx + 1 < len(sys.argv) else ""
        if mode not in ("on", "off"):
            sys.exit("--recorder expects 'on' or 'off'")
        if mode == "off":
            from kubernetes_tpu.config.types import default_config

            config = default_config()
            config.flight_recorder_capacity = 0
    t0 = time.time()
    run_workload(factory(), scale=0.005,   # compile pass, same shapes
                 config=config)
    t_warm = time.time() - t0
    t0 = time.time()
    r = run_workload(factory(), scale=scale, config=config,
                     profile=profile)
    r["warm_s"] = round(t_warm, 1)
    r["run_s"] = round(time.time() - t0, 1)
    print(json.dumps(r))


if __name__ == "__main__":
    main()
