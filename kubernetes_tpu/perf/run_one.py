"""Run ONE perf workload in a fresh process and print its result as JSON.

`python -m kubernetes_tpu.perf.run_one <workload_fn> [--scale X]
 [--profile] [--recorder off] [--regret] [--pipelined on|off]`

--profile includes the flight recorder's per-phase/per-plugin breakdown
in the JSON result (bench.py --profile consumes it); --recorder off
disables the always-on recorder (flight_recorder_capacity=0) for the
--trace-overhead on/off comparison; --regret runs with a throwaway
trace export + the v3 alternative rows on so the result's quality
block carries the per-placement regret_mean/regret_p99 columns
(opt-in: the alt top_k + export I/O are a measured-perf change).

The bench driver (bench.py) shells out here per workload — the same
isolation the reference harness gets from one integration-test process
per workload. Process isolation matters empirically: in-process
back-to-back workloads interfere (device-memory/executable-cache
pressure from earlier workloads shows up as multi-second stalls in later
measured phases), while solo runs are clean and reproducible. The
on-disk XLA compile cache keeps each fresh process warm.
"""

from __future__ import annotations

import json
import os
import sys


def main() -> None:
    name = sys.argv[1]
    scale = 1.0
    if "--scale" in sys.argv:
        scale = float(sys.argv[sys.argv.index("--scale") + 1])
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from kubernetes_tpu.utils import jaxsetup

    jaxsetup.setup(os.path.join(repo, ".jax_cache"))
    import time

    from kubernetes_tpu.perf import workloads as W
    from kubernetes_tpu.perf.harness import run_workload

    factory = getattr(W, name)
    profile = "--profile" in sys.argv
    config = None
    if "--recorder" in sys.argv:
        idx = sys.argv.index("--recorder")
        mode = sys.argv[idx + 1] if idx + 1 < len(sys.argv) else ""
        if mode not in ("on", "off"):
            sys.exit("--recorder expects 'on' or 'off'")
        if mode == "off":
            from kubernetes_tpu.config.types import default_config

            config = default_config()
            config.flight_recorder_capacity = 0
    if "--pipelined" in sys.argv:
        # the pipelined-waves A/B arm selector (paired threshold-ratchet
        # instrumentation): off = strict launch->commit alternation with
        # whole-chain invalidation on every informer event
        idx = sys.argv.index("--pipelined")
        mode = sys.argv[idx + 1] if idx + 1 < len(sys.argv) else ""
        if mode not in ("on", "off"):
            sys.exit("--pipelined expects 'on' or 'off'")
        if config is None:
            from kubernetes_tpu.config.types import default_config

            config = default_config()
        config.pipelined_waves = mode == "on"
    regret_dir = None
    if "--regret" in sys.argv:
        import tempfile

        from kubernetes_tpu.config.types import default_config

        if config is None:
            config = default_config()
        regret_dir = tempfile.mkdtemp(prefix="bench_regret_")
        config.trace_export_path = os.path.join(regret_dir,
                                                "traces.jsonl")
        # regret needs scores + alternatives, not feature vectors; the
        # default keep-last-1 rotation bounds the run's disk footprint
        # (the summary then covers the newest window)
        config.trace_export_alts = True
    t0 = time.time()
    run_workload(factory(), scale=0.005,   # compile pass, same shapes
                 config=config)
    t_warm = time.time() - t0
    if regret_dir is not None:
        # the measured run's regret summary must not include the warm
        # pass's placements
        open(config.trace_export_path, "w").close()
    from kubernetes_tpu.models.pipeline import launch_cache_size

    t0 = time.time()
    # zero-recompile gate: the warm pass (and the chain-patch warmup it
    # triggers) must have compiled every kernel the measured phase needs —
    # a non-zero delta here is a mid-drain recompile eating measured time
    compiles_pre = launch_cache_size()
    r = run_workload(factory(), scale=scale, config=config,
                     profile=profile)
    r["measured_compiles"] = launch_cache_size() - compiles_pre
    if regret_dir is not None:
        import shutil

        shutil.rmtree(regret_dir, ignore_errors=True)
    r["warm_s"] = round(t_warm, 1)
    r["run_s"] = round(time.time() - t0, 1)
    print(json.dumps(r))


if __name__ == "__main__":
    main()
