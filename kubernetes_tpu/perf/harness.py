"""The perf-harness op DSL + workload runner.

TPU-native equivalent of the reference's scheduler_perf test driver
(test/integration/scheduler_perf/scheduler_perf.go:82-97 op registry,
:819+ churnOp; util.go:442-630 collector wiring). A Workload is a list of
ops executed in order against a fresh Hub + production Scheduler:

- CreateNodes / CreateNamespaces: populate the cluster.
- CreatePods: create pods through hub.create_pod and drain the scheduler
  until every pod of the op is bound (the reference's
  waitUntilPodsScheduled); with collect_metrics=True the drain is timed
  by a ThroughputCollector observing the hub watch stream.
- Churn: from this point on, create pods from the given templates at a
  fixed interval while later ops drain (scheduler_perf.go:819 churnOp,
  mode=create).
- Barrier: wait for all currently-pending pods to schedule.

The drain drives Scheduler.run_until_idle — the production batched loop
(queue pop -> mirror pack -> device launch -> framework commit -> hub
bind) — NOT a raw launch_batch drain, so measured pods/s is
production-path throughput.
"""

from __future__ import annotations

import copy
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from kubernetes_tpu.api.objects import Namespace, ObjectMeta, Pod
from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import EventHandlers, Hub
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.perf.collector import ThroughputCollector
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.telemetry.slo import time_to_bind_stats

# ---------------------------------------------------------------- op DSL


@dataclass
class CreateNodes:
    """createNodes op. ``make_node(i)`` -> Node; zones (if set) are applied
    by the factory (labelNodePrepareStrategy equivalent is the factory's
    business — the DSL just counts)."""

    count: int
    make_node: Callable[[int], object]


@dataclass
class CreateNamespaces:
    prefix: str
    count: int
    labels: Optional[Callable[[int], dict]] = None


@dataclass
class CreatePods:
    """createPods op: create ``count`` pods via ``make_pod(i)`` and wait
    for all of them to schedule (waitUntilPodsScheduled). When
    ``collect_metrics`` the phase is timed."""

    count: int
    make_pod: Callable[[int], Pod]
    collect_metrics: bool = False
    # maximum wall-clock seconds to wait for the phase to finish before
    # declaring the workload stuck (the reference fails the test case)
    timeout_s: float = 600.0
    # wait=False: create without draining (pods that are NOT expected to
    # schedule — e.g. permanently gated pods parked by PreEnqueue)
    wait: bool = True


@dataclass
class CreateObjects:
    """Generic typed-object create op (the reference DSL's createAny:
    scheduler_perf.go createAny op for ResourceSlices/Claims/classes):
    calls hub.<create_verb>(make(i)) count times."""

    count: int
    make: Callable[[int], object]
    create_verb: str = "create_resource_claim"


@dataclass
class Churn:
    """churnOp (scheduler_perf.go:819): once reached, inject one object
    per template every ``interval_ms`` while subsequent ops drain.
    mode=create keeps creating; mode=recreate deletes the previous copy of
    each template first, keeping ``number`` alive (the MixedChurn shape).
    Templates may build Pods or Nodes."""

    templates: list[Callable[[int], object]]
    interval_ms: int = 200
    mode: str = "create"


@dataclass
class Barrier:
    timeout_s: float = 600.0


@dataclass
class Workload:
    name: str
    ops: list
    threshold: float = 0.0      # reference CI floor, pods/s
    baseline: float = 0.0       # same as threshold unless overridden
    node_capacity: int = 8192   # mirror bucket hints (pow2; fixed up front
    pod_capacity: int = 16384   # so warmup compiles the full-size programs)
    batch_size: int = 2048
    # hostname-keyed topology workloads: the domain bucket (a STATIC jit
    # arg) tracks the number of distinct domains = nodes, so a scaled-down
    # warmup would compile the wrong program; keep CreateNodes unscaled
    warm_full_nodes: bool = False
    # featureGates overrides for this workload (the reference per-workload
    # featureGates block), merged onto the scheduler config's gates
    feature_gates: dict = field(default_factory=dict)
    # run a ResourceClaimController against the hub (the reference's
    # resourceclaim controller runs in kube-controller-manager): needed by
    # claim-TEMPLATE workloads, whose claims the controller materializes
    dra_claim_controller: bool = False
    # multi-tenant job queues: tenant name -> {"weight", "quota"} merged
    # onto SchedulerConfiguration.tenants for this workload
    tenants: dict = field(default_factory=dict)
    # gang workloads: op counts must stay GANG-ALIGNED, so the uniform
    # per-op scaling would strand partial gangs behind min_member — the
    # factory rebuilds the whole workload at the requested scale instead
    # (capacities/batch stay identical, so jit shapes are preserved)
    rescale: Optional[Callable[[float], "Workload"]] = None
    # post-run assertion hook: validate(hub, result) inspects the final
    # cluster state, may attach extra result fields, and RAISES on a
    # violated workload invariant (e.g. GangTopologyPacking's
    # members-land-topology-close criterion) — a red validate fails the
    # bench row like a missed threshold would
    validate: Optional[Callable] = None

    def __post_init__(self) -> None:
        if not self.baseline:
            self.baseline = self.threshold


class _ChurnState:
    def __init__(self, op: Churn, now: Callable[[], float]) -> None:
        self.op = op
        self.t0 = now()
        self.created = 0
        # mode=recreate: previous live copy per template index
        self._live: dict[int, object] = {}

    def due(self, t: float) -> int:
        # first injection fires immediately: a warm/compile pass whose
        # drain completes inside one interval must still exercise the
        # churn path (and compile its programs — e.g. the preemption
        # sweep) or the full-scale run pays the XLA compile mid-phase
        return 1 + int((t - self.t0) * 1000.0 / self.op.interval_ms)

    def _create(self, hub: Hub, obj, i: int) -> None:
        from kubernetes_tpu.api.objects import Node
        from kubernetes_tpu.scenario.lifecycle import NodeLifecycle

        obj.metadata.name = f"churn-{obj.metadata.name}-{i}"
        if isinstance(obj, Node):
            NodeLifecycle(hub).add(obj)
        else:
            hub.create_pod(obj)

    def _delete(self, hub: Hub, obj) -> None:
        from kubernetes_tpu.api.objects import Node
        from kubernetes_tpu.scenario.lifecycle import NodeLifecycle

        try:
            if isinstance(obj, Node):
                NodeLifecycle(hub).remove(obj.metadata.name)
            else:
                hub.delete_pod(obj.metadata.uid)
        except Exception:  # noqa: BLE001 — already gone is fine
            pass

    def inject(self, hub: Hub, t: float) -> None:
        want = self.due(t)
        while self.created < want:
            i = self.created
            ti = i % len(self.op.templates)
            obj = self.op.templates[ti](i)
            if self.op.mode == "recreate":
                prev = self._live.pop(ti, None)
                if prev is not None:
                    self._delete(hub, prev)
                self._live[ti] = obj
            self._create(hub, obj, i)
            self.created += 1


# ---------------------------------------------------------------- runner


class WorkloadStuck(Exception):
    """A phase did not finish within its timeout (pods stayed pending)."""


def run_workload(w: Workload, now: Callable[[], float] = time.time,
                 sleep: Callable[[float], None] = time.sleep,
                 scale: float = 1.0,
                 config=None, profile: bool = False,
                 cycle_times: Optional[list] = None) -> dict:
    """Execute one workload; returns the result dict (throughput summary,
    threshold verdict, scheduler stats).

    ``scale`` shrinks every op count (for warmup/compile passes and unit
    tests) while keeping capacities — and therefore every jitted program
    shape — identical to the full-size run, so a scale=0.01 pass populates
    the XLA compile cache for the real one.

    ``profile`` adds the flight recorder's per-phase/per-plugin
    percentiles and host-tail share to the result (bench.py --profile).
    ``cycle_times`` (a caller-owned list) collects every RAW cycle
    duration in seconds — exact samples, not bucket-resolution histogram
    reads — for the --trace-overhead on/off comparison.
    """
    if scale != 1.0 and w.rescale is not None:
        w = w.rescale(scale)
        scale = 1.0
    hub = Hub()
    if w.dra_claim_controller:
        from kubernetes_tpu.plugins.dra import ResourceClaimController

        ResourceClaimController(hub)
    cfg = copy.deepcopy(config) if config is not None else default_config()
    cfg.batch_size = w.batch_size
    # quality rows gate on time-to-bind percentiles over PodTimelines —
    # the LRU must hold every pod of the run or the oldest (slowest-era)
    # pods fall out of the percentile pass
    cfg.timelines_capacity = max(
        getattr(cfg, "timelines_capacity", 4096), 2 * w.pod_capacity)
    if w.tenants:
        cfg.tenants = {**cfg.tenants, **w.tenants}
    cfg.feature_gates.update(w.feature_gates)
    sched = Scheduler(hub, cfg, caps=Capacities(
        nodes=w.node_capacity, pods=w.pod_capacity), now=now)
    if cycle_times is not None:
        # exact per-cycle samples: wrap the cycle histogram's observe so
        # every recorded duration also lands in the caller's list
        _obs = sched.metrics.batch_duration.observe

        def _capture(value: float, n: int = 1, **labels) -> None:
            cycle_times.append(value)
            _obs(value, n, **labels)

        sched.metrics.batch_duration.observe = _capture
    churns: list[_ChurnState] = []
    summary = None
    phases: list[dict] = []

    def scaled(n: int) -> int:
        return max(1, int(n * scale)) if scale != 1.0 else n

    def pump() -> None:
        for ch in churns:
            ch.inject(hub, now())

    def drain(done_fn: Callable[[], bool], timeout_s: float) -> None:
        """Run the production loop until done_fn(); churn pods are injected
        between batches; idle waits advance backoff."""
        deadline = now() + timeout_s

        def step() -> bool:
            pump()
            return done_fn()

        while not done_fn():
            pump()
            sched.run_until_idle(on_step=step)
            if done_fn():
                return
            if now() > deadline:
                raise WorkloadStuck(
                    f"{w.name}: phase timed out after {timeout_s}s "
                    f"(pending={sched.queue.pending_counts()})")
            # queue idle but phase incomplete: pods are parked in backoff /
            # unschedulable (e.g. waiting on preemption victims) or the
            # next churn pod isn't due yet — let time pass, flush, retry
            sleep(0.05)
            sched.queue.flush_backoff_completed()

    try:
        for op in w.ops:
            if isinstance(op, CreateNodes):
                n_nodes = op.count if w.warm_full_nodes else scaled(op.count)
                for i in range(n_nodes):
                    hub.create_node(op.make_node(i))
            elif isinstance(op, CreateObjects):
                make = getattr(hub, op.create_verb)
                for i in range(scaled(op.count)):
                    make(op.make(i))
            elif isinstance(op, CreateNamespaces):
                for i in range(op.count):
                    hub.create_namespace(Namespace(metadata=ObjectMeta(
                        name=f"{op.prefix}-{i}",
                        labels=op.labels(i) if op.labels else {})))
            elif isinstance(op, Churn):
                churns.append(_ChurnState(op, now))
            elif isinstance(op, Barrier):
                drain(lambda: len(sched.queue) == 0, op.timeout_s)
            elif isinstance(op, CreatePods):
                n = scaled(op.count)
                pods = [op.make_pod(i) for i in range(n)]
                uids = {p.metadata.uid for p in pods}
                collector = None
                if op.collect_metrics:
                    collector = ThroughputCollector(uids, now)
                    hub.watch_pods(EventHandlers(
                        on_add=collector.on_add,
                        on_update=collector.on_update), replay=False)
                    collector.begin()
                for p in pods:
                    hub.create_pod(p)
                if not op.wait:
                    phases.append({"op": "createPods", "count": n,
                                   "measured": False, "waited": False})
                    continue
                if collector is not None:
                    drain(collector.done, op.timeout_s)
                    summary = collector.summarize()
                    phases.append({"op": "createPods", "count": n,
                                   "measured": True})
                else:
                    def all_bound() -> bool:
                        for u in uids:
                            p = hub.get_pod(u)
                            if p is not None and not p.spec.node_name:
                                return False
                        return True

                    drain(all_bound, op.timeout_s)
                    phases.append({"op": "createPods", "count": n,
                                   "measured": False})
            else:
                raise TypeError(f"unknown op {op!r}")

    finally:
        sched.close()  # binder threads released even on failure
    m = sched.metrics
    # scheduling-quality outcomes for the A/B scorer harness (bench.py
    # --ab-scorer): preemption count, end-state per-node bound-pod
    # spread, and time-to-bind tail — the metrics a latency-neutral
    # learned scorer is supposed to move
    # seed EVERY node at 0 first: a scorer that hotspots all pods onto
    # one node must read as maximal imbalance, not perfect spread
    per_node: dict[str, int] = {n.metadata.name: 0
                                for n in hub.list_nodes()}
    for p in hub.list_pods():
        if p.spec.node_name:
            per_node[p.spec.node_name] = per_node.get(p.spec.node_name,
                                                      0) + 1
    counts = list(per_node.values())
    if counts:
        mean = sum(counts) / len(counts)
        spread_std = (sum((c - mean) ** 2 for c in counts)
                      / len(counts)) ** 0.5
        spread_maxmin = max(counts) - min(counts)
    else:
        spread_std = spread_maxmin = 0.0
    result = {
        "name": w.name,
        "threshold": w.threshold,
        "stats": dict(sched.stats),
        # the metric slices the reference harness scrapes
        # (scheduler_perf.go:140-166): attempt latency percentiles + counts
        "metrics": {
            "attempt_p50_ms": round(
                m.attempt_duration.percentile(50) * 1e3, 2),
            "attempt_p99_ms": round(
                m.attempt_duration.percentile(99) * 1e3, 2),
            "cycle_p99_ms": round(
                m.batch_duration.percentile(99) * 1e3, 2),
            "attempts": int(sum(
                m.schedule_attempts._values.values())),
        },
        "quality": {
            "preemptions": int(sched.stats.get("preemptions", 0)),
            "spread_stddev": round(spread_std, 3),
            "spread_max_min": int(spread_maxmin),
            # p50/p99/max from ONE PodTimelines pass — the same helper
            # the scenario replay driver's SLO gate uses (ISSUE 17),
            # so bench rows and trace gates cannot drift apart
            **{k: v for k, v in time_to_bind_stats(
                sched.timelines).items() if k != "count"},
        },
    }
    # per-placement regret columns (ISSUE 14): whenever the run exported
    # the v3 alternative rows, summarize (chosen outcome − best
    # counterfactual) over this workload's placements into the artifact
    # row — outcomes harvested from the live hub's journal the same way
    # replay harvests them from the WAL
    if getattr(cfg, "trace_export_path", None) \
            and getattr(cfg, "trace_export_alts", False):
        try:
            from kubernetes_tpu.learn import regret as RG
            from kubernetes_tpu.learn.replay import (
                iter_placement_rows,
                iter_trace_lines,
            )

            paths = [cfg.trace_export_path + ".1", cfg.trace_export_path]
            rows = [r for p in paths if os.path.exists(p)
                    for r in iter_placement_rows(iter_trace_lines(p))]
            evicted, node_domain = RG.harvest_hub_outcomes(hub)
            # the export opens in APPEND mode: a reused path carries
            # earlier runs' rows — keep only uids THIS run's (fresh)
            # hub knows, so the columns summarize this workload only
            run_uids = {p.metadata.uid for p in hub.list_pods()} \
                | evicted
            rows = [r for r in rows if r.get("uid") in run_uids]
            reg = RG.summarize_regret(
                RG.compute_regret(rows, evicted, node_domain))
            result["quality"]["regret_mean"] = reg["regret_mean"]
            result["quality"]["regret_p99"] = reg["regret_p99"]
            result["regret"] = reg
        except Exception:  # noqa: BLE001 — a torn export must not fail
            pass           # the bench row it decorates
    if sched.jobqueue.active:
        # per-tenant admission/fairness accounting for the gang-storm
        # artifact rows (weights should show up as contended ratios)
        result["tenants"] = sched.jobqueue.tenant_stats()
        result["gangs"] = sched._gang.debug_state()["stats"]
    if profile:
        fl = sched.flight
        result["flight"] = {
            "enabled": fl.enabled,
            "cycles_recorded": len(fl.ring),
            "phases": fl.phase_percentiles(),
            "plugins": fl.plugin_percentiles(),
            "host_tail_share": round(fl.host_tail_share(), 4),
            # pipelined waves: per-cycle device occupancy (launch span
            # over cycle wall) — the pipelining win shows up here as a
            # mean close to 1.0 while the strict-alternation arm idles
            "occupancy": fl.occupancy_stats(),
            # the device-launch profiler column: compiles by attributed
            # cause, per-shape walltime, resident buffer bytes
            "device": (sched.profiler.snapshot()
                       if sched.profiler is not None else None),
        }
    if w.validate is not None:
        w.validate(hub, result)
    if summary is not None:
        result.update(summary.to_dict())
        result["vs_baseline"] = (
            round(summary.pods_per_sec / w.baseline, 2) if w.baseline else 0)
        result["passed"] = summary.pods_per_sec >= w.threshold
    return result
