"""The reference scheduler_perf workloads, mirroring performance-config
shapes (node/pod templates from test/integration/scheduler_perf/templates;
op sequences and thresholds from the per-suite performance-config.yaml).
Every thresholded row of BASELINE.md is implemented — the 5 BASELINE.json
headliners plus the affinity suite (required/preferred, NSSelector
variants, MixedSchedulingBasePod, gated-with-affinity), the topology
suite (required/preferred spreading, node-inclusion policy), churn,
daemonset, gated, unschedulable (hints on/off), DRA steady state
(direct claims + claim templates with CEL selectors), and the
feature-gate variants (QueueingHints, AsyncPreemption, preferred
NSSelector anti-affinity) — 25 configs, all run and published by
bench.py.

Node template (node-default.yaml): cpu 4, memory 32Gi, pods 110.
Pod template (pod-default.yaml): requests cpu 100m, memory 500Mi.
"""

from __future__ import annotations

from kubernetes_tpu.api.objects import (
    Affinity,
    Container,
    LABEL_HOSTNAME,
    LABEL_POD_GROUP,
    LABEL_QUEUE,
    LABEL_ZONE,
    LabelSelector,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodGroup,
    PodSpec,
    ResourceRequirements,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from kubernetes_tpu.perf.harness import (
    Churn,
    CreateNamespaces,
    CreateNodes,
    CreateObjects,
    CreatePods,
    Workload,
)


def _node(i: int, zones: list[str] | None = None) -> Node:
    """node-default.yaml + labelNodePrepareStrategy zone labels."""
    name = f"node-{i}"
    labels = {LABEL_HOSTNAME: name}
    if zones:
        labels[LABEL_ZONE] = zones[i % len(zones)]
    return Node(
        metadata=ObjectMeta(name=name, labels=labels),
        spec=NodeSpec(),
        status=NodeStatus(allocatable={
            "cpu": "4", "memory": "32Gi", "pods": "110"}))


def _pod(name: str, cpu: str = "100m", mem: str = "500Mi",
         namespace: str = "default", labels: dict | None = None,
         affinity: Affinity | None = None, tsc: list | None = None,
         priority: int | None = None) -> Pod:
    # cpu/mem "0" = a request-less pod (pod-with-label.yaml: fit consumes
    # only a pod slot; scoring sees the NonZeroRequested defaults)
    requests = {}
    if cpu != "0":
        requests["cpu"] = cpu
    if mem != "0":
        requests["memory"] = mem
    return Pod(
        metadata=ObjectMeta(name=name, namespace=namespace,
                            labels=labels or {}),
        spec=PodSpec(
            containers=[Container(
                name="pause",
                resources=ResourceRequirements(requests=requests))],
            affinity=affinity,
            topology_spread_constraints=tsc or [],
            priority=priority))


# ------------------------------------------------- 1. SchedulingBasic
# misc/performance-config.yaml:40-66 (5000Nodes_10000Pods, threshold 270)

def scheduling_basic(init_nodes=5000, init_pods=1000,
                     measure_pods=10000) -> Workload:
    return Workload(
        name="SchedulingBasic/5000Nodes_10000Pods",
        threshold=270,
        batch_size=4096,   # auction path: bigger launches amortize better
        ops=[
            CreateNodes(init_nodes, _node),
            CreatePods(init_pods, lambda i: _pod(f"init-{i}")),
            CreatePods(measure_pods, lambda i: _pod(f"measure-{i}"),
                       collect_metrics=True),
        ])


# ------------------------------------------- 2. SchedulingNodeAffinity
# affinity/performance-config.yaml:280-330 (5000Nodes_10000Pods, 220):
# nodes labeled zone1; measured pods require zone In [zone1, zone2]
# (pod-with-node-affinity.yaml); scoring includes BalancedAllocation via
# the default plugin set.

def _node_affinity_pod(i: int) -> Pod:
    aff = Affinity(node_affinity=NodeAffinity(required=NodeSelector(
        node_selector_terms=[NodeSelectorTerm(match_expressions=[
            NodeSelectorRequirement(key=LABEL_ZONE, operator="In",
                                    values=["zone1", "zone2"])])])))
    return _pod(f"na-{i}", affinity=aff)


def scheduling_node_affinity(init_nodes=5000, init_pods=5000,
                             measure_pods=10000) -> Workload:
    return Workload(
        name="SchedulingNodeAffinity/5000Nodes_10000Pods",
        threshold=220,
        pod_capacity=32768,
        batch_size=4096,   # auction path
        ops=[
            CreateNodes(init_nodes, lambda i: _node(i, zones=["zone1"])),
            CreatePods(init_pods, lambda i: _pod(f"init-{i}")),
            CreatePods(measure_pods, _node_affinity_pod,
                       collect_metrics=True),
        ])


# --------------------------------------- 3. SchedulingPodAntiAffinity
# affinity/performance-config.yaml:20-70 (5000Nodes_2000Pods, 60):
# 2 namespaces; pods labeled color=green with required hostname
# anti-affinity across both namespaces
# (pod-with-pod-anti-affinity.yaml).

def _anti_affinity_pod(i: int, ns: str) -> Pod:
    aff = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
        PodAffinityTerm(
            topology_key=LABEL_HOSTNAME,
            label_selector=LabelSelector(match_labels={"color": "green"}),
            namespaces=["sched-1", "sched-0"])]))
    return _pod(f"anti-{ns}-{i}", namespace=ns,
                labels={"color": "green"}, affinity=aff)


def scheduling_pod_anti_affinity(init_nodes=5000, init_pods=1000,
                                 measure_pods=2000) -> Workload:
    return Workload(
        name="SchedulingPodAntiAffinity/5000Nodes_2000Pods",
        threshold=60,
        warm_full_nodes=True,   # hostname anti-affinity: domains = nodes
        ops=[
            CreateNodes(init_nodes, _node),
            CreateNamespaces("sched", 2),
            CreatePods(init_pods,
                       lambda i: _anti_affinity_pod(i, "sched-0")),
            CreatePods(measure_pods,
                       lambda i: _anti_affinity_pod(i, "sched-1"),
                       collect_metrics=True),
        ])


# ------------------------------------------- 4. TopologySpreading
# topology_spreading/performance-config.yaml:21-70 (5000Nodes_5000Pods,
# 85): nodes across 3 zones; measured pods spread maxSkew=5 on zone
# (pod-with-topology-spreading.yaml).

def _spreading_pod(i: int) -> Pod:
    tsc = [TopologySpreadConstraint(
        max_skew=5, topology_key=LABEL_ZONE,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels={"color": "blue"}))]
    return _pod(f"spread-{i}", labels={"color": "blue"}, tsc=tsc)


def topology_spreading(init_nodes=5000, init_pods=5000,
                       measure_pods=5000) -> Workload:
    return Workload(
        name="TopologySpreading/5000Nodes_5000Pods",
        threshold=85,
        pod_capacity=32768,
        ops=[
            CreateNodes(init_nodes, lambda i: _node(
                i, zones=["moon-1", "moon-2", "moon-3"])),
            CreatePods(init_pods, lambda i: _pod(f"init-{i}")),
            CreatePods(measure_pods, _spreading_pod, collect_metrics=True),
        ])


# ------------------------------------------- 5. PreemptionAsync
# misc/performance-config.yaml:195-250 (5000Nodes, 160): 20k low-priority
# 900m fillers (4 per 4-CPU node), churn creating a 3000m priority-10 pod
# every 200ms (each must preempt 3 fillers), 5000 always-schedulable
# 100m measured pods.

def _low_priority_pod(i: int) -> Pod:
    return _pod(f"low-{i}", cpu="900m", mem="500Mi")


def _high_priority_pod(i: int) -> Pod:
    return _pod(f"high-{i}", cpu="3000m", mem="500Mi", priority=10)


def preemption_async(init_nodes=5000, init_pods=20000,
                     measure_pods=5000) -> Workload:
    return Workload(
        name="PreemptionAsync/5000Nodes",
        threshold=160,
        pod_capacity=32768,
        ops=[
            CreateNodes(init_nodes, _node),
            CreatePods(init_pods, _low_priority_pod),
            Churn([_high_priority_pod], interval_ms=200),
            CreatePods(measure_pods, lambda i: _pod(f"measure-{i}"),
                       collect_metrics=True),
        ])


# ------------------------------------------- 6. Unschedulable
# misc/performance-config.yaml:280+ (5kNodes/100Init/10kPods, 140): a
# 200ms churn of 9-CPU high-priority pods that can NEVER fit a 4-CPU node
# parks in the unschedulable pool; the measured default pods must flow
# past them (the queueing-hint discipline this workload exists to test).

def _large_cpu_pod(i: int) -> Pod:
    return _pod(f"big-{i}", cpu="9", mem="500Mi", priority=10)


def unschedulable(init_nodes=5000, init_pods=100,
                  measure_pods=10000) -> Workload:
    return Workload(
        name="Unschedulable/5kNodes_100Init_10kPods",
        threshold=140,
        # the 140 floor is the reference's hints-OFF row
        # (misc/performance-config.yaml:315); the QHints variant re-enables
        feature_gates={"SchedulerQueueingHints": False},
        ops=[
            CreateNodes(init_nodes, _node),
            CreatePods(init_pods, lambda i: _pod(f"init-{i}")),
            Churn([_large_cpu_pod], interval_ms=200),
            CreatePods(measure_pods, lambda i: _pod(f"measure-{i}"),
                       collect_metrics=True),
        ])


# ------------------------------------- 7. SchedulingWithMixedChurn
# misc/performance-config.yaml:360+ (5000Nodes_10000Pods, 265): a 1s
# recreate-churn of {node, unschedulable high-priority pod} while 10k
# default pods schedule (the reference's template set also recreates a
# Service, which has no scheduler-visible effect here).

def _churn_node(i: int) -> object:
    return _node(100000 + i)


def mixed_churn(init_nodes=5000, measure_pods=10000) -> Workload:
    return Workload(
        name="SchedulingWithMixedChurn/5000Nodes_10000Pods",
        # ratcheted off the r15 lock (1400) by pipelined waves
        # (BENCH_r19): chain-surviving churn keeps the device-resident
        # free/nzr chain alive across the 1s recreate-churn (patches
        # instead of whole-chain invalidation + resync), zero measured-
        # phase recompiles. Paired same-box A/B best-of-3 reads 1.29x
        # (on 429.4 vs off 334.1 pods/s on the throttled 2-CPU box) but
        # the on-arm single-run swing is ±50%, so the ratchet is the
        # modest, defensible slice of it
        threshold=1500,
        baseline=265,
        ops=[
            CreateNodes(init_nodes, _node),
            Churn([_churn_node, _large_cpu_pod], interval_ms=1000,
                  mode="recreate"),
            CreatePods(measure_pods, lambda i: _pod(f"measure-{i}"),
                       collect_metrics=True),
        ])


# --------------------------------------------- 8. SchedulingDaemonset
# misc/performance-config.yaml:100-128 (15000Nodes, 390): one pod per node,
# pinned the way the daemonset controller pins them — a required
# nodeAffinity matchFields term on metadata.name (the scheduler still runs
# the full pipeline; NodeAffinity's PreFilter narrows to the one node).

def _daemonset_pod(i: int) -> Pod:
    aff = Affinity(node_affinity=NodeAffinity(required=NodeSelector(
        node_selector_terms=[NodeSelectorTerm(match_fields=[
            NodeSelectorRequirement(key="metadata.name", operator="In",
                                    values=[f"node-{i}"])])])))
    return _pod(f"ds-{i}", cpu="100m", mem="200Mi", affinity=aff)


def scheduling_daemonset(init_nodes=15000, measure_pods=15000) -> Workload:
    return Workload(
        name="SchedulingDaemonset/15000Nodes",
        threshold=3900,   # ratcheted: 10x the reference 390 floor (ISSUE 15)
        baseline=390,
        node_capacity=16384,
        pod_capacity=32768,
        ops=[
            CreateNodes(init_nodes, _node),
            CreatePods(measure_pods, _daemonset_pod,
                       collect_metrics=True),
        ],
        # matchFields pin per pod: every pod is its own topology-free spec;
        # warmup must see the same node bucket so the full-size node table
        # compiles up front
        warm_full_nodes=True)


# ------------------------------------------- 9. SchedulingWhileGated
# misc/performance-config.yaml:425-460 (1Node_10000GatedPods, 130): 10k
# permanently gated pods park in unschedulablePods; 10k plain pods then
# schedule onto one huge node — measures that the gated pool costs the
# hot path nothing (PreEnqueue gate + no requeue events).

def _gated_pod(i: int) -> Pod:
    from kubernetes_tpu.api.objects import PodSchedulingGate

    p = _pod(f"gated-{i}", cpu="1m", mem="1Mi")
    p.spec.scheduling_gates = [PodSchedulingGate(name="example.com/hold")]
    return p


def _big_node(i: int) -> Node:
    name = f"node-{i}"
    return Node(
        metadata=ObjectMeta(name=name, labels={LABEL_HOSTNAME: name}),
        spec=NodeSpec(),
        status=NodeStatus(allocatable={
            "cpu": "4000", "memory": "64Ti", "pods": "30000"}))


def scheduling_while_gated(gated_pods=10000, measure_pods=10000) -> Workload:
    return Workload(
        name="SchedulingWhileGated/1Node_10000GatedPods",
        threshold=130,
        node_capacity=64,
        pod_capacity=32768,
        ops=[
            CreateNodes(1, _big_node),
            CreatePods(gated_pods, _gated_pod, wait=False),
            CreatePods(measure_pods, lambda i: _pod(f"measure-{i}",
                                                    cpu="1m", mem="1Mi"),
                       collect_metrics=True),
        ])


# -------------------------------- 10/11. Preferred pod (anti)affinity
# affinity/performance-config.yaml:141-198 / :204-261
# (SchedulingPreferredPodAffinity / ...AntiAffinity, 5000Nodes_5000Pods,
# both 90): soft zone-level terms — pure Score work, the weighted
# preferred-term kernel (scoring.go:35) rather than the Filter path.

def _preferred_affinity_pod(i: int, anti: bool) -> Pod:
    term = WeightedPodAffinityTerm(weight=10, pod_affinity_term=(
        PodAffinityTerm(
            topology_key=LABEL_ZONE,
            label_selector=LabelSelector(match_labels={"team": "perf"}))))
    aff = (Affinity(pod_anti_affinity=PodAntiAffinity(preferred=[term]))
           if anti else
           Affinity(pod_affinity=PodAffinity(preferred=[term])))
    kind = "panti" if anti else "paff"
    return _pod(f"{kind}-{i}", labels={"team": "perf"}, affinity=aff)


def preferred_pod_affinity(init_nodes=5000, init_pods=1000,
                           measure_pods=5000) -> Workload:
    return Workload(
        name="SchedulingPreferredPodAffinity/5000Nodes_5000Pods",
        threshold=900,   # ratcheted: 10x the reference 90 floor (ISSUE 15)
        baseline=90,
        pod_capacity=32768,
        ops=[
            CreateNodes(init_nodes,
                        lambda i: _node(i, zones=["z1", "z2", "z3"])),
            CreatePods(init_pods, lambda i: _pod(f"init-{i}")),
            CreatePods(measure_pods,
                       lambda i: _preferred_affinity_pod(i, anti=False),
                       collect_metrics=True),
        ])


def preferred_pod_anti_affinity(init_nodes=5000, init_pods=1000,
                                measure_pods=5000) -> Workload:
    return Workload(
        name="SchedulingPreferredPodAntiAffinity/5000Nodes_5000Pods",
        threshold=900,   # ratcheted: 10x the reference 90 floor (ISSUE 15)
        baseline=90,
        pod_capacity=32768,
        ops=[
            CreateNodes(init_nodes,
                        lambda i: _node(i, zones=["z1", "z2", "z3"])),
            CreatePods(init_pods, lambda i: _pod(f"init-{i}")),
            CreatePods(measure_pods,
                       lambda i: _preferred_affinity_pod(i, anti=True),
                       collect_metrics=True),
        ])


# ------------------- 12. RequiredPodAntiAffinityWithNSSelector
# affinity/performance-config.yaml:425-480 (5000Nodes_2000Pods, 24 — the
# LOWEST floor in the reference's affinity suite): measured pods carry
# required hostname anti-affinity whose namespaceSelector picks out the
# team's namespaces, so the match set spans namespaces selected by LABEL.

def _ns_selector_anti_pod(i: int, ns: str) -> Pod:
    aff = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
        PodAffinityTerm(
            topology_key=LABEL_HOSTNAME,
            label_selector=LabelSelector(match_labels={"color": "teal"}),
            namespace_selector=LabelSelector(
                match_labels={"team": "sched"}))]))
    return _pod(f"nsanti-{ns}-{i}", namespace=ns,
                labels={"color": "teal"}, affinity=aff)


def ns_selector_anti_affinity(init_nodes=5000, init_pods=1000,
                              measure_pods=2000, namespaces=10) -> Workload:
    return Workload(
        name="SchedulingRequiredPodAntiAffinityWithNSSelector"
             "/5000Nodes_2000Pods",
        threshold=24,
        warm_full_nodes=True,   # hostname topology: domains = nodes
        ops=[
            CreateNodes(init_nodes, _node),
            CreateNamespaces("team", namespaces,
                             labels=lambda i: {"team": "sched"}),
            CreatePods(init_pods,
                       lambda i: _ns_selector_anti_pod(
                           i, f"team-{i % namespaces}")),
            CreatePods(measure_pods,
                       lambda i: _ns_selector_anti_pod(
                           i + 10**6, f"team-{i % namespaces}"),
                       collect_metrics=True),
        ])


# --------------------------- 13. DRA steady-state claim scheduling
# dra/performance-config.yaml:60-110 (SteadyStateClusterClaimTemplate,
# ~100 nodes, floor ~50): every node publishes a ResourceSlice of
# devices; each measured pod carries its own single-device ResourceClaim
# which the DynamicResources host plugin allocates at Reserve and
# persists through PreBind — the reference's own accelerator path.

def _dra_node(i: int) -> Node:
    name = f"node-{i}"
    return Node(metadata=ObjectMeta(name=name,
                                    labels={LABEL_HOSTNAME: name}),
                spec=NodeSpec(),
                status=NodeStatus(allocatable={
                    "cpu": "16", "memory": "64Gi", "pods": "110"}))


def _dra_slice(i: int):
    from kubernetes_tpu.api.objects import Device, ResourceSlice

    node = f"node-{i}"
    return ResourceSlice(
        metadata=ObjectMeta(name=f"slice-{node}"),
        node_name=node, driver="tpu.example.com", pool=node,
        devices=[Device(name=f"dev-{d}", device_class_name="tpu")
                 for d in range(8)])


def _dra_claim(i: int):
    from kubernetes_tpu.api.objects import (
        DeviceRequest,
        ResourceClaim,
        ResourceClaimSpec,
    )

    return ResourceClaim(
        metadata=ObjectMeta(name=f"dra-claim-{i}"),
        spec=ResourceClaimSpec(device_requests=[
            DeviceRequest(name="accel", device_class_name="tpu",
                          count=1)]))


def _dra_pod(i: int) -> Pod:
    from kubernetes_tpu.api.objects import PodResourceClaim

    p = _pod(f"dra-{i}", cpu="100m", mem="200Mi")
    p.spec.resource_claims = [PodResourceClaim(
        name="accel", resource_claim_name=f"dra-claim-{i}")]
    return p


def dra_steady_state(init_nodes=100, measure_pods=500) -> Workload:
    return Workload(
        name="DRASteadyState/100Nodes_500Pods",
        threshold=50,
        node_capacity=128,
        pod_capacity=2048,
        batch_size=256,
        ops=[
            CreateNodes(init_nodes, _dra_node),
            CreateObjects(init_nodes, _dra_slice,
                          create_verb="create_resource_slice"),
            CreateObjects(measure_pods, _dra_claim,
                          create_verb="create_resource_claim"),
            CreatePods(measure_pods, _dra_pod, collect_metrics=True),
        ])


# --------------- 13b. DRA steady-state via claim TEMPLATES + CEL
# dra/performance-config.yaml SteadyStateClusterClaimTemplate (+
# resourceclaim-with-selector.yaml): pods reference a
# ResourceClaimTemplate; the resourceclaim controller stamps a per-pod
# claim whose request carries a CEL device selector; the structured
# allocator matches attributes/capacity per device.

def _dra_attr_slice(i: int):
    from kubernetes_tpu.api.objects import Device, ResourceSlice

    node = f"node-{i}"
    return ResourceSlice(
        metadata=ObjectMeta(name=f"slice-{node}"),
        node_name=node, driver="tpu.example.com", pool=node,
        devices=[Device(name=f"dev-{d}",
                        attributes={"preallocate": d % 2 == 0},
                        capacity={"counters": "2"})
                 for d in range(8)])


def _dra_template(i: int):
    from kubernetes_tpu.api.objects import (
        DeviceRequest,
        DeviceSelector,
        ResourceClaimSpec,
        ResourceClaimTemplate,
    )

    expr = ("device.capacity['tpu.example.com'].counters"
            ".compareTo(quantity('2')) >= 0 && "
            "device.attributes['tpu.example.com'].preallocate")
    return ResourceClaimTemplate(
        metadata=ObjectMeta(name="perf-claim-template"),
        spec=ResourceClaimSpec(device_requests=[
            DeviceRequest(name="accel", selectors=[
                DeviceSelector(cel_expression=expr)])]))


def _dra_template_pod(i: int) -> Pod:
    from kubernetes_tpu.api.objects import PodResourceClaim

    p = _pod(f"drat-{i}", cpu="100m", mem="200Mi")
    p.spec.resource_claims = [PodResourceClaim(
        name="accel", resource_claim_template_name="perf-claim-template")]
    return p


def dra_steady_state_templates(init_nodes=100,
                               measure_pods=400) -> Workload:
    return Workload(
        name="DRASteadyStateClaimTemplates/100Nodes_400Pods",
        threshold=40,   # dra/performance-config.yaml:97 (template variant)
        node_capacity=128,
        pod_capacity=2048,
        batch_size=256,
        dra_claim_controller=True,
        ops=[
            CreateNodes(init_nodes, _dra_node),
            CreateObjects(init_nodes, _dra_attr_slice,
                          create_verb="create_resource_slice"),
            CreateObjects(1, _dra_template,
                          create_verb="create_resource_claim_template"),
            CreatePods(measure_pods, _dra_template_pod,
                       collect_metrics=True),
        ])


# --------------- 13c. DRA steady-state with CEL `in` membership
# the first of the previously-unmeasured DRA variants ROADMAP item 1
# sequences behind the batched allocator: the selector corpus's
# membership test (dra/performance-config.yaml's attribute-selector
# shapes) over a heterogeneous device fleet — half the devices match.

def _dra_model_slice(i: int):
    from kubernetes_tpu.api.objects import Device, ResourceSlice

    node = f"node-{i}"
    models = ("v4", "v5e", "v5p", "v6e")
    return ResourceSlice(
        metadata=ObjectMeta(name=f"slice-{node}"),
        node_name=node, driver="tpu.example.com", pool=node,
        devices=[Device(name=f"dev-{d}",
                        attributes={"model": models[d % 4]})
                 for d in range(8)])


def _dra_cel_in_template(i: int):
    from kubernetes_tpu.api.objects import (
        DeviceRequest,
        DeviceSelector,
        ResourceClaimSpec,
        ResourceClaimTemplate,
    )

    expr = ("device.attributes['tpu.example.com'].model"
            " in ['v5e', 'v5p']")
    return ResourceClaimTemplate(
        metadata=ObjectMeta(name="perf-claim-template"),
        spec=ResourceClaimSpec(device_requests=[
            DeviceRequest(name="accel", selectors=[
                DeviceSelector(cel_expression=expr)])]))


def dra_steady_state_cel_in(init_nodes=100, measure_pods=300) -> Workload:
    return Workload(
        name="DRASteadyStateCELIn/100Nodes_300Pods",
        threshold=40,   # template-variant floor: same shape, `in` selector
        node_capacity=128,
        pod_capacity=2048,
        batch_size=256,
        dra_claim_controller=True,
        ops=[
            CreateNodes(init_nodes, _dra_node),
            CreateObjects(init_nodes, _dra_model_slice,
                          create_verb="create_resource_slice"),
            CreateObjects(1, _dra_cel_in_template,
                          create_verb="create_resource_claim_template"),
            CreatePods(measure_pods, _dra_template_pod,
                       collect_metrics=True),
        ])


# --------------- 13d. DRA multi-request claims
# the second unmeasured variant: each claim carries TWO requests (a
# class-matched pair + one attribute-selected device, 3 devices per
# pod), exercising the allocator's greedy multi-request walk — on
# device, the carried `taken` mask across request slots.

def _dra_multi_slice(i: int):
    from kubernetes_tpu.api.objects import Device, ResourceSlice

    node = f"node-{i}"
    return ResourceSlice(
        metadata=ObjectMeta(name=f"slice-{node}"),
        node_name=node, driver="tpu.example.com", pool=node,
        devices=[Device(name=f"dev-{d}", device_class_name="tpu",
                        attributes={"preallocate": d % 2 == 0})
                 for d in range(16)])


def _dra_multi_template(i: int):
    from kubernetes_tpu.api.objects import (
        DeviceRequest,
        DeviceSelector,
        ResourceClaimSpec,
        ResourceClaimTemplate,
    )

    expr = "device.attributes['tpu.example.com'].preallocate"
    return ResourceClaimTemplate(
        metadata=ObjectMeta(name="perf-claim-template"),
        spec=ResourceClaimSpec(device_requests=[
            DeviceRequest(name="pair", device_class_name="tpu", count=2),
            DeviceRequest(name="probe", count=1, selectors=[
                DeviceSelector(cel_expression=expr)]),
        ]))


def dra_multi_request(init_nodes=100, measure_pods=250) -> Workload:
    return Workload(
        name="DRAMultiRequest/100Nodes_250Pods",
        threshold=40,   # template-variant floor: 3 devices per pod
        node_capacity=128,
        pod_capacity=2048,
        batch_size=256,
        dra_claim_controller=True,
        ops=[
            CreateNodes(init_nodes, _dra_node),
            CreateObjects(init_nodes, _dra_multi_slice,
                          create_verb="create_resource_slice"),
            CreateObjects(1, _dra_multi_template,
                          create_verb="create_resource_claim_template"),
            CreatePods(measure_pods, _dra_template_pod,
                       collect_metrics=True),
        ])


# -------------------------------------- 14. SchedulingPodAffinity
# affinity/performance-config.yaml:83-148 (5000Nodes_5000Pods, 35 — the
# reference's SLOWEST headline shape): every node in ONE zone; init and
# measured pods carry required zone-level podAffinity on color=blue
# across namespaces sched-0/sched-1 (pod-with-pod-affinity.yaml), so
# every placement updates the single shared affinity domain.

def _pod_affinity_pod(i: int, ns: str) -> Pod:
    aff = Affinity(pod_affinity=PodAffinity(required=[
        PodAffinityTerm(
            topology_key=LABEL_ZONE,
            label_selector=LabelSelector(match_labels={"color": "blue"}),
            namespaces=["sched-1", "sched-0"])]))
    return _pod(f"aff-{ns}-{i}", namespace=ns, labels={"color": "blue"},
                affinity=aff)


def scheduling_pod_affinity(init_nodes=5000, init_pods=5000,
                            measure_pods=5000) -> Workload:
    return Workload(
        name="SchedulingPodAffinity/5000Nodes_5000Pods",
        threshold=35,
        pod_capacity=32768,
        ops=[
            CreateNodes(init_nodes, lambda i: _node(i, zones=["zone1"])),
            CreateNamespaces("sched", 2),
            CreatePods(init_pods,
                       lambda i: _pod_affinity_pod(i, "sched-0")),
            CreatePods(measure_pods,
                       lambda i: _pod_affinity_pod(i, "sched-1"),
                       collect_metrics=True),
        ])


# -------------------------------------- 15. MixedSchedulingBasePod
# affinity/performance-config.yaml:338-418 (5000Nodes_5000Pods, 140):
# one zone; 2000 init pods of EACH of five templates — plain, required
# zone affinity (blue), required hostname anti-affinity (green),
# preferred hostname affinity (red), preferred hostname anti-affinity
# (yellow) — then 5000 plain measured pods scored against that mixture.

def _mixed_init_pod(i: int) -> Pod:
    kind = i % 5
    j = i // 5
    if kind == 0:
        return _pod(f"mix-plain-{j}", namespace="sched-0")
    if kind == 1:
        aff = Affinity(pod_affinity=PodAffinity(required=[
            PodAffinityTerm(
                topology_key=LABEL_ZONE,
                label_selector=LabelSelector(
                    match_labels={"color": "blue"}),
                namespaces=["sched-1", "sched-0"])]))
        return _pod(f"mix-aff-{j}", namespace="sched-0",
                    labels={"color": "blue"}, affinity=aff)
    if kind == 2:
        aff = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
            PodAffinityTerm(
                topology_key=LABEL_HOSTNAME,
                label_selector=LabelSelector(
                    match_labels={"color": "green"}),
                namespaces=["sched-1", "sched-0"])]))
        return _pod(f"mix-anti-{j}", namespace="sched-0",
                    labels={"color": "green"}, affinity=aff)
    term = WeightedPodAffinityTerm(weight=1, pod_affinity_term=(
        PodAffinityTerm(
            topology_key=LABEL_HOSTNAME,
            label_selector=LabelSelector(match_labels={
                "color": "red" if kind == 3 else "yellow"}),
            namespaces=["sched-1", "sched-0"])))
    if kind == 3:
        aff = Affinity(pod_affinity=PodAffinity(preferred=[term]))
        return _pod(f"mix-paff-{j}", namespace="sched-0",
                    labels={"color": "red"}, affinity=aff)
    aff = Affinity(pod_anti_affinity=PodAntiAffinity(preferred=[term]))
    return _pod(f"mix-panti-{j}", namespace="sched-0",
                labels={"color": "yellow"}, affinity=aff)


def mixed_scheduling_base_pod(init_nodes=5000, init_pods_each=2000,
                              measure_pods=5000) -> Workload:
    return Workload(
        name="MixedSchedulingBasePod/5000Nodes_5000Pods",
        threshold=140,
        pod_capacity=32768,
        warm_full_nodes=True,   # hostname terms: domains = nodes
        ops=[
            CreateNodes(init_nodes, lambda i: _node(i, zones=["zone1"])),
            CreateNamespaces("sched", 1),
            CreatePods(init_pods_each * 5, _mixed_init_pod),
            CreatePods(measure_pods,
                       lambda i: _pod(f"measure-{i}", namespace="sched-0"),
                       collect_metrics=True),
        ])


# ------------------ 16. RequiredPodAffinityWithNSSelector
# affinity/performance-config.yaml:574-648 (5000Nodes_2000Pods, 35):
# one zone (labelNodePrepareStrategy zone1); 100 team=devops namespaces
# x 50 init pods; measured pods carry required zone-level podAffinity
# whose namespaceSelector picks team=devops — every placement feeds the
# one shared domain through namespace-unrolled terms.

def _ns_selector_aff_pod(i: int, ns: str) -> Pod:
    aff = Affinity(pod_affinity=PodAffinity(required=[
        PodAffinityTerm(
            topology_key=LABEL_ZONE,
            label_selector=LabelSelector(match_labels={"color": "blue"}),
            namespace_selector=LabelSelector(
                match_labels={"team": "devops"}))]))
    return _pod(f"nsaff-{ns}-{i}", namespace=ns, labels={"color": "blue"},
                affinity=aff)


def ns_selector_pod_affinity(init_nodes=5000, init_namespaces=100,
                             init_pods_per_ns=50,
                             measure_pods=2000) -> Workload:
    return Workload(
        name="SchedulingRequiredPodAffinityWithNSSelector"
             "/5000Nodes_2000Pods",
        threshold=35,
        pod_capacity=32768,
        ops=[
            CreateNodes(init_nodes, lambda i: _node(i, zones=["zone1"])),
            CreateNamespaces("init-ns", init_namespaces,
                             labels=lambda i: {"team": "devops"}),
            CreateNamespaces("measure-ns", 1,
                             labels=lambda i: {"team": "devops"}),
            CreatePods(init_namespaces * init_pods_per_ns,
                       lambda i: _ns_selector_aff_pod(
                           i, f"init-ns-{i % init_namespaces}")),
            CreatePods(measure_pods,
                       lambda i: _ns_selector_aff_pod(
                           i + 10**6, "measure-ns-0"),
                       collect_metrics=True),
        ])


# ------------------ 17. PreferredAffinityWithNSSelector
# affinity/performance-config.yaml:650-728 (5000Nodes_5000Pods, 90):
# same namespace layout; measured pods carry a weight-1 PREFERRED
# hostname affinity (red) with the devops namespaceSelector — pure Score
# work over namespace-unrolled terms.

def _ns_selector_pref_pod(i: int, ns: str) -> Pod:
    term = WeightedPodAffinityTerm(weight=1, pod_affinity_term=(
        PodAffinityTerm(
            topology_key=LABEL_HOSTNAME,
            label_selector=LabelSelector(match_labels={"color": "red"}),
            namespace_selector=LabelSelector(
                match_labels={"team": "devops"}))))
    aff = Affinity(pod_affinity=PodAffinity(preferred=[term]))
    return _pod(f"nspref-{ns}-{i}", namespace=ns, labels={"color": "red"},
                affinity=aff)


def ns_selector_preferred_affinity(init_nodes=5000, init_namespaces=100,
                                   init_pods_per_ns=50,
                                   measure_pods=5000) -> Workload:
    return Workload(
        name="SchedulingPreferredAffinityWithNSSelector"
             "/5000Nodes_5000Pods",
        threshold=900,   # ratcheted: 10x the reference 90 floor (ISSUE 15)
        baseline=90,
        pod_capacity=32768,
        warm_full_nodes=True,   # hostname topology: domains = nodes
        ops=[
            CreateNodes(init_nodes, _node),
            CreateNamespaces("init-ns", init_namespaces,
                             labels=lambda i: {"team": "devops"}),
            CreateNamespaces("measure-ns", 1,
                             labels=lambda i: {"team": "devops"}),
            CreatePods(init_namespaces * init_pods_per_ns,
                       lambda i: _ns_selector_pref_pod(
                           i, f"init-ns-{i % init_namespaces}")),
            CreatePods(measure_pods,
                       lambda i: _ns_selector_pref_pod(
                           i + 10**6, "measure-ns-0"),
                       collect_metrics=True),
        ])


# ---------- 18. SchedulingGatedPodsWithPodAffinityImpactForThroughput
# affinity/performance-config.yaml:731-800 (1Node_10000GatedPods, 110):
# 10k gated pods carrying required hostname affinity on the measured
# pods' label park in the gated pool; 20k app=scheduler-perf pods then
# bind to the single 90000-pod node (node-with-name.yaml). Every bind
# fires an AssignedPodAdd the gated pods' affinity COULD match — the
# throughput must survive the event volume (the park-index discipline).

def _gated_affinity_pod(i: int) -> Pod:
    from kubernetes_tpu.api.objects import PodSchedulingGate

    aff = Affinity(pod_affinity=PodAffinity(required=[
        PodAffinityTerm(
            topology_key=LABEL_HOSTNAME,
            label_selector=LabelSelector(
                match_labels={"app": "scheduler-perf"}))]))
    p = _pod(f"gated-{i}", cpu="0", mem="0",
             labels={"app": "scheduler-perf"}, affinity=aff)
    p.spec.scheduling_gates = [PodSchedulingGate(name="scheduling-gate-1")]
    return p


def _perf_node(i: int) -> Node:
    name = "scheduler-perf-node"
    return Node(
        metadata=ObjectMeta(name=name, labels={LABEL_HOSTNAME: name}),
        spec=NodeSpec(),
        status=NodeStatus(allocatable={
            "cpu": "4", "memory": "32Gi", "pods": "90000"}))


def gated_pods_with_pod_affinity(gated_pods=10000,
                                 measure_pods=20000) -> Workload:
    return Workload(
        name="SchedulingGatedPodsWithPodAffinityImpactForThroughput"
             "/1Node_10000GatedPods",
        threshold=110,
        node_capacity=64,
        pod_capacity=65536,
        batch_size=4096,
        ops=[
            CreateNodes(1, _perf_node),
            CreatePods(gated_pods, _gated_affinity_pod, wait=False),
            CreatePods(measure_pods,
                       lambda i: _pod(f"measure-{i}", cpu="0", mem="0",
                                      labels={"app": "scheduler-perf"}),
                       collect_metrics=True),
        ])


# ------------------------------ 19. PreferredTopologySpreading
# topology_spreading/performance-config.yaml:83-145 (5000Nodes_5000Pods,
# 125): three zones; measured pods carry a maxSkew=5 ScheduleAnyway zone
# constraint (pod-with-preferred-topology-spreading.yaml) — the SOFT
# spread Score path rather than the DoNotSchedule Filter.

def _preferred_spreading_pod(i: int) -> Pod:
    return _pod(f"pspread-{i}", labels={"color": "blue"}, tsc=[
        TopologySpreadConstraint(
            max_skew=5, topology_key=LABEL_ZONE,
            when_unsatisfiable="ScheduleAnyway",
            label_selector=LabelSelector(match_labels={"color": "blue"}))])


def preferred_topology_spreading(init_nodes=5000, init_pods=5000,
                                 measure_pods=5000) -> Workload:
    return Workload(
        name="PreferredTopologySpreading/5000Nodes_5000Pods",
        threshold=1250,  # ratcheted: 10x the reference 125 floor (ISSUE 15)
        baseline=125,
        pod_capacity=32768,
        ops=[
            CreateNodes(init_nodes, lambda i: _node(
                i, zones=["moon-1", "moon-2", "moon-3"])),
            CreatePods(init_pods, lambda i: _pod(f"init-{i}")),
            CreatePods(measure_pods, _preferred_spreading_pod,
                       collect_metrics=True),
        ])


# --------------------------- 20. SchedulingWithNodeInclusionPolicy
# topology_spreading/performance-config.yaml:210-273 (5000Nodes, 68):
# 4000 normal + 1000 tainted (foo:NoSchedule) nodes; measured pods carry
# a hostname DoNotSchedule spread with Honor/Honor inclusion policies
# (pod-with-node-inclusion-policy.yaml), so tainted nodes drop out of
# both the domain set and the skew accounting.

def _tainted_node(i: int) -> Node:
    from kubernetes_tpu.api.objects import Taint

    name = f"taint-node-{i}"
    return Node(
        metadata=ObjectMeta(name=name, labels={LABEL_HOSTNAME: name}),
        spec=NodeSpec(taints=[Taint(key="foo", value="",
                                    effect="NoSchedule")]),
        status=NodeStatus(allocatable={
            "cpu": "4", "memory": "32Gi", "pods": "110"}))


def _inclusion_policy_pod(i: int) -> Pod:
    from kubernetes_tpu.api.objects import POLICY_HONOR

    return _pod(f"incl-{i}", labels={"foo": "bar"}, tsc=[
        TopologySpreadConstraint(
            max_skew=1, topology_key=LABEL_HOSTNAME,
            when_unsatisfiable="DoNotSchedule",
            node_affinity_policy=POLICY_HONOR,
            node_taints_policy=POLICY_HONOR,
            label_selector=LabelSelector(match_labels={"foo": "bar"}))])


def scheduling_with_node_inclusion_policy(normal_nodes=4000,
                                          taint_nodes=1000,
                                          measure_pods=4000) -> Workload:
    return Workload(
        name="SchedulingWithNodeInclusionPolicy/5000Nodes",
        threshold=68,
        pod_capacity=16384,
        warm_full_nodes=True,   # hostname topology: domains = nodes
        ops=[
            CreateNodes(normal_nodes, _node),
            CreateNodes(taint_nodes, _tainted_node),
            CreatePods(measure_pods, _inclusion_policy_pod,
                       collect_metrics=True),
        ])


# ------------------------------ 21. Unschedulable (QHints enabled)
# misc/performance-config.yaml:324 (170 with SchedulerQueueingHints):
# same shape as Unschedulable, floor raised — the hints must prove they
# keep the parked 9-CPU pods from re-entering on irrelevant events.

def unschedulable_qhints(init_nodes=5000, init_pods=100,
                         measure_pods=10000) -> Workload:
    w = unschedulable(init_nodes, init_pods, measure_pods)
    w.name = "Unschedulable/5kNodes_100Init_10kPods_QueueingHintsEnabled"
    w.threshold = w.baseline = 170
    w.feature_gates = {"SchedulerQueueingHints": True}
    return w


# ------------------------------ 22. SchedulingBasic (QHints enabled)
# misc/performance-config.yaml:72 (270): the headline shape with
# SchedulerQueueingHints pinned on — its own thresholded reference row
# (the gate defaults on here, but the variant is measured separately so
# a hints regression shows up against its own floor).

def scheduling_basic_qhints(init_nodes=5000, init_pods=1000,
                            measure_pods=10000) -> Workload:
    w = scheduling_basic(init_nodes, init_pods, measure_pods)
    w.name = "SchedulingBasic/5000Nodes_10000Pods_QueueingHintsEnabled"
    w.threshold = w.baseline = 270
    w.feature_gates = {"SchedulerQueueingHints": True}
    return w


# ------------------------------ 23. PreemptionAsync (async enabled)
# misc/performance-config.yaml:247 (160): the preemption shape with
# SchedulerAsyncPreemption pinned on — victims are evicted between
# cycles (kep 4832) instead of inside the failure handler.

def preemption_async_enabled(init_nodes=5000, init_pods=20000,
                             measure_pods=5000) -> Workload:
    w = preemption_async(init_nodes, init_pods, measure_pods)
    w.name = "PreemptionAsync/5000Nodes_AsyncPreemptionEnabled"
    w.feature_gates = {"SchedulerAsyncPreemption": True}
    return w


# ------------------ 24. PreferredAntiAffinityWithNSSelector
# affinity/performance-config.yaml:488-557 (5000Nodes_2000Pods, 55):
# the namespace-selector layout with a weight-1 PREFERRED hostname
# ANTI-affinity term — soft avoidance Score work over
# namespace-unrolled terms.

def _ns_selector_pref_anti_pod(i: int, ns: str) -> Pod:
    term = WeightedPodAffinityTerm(weight=1, pod_affinity_term=(
        PodAffinityTerm(
            topology_key=LABEL_HOSTNAME,
            label_selector=LabelSelector(match_labels={"color": "teal"}),
            namespace_selector=LabelSelector(
                match_labels={"team": "sched"}))))
    aff = Affinity(pod_anti_affinity=PodAntiAffinity(preferred=[term]))
    return _pod(f"nspanti-{ns}-{i}", namespace=ns,
                labels={"color": "teal"}, affinity=aff)


def ns_selector_preferred_anti_affinity(init_nodes=5000, init_pods=1000,
                                        measure_pods=2000,
                                        namespaces=10) -> Workload:
    return Workload(
        name="SchedulingPreferredAntiAffinityWithNSSelector"
             "/5000Nodes_2000Pods",
        threshold=550,   # ratcheted: 10x the reference 55 floor (ISSUE 15)
        baseline=55,
        pod_capacity=32768,
        warm_full_nodes=True,   # hostname topology: domains = nodes
        ops=[
            CreateNodes(init_nodes, _node),
            CreateNamespaces("team", namespaces,
                             labels=lambda i: {"team": "sched"}),
            CreatePods(init_pods,
                       lambda i: _ns_selector_pref_anti_pod(
                           i, f"team-{i % namespaces}")),
            CreatePods(measure_pods,
                       lambda i: _ns_selector_pref_anti_pod(
                           i + 10**6, f"team-{i % namespaces}"),
                       collect_metrics=True),
        ])


# ------------------------------------- 26-28. gang / multi-tenant (ISSUE 6)
# The multi-tenant job-storm workload class the gang subsystem opens
# (Kant, PAPERS.md): PodGroups with mixed gang sizes 2-64 across weighted
# tenants, quota exhaustion that must not starve other tenants, and
# priority preemption of whole gangs. No reference floors exist for
# these — the thresholds are OUR floors, set from the first measured
# round and ratcheted like the rest of the table. All three carry a
# ``rescale`` hook: op counts must stay gang-aligned, so the harness's
# uniform per-op warmup scaling would strand partial gangs behind
# min_member; the factory rebuilds the whole workload at the requested
# scale instead (capacities/batch stay identical, preserving jit shapes).

GANG_SIZES = (2, 4, 8, 16, 32, 64)


def _gang_member(name: str, gang: str, tenant: str, cpu: str = "100m",
                 priority: int | None = None) -> Pod:
    p = _pod(name, cpu=cpu, mem="200Mi", priority=priority)
    p.metadata.labels[LABEL_POD_GROUP] = gang
    p.metadata.labels[LABEL_QUEUE] = tenant
    return p


def _tenant_pod(name: str, tenant: str, cpu: str = "100m") -> Pod:
    p = _pod(name, cpu=cpu, mem="200Mi")
    p.metadata.labels[LABEL_QUEUE] = tenant
    return p


def multi_tenant_gang_storm(init_nodes=500,
                            gangs_per_tenant=24) -> Workload:
    """Two weighted tenants (2:1), mixed gang sizes 2-64: every gang
    admits whole through the DRR queue and commits through Permit; the
    artifact's per-tenant ``contended_admitted`` ratio is the fairness
    number (≈ the weight ratio while both tenants have backlog)."""
    plan = []        # (gang name, tenant, size)
    for tenant in ("tenant-a", "tenant-b"):
        for g in range(gangs_per_tenant):
            plan.append((f"{tenant}-job-{g}", tenant,
                         GANG_SIZES[g % len(GANG_SIZES)]))
    members = [(f"{gang}-m{m}", gang, tenant)
               for gang, tenant, size in plan for m in range(size)]

    def mkgroup(i: int) -> PodGroup:
        gang, tenant, size = plan[i]
        return PodGroup(metadata=ObjectMeta(name=gang),
                        min_member=size, queue=tenant,
                        schedule_timeout_seconds=120.0)

    def mkpod(i: int) -> Pod:
        name, gang, tenant = members[i]
        return _gang_member(name, gang, tenant)

    return Workload(
        name="MultiTenantGangStorm/500Nodes",
        threshold=25,
        node_capacity=512,     # tracks the 500-node cluster (ISSUE-12)
        batch_size=1024,
        tenants={"tenant-a": {"weight": 2.0},
                 "tenant-b": {"weight": 1.0}},
        ops=[
            CreateNodes(init_nodes, _node),
            CreateObjects(len(plan), mkgroup,
                          create_verb="create_pod_group"),
            CreatePods(len(members), mkpod, collect_metrics=True),
        ],
        rescale=lambda s: multi_tenant_gang_storm(
            init_nodes=max(8, int(init_nodes * s)),
            gangs_per_tenant=max(1, int(gangs_per_tenant * s))))


def quota_exhaustion_churn(init_nodes=200, blocked_pods=400,
                           quota_pods=100, measure_pods=2000) -> Workload:
    """A burst tenant whose demand exceeds its pod quota (only
    ``quota_pods`` admit; the rest hold in its job queue) while an
    unconstrained steady tenant's measured pods must flow at full rate —
    the "blocked tenants don't starve others" criterion."""
    return Workload(
        name="QuotaExhaustionChurn/200Nodes",
        threshold=150,
        # bucket tracks the 200-node cluster: a 1024-row bucket made
        # every [B, N] auction round pay 5x dead-row work (ISSUE-12)
        node_capacity=256,
        batch_size=1024,
        tenants={"burst": {"quota": {"pods": str(quota_pods)}},
                 "steady": {}},
        ops=[
            CreateNodes(init_nodes, _node),
            CreatePods(blocked_pods,
                       lambda i: _tenant_pod(f"burst-{i}", "burst"),
                       wait=False),    # over-quota tail never schedules
            CreatePods(measure_pods,
                       lambda i: _tenant_pod(f"steady-{i}", "steady"),
                       collect_metrics=True),
        ],
        rescale=lambda s: quota_exhaustion_churn(
            init_nodes=max(8, int(init_nodes * s)),
            blocked_pods=max(4, int(blocked_pods * s)),
            quota_pods=max(1, int(quota_pods * s)),
            measure_pods=max(4, int(measure_pods * s))))


def gang_preemption(init_nodes=128, high_gangs=24) -> Workload:
    """Whole-gang priority preemption: low-priority gangs of 4 saturate
    the cluster's CPU; measured high-priority gangs of 4 must evict
    ENTIRE lower gangs (never a slice) to land — the eviction path runs
    through the fenced flush + _expand_gang_victims."""
    low_gangs = init_nodes               # 4 x 900m per 4-cpu node
    low = [(f"low-{g}-m{m}", f"low-{g}") for g in range(low_gangs)
           for m in range(4)]
    high = [(f"high-{g}-m{m}", f"high-{g}") for g in range(high_gangs)
            for m in range(4)]

    def mkgroup(i: int) -> PodGroup:
        if i < low_gangs:
            name, prio = f"low-{i}", 0
        else:
            name, prio = f"high-{i - low_gangs}", 10
        return PodGroup(metadata=ObjectMeta(name=name), min_member=4,
                        queue="jobs", priority=prio,
                        schedule_timeout_seconds=120.0)

    return Workload(
        name="GangPreemption/128Nodes",
        # ratcheted off the r15 lock (220) by pipelined waves
        # (BENCH_r19): preemptor re-probes ride the next wave the
        # moment the eviction flush fires (activation instead of
        # backoff routing), attacking exactly the victim-drain-latency
        # residue r15 documented. Paired same-box A/B best-of-3 reads
        # 5.19x (on 421.0 vs off 81.2 pods/s; even the WORST on-arm
        # sample beats the best off-arm 3.7x, and the win is wait
        # elimination, not CPU, so it does not ride the box's throttle)
        threshold=800,
        baseline=30,
        node_capacity=256,
        batch_size=512,
        ops=[
            CreateNodes(init_nodes, _node),
            CreateObjects(low_gangs + high_gangs, mkgroup,
                          create_verb="create_pod_group"),
            CreatePods(len(low),
                       lambda i: _gang_member(low[i][0], low[i][1],
                                              "jobs", cpu="900m")),
            CreatePods(len(high),
                       lambda i: _gang_member(high[i][0], high[i][1],
                                              "jobs", cpu="900m",
                                              priority=10),
                       collect_metrics=True),
        ],
        rescale=lambda s: gang_preemption(
            init_nodes=max(4, int(init_nodes * s)),
            high_gangs=max(1, int(high_gangs * s))))


def _colocation_validate(hub, result) -> None:
    """GangTopologyPacking's acceptance criterion: members of each gang
    land topology-close. Computes per-gang zone spans from the final
    placements and RAISES when the mean strays — the device packer's
    domain-major fill keeps each fitting gang inside one zone, while a
    per-member spreading placement would scatter it."""
    node_zone = {n.metadata.name: n.metadata.labels.get(LABEL_ZONE)
                 for n in hub.list_nodes()}
    by_gang: dict[str, set] = {}
    for p in hub.list_pods():
        g = p.metadata.labels.get(LABEL_POD_GROUP)
        if g and p.spec.node_name:
            by_gang.setdefault(g, set()).add(node_zone.get(p.spec.node_name))
    spans = sorted(len(z) for z in by_gang.values())
    assert spans, "no gang placed anything"
    mean = sum(spans) / len(spans)
    result["colocation"] = {
        "gangs": len(spans),
        "mean_zone_spans": round(mean, 3),
        "max_zone_spans": spans[-1],
        "one_zone_frac": round(
            sum(1 for s in spans if s == 1) / len(spans), 3),
    }
    assert mean <= 1.5, \
        f"gang members not topology-close: mean zone spans {mean:.2f}"


def gang_topology_packing(init_nodes=96, zones=8, gangs=8) -> Workload:
    """Zoned cluster, gangs sized to FIT one zone, cluster at half
    demand: every gang must land topology-close (the validate hook
    asserts mean zone spans <= 1.5 — the device packer's domain-major
    fill puts each gang in ONE zone, where per-member least-allocated
    spreading would scatter it across the cluster)."""
    nodes_per_zone = max(1, init_nodes // zones)
    zone_cap = nodes_per_zone * 4           # 900m members on 4-cpu nodes
    size = max(2, zone_cap // 2)            # each gang fits half a zone
    zone_names = [f"zone-{z}" for z in range(zones)]

    def mkgroup(i: int) -> PodGroup:
        return PodGroup(metadata=ObjectMeta(name=f"pack-{i}"),
                        min_member=size, queue="jobs",
                        schedule_timeout_seconds=120.0)

    def mkpod(i: int) -> Pod:
        return _gang_member(f"pack-{i // size}-m{i % size}",
                            f"pack-{i // size}", "jobs", cpu="900m")

    return Workload(
        name="GangTopologyPacking/96Nodes",
        # our own floor (first-round cpu measurement ~570 pods/s; the
        # real acceptance gate is the validate hook's co-location bound)
        threshold=150,
        node_capacity=128,
        batch_size=512,
        ops=[
            CreateNodes(init_nodes, lambda i: _node(i, zone_names)),
            CreateObjects(gangs, mkgroup,
                          create_verb="create_pod_group"),
            CreatePods(gangs * size, mkpod, collect_metrics=True),
        ],
        validate=_colocation_validate,
        rescale=lambda s: gang_topology_packing(
            init_nodes=max(zones * 2, int(init_nodes * s)),
            zones=zones,
            gangs=max(2, int(gangs * s))))


# every thresholded reference workload — bench.py runs the whole list,
# one subprocess each, and publishes every row in its JSON (bench.py
# mirrors these BY NAME in BENCH_WORKLOAD_FNS —
# tests/test_perf_harness.py asserts the two stay in sync). The first
# five are the BASELINE.json headline configs; the last three are the
# VERDICT r05 "still unmeasured" thresholded variants.
BENCH_WORKLOADS = (
    scheduling_basic,
    scheduling_node_affinity,
    scheduling_pod_anti_affinity,
    topology_spreading,
    preemption_async,
    unschedulable,
    unschedulable_qhints,
    mixed_churn,
    scheduling_daemonset,
    scheduling_while_gated,
    preferred_pod_affinity,
    preferred_pod_anti_affinity,
    ns_selector_anti_affinity,
    dra_steady_state,
    dra_steady_state_templates,
    dra_steady_state_cel_in,
    dra_multi_request,
    scheduling_pod_affinity,
    mixed_scheduling_base_pod,
    ns_selector_pod_affinity,
    ns_selector_preferred_affinity,
    gated_pods_with_pod_affinity,
    preferred_topology_spreading,
    scheduling_with_node_inclusion_policy,
    scheduling_basic_qhints,
    preemption_async_enabled,
    ns_selector_preferred_anti_affinity,
    multi_tenant_gang_storm,
    quota_exhaustion_churn,
    gang_preemption,
    gang_topology_packing,
)

ALL_WORKLOADS = BENCH_WORKLOADS

# the ROADMAP's sub-10x offenders — the `bench.py --profile` set: each
# runs with the flight recorder's phase attribution in the artifact.
# Both DRA steady-state rows ride along so the batched device allocator
# (ops/dra.py) keeps its host-tail collapse visible per phase.
PROFILE_WORKLOADS = (
    "scheduling_daemonset",
    "mixed_churn",
    # the preferred-scoring band (ISSUE 15): soft terms now run fused in
    # the auction — the per-phase rows prove the host tail stays burned
    # down
    "preferred_pod_anti_affinity",
    "preferred_topology_spreading",
    "ns_selector_preferred_affinity",
    "ns_selector_preferred_anti_affinity",
    "dra_steady_state",
    "dra_steady_state_templates",
    # the whole gang suite rides the per-phase attribution + the
    # DeviceProfiler's device column (ISSUE-12: launches per gang must
    # read O(1), gang-shape compiles attributed); bench --profile
    # additionally runs the fanout smoke for the fabric-side numbers
    "multi_tenant_gang_storm",
    "quota_exhaustion_churn",
    "gang_preemption",
    "gang_topology_packing",
)
