"""The reference scheduler_perf workloads, mirroring performance-config
shapes (node/pod templates from test/integration/scheduler_perf/templates;
op sequences and thresholds from the per-suite performance-config.yaml):
the 5 BASELINE.json configs bench.py runs, plus Unschedulable,
SchedulingWithMixedChurn, SchedulingDaemonset, SchedulingWhileGated, and
the preferred pod-(anti)affinity pair — 11 reference configs total.

Node template (node-default.yaml): cpu 4, memory 32Gi, pods 110.
Pod template (pod-default.yaml): requests cpu 100m, memory 500Mi.
"""

from __future__ import annotations

from kubernetes_tpu.api.objects import (
    Affinity,
    Container,
    LABEL_HOSTNAME,
    LABEL_ZONE,
    LabelSelector,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    ResourceRequirements,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from kubernetes_tpu.perf.harness import (
    Churn,
    CreateNamespaces,
    CreateNodes,
    CreateObjects,
    CreatePods,
    Workload,
)


def _node(i: int, zones: list[str] | None = None) -> Node:
    """node-default.yaml + labelNodePrepareStrategy zone labels."""
    name = f"node-{i}"
    labels = {LABEL_HOSTNAME: name}
    if zones:
        labels[LABEL_ZONE] = zones[i % len(zones)]
    return Node(
        metadata=ObjectMeta(name=name, labels=labels),
        spec=NodeSpec(),
        status=NodeStatus(allocatable={
            "cpu": "4", "memory": "32Gi", "pods": "110"}))


def _pod(name: str, cpu: str = "100m", mem: str = "500Mi",
         namespace: str = "default", labels: dict | None = None,
         affinity: Affinity | None = None, tsc: list | None = None,
         priority: int | None = None) -> Pod:
    return Pod(
        metadata=ObjectMeta(name=name, namespace=namespace,
                            labels=labels or {}),
        spec=PodSpec(
            containers=[Container(
                name="pause",
                resources=ResourceRequirements(
                    requests={"cpu": cpu, "memory": mem}))],
            affinity=affinity,
            topology_spread_constraints=tsc or [],
            priority=priority))


# ------------------------------------------------- 1. SchedulingBasic
# misc/performance-config.yaml:40-66 (5000Nodes_10000Pods, threshold 270)

def scheduling_basic(init_nodes=5000, init_pods=1000,
                     measure_pods=10000) -> Workload:
    return Workload(
        name="SchedulingBasic/5000Nodes_10000Pods",
        threshold=270,
        batch_size=4096,   # auction path: bigger launches amortize better
        ops=[
            CreateNodes(init_nodes, _node),
            CreatePods(init_pods, lambda i: _pod(f"init-{i}")),
            CreatePods(measure_pods, lambda i: _pod(f"measure-{i}"),
                       collect_metrics=True),
        ])


# ------------------------------------------- 2. SchedulingNodeAffinity
# affinity/performance-config.yaml:280-330 (5000Nodes_10000Pods, 220):
# nodes labeled zone1; measured pods require zone In [zone1, zone2]
# (pod-with-node-affinity.yaml); scoring includes BalancedAllocation via
# the default plugin set.

def _node_affinity_pod(i: int) -> Pod:
    aff = Affinity(node_affinity=NodeAffinity(required=NodeSelector(
        node_selector_terms=[NodeSelectorTerm(match_expressions=[
            NodeSelectorRequirement(key=LABEL_ZONE, operator="In",
                                    values=["zone1", "zone2"])])])))
    return _pod(f"na-{i}", affinity=aff)


def scheduling_node_affinity(init_nodes=5000, init_pods=5000,
                             measure_pods=10000) -> Workload:
    return Workload(
        name="SchedulingNodeAffinity/5000Nodes_10000Pods",
        threshold=220,
        pod_capacity=32768,
        batch_size=4096,   # auction path
        ops=[
            CreateNodes(init_nodes, lambda i: _node(i, zones=["zone1"])),
            CreatePods(init_pods, lambda i: _pod(f"init-{i}")),
            CreatePods(measure_pods, _node_affinity_pod,
                       collect_metrics=True),
        ])


# --------------------------------------- 3. SchedulingPodAntiAffinity
# affinity/performance-config.yaml:20-70 (5000Nodes_2000Pods, 60):
# 2 namespaces; pods labeled color=green with required hostname
# anti-affinity across both namespaces
# (pod-with-pod-anti-affinity.yaml).

def _anti_affinity_pod(i: int, ns: str) -> Pod:
    aff = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
        PodAffinityTerm(
            topology_key=LABEL_HOSTNAME,
            label_selector=LabelSelector(match_labels={"color": "green"}),
            namespaces=["sched-1", "sched-0"])]))
    return _pod(f"anti-{ns}-{i}", namespace=ns,
                labels={"color": "green"}, affinity=aff)


def scheduling_pod_anti_affinity(init_nodes=5000, init_pods=1000,
                                 measure_pods=2000) -> Workload:
    return Workload(
        name="SchedulingPodAntiAffinity/5000Nodes_2000Pods",
        threshold=60,
        warm_full_nodes=True,   # hostname anti-affinity: domains = nodes
        ops=[
            CreateNodes(init_nodes, _node),
            CreateNamespaces("sched", 2),
            CreatePods(init_pods,
                       lambda i: _anti_affinity_pod(i, "sched-0")),
            CreatePods(measure_pods,
                       lambda i: _anti_affinity_pod(i, "sched-1"),
                       collect_metrics=True),
        ])


# ------------------------------------------- 4. TopologySpreading
# topology_spreading/performance-config.yaml:21-70 (5000Nodes_5000Pods,
# 85): nodes across 3 zones; measured pods spread maxSkew=5 on zone
# (pod-with-topology-spreading.yaml).

def _spreading_pod(i: int) -> Pod:
    tsc = [TopologySpreadConstraint(
        max_skew=5, topology_key=LABEL_ZONE,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels={"color": "blue"}))]
    return _pod(f"spread-{i}", labels={"color": "blue"}, tsc=tsc)


def topology_spreading(init_nodes=5000, init_pods=5000,
                       measure_pods=5000) -> Workload:
    return Workload(
        name="TopologySpreading/5000Nodes_5000Pods",
        threshold=85,
        pod_capacity=32768,
        ops=[
            CreateNodes(init_nodes, lambda i: _node(
                i, zones=["moon-1", "moon-2", "moon-3"])),
            CreatePods(init_pods, lambda i: _pod(f"init-{i}")),
            CreatePods(measure_pods, _spreading_pod, collect_metrics=True),
        ])


# ------------------------------------------- 5. PreemptionAsync
# misc/performance-config.yaml:195-250 (5000Nodes, 160): 20k low-priority
# 900m fillers (4 per 4-CPU node), churn creating a 3000m priority-10 pod
# every 200ms (each must preempt 3 fillers), 5000 always-schedulable
# 100m measured pods.

def _low_priority_pod(i: int) -> Pod:
    return _pod(f"low-{i}", cpu="900m", mem="500Mi")


def _high_priority_pod(i: int) -> Pod:
    return _pod(f"high-{i}", cpu="3000m", mem="500Mi", priority=10)


def preemption_async(init_nodes=5000, init_pods=20000,
                     measure_pods=5000) -> Workload:
    return Workload(
        name="PreemptionAsync/5000Nodes",
        threshold=160,
        pod_capacity=32768,
        ops=[
            CreateNodes(init_nodes, _node),
            CreatePods(init_pods, _low_priority_pod),
            Churn([_high_priority_pod], interval_ms=200),
            CreatePods(measure_pods, lambda i: _pod(f"measure-{i}"),
                       collect_metrics=True),
        ])


# ------------------------------------------- 6. Unschedulable
# misc/performance-config.yaml:280+ (5kNodes/100Init/10kPods, 140): a
# 200ms churn of 9-CPU high-priority pods that can NEVER fit a 4-CPU node
# parks in the unschedulable pool; the measured default pods must flow
# past them (the queueing-hint discipline this workload exists to test).

def _large_cpu_pod(i: int) -> Pod:
    return _pod(f"big-{i}", cpu="9", mem="500Mi", priority=10)


def unschedulable(init_nodes=5000, init_pods=100,
                  measure_pods=10000) -> Workload:
    return Workload(
        name="Unschedulable/5kNodes_100Init_10kPods",
        threshold=140,
        ops=[
            CreateNodes(init_nodes, _node),
            CreatePods(init_pods, lambda i: _pod(f"init-{i}")),
            Churn([_large_cpu_pod], interval_ms=200),
            CreatePods(measure_pods, lambda i: _pod(f"measure-{i}"),
                       collect_metrics=True),
        ])


# ------------------------------------- 7. SchedulingWithMixedChurn
# misc/performance-config.yaml:360+ (5000Nodes_10000Pods, 265): a 1s
# recreate-churn of {node, unschedulable high-priority pod} while 10k
# default pods schedule (the reference's template set also recreates a
# Service, which has no scheduler-visible effect here).

def _churn_node(i: int) -> object:
    return _node(100000 + i)


def mixed_churn(init_nodes=5000, measure_pods=10000) -> Workload:
    return Workload(
        name="SchedulingWithMixedChurn/5000Nodes_10000Pods",
        threshold=265,
        ops=[
            CreateNodes(init_nodes, _node),
            Churn([_churn_node, _large_cpu_pod], interval_ms=1000,
                  mode="recreate"),
            CreatePods(measure_pods, lambda i: _pod(f"measure-{i}"),
                       collect_metrics=True),
        ])


# --------------------------------------------- 8. SchedulingDaemonset
# misc/performance-config.yaml:100-128 (15000Nodes, 390): one pod per node,
# pinned the way the daemonset controller pins them — a required
# nodeAffinity matchFields term on metadata.name (the scheduler still runs
# the full pipeline; NodeAffinity's PreFilter narrows to the one node).

def _daemonset_pod(i: int) -> Pod:
    aff = Affinity(node_affinity=NodeAffinity(required=NodeSelector(
        node_selector_terms=[NodeSelectorTerm(match_fields=[
            NodeSelectorRequirement(key="metadata.name", operator="In",
                                    values=[f"node-{i}"])])])))
    return _pod(f"ds-{i}", cpu="100m", mem="200Mi", affinity=aff)


def scheduling_daemonset(init_nodes=15000, measure_pods=15000) -> Workload:
    return Workload(
        name="SchedulingDaemonset/15000Nodes",
        threshold=390,
        node_capacity=16384,
        pod_capacity=32768,
        ops=[
            CreateNodes(init_nodes, _node),
            CreatePods(measure_pods, _daemonset_pod,
                       collect_metrics=True),
        ],
        # matchFields pin per pod: every pod is its own topology-free spec;
        # warmup must see the same node bucket so the full-size node table
        # compiles up front
        warm_full_nodes=True)


# ------------------------------------------- 9. SchedulingWhileGated
# misc/performance-config.yaml:425-460 (1Node_10000GatedPods, 130): 10k
# permanently gated pods park in unschedulablePods; 10k plain pods then
# schedule onto one huge node — measures that the gated pool costs the
# hot path nothing (PreEnqueue gate + no requeue events).

def _gated_pod(i: int) -> Pod:
    from kubernetes_tpu.api.objects import PodSchedulingGate

    p = _pod(f"gated-{i}", cpu="1m", mem="1Mi")
    p.spec.scheduling_gates = [PodSchedulingGate(name="example.com/hold")]
    return p


def _big_node(i: int) -> Node:
    name = f"node-{i}"
    return Node(
        metadata=ObjectMeta(name=name, labels={LABEL_HOSTNAME: name}),
        spec=NodeSpec(),
        status=NodeStatus(allocatable={
            "cpu": "4000", "memory": "64Ti", "pods": "30000"}))


def scheduling_while_gated(gated_pods=10000, measure_pods=10000) -> Workload:
    return Workload(
        name="SchedulingWhileGated/1Node_10000GatedPods",
        threshold=130,
        node_capacity=64,
        pod_capacity=32768,
        ops=[
            CreateNodes(1, _big_node),
            CreatePods(gated_pods, _gated_pod, wait=False),
            CreatePods(measure_pods, lambda i: _pod(f"measure-{i}",
                                                    cpu="1m", mem="1Mi"),
                       collect_metrics=True),
        ])


# -------------------------------- 10/11. Preferred pod (anti)affinity
# affinity/performance-config.yaml:141-198 / :204-261
# (SchedulingPreferredPodAffinity / ...AntiAffinity, 5000Nodes_5000Pods,
# both 90): soft zone-level terms — pure Score work, the weighted
# preferred-term kernel (scoring.go:35) rather than the Filter path.

def _preferred_affinity_pod(i: int, anti: bool) -> Pod:
    term = WeightedPodAffinityTerm(weight=10, pod_affinity_term=(
        PodAffinityTerm(
            topology_key=LABEL_ZONE,
            label_selector=LabelSelector(match_labels={"team": "perf"}))))
    aff = (Affinity(pod_anti_affinity=PodAntiAffinity(preferred=[term]))
           if anti else
           Affinity(pod_affinity=PodAffinity(preferred=[term])))
    kind = "panti" if anti else "paff"
    return _pod(f"{kind}-{i}", labels={"team": "perf"}, affinity=aff)


def preferred_pod_affinity(init_nodes=5000, init_pods=1000,
                           measure_pods=5000) -> Workload:
    return Workload(
        name="SchedulingPreferredPodAffinity/5000Nodes_5000Pods",
        threshold=90,
        pod_capacity=32768,
        ops=[
            CreateNodes(init_nodes,
                        lambda i: _node(i, zones=["z1", "z2", "z3"])),
            CreatePods(init_pods, lambda i: _pod(f"init-{i}")),
            CreatePods(measure_pods,
                       lambda i: _preferred_affinity_pod(i, anti=False),
                       collect_metrics=True),
        ])


def preferred_pod_anti_affinity(init_nodes=5000, init_pods=1000,
                                measure_pods=5000) -> Workload:
    return Workload(
        name="SchedulingPreferredPodAntiAffinity/5000Nodes_5000Pods",
        threshold=90,
        pod_capacity=32768,
        ops=[
            CreateNodes(init_nodes,
                        lambda i: _node(i, zones=["z1", "z2", "z3"])),
            CreatePods(init_pods, lambda i: _pod(f"init-{i}")),
            CreatePods(measure_pods,
                       lambda i: _preferred_affinity_pod(i, anti=True),
                       collect_metrics=True),
        ])


# ------------------- 12. RequiredPodAntiAffinityWithNSSelector
# affinity/performance-config.yaml:425-480 (5000Nodes_2000Pods, 24 — the
# LOWEST floor in the reference's affinity suite): measured pods carry
# required hostname anti-affinity whose namespaceSelector picks out the
# team's namespaces, so the match set spans namespaces selected by LABEL.

def _ns_selector_anti_pod(i: int, ns: str) -> Pod:
    aff = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
        PodAffinityTerm(
            topology_key=LABEL_HOSTNAME,
            label_selector=LabelSelector(match_labels={"color": "teal"}),
            namespace_selector=LabelSelector(
                match_labels={"team": "sched"}))]))
    return _pod(f"nsanti-{ns}-{i}", namespace=ns,
                labels={"color": "teal"}, affinity=aff)


def ns_selector_anti_affinity(init_nodes=5000, init_pods=1000,
                              measure_pods=2000, namespaces=10) -> Workload:
    return Workload(
        name="SchedulingRequiredPodAntiAffinityWithNSSelector"
             "/5000Nodes_2000Pods",
        threshold=24,
        warm_full_nodes=True,   # hostname topology: domains = nodes
        ops=[
            CreateNodes(init_nodes, _node),
            CreateNamespaces("team", namespaces,
                             labels=lambda i: {"team": "sched"}),
            CreatePods(init_pods,
                       lambda i: _ns_selector_anti_pod(
                           i, f"team-{i % namespaces}")),
            CreatePods(measure_pods,
                       lambda i: _ns_selector_anti_pod(
                           i + 10**6, f"team-{i % namespaces}"),
                       collect_metrics=True),
        ])


# --------------------------- 13. DRA steady-state claim scheduling
# dra/performance-config.yaml:60-110 (SteadyStateClusterClaimTemplate,
# ~100 nodes, floor ~50): every node publishes a ResourceSlice of
# devices; each measured pod carries its own single-device ResourceClaim
# which the DynamicResources host plugin allocates at Reserve and
# persists through PreBind — the reference's own accelerator path.

def _dra_node(i: int) -> Node:
    name = f"node-{i}"
    return Node(metadata=ObjectMeta(name=name,
                                    labels={LABEL_HOSTNAME: name}),
                spec=NodeSpec(),
                status=NodeStatus(allocatable={
                    "cpu": "16", "memory": "64Gi", "pods": "110"}))


def _dra_slice(i: int):
    from kubernetes_tpu.api.objects import Device, ResourceSlice

    node = f"node-{i}"
    return ResourceSlice(
        metadata=ObjectMeta(name=f"slice-{node}"),
        node_name=node, driver="tpu.example.com", pool=node,
        devices=[Device(name=f"dev-{d}", device_class_name="tpu")
                 for d in range(8)])


def _dra_claim(i: int):
    from kubernetes_tpu.api.objects import (
        DeviceRequest,
        ResourceClaim,
        ResourceClaimSpec,
    )

    return ResourceClaim(
        metadata=ObjectMeta(name=f"dra-claim-{i}"),
        spec=ResourceClaimSpec(device_requests=[
            DeviceRequest(name="accel", device_class_name="tpu",
                          count=1)]))


def _dra_pod(i: int) -> Pod:
    from kubernetes_tpu.api.objects import PodResourceClaim

    p = _pod(f"dra-{i}", cpu="100m", mem="200Mi")
    p.spec.resource_claims = [PodResourceClaim(
        name="accel", resource_claim_name=f"dra-claim-{i}")]
    return p


def dra_steady_state(init_nodes=100, measure_pods=500) -> Workload:
    return Workload(
        name="DRASteadyState/100Nodes_500Pods",
        threshold=50,
        node_capacity=128,
        pod_capacity=2048,
        batch_size=256,
        ops=[
            CreateNodes(init_nodes, _dra_node),
            CreateObjects(init_nodes, _dra_slice,
                          create_verb="create_resource_slice"),
            CreateObjects(measure_pods, _dra_claim,
                          create_verb="create_resource_claim"),
            CreatePods(measure_pods, _dra_pod, collect_metrics=True),
        ])


# the 5 BASELINE.json configs bench.py runs within the driver's budget
# (bench.py shells out per workload and mirrors these BY NAME in its
# BENCH_WORKLOAD_FNS — tests/test_perf_harness.py asserts the two stay
# in sync)
BENCH_WORKLOADS = (
    scheduling_basic,
    scheduling_node_affinity,
    scheduling_pod_anti_affinity,
    topology_spreading,
    preemption_async,
)

# the full suite (python -c "...run any of these on demand")
ALL_WORKLOADS = BENCH_WORKLOADS + (
    unschedulable,
    mixed_churn,
    scheduling_daemonset,
    scheduling_while_gated,
    preferred_pod_affinity,
    preferred_pod_anti_affinity,
    ns_selector_anti_affinity,
    dra_steady_state,
)
