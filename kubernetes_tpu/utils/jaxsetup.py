"""Process-wide JAX configuration for the scheduler runtime.

XLA compilation on the target environment is expensive (seconds per program,
including trivial ones), while cached executions are microseconds. The
framework therefore (a) funnels all per-cycle math through a small number of
large jitted programs keyed by static capacity buckets, and (b) enables the
persistent compilation cache so restarts skip recompiles entirely.
"""

from __future__ import annotations

import os

_done = False


def setup(cache_dir: str | None = None) -> None:
    global _done
    if _done:
        return
    import jax

    default = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        ".jax_cache")
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("KTPU_JAX_CACHE") or cache_dir or default,
    )
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    _done = True
