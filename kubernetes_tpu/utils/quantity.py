"""Kubernetes-style resource quantity parsing.

The reference models quantities with ``k8s.io/apimachinery/pkg/api/resource``
(arbitrary-precision decimal + binary/decimal SI suffixes). The scheduler only
ever needs integer milli-CPU and integer byte counts, so we parse directly to
ints (reference usage: ``pkg/scheduler/framework/types.go:846`` Resource —
MilliCPU/Memory/EphemeralStorage int64).

Supported syntax: plain integers/decimals ("2", "0.5"), exponents ("129e6"),
milli suffix ("500m"), decimal SI (k, M, G, T, P, E) and binary SI
(Ki, Mi, Gi, Ti, Pi, Ei).
"""

from __future__ import annotations

import math
from decimal import Decimal, InvalidOperation

from kubernetes_tpu.native import mod as _native

_BINARY = {"Ki": 1024, "Mi": 1024**2, "Gi": 1024**3, "Ti": 1024**4,
           "Pi": 1024**5, "Ei": 1024**6}
_DECIMAL = {"n": Decimal("1e-9"), "u": Decimal("1e-6"), "m": Decimal("1e-3"),
            "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15,
            "E": 10**18}


def parse_quantity(s: str | int | float) -> Decimal:
    """Parse a quantity string to an exact Decimal value.

    Raises ValueError on malformed input.
    """
    if isinstance(s, (int, float)):
        return Decimal(str(s))
    s = s.strip()
    if not s:
        raise ValueError("empty quantity")
    try:
        for suf, mult in _BINARY.items():
            if s.endswith(suf):
                return Decimal(s[: -len(suf)]) * mult
        if s[-1] in _DECIMAL:
            return Decimal(s[:-1]) * _DECIMAL[s[-1]]
        return Decimal(s)
    except InvalidOperation:
        raise ValueError(f"malformed quantity {s!r}") from None


def parse_cpu_milli(s: str | int | float) -> int:
    """CPU quantity -> integer milli-cores, rounding up (never under-reserve).

    Mirrors Quantity.MilliValue() semantics (scale by 1000, ceil). String
    parses run in the C++ extension when available (native/src/_native.cpp
    parse_milli — exact int128 arithmetic); values past int64 fall back to
    the Decimal path here."""
    if _native is not None and type(s) is str:
        try:
            return _native.parse_milli(s)
        except (OverflowError, ValueError):
            pass  # out-of-int64 or C-grammar gap: exact Decimal path
    return math.ceil(parse_quantity(s) * 1000)


def parse_bytes(s: str | int | float) -> int:
    """Memory/storage quantity -> integer bytes, rounding up."""
    if _native is not None and type(s) is str:
        try:
            return _native.parse_ceil(s)
        except (OverflowError, ValueError):
            pass
    return math.ceil(parse_quantity(s))


def parse_int(s: str | int | float) -> int:
    """Generic scalar resource (pods, GPUs, hugepages counts) -> int, ceil."""
    if _native is not None and type(s) is str:
        try:
            return _native.parse_ceil(s)
        except (OverflowError, ValueError):
            pass
    return math.ceil(parse_quantity(s))
