"""Scheduling-loop GC management.

The reference rides Go's concurrent GC; CPython's generational collector
instead stops the world whenever allocation counts trip a threshold — and a
2048-pod commit wave allocates enough to trip it several times per batch,
costing ~30% of production-path throughput (measured on SchedulingBasic).
The cure mirrors the well-known server recipe (gc.freeze): keep the
collector OFF while the loop is draining, sweep the young generations at
known-idle points where a bounded pause is invisible.

Reference-counting still reclaims the (acyclic) bulk of per-cycle garbage
immediately; what the guard defers is only cycle detection.
"""

from __future__ import annotations

import gc
import threading


class GCGuard:
    """Re-entrant "collector off while busy" scope.

    ``with guard:`` disables the collector on first entry and on last exit
    re-enables it and sweeps the young generations (gen 0+1 — bounded work,
    independent of total heap size). Nested/concurrent scopes share one
    disable. If the collector was already off (a test or embedder turned it
    off), the guard leaves it alone entirely.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._depth = 0
        self._managed = False

    def __enter__(self) -> "GCGuard":
        with self._lock:
            if self._depth == 0:
                self._managed = gc.isenabled()
                if self._managed:
                    gc.disable()
            self._depth += 1
        return self

    def __exit__(self, *exc) -> None:
        with self._lock:
            self._depth -= 1
            if self._depth == 0 and self._managed:
                gc.enable()
                gc.collect(1)

    def idle_sweep(self) -> None:
        """Bounded young-generation sweep for periodic ticks inside a long
        drain (call where a ~ms pause is acceptable, e.g. the 1s backoff
        flush): keeps deferred cyclic garbage from accumulating without
        ever paying a full gen-2 pass on the hot path."""
        with self._lock:
            if self._depth > 0 and self._managed:
                gc.collect(1)


# process-wide guard shared by every Scheduler in the process (the
# collector is process state; two schedulers must not fight over it)
guard = GCGuard()
