"""JSON wire codec for the API dataclasses.

The serialization layer under the HTTP hub transport (hubserver/hubclient
— the stack's analog of the reference's JSON+protobuf REST layer,
apimachinery runtime.Scheme). Dataclasses encode as plain dicts carrying a
``__kind__`` tag; decode reconstructs from a registry of the api.objects
(+ leaderelection Lease) classes, so nested objects round-trip without
per-type code.
"""

from __future__ import annotations

import dataclasses
from typing import Any

_REGISTRY: dict[str, type] = {}


def _registry() -> dict[str, type]:
    if not _REGISTRY:
        import kubernetes_tpu.api.objects as objects
        from kubernetes_tpu.leaderelection import Lease
        from kubernetes_tpu.telemetry.trace import TraceContext

        for mod_attr in vars(objects).values():
            if dataclasses.is_dataclass(mod_attr) and isinstance(mod_attr,
                                                                 type):
                _REGISTRY[mod_attr.__name__] = mod_attr
        _REGISTRY["Lease"] = Lease
        # the per-commit trace stamp rides inside watch events on both
        # codecs (a new kind = a bin1 registry-fingerprint bump; the
        # negotiation's JSON fallback covers fingerprint-skewed peers)
        _REGISTRY["TraceContext"] = TraceContext
    return _REGISTRY


def to_wire(v: Any) -> Any:
    """Object -> JSON-compatible value. Dataclasses become tagged dicts;
    sets become ``{"__kind__": "__set__", "items": [...]}`` (sorted for
    wire stability) so they round-trip typed and version-skewed peers
    fail loudly on the unknown kind rather than half-decoding."""
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        out = {"__kind__": type(v).__name__}
        for f in dataclasses.fields(v):
            out[f.name] = to_wire(getattr(v, f.name))
        return out
    if isinstance(v, dict):
        return {k: to_wire(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [to_wire(x) for x in v]
    if isinstance(v, (set, frozenset)):
        items = [to_wire(x) for x in v]
        try:
            items.sort()
        except TypeError:  # mixed-type set: stable but arbitrary order
            items.sort(key=repr)
        return {"__kind__": "__set__", "items": items}
    return v


def from_wire(v: Any) -> Any:
    """Inverse of to_wire. Unknown ``__kind__`` tags raise ValueError
    (wire from a newer/older peer must fail loudly, not half-decode)."""
    if isinstance(v, dict):
        kind = v.get("__kind__")
        if kind is None:
            return {k: from_wire(x) for k, x in v.items()}
        if kind == "__set__":
            return set(from_wire(x) for x in v["items"])
        cls = _registry().get(kind)
        if cls is None:
            raise ValueError(f"unknown wire kind {kind!r}")
        kwargs = {k: from_wire(x) for k, x in v.items() if k != "__kind__"}
        return cls(**kwargs)
    if isinstance(v, list):
        return [from_wire(x) for x in v]
    return v
