"""Retry/backoff primitives for the hub transport (client-go's
util/retry + flowcontrol.Backoff distilled).

Three pieces, composable and clock-injectable so tests run them on a
fake clock:

* ``Backoff`` — decorrelated-jitter exponential backoff (the AWS
  "Exponential Backoff and Jitter" recipe: ``sleep = min(cap,
  uniform(base, prev * 3))``), seeded-deterministic when given an rng.
* ``RetryBudget`` — a token bucket capping the cluster-wide retry
  amplification: each retry spends a token, tokens refill at a fixed
  rate, and an empty bucket means *fail fast* instead of piling a retry
  storm onto a hub that is already down (client-go's
  flowcontrol/throttle + gRPC retry-budget semantics).
* ``retry_call`` — drive a callable through both plus a per-call
  deadline: the total time spent including sleeps never exceeds
  ``deadline`` seconds.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional


class Backoff:
    """Decorrelated-jitter backoff sequence. ``next()`` returns the next
    sleep; ``reset()`` after a success so the next failure starts small."""

    def __init__(self, base: float = 0.05, cap: float = 2.0,
                 rng: Optional[random.Random] = None):
        self.base = base
        self.cap = cap
        # default: the module-level rng — constructing a fresh
        # urandom-seeded Random per Backoff would tax the call hot path
        self._rng = rng if rng is not None else random
        self._prev = base

    def next(self) -> float:
        sleep = min(self.cap, self._rng.uniform(self.base, self._prev * 3))
        self._prev = sleep
        return sleep

    def reset(self) -> None:
        self._prev = self.base


class RetryBudget:
    """Token bucket over retries: ``try_spend()`` is True while budget
    remains; refills continuously at ``refill_per_sec`` up to ``budget``."""

    def __init__(self, budget: float = 10.0, refill_per_sec: float = 2.0,
                 now: Callable[[], float] = time.monotonic):
        self._capacity = budget
        self._tokens = budget
        self._rate = refill_per_sec
        self._now = now
        self._last = now()
        self._lock = threading.Lock()

    def try_spend(self, cost: float = 1.0) -> bool:
        with self._lock:
            now = self._now()
            self._tokens = min(self._capacity,
                               self._tokens + (now - self._last) * self._rate)
            self._last = now
            if self._tokens < cost:
                return False
            self._tokens -= cost
            return True

    def remaining(self) -> float:
        with self._lock:
            now = self._now()
            return min(self._capacity,
                       self._tokens + (now - self._last) * self._rate)


def retry_call(fn: Callable, *,
               retry_on: tuple = (OSError,),
               deadline: float = 8.0,
               backoff: Optional[Backoff] = None,
               budget: Optional[RetryBudget] = None,
               sleep: Callable[[float], None] = time.sleep,
               now: Callable[[], float] = time.monotonic,
               on_retry: Optional[Callable[[int, BaseException], None]] = None):
    """Call ``fn()`` until it succeeds, a non-retryable exception escapes,
    the deadline passes, or the budget runs dry (then the last retryable
    exception re-raises). A sleep is clipped so it never overshoots the
    deadline just to fail on wakeup."""
    bo = backoff or Backoff()
    t_end = now() + deadline
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            attempt += 1
            remaining = t_end - now()
            if remaining <= 0 or (budget is not None
                                  and not budget.try_spend()):
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(min(bo.next(), max(remaining, 0.0)))
