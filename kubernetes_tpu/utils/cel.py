"""CEL-subset evaluator for DRA device selectors.

The reference evaluates device selector expressions with cel-go
(staging/src/k8s.io/dynamic-resource-allocation/cel/compile.go); the
expressions the DRA API uses are small predicates over one ``device``
variable:

    device.driver == "test-driver.cdi.k8s.io"
    device.attributes['test-driver.cdi.k8s.io'].preallocate
    device.capacity['drv'].counters.compareTo(quantity('2')) >= 0

This module evaluates that subset without a CEL engine: the expression is
tokenized into Python-compatible operators (``&&``/``||``/``!`` →
``and``/``or``/``not``), parsed with ``ast.parse``, and walked by a
restricted evaluator that only admits boolean/compare/arithmetic
operations (including ``in`` over list literals), attribute and
subscript access on the ``device`` variable, and the ``quantity()`` /
``.compareTo()`` / ``.matches()`` helpers. Any construct outside the
subset raises ``CelError`` — callers surface that as an unschedulable
status, mirroring the reference's CEL compile errors.

Semantics notes:
- ``device.attributes['qualified.name']`` resolves attributes by their
  qualified name with the driver's domain as default (attributes stored
  under plain names match when the subscript names the driver domain).
- Attribute access on a missing attribute raises (CEL errors on absent
  map keys); use ``'name' in device.attributes['domain']`` — not part of
  the common perf expressions, so unsupported.
- Quantities compare through Quantity.compareTo like the CEL extension.
"""

from __future__ import annotations

import ast
import functools
from dataclasses import dataclass

from kubernetes_tpu.utils.quantity import parse_quantity


class CelError(Exception):
    pass


@dataclass
class _Quantity:
    value: float

    def compareTo(self, other):  # noqa: N802 — CEL method name
        if not isinstance(other, _Quantity):
            raise CelError("compareTo expects a quantity")
        return (self.value > other.value) - (self.value < other.value)

    def __eq__(self, other):
        return isinstance(other, _Quantity) and self.value == other.value

    def __lt__(self, other):
        return self.value < other.value

    def __le__(self, other):
        return self.value <= other.value

    def __gt__(self, other):
        return self.value > other.value

    def __ge__(self, other):
        return self.value >= other.value


def quantity(s) -> _Quantity:
    try:
        return _Quantity(float(parse_quantity(str(s))))
    except Exception as e:  # noqa: BLE001
        raise CelError(f"bad quantity {s!r}: {e}") from e


class _AttrBag:
    """One domain's attributes: CEL sees ``.name`` accessors; values are
    the raw bool/int/str/version payloads."""

    def __init__(self, entries: dict):
        self._entries = entries

    def __getattr__(self, name: str):
        try:
            return self._entries[name]
        except KeyError:
            raise CelError(f"attribute {name!r} not present") from None


class _DomainMap:
    """``device.attributes['<domain>']`` / ``device.capacity['<domain>']``:
    entries are stored under qualified names ``domain/name`` or plain
    names (plain = the slice driver's own domain)."""

    def __init__(self, entries: dict, default_domain: str, wrap=None):
        self._entries = entries
        self._default = default_domain
        self._wrap = wrap

    def __getitem__(self, domain: str):
        picked = {}
        for key, value in self._entries.items():
            if "/" in key:
                dom, name = key.split("/", 1)
            else:
                dom, name = self._default, key
            if dom == domain:
                picked[name] = self._wrap(value) if self._wrap else value
        return _AttrBag(picked)


class CelDevice:
    """The ``device`` variable: driver, attributes, capacity."""

    def __init__(self, driver: str, attributes: dict, capacity: dict):
        self.driver = driver
        self.attributes = _DomainMap(attributes or {}, driver)
        self.capacity = _DomainMap(capacity or {}, driver, wrap=quantity)


_ALLOWED_COMPARE = (ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE,
                    ast.In, ast.NotIn)


class _Evaluator(ast.NodeVisitor):
    def __init__(self, device: CelDevice):
        self.device = device

    def eval(self, node):
        method = "visit_" + type(node).__name__
        fn = getattr(self, method, None)
        if fn is None:
            raise CelError(
                f"unsupported expression construct {type(node).__name__}")
        return fn(node)

    def visit_Expression(self, node):
        return self.eval(node.body)

    def visit_Constant(self, node):
        if isinstance(node.value, (bool, int, float, str)):
            return node.value
        raise CelError(f"unsupported literal {node.value!r}")

    def visit_List(self, node):
        # CEL list literals, e.g. `device.attributes['d'].model in
        # ['v5e', 'v5p']` — the membership test the reference's selector
        # corpus uses heavily
        return [self.eval(e) for e in node.elts]

    visit_Tuple = visit_List

    def visit_Name(self, node):
        if node.id == "device":
            return self.device
        if node.id == "true":
            return True
        if node.id == "false":
            return False
        raise CelError(f"unknown identifier {node.id!r}")

    def visit_BoolOp(self, node):
        if isinstance(node.op, ast.And):
            return all(bool(self.eval(v)) for v in node.values)
        if isinstance(node.op, ast.Or):
            return any(bool(self.eval(v)) for v in node.values)
        raise CelError("unsupported boolean operator")

    def visit_UnaryOp(self, node):
        if isinstance(node.op, ast.Not):
            return not self.eval(node.operand)
        if isinstance(node.op, ast.USub):
            return -self.eval(node.operand)
        raise CelError("unsupported unary operator")

    def visit_Compare(self, node):
        left = self.eval(node.left)
        for op, comp in zip(node.ops, node.comparators):
            if not isinstance(op, _ALLOWED_COMPARE):
                raise CelError("unsupported comparison")
            right = self.eval(comp)
            ok = {
                ast.Eq: lambda a, b: a == b,
                ast.NotEq: lambda a, b: a != b,
                ast.Lt: lambda a, b: a < b,
                ast.LtE: lambda a, b: a <= b,
                ast.Gt: lambda a, b: a > b,
                ast.GtE: lambda a, b: a >= b,
                ast.In: lambda a, b: a in b,
                ast.NotIn: lambda a, b: a not in b,
            }[type(op)](left, right)
            if not ok:
                return False
            left = right
        return True

    def visit_Attribute(self, node):
        base = self.eval(node.value)
        if node.attr.startswith("_"):
            raise CelError("private attribute access")
        try:
            return getattr(base, node.attr)
        except AttributeError:
            raise CelError(f"no attribute {node.attr!r}") from None

    def visit_Subscript(self, node):
        base = self.eval(node.value)
        key = self.eval(node.slice)
        try:
            return base[key]
        except (KeyError, TypeError, IndexError):
            raise CelError(f"no entry {key!r}") from None

    def visit_Call(self, node):
        if isinstance(node.func, ast.Name):
            if node.func.id == "quantity":
                args = [self.eval(a) for a in node.args]
                if len(args) != 1:
                    raise CelError("quantity() takes one argument")
                return quantity(args[0])
            raise CelError(f"unknown function {node.func.id!r}")
        if isinstance(node.func, ast.Attribute):
            recv = self.eval(node.func.value)
            name = node.func.attr
            args = [self.eval(a) for a in node.args]
            if name == "compareTo" and isinstance(recv, _Quantity):
                return recv.compareTo(*args)
            if name == "matches" and isinstance(recv, str):
                import re

                return re.search(args[0], recv) is not None
            if name in ("startsWith", "endsWith") and isinstance(recv, str):
                fn = recv.startswith if name == "startsWith" else \
                    recv.endswith
                return fn(args[0])
            raise CelError(f"unsupported method {name!r}")
        raise CelError("unsupported call")


def _translate(expr: str) -> str:
    """CEL operator spelling -> Python: &&, ||, and prefix ! (but not !=)."""
    out = []
    i = 0
    in_str: str | None = None
    while i < len(expr):
        ch = expr[i]
        if in_str:
            out.append(ch)
            if ch == in_str and expr[i - 1] != "\\":
                in_str = None
            i += 1
            continue
        if ch in "'\"":
            in_str = ch
            out.append(ch)
            i += 1
            continue
        if expr.startswith("&&", i):
            out.append(" and ")
            i += 2
            continue
        if expr.startswith("||", i):
            out.append(" or ")
            i += 2
            continue
        if ch == "!" and not expr.startswith("!=", i):
            out.append(" not ")
            i += 1
            continue
        out.append(ch)
        i += 1
    return "".join(out)


@functools.lru_cache(maxsize=1024)
def _parse(expression: str):
    try:
        # parenthesize: eval mode rejects leading whitespace (from a
        # translated leading '!') and bare newlines (multi-line YAML
        # expressions); parens make both legal continuations
        return ast.parse("(" + _translate(expression) + ")", mode="eval")
    except SyntaxError as e:
        raise CelError(f"cannot parse CEL expression: {e}") from e


def evaluate(expression: str, device: CelDevice) -> bool:
    """Evaluate one CEL selector expression against a device (the parsed
    AST is cached per expression — allocator hot path evaluates one
    selector across many devices). Raises CelError for anything outside
    the supported subset."""
    tree = _parse(expression)
    try:
        return bool(_Evaluator(device).eval(tree))
    except CelError:
        raise
    except Exception as e:  # noqa: BLE001 — type mismatches, bad regexes:
        # everything outside the subset must surface as CelError so the
        # caller can turn it into an unschedulable status, not a crash
        raise CelError(f"CEL evaluation failed: {e}") from e
