"""String interning: the bridge between the host object model and device tensors.

Every string the device-side kernels ever compare — label keys/values, taint
keys/effects, namespaces, node/pod names, resource names, topology keys,
image names — is interned host-side into a dense int32 id. Device predicates
are then pure integer tensor ops (SURVEY.md section 7.0 design stance).

Ids are never reused; id 0 is reserved for the empty string and NONE = -1
marks "absent" in padded tensors. The interner additionally keeps a parsed
numeric value per id (NaN when the string is not an integer) so that node
label values can be compared with Gt/Lt NodeSelector operators on device
(reference semantics: k8s.io/apimachinery/pkg/selection + nodeaffinity
helpers parse the label value as an integer for Gt/Lt).
"""

from __future__ import annotations

import math
import re
import threading

NONE = -1  # padded-slot marker in every id tensor

_INT_RE = re.compile(r"^[+-]?[0-9]+$")


class Interner:
    """Thread-safe append-only string <-> int32 id table."""

    __slots__ = ("_lock", "_to_id", "_to_str", "_numeric", "_version")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._to_id: dict[str, int] = {}
        self._to_str: list[str] = []
        self._numeric: list[float] = []
        self._version = 0
        self.intern("")  # id 0

    def intern(self, s: str) -> int:
        i = self._to_id.get(s)
        if i is not None:
            return i
        with self._lock:
            i = self._to_id.get(s)
            if i is not None:
                return i
            i = len(self._to_str)
            self._to_str.append(s)
            # strconv.ParseInt-strict; stored as float64 — exact for |v| < 2^53,
            # which covers every realistic label value (device-side Gt/Lt uses
            # this table; values beyond 2^53 would compare approximately)
            if _INT_RE.match(s):
                self._numeric.append(float(int(s)))
            else:
                self._numeric.append(math.nan)
            self._to_id[s] = i
            self._version += 1
            return i

    def lookup(self, s: str) -> int:
        """Id for an already-interned string, NONE if unseen (read-only path)."""
        return self._to_id.get(s, NONE)

    def string(self, i: int) -> str:
        return self._to_str[i]

    def numeric(self, i: int) -> float:
        return self._numeric[i]

    def __len__(self) -> int:
        return len(self._to_str)

    @property
    def version(self) -> int:
        """Bumped on every new id — lets the device mirror detect vocab growth."""
        return self._version

    def numeric_table(self) -> list[float]:
        """Snapshot of id -> numeric value (for the device Gt/Lt lookup tensor)."""
        return list(self._numeric)
