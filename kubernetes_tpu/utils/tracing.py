"""Lightweight scheduling traces.

The slice of the reference's tracing the scheduler actually uses
(utiltrace in schedule_one.go:404 + the component-base/tracing spans):
nested timed steps collected per operation, logged ONLY when the whole
operation exceeds its threshold — so the hot path pays two clock reads
per step and nothing else.
"""

from __future__ import annotations

import logging
import time
from contextlib import contextmanager
from typing import Callable, Optional

logger = logging.getLogger("kubernetes_tpu.trace")


class Trace:
    """utiltrace.Trace: nested spans via span(); log_if_long at end."""

    def __init__(self, name: str, now: Callable[[], float] = time.monotonic,
                 **fields):
        self.name = name
        self.fields = fields
        self._now = now
        self.start = now()
        # (name, start offset, secs, depth)
        self.steps: list[tuple[str, float, float, int]] = []
        self._depth = 0

    @contextmanager
    def span(self, name: str):
        self._depth += 1
        t0 = self._now()
        try:
            yield self
        finally:
            self._depth -= 1
            # (name, start offset, secs, depth): the dump sorts by start
            # so parents print above their children
            self.steps.append((name, t0 - self.start,
                               self._now() - t0, self._depth))

    def total(self) -> float:
        return self._now() - self.start

    def log_if_long(self, threshold: float,
                    log: Optional[logging.Logger] = None) -> bool:
        """Emit the trace when total exceeds ``threshold`` (the reference's
        100ms slow-attempt log). Returns whether it logged."""
        total = self.total()
        if total < threshold:
            return False
        log = log or logger
        fields = " ".join(f"{k}={v}" for k, v in self.fields.items())
        lines = [f"Trace[{self.name}] {fields} total={total * 1e3:.0f}ms"]
        for name, _start, secs, depth in sorted(self.steps,
                                                key=lambda s: (s[1], s[3])):
            lines.append(f"{'  ' * (depth + 1)}- {name}: {secs * 1e3:.0f}ms")
        log.info("%s", "\n".join(lines))
        return True
