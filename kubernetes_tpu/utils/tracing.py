"""Lightweight scheduling traces + the always-on flight recorder.

Two generations of tracing live here:

- ``Trace``: the slice of the reference's tracing the scheduler used
  first (utiltrace in schedule_one.go:404 + the component-base/tracing
  spans) — nested timed steps collected per operation, logged ONLY when
  the whole operation exceeds its threshold. Still used for the
  slow-cycle log line.

- ``CycleTrace`` / ``FlightRecorder``: the always-on successor. EVERY
  scheduling cycle records its fine-grained phases (queue pop, snapshot
  sync, host plugins, DRA allocator, pack, device launch, D2H pull,
  commit, failure handling, binder drain, eviction flush, host
  fallback) into a bounded ring buffer, and each phase feeds a
  per-phase histogram in the metrics Registry — the continuous
  per-stage latency attribution Kant (arxiv 2510.01256) argues
  large-cluster schedulers need, instead of sampling-on-slow. The
  recorder's overhead budget is <2% of p50 cycle time (enforced by
  ``bench.py --trace-overhead``): recording a phase is two clock reads
  plus one dict write, and the ring is a deque append.

- ``PodTimelines``: per-pod lifecycle stamps (enqueue, pop/attempt,
  assume, bind, parks) plus the last unschedulable diagnosis (which
  device filter rejected how many nodes, which host plugin rejected),
  bounded LRU — the data behind ``/debug/pod?name=``.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import time
from contextlib import contextmanager
from typing import Callable, Optional

logger = logging.getLogger("kubernetes_tpu.trace")

# canonical cycle phases, in rough hot-path order. Host-tail share (the
# bench --profile headline) is the HOST_PHASES fraction of total cycle
# time; the dra_* phases are VIEWS (the DynamicResources slices of
# pack/host_plugins/commit), not disjoint phases, so they are excluded
# from the share arithmetic.
CYCLE_PHASES = (
    "queue_pop",          # pop_batch + per-pod hub vetting
    "snapshot_sync",      # cache.update_snapshot + mirror.sync (H2D pack)
    "chain_patch",        # churn deltas scattered into the live chain
                          # (chain-surviving churn: the cheap substitute
                          # for a whole-chain invalidate + snapshot_sync)
    "host_plugins",       # host PreFilter/Filter/Score + extenders
    "pack",               # mirror.prepare_launch (pod-side H2D)
    "device_dispatch",    # async launch_batch dispatch
    "device_launch",      # dispatch -> results pulled (device + queue wait)
    "d2h_pull",           # device_get of rows/guard/reject_counts
    "commit",             # assume/reserve/permit per winner
    "failure_handling",   # diagnoses, PostFilter/preemption, parks
    "binder_drain",       # collecting finished binding cycles
    "eviction_flush",     # queued preemption evictions
    "host_fallback",      # serial host path after a device fault
    "dra_mask_compile",   # CEL -> bitmask compile + inventory refresh (view)
    "dra_device_eval",    # per-cycle DRA tensor pack + host-path
                          # DynamicResources PreFilter/Filter time (view;
                          # the fused in-launch eval rides device_launch)
    "dra_commit",         # DynamicResources Reserve/PreBind time (view)
    "learned_score",      # learned-scorer checkpoint mtime poll /
                          # reload / params fetch at snapshot-sync time
                          # (a REAL exclusive phase, counted in totals —
                          # a slow checkpoint path must show up in the
                          # A/B latency gate; the fused MLP eval itself
                          # rides device_launch)
    "device_compile",     # launch walltime of a cycle whose dispatch
                          # triggered an XLA compile (view: the same
                          # seconds already sit in device_launch — the
                          # DeviceProfiler's attribution of WHY that
                          # launch stalled)
    "gang_device",        # fused gang-pack launch: pack + dispatch +
                          # the verdict pull (device + transfer time,
                          # the gang analog of device_launch)
    "gang_commit",        # host commit of device-placed gang units
                          # (reserve-all -> bind-all, atomic rollback)
    "commit_pull",        # pipelined waves only: the commit thread's
                          # device pull, measured on the commit thread
                          # (overlap view: that wall time runs CONCURRENT
                          # with the loop thread's next dispatch, so it is
                          # excluded from totals/host-tail — the loop
                          # thread's actual blocked wait lands in
                          # device_launch)
)

# the dra_* attribution views, excluded from total/host-tail arithmetic
# (they double-count time already inside pack/host_plugins/commit)
DRA_VIEW_PHASES = ("dra_mask_compile", "dra_device_eval", "dra_commit")

# attribution views excluded from cycle totals and the host-tail share.
# NOTE: learned_score is NOT here — its time is exclusive (nothing else
# measures the checkpoint poll), so hiding it would let a slow reload
# path pass the --ab-scorer parity gate unseen
VIEW_PHASES = DRA_VIEW_PHASES + ("device_compile",)

# phases measured on the commit thread, CONCURRENT with loop-thread
# work. Counting them in totals/host-tail would book overlapped wall
# time as if serial (the pipelined arm's host-tail share over-reported
# before these were split out). Like VIEW_PHASES they still render in
# /debug/trace and phase_percentiles — they are attribution, not cost.
OVERLAP_PHASES = ("commit_pull",)

# everything excluded from the serial-cycle-time arithmetic
EXCLUDED_PHASES = VIEW_PHASES + OVERLAP_PHASES

# trace-export JSON-lines format version (CycleTrace.to_dict "v"):
# v2 added per-pod placement rows (pod, chosen node, aggregate score,
# chosen-node learned-feature vector) — the replay-dataset substrate;
# v3 adds the opt-in top-K alternative-node scores per placement
# ("alt": [[node, score], ...], trace_export_alts) — the counterfactual
# substrate behind per-placement regret (learn/regret.py). Additive:
# v2 rows remain valid replay input (learn/replay.py reads >= 2).
EXPORT_VERSION = 3

# phases that are host-side Python work (the "host tail" the ROADMAP's
# sub-10x offenders ask us to attribute); device_launch is device +
# transfer, d2h_pull is transfer, the dra_* views double-count host time
HOST_PHASES = (
    "queue_pop", "snapshot_sync", "chain_patch", "host_plugins", "pack",
    "commit",
    "failure_handling", "binder_drain", "eviction_flush", "host_fallback",
    "learned_score", "gang_commit",
)


class Trace:
    """utiltrace.Trace: nested spans via span(); log_if_long at end."""

    def __init__(self, name: str, now: Callable[[], float] = time.monotonic,
                 **fields):
        self.name = name
        self.fields = fields
        self._now = now
        self.start = now()
        # (name, start offset, secs, depth)
        self.steps: list[tuple[str, float, float, int]] = []
        self._depth = 0

    @contextmanager
    def span(self, name: str):
        self._depth += 1
        t0 = self._now()
        try:
            yield self
        finally:
            self._depth -= 1
            # (name, start offset, secs, depth): the dump sorts by start
            # so parents print above their children
            self.steps.append((name, t0 - self.start,
                               self._now() - t0, self._depth))

    def total(self) -> float:
        return self._now() - self.start

    def log_if_long(self, threshold: float,
                    log: Optional[logging.Logger] = None) -> bool:
        """Emit the trace when total exceeds ``threshold`` (the reference's
        100ms slow-attempt log). Returns whether it logged."""
        total = self.total()
        if total < threshold:
            return False
        log = log or logger
        fields = " ".join(f"{k}={v}" for k, v in self.fields.items())
        lines = [f"Trace[{self.name}] {fields} total={total * 1e3:.0f}ms"]
        for name, _start, secs, depth in sorted(self.steps,
                                                key=lambda s: (s[1], s[3])):
            lines.append(f"{'  ' * (depth + 1)}- {name}: {secs * 1e3:.0f}ms")
        log.info("%s", "\n".join(lines))
        return True


class CycleTrace:
    """One scheduling cycle's phase durations. ``add`` accumulates (a
    phase may be touched several times per cycle, e.g. the re-bucketing
    retry loop re-syncing); the recorder flushes the whole dict to the
    phase histogram when the cycle is recorded."""

    __slots__ = ("cycle", "start", "pods", "scheduled", "failed",
                 "chained", "phases", "plugins", "placements",
                 "occupancy", "depth")

    def __init__(self, cycle: int, start: float, pods: int,
                 chained: bool = False):
        self.cycle = cycle
        self.start = start          # wall-clock cycle start
        self.pods = pods
        self.scheduled = 0
        self.failed = 0
        self.chained = chained
        # device occupancy: fraction of this cycle's wall (dispatch ->
        # finish) with its launch in flight — the pipelining instrument
        # (1.0 = the device never waited on host commit work). None until
        # the cycle finishes; stays None for host-fallback cycles.
        self.occupancy: float | None = None
        # pipeline depth observed right after this cycle dispatched
        # (how many waves were in flight, the stall detector)
        self.depth = 0
        self.phases: dict[str, float] = {}
        self.plugins: dict[str, float] = {}   # "plugin/point" -> secs
        # per-pod placement rows (export v2+): {"pod", "uid", "node",
        # "score"[, "feat"][, "alt"]} — node None for failed attempts,
        # "alt" the v3 top-K alternative (node, score) pairs. Populated
        # by the scheduler only while the export file is open.
        self.placements: list[dict] | None = None

    def add(self, phase: str, secs: float) -> None:
        self.phases[phase] = self.phases.get(phase, 0.0) + secs

    def total(self) -> float:
        # view phases double-count time inside the real phases; overlap
        # phases ran on the commit thread concurrent with the loop
        return sum(v for k, v in self.phases.items()
                   if k not in EXCLUDED_PHASES)

    def to_dict(self) -> dict:
        d = {
            "v": EXPORT_VERSION,
            "cycle": self.cycle,
            "start": round(self.start, 6),
            "pods": self.pods,
            "scheduled": self.scheduled,
            "failed": self.failed,
            "chained": self.chained,
            "depth": self.depth,
            "total_ms": round(self.total() * 1e3, 3),
            "phases_ms": {k: round(v * 1e3, 3)
                          for k, v in self.phases.items()},
        }
        if self.occupancy is not None:
            d["occupancy"] = round(self.occupancy, 4)
        if self.plugins:
            d["plugins_ms"] = {k: round(v * 1e3, 3)
                               for k, v in self.plugins.items()}
        if self.placements is not None:
            d["placements"] = self.placements
        return d


class _NullTrace(CycleTrace):
    """The disabled recorder's trace: add() is a no-op so the scheduler
    keeps one unconditional code path."""

    def __init__(self):
        super().__init__(-1, 0.0, 0)

    def add(self, phase: str, secs: float) -> None:
        pass


_NULL_TRACE = _NullTrace()


class FlightRecorder:
    """Always-on, low-overhead cycle recorder: a bounded ring of
    CycleTraces + per-phase / per-plugin histograms feeding the metrics
    Registry, with an optional JSON-lines export for offline analysis.

    Thread model: begin/record/observe_phase/plugin_observe run on the
    scheduling-loop thread only (binder-thread observations go through
    the scheduler's AsyncRecorder instead); readers (``/debug/trace``)
    take cheap snapshots of the deque."""

    def __init__(self, phase_hist=None, plugin_hist=None,
                 capacity: int = 256, export_path: Optional[str] = None,
                 enabled: bool = True, export_max_bytes: int = 0):
        self.enabled = enabled and capacity > 0
        self.phase_hist = phase_hist
        self.plugin_hist = plugin_hist
        self.ring: collections.deque = collections.deque(
            maxlen=max(1, capacity))
        # device-occupancy ring (floats, same capacity): record() copies
        # each finished cycle's occupancy here so occupancy_stats() needn't
        # walk CycleTrace objects under the readers' snapshot
        self._occ: collections.deque = collections.deque(
            maxlen=max(1, capacity))
        self.current: Optional[CycleTrace] = None
        self._cycle_seq = 0
        self._export_path = export_path
        self._export_file = None
        # size-based rotation (keep-last-1): a long trace-collection run
        # must not fill the disk. 0 = unbounded (tests/offline tooling).
        self._export_max_bytes = max(0, export_max_bytes)
        self._export_bytes = 0
        if export_path and self.enabled:
            self._export_file = open(export_path, "a", buffering=1)
            try:
                self._export_bytes = os.path.getsize(export_path)
            except OSError:
                self._export_bytes = 0

    @property
    def exporting(self) -> bool:
        """True while an export file is open — the scheduler's gate for
        the placement-row pulls (score + feature D2H) that only the
        offline replay consumer needs."""
        return self._export_file is not None

    # ------------- recording (loop thread) -------------

    def begin(self, start: float, pods: int,
              chained: bool = False) -> CycleTrace:
        if not self.enabled:
            return _NULL_TRACE
        self._cycle_seq += 1
        tr = CycleTrace(self._cycle_seq, start, pods, chained)
        self.current = tr
        return tr

    def resume(self, tr: CycleTrace) -> None:
        """Re-attach a dispatched cycle's trace (the pipelined drain
        interleaves dispatch k+1 with finish k) so plugin timings land
        on the cycle whose commit is running."""
        if tr is not _NULL_TRACE:
            self.current = tr

    def record(self, tr: CycleTrace) -> None:
        """Cycle complete: ring + histograms + optional export line."""
        if tr is _NULL_TRACE:
            return
        if self.current is tr:
            self.current = None
        self.ring.append(tr)
        if tr.occupancy is not None:
            self._occ.append(tr.occupancy)
        h = self.phase_hist
        if h is not None:
            for phase, secs in tr.phases.items():
                h.observe(secs, phase=phase)
        if self._export_file is not None:
            line = json.dumps(tr.to_dict()) + "\n"
            if self._export_max_bytes \
                    and self._export_bytes + len(line) \
                    > self._export_max_bytes \
                    and self._export_bytes > 0:
                self._rotate_export()    # may disable the export
            if self._export_file is not None:
                self._export_file.write(line)
                self._export_bytes += len(line)

    def _rotate_export(self) -> None:
        """Keep-last-1 rotation: the current file becomes ``<path>.1``
        (replacing any previous rotation) and a fresh file opens, so the
        on-disk footprint is bounded by ~2x export_max_bytes while the
        newest traces are always intact. A FAILED rotation (permissions
        changed, directory vanished) disables the export outright — the
        bound is the contract; silently resuming unbounded appends would
        reintroduce the disk-fill this exists to prevent."""
        try:
            self._export_file.close()
            os.replace(self._export_path, self._export_path + ".1")
            self._export_file = open(self._export_path, "a", buffering=1)
            self._export_bytes = 0
        except OSError:
            logger.error("trace export rotation failed for %s; "
                         "disabling the export (the size bound is the "
                         "contract)", self._export_path, exc_info=True)
            try:
                self._export_file.close()
            except OSError:
                pass
            self._export_file = None

    def occupancy_stats(self) -> dict:
        """Device-occupancy summary over the ring: mean/p50/p99 fraction
        of cycle wall with a launch in flight. The pipelining headline —
        a mean near 1.0 means commit work fully overlapped device time;
        strict alternation (pipelined_waves off) sits at launch/(launch +
        commit). Empty dict when no device cycle has finished yet."""
        vals = sorted(self._occ)
        n = len(vals)
        if n == 0:
            return {}
        return {
            "n": n,
            "mean": round(sum(vals) / n, 4),
            "p50": round(vals[n // 2], 4),
            "p99": round(vals[min(n - 1, int(n * 0.99))], 4),
        }

    def observe_phase(self, phase: str, secs: float) -> None:
        """A standalone phase observation outside a cycle (binder drain
        between cycles, eviction flush, the host-fallback path)."""
        if not self.enabled:
            return
        if self.phase_hist is not None:
            self.phase_hist.observe(secs, phase=phase)

    def plugin_observe(self, plugin: str, point: str, secs: float) -> None:
        """Per-plugin timing from the framework runners; DynamicResources
        time additionally lands in the current cycle's dra_* view phases
        (the ROADMAP's 'DRA allocator Python time' attribution, split so
        future regressions attribute cleanly): host-path PreFilter/Filter
        evaluation feeds dra_device_eval, Reserve/PreBind commit
        bookkeeping feeds dra_commit (dra_mask_compile is observed
        directly by the Scheduler's tensor-build step)."""
        if not self.enabled:
            return
        if self.plugin_hist is not None:
            self.plugin_hist.observe(secs, plugin=plugin,
                                     extension_point=point)
        cur = self.current
        if cur is not None:
            key = f"{plugin}/{point}"
            cur.plugins[key] = cur.plugins.get(key, 0.0) + secs
            if plugin == "DynamicResources":
                cur.add("dra_commit" if point in ("Reserve", "PreBind")
                        else "dra_device_eval", secs)

    def close(self) -> None:
        if self._export_file is not None:
            self._export_file.close()
            self._export_file = None

    # ------------- reading (/debug/trace, bench --profile) -------------

    def last(self, n: int = 32) -> list[dict]:
        if n <= 0:        # [-0:] would be the WHOLE ring, not none of it
            return []
        return [tr.to_dict() for tr in list(self.ring)[-n:]]

    def phase_percentiles(self) -> dict:
        """{phase: {p50_ms, p90_ms, p99_ms, count, total_s}} from the
        phase histogram (bucket-resolution percentiles, like the rest of
        the registry)."""
        h = self.phase_hist
        if h is None:
            return {}
        out = {}
        for k in list(h._series):
            labels = dict(k)
            phase = labels.get("phase", "?")
            s = h._series.get(k)
            if not s:
                continue
            out[phase] = {
                "p50_ms": round(h.percentile(50, **labels) * 1e3, 3),
                "p90_ms": round(h.percentile(90, **labels) * 1e3, 3),
                "p99_ms": round(h.percentile(99, **labels) * 1e3, 3),
                "count": s[2],
                "total_s": round(s[1], 6),
            }
        return out

    def plugin_percentiles(self) -> dict:
        """{"plugin/point": {p50_ms, p99_ms, count, total_s}} from the
        per-plugin histogram — the host-plugin / DRA-allocator slice of
        the bench --profile breakdown."""
        h = self.plugin_hist
        if h is None:
            return {}
        out = {}
        for k in list(h._series):
            labels = dict(k)
            s = h._series.get(k)
            if not s:
                continue
            key = (f"{labels.get('plugin', '?')}/"
                   f"{labels.get('extension_point', '?')}")
            out[key] = {
                "p50_ms": round(h.percentile(50, **labels) * 1e3, 3),
                "p99_ms": round(h.percentile(99, **labels) * 1e3, 3),
                "count": s[2],
                "total_s": round(s[1], 6),
            }
        return out

    def host_tail_share(self) -> float:
        """Fraction of recorded cycle time spent in host-side phases
        (HOST_PHASES) vs everything measured except the dra_* views —
        the per-phase attribution headline for the sub-10x workloads."""
        h = self.phase_hist
        if h is None:
            return 0.0
        host = total = 0.0
        for k in list(h._series):
            phase = dict(k).get("phase", "?")
            if phase in EXCLUDED_PHASES:
                continue
            s = h._series.get(k)
            if not s:
                continue
            total += s[1]
            if phase in HOST_PHASES:
                host += s[1]
        return host / total if total > 0 else 0.0


class PodTimelines:
    """Per-pod lifecycle timelines + last unschedulable diagnosis,
    bounded LRU over pods (the newest ``capacity`` pods touched). Events
    are (t, event, detail) tuples; the per-pod event list is capped so a
    requeue-storm pod cannot grow without bound. Lookup by name or uid
    (``/debug/pod?name=``)."""

    MAX_EVENTS_PER_POD = 64

    def __init__(self, capacity: int = 4096,
                 now: Callable[[], float] = time.time):
        self._now = now
        self._capacity = max(1, capacity)
        # uid -> {"name", "namespace", "events": [...], "diagnosis"}
        self._pods: collections.OrderedDict = collections.OrderedDict()
        self._by_name: dict[str, str] = {}   # "ns/name" -> uid (last wins)

    def _entry(self, pod) -> dict:
        uid = pod.metadata.uid
        e = self._pods.get(uid)
        if e is None:
            e = {"uid": uid, "name": pod.metadata.name,
                 "namespace": pod.metadata.namespace,
                 "events": [], "diagnosis": None, "wire": {}}
            self._pods[uid] = e
            self._by_name[f"{pod.metadata.namespace}/"
                          f"{pod.metadata.name}"] = uid
            while len(self._pods) > self._capacity:
                old_uid, old = self._pods.popitem(last=False)
                key = f"{old['namespace']}/{old['name']}"
                if self._by_name.get(key) == old_uid:
                    del self._by_name[key]
        else:
            self._pods.move_to_end(uid)
        return e

    def event(self, pod, event: str, detail: str = "",
              t: Optional[float] = None) -> None:
        e = self._entry(pod)
        ev = e["events"]
        ev.append((t if t is not None else self._now(), event, detail))
        if len(ev) > self.MAX_EVENTS_PER_POD:
            # keep the first events (enqueue/first attempt anchor the
            # timeline) and the newest tail
            del ev[8:len(ev) - self.MAX_EVENTS_PER_POD + 8]

    def diagnose(self, pod, device_rejects: dict, host_rejects: dict,
                 message: str = "") -> None:
        """Record why the pod's last attempt failed: device filter ->
        nodes-rejected counts (from the pulled reject_counts) and host
        plugin -> counts (from the host/fallback path)."""
        e = self._entry(pod)
        e["diagnosis"] = {
            "at": self._now(),
            "device_rejects": dict(device_rejects),
            "host_rejects": dict(host_rejects),
            "message": message,
        }

    def wire_stamp(self, pod, stamp: str, t: float, origin: str = "",
                   hops: int = 0) -> None:
        """Record one cross-wire trace stamp (telemetry.trace) on this
        pod's timeline: ``created`` (the pod's hub add commit),
        ``bound`` (the bind's hub commit), ``acked`` (the kubelet's
        status-Running commit), ``kubelet_recv`` (the bound event's
        arrival at the kubelet after its relay hops). Last stamp wins —
        a relist replaying an event re-stamps identically. Also logged
        as an ordinary timeline event so /debug/pod reads as one
        story."""
        e = self._entry(pod)
        e["wire"][stamp] = {"t": round(t, 6), "origin": origin,
                            "hops": hops}
        detail = f"origin={origin} hops={hops}" if origin else ""
        ev = e["events"]
        ev.append((t, f"wire:{stamp}", detail))
        if len(ev) > self.MAX_EVENTS_PER_POD:
            del ev[8:len(ev) - self.MAX_EVENTS_PER_POD + 8]

    def wire_of(self, uid: str) -> Optional[dict]:
        """The raw wire stamps recorded so far for one pod (None when
        the pod is untracked or unstamped) — the export rows' trace
        column reads this at commit time."""
        e = self._pods.get(uid)
        return (e["wire"] or None) if e else None

    def joined(self, uid: str) -> Optional[dict]:
        """The joined end-to-end trace for one pod (or None while
        incomplete) — telemetry.trace.joined_latency over the wire
        stamps."""
        from kubernetes_tpu.telemetry.trace import joined_latency

        e = self._pods.get(uid)
        return joined_latency(e) if e else None

    def uids(self) -> list[str]:
        return list(self._pods)

    def bind_latencies(self) -> dict[str, float]:
        """uid -> first-enqueued → first-bound seconds for every tracked
        pod that bound — the ONE time-to-bind pass behind both the bench
        quality rows and the scenario replay driver's SLO gate
        (telemetry.slo). Pods that never bound (or whose enqueue stamp
        was LRU-evicted) are absent; callers that need full coverage
        size the timelines to the workload (config.timelines_capacity)."""
        out: dict[str, float] = {}
        for uid, e in self._pods.items():
            enq = bind = None
            for t, ev, _detail in e["events"]:
                if enq is None and ev == "enqueued":
                    enq = t
                elif bind is None and ev == "bound":
                    bind = t
                if enq is not None and bind is not None:
                    break
            if enq is not None and bind is not None and bind >= enq:
                out[uid] = bind - enq
        return out

    def get(self, name: str = "", uid: str = "",
            namespace: str = "default") -> Optional[dict]:
        if not uid and name:
            uid = self._by_name.get(f"{namespace}/{name}", "")
        e = self._pods.get(uid)
        if e is None:
            return None
        from kubernetes_tpu.telemetry.trace import joined_latency

        return {
            "uid": e["uid"], "name": e["name"],
            "namespace": e["namespace"],
            "events": [{"t": round(t, 6), "event": ev, "detail": d}
                       for t, ev, d in e["events"]],
            "diagnosis": e["diagnosis"],
            "wire": dict(e["wire"]),
            "joined": joined_latency(e),
        }

    def forget(self, uid: str) -> None:
        e = self._pods.pop(uid, None)
        if e is not None:
            key = f"{e['namespace']}/{e['name']}"
            if self._by_name.get(key) == uid:
                del self._by_name[key]

    def __len__(self) -> int:
        return len(self._pods)
