"""Kubemark-style hollow nodes: fake kubelets against the HTTP hub.

From-scratch equivalent of the reference's kubemark rung
(pkg/kubemark/hollow_kubelet.go:63, cmd/kubemark/hollow-node.go): a
standalone process registers N Node objects against a REAL (HTTP) hub,
heartbeats them, watches for pods bound to its nodes, and acks each
binding by driving the pod's status to Running — 5k-node-scale control
plane testing with no machines behind the nodes.

Run against a hubserver:

    python -m kubernetes_tpu.hubserver --port 8080      # (or in-process)
    python -m kubernetes_tpu.kubemark --hub http://127.0.0.1:8080 \
        --nodes 1000 [--prefix hollow] [--heartbeat 10]

The scheduler (kubernetes_tpu --hub ...) then schedules onto the hollow
nodes exactly as it would onto real ones; tests/test_kubemark.py drives
the whole stack across three processes' worth of components.
"""

from __future__ import annotations

import threading
import time

from kubernetes_tpu.api.objects import (
    LABEL_HOSTNAME,
    LABEL_ZONE,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
)
from kubernetes_tpu.hub import EventHandlers

PHASE_RUNNING = "Running"


class HollowNodes:
    """N hollow kubelets sharing one hub client (the reference runs one
    process per hollow node; one feeder process with N node identities
    registers the same API objects at a fraction of the overhead)."""

    def __init__(self, hub, count: int, prefix: str = "hollow",
                 cpu: str = "4", memory: str = "32Gi", pods: str = "110",
                 zones: int = 0, watch_hub=None):
        """``watch_hub`` splits the read fan-out from the write path:
        pod WATCHES go to it (typically a fabric.relay node, so 10k
        hollow kubelets cost the hub one socket per relay) while
        writes — node registration, heartbeats, status acks — still go
        straight to ``hub``. Default: watch the same hub."""
        self.hub = hub
        self.watch_hub = watch_hub or hub
        self.prefix = prefix
        self.names: set[str] = set()
        self.acked: set[str] = set()        # pod uids driven to Running
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._hb: threading.Thread | None = None
        for i in range(count):
            name = f"{prefix}-{i}"
            labels = {LABEL_HOSTNAME: name}
            if zones:
                labels[LABEL_ZONE] = f"zone-{i % zones}"
            node = Node(metadata=ObjectMeta(name=name, labels=labels),
                        spec=NodeSpec(),
                        status=NodeStatus(allocatable={
                            "cpu": cpu, "memory": memory, "pods": pods}))
            self.hub.create_node(node)
            self.names.add(name)
        # ack bindings: the kubelet side of the contract — a pod bound to
        # one of OUR nodes gets its status driven to Running
        # (hollow_kubelet runs a real kubelet loop against a fake runtime;
        # the scheduler-visible effect is exactly this status update).
        # on_event (not the typed trio) so the bound event's trace stamp
        # is visible: the ack carries it back as baggage, closing the
        # hub -> relay -> kubelet leg of the end-to-end pod timeline.
        self.watch_hub.watch_pods(EventHandlers(
            on_event=self._on_pod_event))

    def _on_pod_event(self, ev) -> None:
        if ev.type == "delete":
            return
        self._maybe_ack(ev.new, ev.trace)

    def _maybe_ack(self, pod: Pod, trace=None) -> None:
        if pod.spec.node_name not in self.names:
            return
        if pod.status.phase == PHASE_RUNNING:
            return
        # re-fetch and mutate only the phase: the watch-event object can
        # be STALE, and hub updates are last-write-wins — writing a clone
        # of the event object back would roll back any field another
        # writer (the scheduler's status patches) set in between
        fresh = self.hub.get_pod(pod.metadata.uid)
        if fresh is None or fresh.status.phase == PHASE_RUNNING:
            return
        new = fresh.clone()
        new.status.phase = PHASE_RUNNING
        if trace is not None:
            # trace baggage: when the bound event arrived here, and how
            # many relay hops it crossed — the scheduler's timeline join
            # reads this off the ack's update event (telemetry.trace).
            # clone() shares the annotations dict with the stored object
            # (only labels are copied), so copy before writing: mutating
            # it in place would annotate the hub's committed pod with no
            # commit — and permanently, if the update below fails.
            from kubernetes_tpu.telemetry.trace import (
                ACK_TRACE_ANNOTATION,
                TraceContext,
                format_ack_trace,
            )

            new.metadata.annotations = dict(new.metadata.annotations)
            new.metadata.annotations[ACK_TRACE_ANNOTATION] = \
                format_ack_trace(TraceContext(
                    origin=trace.origin, ts=time.time(),
                    hops=trace.hops))
        try:
            self.hub.update_pod(new)
        except Exception:  # noqa: BLE001 — pod vanished mid-ack; the
            return         # next watch event (if any) retries
        with self._lock:
            self.acked.add(pod.metadata.uid)

    def ack_count(self) -> int:
        with self._lock:
            return len(self.acked)

    # --- heartbeats (node-status updater) ---

    def resync_acks(self) -> None:
        """Retry acks a transient update failure dropped: a bound pod
        generates no further watch events, so the status loop (like the
        kubelet's) rescans bound-but-not-Running pods on our nodes."""
        try:
            pods = self.hub.list_pods()
        except Exception:  # noqa: BLE001 — hub restarting
            return
        for pod in pods:
            self._maybe_ack(pod)

    def start_heartbeat(self, interval_s: float = 10.0) -> None:
        def beat() -> None:
            while not self._stop.wait(interval_s):
                self.resync_acks()
                for name in list(self.names):
                    node = self.hub.get_node(name)
                    if node is None:
                        continue
                    hb = node.clone() if hasattr(node, "clone") else node
                    hb.metadata.annotations["kubemark.alpha/heartbeat"] = \
                        str(time.time())
                    try:
                        self.hub.update_node(hb)
                    except Exception:  # noqa: BLE001 — hub restarting
                        pass

        self._hb = threading.Thread(target=beat, daemon=True,
                                    name="kubemark-heartbeat")
        self._hb.start()

    def serve_metrics(self, host: str = "127.0.0.1", port: int = 0):
        """Mount /metrics + /healthz for this feeder (the fleet scrape
        surface every fabric component answers; telemetry.fleet)."""
        from kubernetes_tpu.telemetry.fleet import (
            ComponentEndpoints,
            kubemark_metrics_text,
        )

        self._endpoints = ComponentEndpoints(
            lambda: kubemark_metrics_text(self),
            host=host, port=port).start()
        return self._endpoints

    def stop(self) -> None:
        self._stop.set()
        if self._hb is not None:
            self._hb.join(timeout=5)
        ep = getattr(self, "_endpoints", None)
        if ep is not None:
            ep.stop()


def main() -> None:
    import argparse

    from kubernetes_tpu.hubclient import RemoteHub

    ap = argparse.ArgumentParser(description="kubemark hollow-node feeder")
    ap.add_argument("--hub", required=True, help="hub URL")
    ap.add_argument("--relay", default=None,
                    help="watch-relay URL (fabric.relay): pod watches "
                         "go through the relay tree, writes go to "
                         "--hub — the 10k-kubelet fan-in shape")
    ap.add_argument("--topology", default=None,
                    help="auto-topology: discover a relay for pod "
                         "watches from this router's served topology "
                         "map (/topology) instead of --relay's "
                         "explicit URL; falls back to the router "
                         "itself while no relay is advertised")
    ap.add_argument("--nodes", type=int, default=100)
    ap.add_argument("--prefix", default="hollow")
    ap.add_argument("--zones", type=int, default=0)
    ap.add_argument("--heartbeat", type=float, default=0.0,
                    help="node heartbeat interval seconds (0 = off)")
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve /metrics + /healthz on this port "
                         "(0 = ephemeral; -1 = off)")
    args = ap.parse_args()
    client = RemoteHub(args.hub)
    relay_url = args.relay
    if args.topology and not relay_url:
        from kubernetes_tpu.fabric.relay import discover_relay_url

        relay_url = discover_relay_url(args.topology)
        print(f"kubemark: discovered relay {relay_url}", flush=True)
    watch_client = RemoteHub(relay_url) if relay_url else None
    hollow = HollowNodes(client, args.nodes, prefix=args.prefix,
                         zones=args.zones, watch_hub=watch_client)
    if args.heartbeat:
        hollow.start_heartbeat(args.heartbeat)
    if args.metrics_port >= 0:
        ep = hollow.serve_metrics(port=args.metrics_port)
        print(f"kubemark: metrics at {ep.address}/metrics", flush=True)
    print(f"kubemark: {args.nodes} hollow nodes registered", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        hollow.stop()
        if watch_client is not None:
            watch_client.close()


if __name__ == "__main__":
    main()
