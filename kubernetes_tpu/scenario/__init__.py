"""Trace-driven scenario engine (ROADMAP item 5).

- ``trace``: versioned trace format (arrival-timestamped pod shapes,
  node lifecycle, priority/tenant/gang mix, optional DRA objects) with
  JSON-lines and bin1 codecs.
- ``generators``: pure seeded params -> Trace functions for named
  regimes (diurnal ramp, sawtooth churn, zone outage + stampede,
  quota storm, gang+DRA+preemption crossfire).
- ``lifecycle``: the one node add/remove/cordon code path shared by the
  perf-harness Churn op and the replayer.
- ``replay``: drives a trace against the real Hub + Scheduler at
  recorded (or K×-compressed) rates, gating on time-to-bind SLOs and
  journal-audit exactly-once.
- ``fuzz``: adversarial search over generator parameter space; losing
  traces are auto-filed under tests/regression_traces/.
"""

from kubernetes_tpu.scenario.generators import GENERATORS, generate
from kubernetes_tpu.scenario.lifecycle import NodeLifecycle
from kubernetes_tpu.scenario.replay import replay_trace
from kubernetes_tpu.scenario.trace import (
    Trace,
    TraceEvent,
    load_trace,
    save_trace,
)

__all__ = [
    "GENERATORS",
    "NodeLifecycle",
    "Trace",
    "TraceEvent",
    "generate",
    "load_trace",
    "replay_trace",
    "save_trace",
]
