"""The trace replay driver: feed a Trace into a fresh Hub + production
Scheduler at recorded (or K×-compressed) rates, then gate.

Replay semantics:

- Event times are TRACE time; ``speed`` compresses them onto the wall
  clock (speed=10 plays a 12-trace-second trace in 1.2 wall seconds).
  There are no raw arrival sleeps — injection happens from the
  scheduler's own ``on_step`` callback plus short idle waits, and the
  driver records how far injection fell behind the recorded schedule
  (``pacing.max_lag_s``). When the box can't hold the schedule the
  report says ``hardware_limited`` honestly (the bench --scaleout
  convention) instead of letting the lag silently poison the verdict.

- SLOs are evaluated in TRACE time: measured wall time-to-bind × speed.
  Waits engineered by the trace (an outage window, a quota turn) are
  trace-time invariant across speeds; pure scheduler compute is NOT
  (it doesn't compress), which is why filed regression traces record
  the speed they were judged at and the pytest gate replays at the
  same speed.

- A warmup pass (2 throwaway nodes + a few pods, deleted afterwards)
  compiles the device programs before the clock starts; warmup pods
  never enter the SLO stats because stats are filtered to the trace's
  own pod uids.

- The gate: ``trace.slo`` (regime intent target) and ``trace.gate``
  (the ratchet bound stamped on filed regression traces) are both
  evaluated; journal-audit exactly-once over the hub's full journal is
  always part of the verdict.
"""

from __future__ import annotations

import copy
import os
import time
from typing import Callable, Optional

from kubernetes_tpu.config.types import default_config
from kubernetes_tpu.hub import Hub
from kubernetes_tpu.ops.features import Capacities
from kubernetes_tpu.scenario.lifecycle import NodeLifecycle
from kubernetes_tpu.scenario.trace import Trace
from kubernetes_tpu.scheduler import Scheduler
from kubernetes_tpu.telemetry.slo import evaluate_slo, time_to_bind_stats
from kubernetes_tpu.testing.audit import audit_bind_journal
from kubernetes_tpu.utils.wire import from_wire


class ReplayStuck(Exception):
    """The trace could not drain within the wall timeout."""


def _peak_nodes(events) -> int:
    """Peak CONCURRENT node count over the trace: the warmup node set
    must reach it, because the topology domain bucket (``d_cap``, a
    static jit arg with a sticky high-water) grows with distinct
    domains — a trace whose node wave first crosses a pow2 domain
    boundary MID-replay pays that compile inside the paced window."""
    cur = peak = 0
    for e in events:
        if e.kind == "node_up":
            cur += 1
            peak = max(peak, cur)
        elif e.kind == "node_down":
            cur -= 1
    return max(peak, 2)


def _trace_zones(events) -> list[str]:
    """Distinct zone labels across the trace's nodes (build order):
    warmup nodes cycle the same zones so the domain count — hostnames
    plus zones — lands in the same pow2 bucket the replay will use."""
    from kubernetes_tpu.api.objects import LABEL_ZONE

    zones: list[str] = []
    for e in events:
        if e.kind != "node_up":
            continue
        n = from_wire(e.data["node"])
        z = n.metadata.labels.get(LABEL_ZONE)
        if z and z not in zones:
            zones.append(z)
    return zones


def _warmup(hub: Hub, sched: Scheduler, now, sleep,
            kinds: set | None = None, peak_nodes: int = 2,
            zones: list[str] | None = None, batch: int = 0) -> None:
    """Compile the device programs before the paced clock starts: bind
    throwaway pods on throwaway nodes, then remove every trace.

    Coverage matters more than count — a program that first compiles
    MID-replay stalls injection for ~a second, and that lag directly
    distorts trace-time waits (a pod injected late against an on-time
    recovery measures a shorter wait than the trace engineered). So the
    warmup covers the trace's SHAPE FAMILIES, not just the plain-fit
    path: the node set is sized to the trace's peak concurrent node
    count and cycles its zones (the topology domain bucket, sticky via
    hysteresis, reaches replay size here), a full-batch wave of plain
    pods drives one launch at the production batch shape, and the
    zone-affinity / priority / DRA-claim / gang pods compile their
    field-subset programs when the trace uses those kinds."""
    from kubernetes_tpu.api.objects import (
        LABEL_HOSTNAME,
        LABEL_POD_GROUP,
        LABEL_ZONE,
        ObjectMeta,
        PodGroup,
    )
    from kubernetes_tpu.perf.workloads import (
        _dra_claim,
        _dra_slice,
        _node,
        _pod,
    )
    from kubernetes_tpu.scenario.generators import _zone_affinity

    zones = zones or ["warmup-zone"]
    life = NodeLifecycle(hub)
    nodes = []
    for i in range(max(peak_nodes, 2)):
        n = _node(i, zones=zones)
        n.metadata.name = f"warmup-node-{i}"
        n.metadata.labels[LABEL_HOSTNAME] = n.metadata.name
        n.metadata.labels[LABEL_ZONE] = zones[i % len(zones)]
        nodes.append(life.add(n))
    # the full-batch wave: enough plain pods that one pop fills the
    # production batch (padding is to batch_size, so this compiles the
    # same [B]-shaped programs the replay's own waves will launch)
    pods = [_pod(f"warmup-pod-{i}") for i in range(max(batch, 3))]
    pods.append(_pod("warmup-aff",
                     affinity=_zone_affinity(zones[0])))
    pods.append(_pod("warmup-prio", priority=100))
    kinds = kinds or set()
    if "obj" in kinds:   # trace creates slices/claims: warm DRA
        sl = _dra_slice(0)
        sl.metadata.name = "warmup-slice"
        sl.node_name = sl.pool = "warmup-node-0"
        hub.create_resource_slice(sl)
        claim = _dra_claim(0)
        claim.metadata.name = "warmup-claim"
        hub.create_resource_claim(claim)
        dra_pod = _pod("warmup-dra")
        from kubernetes_tpu.api.objects import PodResourceClaim

        dra_pod.spec.resource_claims = [PodResourceClaim(
            name="accel", resource_claim_name="warmup-claim")]
        pods.append(dra_pod)
    if "group" in kinds:   # gang regimes: warm the device packer —
        # gated on use because a PodGroup activates the jobqueue layer,
        # and non-gang regimes must not replay through it
        hub.create_pod_group(PodGroup(
            metadata=ObjectMeta(name="warmup-gang"), min_member=2,
            queue="default", schedule_timeout_seconds=60.0))
        for m in range(2):
            gp = _pod(f"warmup-gang-m{m}")
            gp.metadata.labels[LABEL_POD_GROUP] = "warmup-gang"
            pods.append(gp)
    for p in pods:
        hub.create_pod(p)

    def bound() -> bool:
        for p in pods:
            cur = hub.get_pod(p.metadata.uid)
            if cur is None or not cur.spec.node_name:
                return False
        return True

    deadline = now() + 60.0
    while not bound():
        sched.run_until_idle(on_step=bound)
        if bound():
            break
        if now() > deadline:
            raise ReplayStuck("warmup pods did not bind in 60s")
        sleep(0.02)
        sched.queue.flush_backoff_completed()
    for p in pods:
        try:
            hub.delete_pod(p.metadata.uid)
        except Exception:  # noqa: BLE001
            pass
    for n in nodes:
        life.remove(n.metadata.name)


def replay_trace(trace: Trace, speed: float = 10.0, warmup: bool = True,
                 timeout_s: float = 180.0,
                 config: Optional[object] = None,
                 now: Callable[[], float] = time.time,
                 sleep: Callable[[float], None] = time.sleep) -> dict:
    """Replay one trace; return the full report (stats + verdicts).

    ``config`` (a SchedulerConfiguration) overrides the defaults BEFORE
    the trace's own config hints are applied — the fuzzer uses it to
    turn on the alt-export needed for the regret objective.
    """
    speed = max(float(speed), 1e-6)
    tcfg = trace.config or {}
    cfg = copy.deepcopy(config) if config is not None else default_config()
    cfg.batch_size = int(tcfg.get("batch_size", 32))
    # replaying a K×-compressed world compresses the scheduler's time
    # constants too: un-scaled backoff would make a retry cost K trace-
    # seconds, turning every unschedulable wait speed-DEPENDENT and the
    # filed-trace verdict nondeterministic across boxes
    cfg.pod_initial_backoff_seconds = max(
        cfg.pod_initial_backoff_seconds / speed, 1e-3)
    cfg.pod_max_backoff_seconds = max(
        cfg.pod_max_backoff_seconds / speed, 1e-2)
    if tcfg.get("tenants"):
        cfg.tenants = {**cfg.tenants, **tcfg["tenants"]}
    pod_cap = int(tcfg.get("pod_capacity", 2048))
    node_cap = int(tcfg.get("node_capacity", 64))
    cfg.timelines_capacity = max(
        getattr(cfg, "timelines_capacity", 4096), 2 * pod_cap)
    hub = Hub()
    sched = Scheduler(hub, cfg,
                      caps=Capacities(nodes=node_cap, pods=pod_cap),
                      now=now)
    life = NodeLifecycle(hub)
    events = sorted(trace.events, key=lambda e: e.t)
    trace_pod_uids: set[str] = set()
    injected = {"n": 0}
    max_lag = [0.0]

    def apply(e) -> None:
        data = e.data
        if e.kind == "pod":
            p = from_wire(data["pod"])
            p.metadata.creation_timestamp = now()
            trace_pod_uids.add(p.metadata.uid)
            hub.create_pod(p)
        elif e.kind == "node_up":
            n = from_wire(data["node"])
            n.metadata.creation_timestamp = now()
            life.add(n)
        elif e.kind == "node_down":
            life.remove(data["name"])
        elif e.kind == "node_cordon":
            life.cordon(data["name"])
        elif e.kind == "node_uncordon":
            life.uncordon(data["name"])
        elif e.kind == "group":
            g = from_wire(data["group"])
            g.metadata.creation_timestamp = now()
            hub.create_pod_group(g)
        elif e.kind == "obj":
            o = from_wire(data["obj"])
            if getattr(o, "metadata", None) is not None:
                o.metadata.creation_timestamp = now()
            getattr(hub, data["verb"])(o)
        else:
            raise ValueError(f"unknown trace event kind {e.kind!r}")
        sched.metrics.scenario_events.inc(kind=e.kind)

    try:
        if warmup:
            _warmup(hub, sched, now, sleep,
                    kinds={e.kind for e in events},
                    peak_nodes=_peak_nodes(events),
                    zones=_trace_zones(events),
                    batch=cfg.batch_size)
        prof = sched.profiler
        warm_compiles = prof.compiles if prof is not None else 0
        wall_start = now()
        idx = [0]

        def inject_due() -> None:
            t_rel = now() - wall_start
            while idx[0] < len(events) \
                    and events[idx[0]].t / speed <= t_rel:
                e = events[idx[0]]
                idx[0] += 1
                injected["n"] += 1
                max_lag[0] = max(max_lag[0],
                                 (now() - wall_start) - e.t / speed)
                apply(e)

        def done() -> bool:
            if idx[0] < len(events) or len(sched.queue):
                return False
            for p in hub.list_pods():
                if not p.spec.node_name:
                    return False
            return True

        def step() -> bool:
            inject_due()
            return done()

        deadline = wall_start + timeout_s
        completed = True
        while not done():
            inject_due()
            sched.run_until_idle(on_step=step)
            if done():
                break
            if now() > deadline:
                completed = False
                break
            # idle but incomplete: wait for the next due event or a
            # backoff flush, whichever is sooner
            wait = 0.05
            if idx[0] < len(events):
                due = wall_start + events[idx[0]].t / speed
                wait = min(wait, max(due - now(), 0.0) + 1e-3)
            sleep(wait)
            sched.queue.flush_backoff_completed()
        wall_s = now() - wall_start
    finally:
        sched.close()

    # stats in wall AND trace time; the gates read trace time. A trace
    # may scope its SLO to a uid prefix (overload regimes: best-effort
    # pods are SUPPOSED to wait — gating their p99 would punish correct
    # shedding; the priority pods are the protected class the SLO is
    # about). The audit and survivor counts still cover every pod.
    slo_uids = trace_pod_uids
    slo_prefix = tcfg.get("slo_uid_prefix")
    if slo_prefix:
        scoped = {u for u in trace_pod_uids if u.startswith(slo_prefix)}
        if scoped:
            slo_uids = scoped
    stats_wall = time_to_bind_stats(sched.timelines, uids=slo_uids)
    stats = time_to_bind_stats(sched.timelines, uids=slo_uids,
                               scale=speed)
    slo_verdict = evaluate_slo(stats, trace.slo)
    gate_verdict = evaluate_slo(stats, trace.gate)
    for v, tag in ((slo_verdict, "slo"), (gate_verdict, "gate")):
        for b in v["breaches"]:
            sched.metrics.scenario_slo_breaches.inc(
                metric=f"{tag}:{b['metric']}")
    sched.metrics.scenario_time_to_bind_p99.set(
        stats["time_to_bind_p99_ms"] / 1e3)
    if (not slo_verdict["ok"] or not gate_verdict["ok"]) \
            and getattr(sched, "autopsy", None) is not None:
        # breach → auto-autopsy: the bundle names the filed trace
        # (name/generator/seed/speed) so the incident points straight
        # at the replayable reproducer. Post-close is safe — the
        # flight ring, timelines, and stats are plain host state.
        sched.watchdog.incident(
            "scenario_slo_breach",
            reason=f"replay of trace {trace.name!r} breached its "
                   f"{'SLO' if not slo_verdict['ok'] else 'gate'}",
            details={"trace": trace.name, "generator": trace.generator,
                     "seed": trace.seed, "speed": speed,
                     "stats": stats,
                     "slo_breaches": slo_verdict["breaches"],
                     "gate_breaches": gate_verdict["breaches"]})

    live = hub.list_pods()
    audit = audit_bind_journal(
        hub=hub,
        expected_uids={p.metadata.uid for p in live
                       if p.metadata.uid in trace_pod_uids})
    audit_ok = bool(audit["ok"])

    report = {
        "name": trace.name,
        "generator": trace.generator,
        "seed": trace.seed,
        "speed": speed,
        "events": len(events),
        "injected": injected["n"],
        "completed": completed,
        "wall_s": round(wall_s, 3),
        "trace_s": round(trace.duration(), 3),
        "pods": len(trace_pod_uids),
        "slo_pods": len(slo_uids),
        "survivors": sum(1 for p in live
                         if p.metadata.uid in trace_pod_uids),
        # the shape-family warmup's contract: every compile happened
        # BEFORE the paced clock started (a mid-replay compile stalls
        # injection and silently distorts trace-time waits)
        "device": {
            "warmup_compiles": warm_compiles,
            "mid_replay_compiles": (
                (prof.compiles - warm_compiles)
                if prof is not None else None),
            "launches": prof.launches if prof is not None else None,
        },
        "stats": stats,             # trace-time ms (gated)
        "stats_wall": stats_wall,   # wall ms (informational)
        "slo": {**slo_verdict, "target": dict(trace.slo)},
        "gate": {**gate_verdict, "target": dict(trace.gate)},
        "audit": {k: audit[k] for k in
                  ("ok", "binds", "double_binds", "lost", "too_old")},
        "pacing": {
            "max_lag_s": round(max_lag[0], 3),
            "held": max_lag[0] <= 1.0,
            # 1-core boxes cannot pace injection against a busy drain
            # loop — same honesty rule as bench --scaleout
            "hardware_limited": (os.cpu_count() or 1) < 2
            or max_lag[0] > 1.0,
        },
        "ok": completed and audit_ok and slo_verdict["ok"]
        and gate_verdict["ok"],
    }
    # regret objective support (learn/regret.py over export-v3 alt
    # rows) — only when the caller's config exported alternatives
    if getattr(cfg, "trace_export_path", None) \
            and getattr(cfg, "trace_export_alts", False):
        try:
            from kubernetes_tpu.learn import regret as RG
            from kubernetes_tpu.learn.replay import (
                iter_placement_rows,
                iter_trace_lines,
            )

            paths = [cfg.trace_export_path + ".1", cfg.trace_export_path]
            rows = [r for pth in paths if os.path.exists(pth)
                    for r in iter_placement_rows(iter_trace_lines(pth))]
            evicted, node_domain = RG.harvest_hub_outcomes(hub)
            keep = trace_pod_uids | evicted
            rows = [r for r in rows if r.get("uid") in keep]
            report["regret"] = RG.summarize_regret(
                RG.compute_regret(rows, evicted, node_domain))
        except Exception:  # noqa: BLE001 — a torn export must not fail
            pass           # the replay it decorates
    return report
