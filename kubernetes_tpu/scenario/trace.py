"""The versioned trace format (v1) + JSON-lines / bin1 codecs.

A trace is a header plus a time-ordered list of events. Times are
*trace time* — seconds since trace start; the replayer maps them onto
the wall clock at a compression factor (``speed``). Event kinds:

- ``pod``:          {"pod": to_wire(Pod)} — arrival of one pod shape
- ``node_up``:      {"node": to_wire(Node)}
- ``node_down``:    {"name": str}
- ``node_cordon``:  {"name": str}
- ``node_uncordon``:{"name": str}
- ``group``:        {"group": to_wire(PodGroup)} — gang registration
- ``obj``:          {"verb": "create_resource_slice", "obj": to_wire(x)}
                    — generic typed create (DRA slices/claims, ...)

Typed API objects ride as ``utils.wire`` tagged dicts INSIDE event
data, so the bin1 codec only ever sees plain values and the fabric's
registry fingerprint is untouched by this module.

Two on-disk encodings, sniffed on load:

- JSON-lines (git-diffable; the format regression traces are filed
  in): header line, then one ``{"t","kind","data"}`` object per line.
- bin1: ``b"KTS1"`` magic, then length-prefixed ``fabric.codec``
  frames (header first, then events).

Both readers tolerate a torn tail — a trace cut mid-write yields the
decodable prefix, matching the WAL's crash semantics — EXCEPT a torn
header, which is an error (there is no trace to salvage).
"""

from __future__ import annotations

import io
import json
from dataclasses import dataclass, field

from kubernetes_tpu.fabric.codec import decode, encode, frame, read_frame

TRACE_VERSION = 1
MAGIC = b"KTS1"

EVENT_KINDS = ("pod", "node_up", "node_down", "node_cordon",
               "node_uncordon", "group", "obj")


@dataclass
class TraceEvent:
    t: float     # trace-time seconds since start
    kind: str    # one of EVENT_KINDS
    data: dict   # kind-specific payload (plain JSON-able values only)


@dataclass
class Trace:
    """Header + events. ``config`` carries replay hints (node/pod
    capacities, batch size, tenants) so every replay of one trace
    compiles the same jit shapes; ``slo`` is the regime's trace-time
    intent target; ``gate`` is the enforced ratchet bound a filed
    regression trace must stay under (observed × headroom at filing
    time — green after filing, trips on regressions)."""

    name: str
    events: list[TraceEvent] = field(default_factory=list)
    generator: str = ""
    seed: int = 0
    params: dict = field(default_factory=dict)
    config: dict = field(default_factory=dict)
    slo: dict = field(default_factory=dict)
    gate: dict = field(default_factory=dict)
    meta: dict = field(default_factory=dict)

    # ------------------------------------------------------ header

    def header(self) -> dict:
        return {
            "v": TRACE_VERSION,
            "name": self.name,
            "generator": self.generator,
            "seed": self.seed,
            "params": self.params,
            "config": self.config,
            "slo": self.slo,
            "gate": self.gate,
            "meta": self.meta,
        }

    @classmethod
    def from_header(cls, hdr: dict) -> "Trace":
        v = hdr.get("v")
        if v != TRACE_VERSION:
            raise ValueError(f"unsupported trace version {v!r}")
        return cls(name=hdr.get("name", ""),
                   generator=hdr.get("generator", ""),
                   seed=int(hdr.get("seed", 0)),
                   params=hdr.get("params", {}),
                   config=hdr.get("config", {}),
                   slo=hdr.get("slo", {}),
                   gate=hdr.get("gate", {}),
                   meta=hdr.get("meta", {}))

    def duration(self) -> float:
        return self.events[-1].t if self.events else 0.0

    def counts(self) -> dict:
        out: dict[str, int] = {}
        for e in self.events:
            out[e.kind] = out.get(e.kind, 0) + 1
        return out

    # ------------------------------------------------------ codecs

    def to_bytes(self, fmt: str = "jsonl") -> bytes:
        """Serialize; byte-identical for equal traces (sorted JSON keys,
        deterministic bin1) — the generator-determinism tests compare
        these bytes directly."""
        if fmt == "jsonl":
            lines = [json.dumps(self.header(), sort_keys=True)]
            lines += [json.dumps({"t": e.t, "kind": e.kind,
                                  "data": e.data}, sort_keys=True)
                      for e in self.events]
            return ("\n".join(lines) + "\n").encode()
        if fmt == "bin1":
            out = bytearray(MAGIC)
            out += frame(encode(self.header()))
            for e in self.events:
                out += frame(encode(
                    {"t": e.t, "kind": e.kind, "data": e.data}))
            return bytes(out)
        raise ValueError(f"unknown trace format {fmt!r}")

    @classmethod
    def from_bytes(cls, raw: bytes) -> "Trace":
        """Parse either encoding (sniffed by magic); torn event tails
        are dropped, a torn/absent header raises."""
        if raw[:len(MAGIC)] == MAGIC:
            return cls._from_bin1(raw)
        return cls._from_jsonl(raw)

    @classmethod
    def _from_jsonl(cls, raw: bytes) -> "Trace":
        lines = raw.decode(errors="replace").splitlines()
        if not lines:
            raise ValueError("empty trace")
        tr = cls.from_header(json.loads(lines[0]))
        for ln in lines[1:]:
            if not ln.strip():
                continue
            try:
                rec = json.loads(ln)
            except ValueError:
                break  # torn tail: keep the decodable prefix
            tr.events.append(TraceEvent(
                t=float(rec["t"]), kind=rec["kind"], data=rec["data"]))
        return tr

    @classmethod
    def _from_bin1(cls, raw: bytes) -> "Trace":
        fp = io.BytesIO(raw[len(MAGIC):])
        hdr = read_frame(fp)
        if hdr is None:
            raise ValueError("torn trace header")
        tr = cls.from_header(decode(hdr))
        while True:
            payload = read_frame(fp)
            if payload is None:
                break  # clean or torn tail
            try:
                rec = decode(payload)
            except ValueError:
                break  # corrupt tail frame
            tr.events.append(TraceEvent(
                t=float(rec["t"]), kind=rec["kind"], data=rec["data"]))
        return tr


def save_trace(trace: Trace, path: str, fmt: str | None = None) -> None:
    """Write a trace; format from ``fmt`` or the path suffix
    (``.jsonl`` -> JSON-lines, anything else -> bin1)."""
    if fmt is None:
        fmt = "jsonl" if path.endswith(".jsonl") else "bin1"
    with open(path, "wb") as f:
        f.write(trace.to_bytes(fmt))


def load_trace(path: str) -> Trace:
    with open(path, "rb") as f:
        return Trace.from_bytes(f.read())
