"""Node lifecycle injection — the ONE code path for node add / remove /
cordon / uncordon, shared by the perf-harness Churn op and the scenario
trace replayer (ISSUE 17 satellite: MixedChurn used to manipulate the
hub inline with the drive loop; traces and hand-built workloads now
inject node events identically).

Deliberately depends only on api + hub so scenario.replay and
perf.harness can both import it without a cycle.
"""

from __future__ import annotations

from kubernetes_tpu.api.objects import Node


class NodeLifecycle:
    """Apply node lifecycle events to a hub.

    remove/cordon/uncordon address nodes by NAME (traces don't know
    uids — the hub assigns them at create); ``add`` returns the created
    node so harness callers that track live objects can keep doing so.
    All verbs tolerate already-gone / already-in-state targets: a
    replayed trace must be idempotent across torn-tail resume, and a
    churn delete racing an eviction is routine.
    """

    def __init__(self, hub) -> None:
        self.hub = hub

    def add(self, node: Node) -> Node:
        self.hub.create_node(node)
        return node

    def remove(self, name: str) -> bool:
        node = self.hub.get_node(name)
        if node is None:
            return False
        try:
            self.hub.delete_node(node.metadata.uid)
        except Exception:  # noqa: BLE001 — lost a race with another delete
            return False
        return True

    def _set_unschedulable(self, name: str, value: bool) -> bool:
        node = self.hub.get_node(name)
        if node is None or node.spec.unschedulable == value:
            return False
        patched = node.clone()
        patched.spec.unschedulable = value
        self.hub.update_node(patched)
        return True

    def cordon(self, name: str) -> bool:
        return self._set_unschedulable(name, True)

    def uncordon(self, name: str) -> bool:
        return self._set_unschedulable(name, False)
