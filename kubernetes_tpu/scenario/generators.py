"""Named-regime trace generators: pure seeded functions params -> Trace.

Every generator is deterministic — same params + seed produce a
byte-identical trace (uids and creation timestamps are stamped from the
event stream, never from the wall clock; the replayer re-stamps
creation times at injection). All regimes share ONE set of replay
capacities (``REPLAY_CONFIG``) so every fuzz candidate compiles the
same jit shapes and a whole search pays XLA compilation once.

Feasibility discipline: pods in this world never terminate (only
eviction deletes them), so a regime whose total demand exceeds cluster
capacity or a tenant's quota wedges forever instead of producing a tail
— every generator keeps demand under capacity and engineers its p99
signal through *waiting* (outage windows, quota turn-taking, preemption
waves), which is speed-invariant in trace time.

Each regime registers fuzzable parameter BOUNDS; its SLO is the intent
target computed from the DEFAULT params, so default traces gate green
while fuzzed parameter excursions can breach and get filed.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Callable

from kubernetes_tpu.api.objects import (
    Affinity,
    Device,
    DeviceRequest,
    LABEL_POD_GROUP,
    LABEL_QUEUE,
    LABEL_ZONE,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    PodGroup,
    PodResourceClaim,
    ResourceClaim,
    ResourceClaimSpec,
    ResourceSlice,
)
from kubernetes_tpu.perf.workloads import _node, _pod
from kubernetes_tpu.scenario.trace import Trace, TraceEvent
from kubernetes_tpu.utils.wire import to_wire

# one shape set for every regime: node/pod capacity and batch are jit
# STATIC args, so sharing them lets a fuzz run replay dozens of
# candidate traces against one compile cache
REPLAY_CONFIG = {"node_capacity": 64, "pod_capacity": 2048,
                 "batch_size": 32}

# default node template fits 40 default pods (cpu 4 / 100m binds first,
# before the 65-pod memory and 110-pod slot limits)
PODS_PER_NODE = 40


def _stamp(obj, uid: str):
    """Deterministic identity: ObjectMeta autogenerates uid and
    creation_timestamp from the wall clock — fatal for byte-identical
    traces. The replayer re-stamps creation_timestamp at injection."""
    obj.metadata.uid = uid
    obj.metadata.creation_timestamp = 0.0
    return obj


def _ev(t: float, kind: str, data: dict) -> TraceEvent:
    return TraceEvent(t=round(t, 6), kind=kind, data=data)


def _pod_ev(t: float, pod) -> TraceEvent:
    return _ev(t, "pod", {"pod": to_wire(pod)})


def _finish(tr: Trace) -> Trace:
    tr.events.sort(key=lambda e: e.t)  # stable: ties keep build order
    return tr


# ------------------------------------------------------------ regimes


@dataclass
class Regime:
    """A registered generator: fn(params, seed) -> Trace, its default
    params, and per-param (lo, hi) fuzz bounds (ints stay ints)."""

    fn: Callable[[dict, int], Trace]
    defaults: dict
    bounds: dict = field(default_factory=dict)

    def generate(self, params: dict | None = None, seed: int = 0) -> Trace:
        p = {**self.defaults, **(params or {})}
        return self.fn(p, seed)


def diurnal_ramp(p: dict, seed: int) -> Trace:
    """Sinusoidal arrival rate over ``cycles`` day-cycles: trough load
    keeps the scheduler warm, each peak is a correlated burst. The tail
    signal is queueing at the crest."""
    rng = random.Random(seed)
    tr = Trace(name=f"diurnal_ramp-s{seed}", generator="diurnal_ramp",
               seed=seed, params=dict(p), config=dict(REPLAY_CONFIG),
               slo={"time_to_bind_p99_ms": 2000.0})
    for i in range(p["nodes"]):
        tr.events.append(_ev(0.0, "node_up", {
            "node": to_wire(_stamp(_node(i), f"uid-node-{i}"))}))
    # inverse-CDF sampling of a 1 + (peak-1)*(sin+1)/2 rate curve: pod i
    # arrives where the cumulative rate crosses quantile (i+jitter)/N
    n, dur, peak = p["pods"], float(p["duration"]), float(p["peak_ratio"])
    grid = 512
    dens = [1.0 + (peak - 1.0) * 0.5 *
            (1.0 + math.sin(2.0 * math.pi * p["cycles"] * g / grid
                            - math.pi / 2.0))
            for g in range(grid)]
    cdf, acc = [], 0.0
    for d in dens:
        acc += d
        cdf.append(acc)
    total = cdf[-1]
    g = 0
    for i in range(n):
        q = (i + rng.random()) / n * total
        while g < grid - 1 and cdf[g] < q:
            g += 1
        t = dur * (g + 1) / grid
        pod = _stamp(_pod(f"ramp-{i}"), f"uid-ramp-{i}")
        tr.events.append(_pod_ev(t, pod))
    return _finish(tr)


def sawtooth_churn(p: dict, seed: int) -> Trace:
    """A fixed fraction of nodes saw-tooths down/up on a period while
    pods arrive steadily; demand fits the TROUGH capacity so the regime
    stresses resyncs, not feasibility."""
    rng = random.Random(seed)
    tr = Trace(name=f"sawtooth_churn-s{seed}", generator="sawtooth_churn",
               seed=seed, params=dict(p), config=dict(REPLAY_CONFIG),
               slo={"time_to_bind_p99_ms": 2000.0})
    nodes, dur = p["nodes"], float(p["duration"])
    churned = max(1, int(nodes * p["churn_frac"]))
    for i in range(nodes):
        tr.events.append(_ev(0.0, "node_up", {
            "node": to_wire(_stamp(_node(i), f"uid-node-{i}"))}))
    period = float(p["period"])
    for i in range(churned):
        # per-node phase offset spreads the teeth across the period
        phase = period * i / churned
        t = phase + period * 0.5
        gen = 0
        while t < dur - period * 0.25:
            tr.events.append(_ev(t, "node_down", {"name": f"node-{i}"}))
            up = _stamp(_node(i), f"uid-node-{i}-g{gen + 1}")
            tr.events.append(_ev(t + period * 0.5, "node_up",
                                 {"node": to_wire(up)}))
            t += period
            gen += 1
    for i in range(p["pods"]):
        t = dur * (i + rng.random()) / p["pods"]
        tr.events.append(_pod_ev(
            t, _stamp(_pod(f"saw-{i}"), f"uid-saw-{i}")))
    return _finish(tr)


def _zone_affinity(zone: str) -> Affinity:
    return Affinity(node_affinity=NodeAffinity(
        required=NodeSelector(
            node_selector_terms=[NodeSelectorTerm(match_expressions=[
                NodeSelectorRequirement(key=LABEL_ZONE, operator="In",
                                        values=[zone])])])))


def zone_outage(p: dict, seed: int) -> Trace:
    """Zone outage + recovery stampede: one zone's nodes drop at
    ``outage_start`` and return ``outage_len`` later, together with a
    thundering-herd pod burst. Pods PINNED to the failed zone arrive
    during the window and can only bind after recovery, so their
    time-to-bind is ≈ the remaining window in trace time — a
    speed-invariant p99 signal."""
    rng = random.Random(seed)
    tr = Trace(name=f"zone_outage-s{seed}", generator="zone_outage",
               seed=seed, params=dict(p), config=dict(REPLAY_CONFIG),
               # intent target from the DEFAULT window: a pinned pod can
               # wait the whole default outage; fuzzed longer outages
               # breach this and get filed
               slo={"time_to_bind_p99_ms": 6000.0})
    zones = [f"zone-{z}" for z in range(p["zones"])]
    npz = p["nodes_per_zone"]
    t_out = float(p["outage_start"])
    t_rec = t_out + float(p["outage_len"])
    dur = float(p["duration"])
    for i in range(p["zones"] * npz):
        tr.events.append(_ev(0.0, "node_up", {
            "node": to_wire(_stamp(_node(i, zones=zones),
                                   f"uid-node-{i}"))}))
    # _node assigns zone i % len(zones): zone-0 nodes are i ≡ 0 (mod Z)
    failed = [i for i in range(p["zones"] * npz) if i % p["zones"] == 0]
    for i in failed:
        tr.events.append(_ev(t_out, "node_down", {"name": f"node-{i}"}))
        back = _stamp(_node(i, zones=zones), f"uid-node-{i}-r1")
        tr.events.append(_ev(t_rec, "node_up", {"node": to_wire(back)}))
    for i in range(p["bg_pods"]):
        t = dur * (i + rng.random()) / p["bg_pods"]
        tr.events.append(_pod_ev(
            t, _stamp(_pod(f"bg-{i}"), f"uid-bg-{i}")))
    # the speed-invariant tail: zone-0-only pods landing inside the window
    for i in range(p["pinned_pods"]):
        t = t_out + (t_rec - t_out) * 0.8 * (i + rng.random()) \
            / p["pinned_pods"]
        pod = _stamp(_pod(f"pinned-{i}", affinity=_zone_affinity("zone-0")),
                     f"uid-pinned-{i}")
        tr.events.append(_pod_ev(t, pod))
    # recovery stampede: the herd restarting the moment the zone returns
    for i in range(p["stampede_pods"]):
        t = t_rec + 0.5 * rng.random()
        tr.events.append(_pod_ev(
            t, _stamp(_pod(f"herd-{i}"), f"uid-herd-{i}")))
    return _finish(tr)


def quota_storm(p: dict, seed: int) -> Trace:
    """Every tenant bursts its full pod quota inside one window; DRR
    turn-taking and quota admission — not node capacity — set the
    drain order. Demand is exactly at quota so the storm fully drains
    (over-quota pods would park forever)."""
    rng = random.Random(seed)
    tenants = {f"tenant-{i}": {
        "weight": 1.0 + (i % 3),  # 1/2/3-weighted classes
        "quota": {"pods": str(p["pods_per_tenant"])}}
        for i in range(p["tenants"])}
    tr = Trace(name=f"quota_storm-s{seed}", generator="quota_storm",
               seed=seed, params=dict(p),
               config={**REPLAY_CONFIG, "tenants": tenants},
               slo={"time_to_bind_p99_ms": 2000.0})
    for i in range(p["nodes"]):
        tr.events.append(_ev(0.0, "node_up", {
            "node": to_wire(_stamp(_node(i), f"uid-node-{i}"))}))
    window = float(p["window"])
    for ti in range(p["tenants"]):
        for j in range(p["pods_per_tenant"]):
            t = window * rng.random()
            pod = _pod(f"t{ti}-p{j}", labels={LABEL_QUEUE: f"tenant-{ti}"})
            tr.events.append(_pod_ev(
                t, _stamp(pod, f"uid-t{ti}-p{j}")))
    return _finish(tr)


def _crossfire_node(i: int):
    n = _node(i)
    n.status.allocatable = {"cpu": "16", "memory": "64Gi", "pods": "110"}
    return n


def gang_dra_crossfire(p: dict, seed: int) -> Trace:
    """Low-priority fillers soak most of the CPU, then high-priority
    gangs whose members each claim a TPU device arrive — all-or-nothing
    gang admission, structured DRA allocation, and preemption sweeps
    fire in the same wave."""
    rng = random.Random(seed)
    tr = Trace(name=f"gang_dra_crossfire-s{seed}",
               generator="gang_dra_crossfire",
               seed=seed, params=dict(p), config=dict(REPLAY_CONFIG),
               slo={"time_to_bind_p99_ms": 8000.0})
    nodes = p["nodes"]
    for i in range(nodes):
        tr.events.append(_ev(0.0, "node_up", {
            "node": to_wire(_stamp(_crossfire_node(i), f"uid-node-{i}"))}))
        sl = ResourceSlice(
            metadata=ObjectMeta(name=f"slice-node-{i}"),
            node_name=f"node-{i}", driver="tpu.example.com",
            pool=f"node-{i}",
            devices=[Device(name=f"dev-{d}", device_class_name="tpu")
                     for d in range(8)])
        _stamp(sl, f"uid-slice-{i}")
        tr.events.append(_ev(0.0, "obj", {
            "verb": "create_resource_slice", "obj": to_wire(sl)}))
    fill_end = float(p["filler_window"])
    for i in range(p["filler_pods"]):
        t = fill_end * (i + rng.random()) / p["filler_pods"]
        pod = _pod(f"fill-{i}", cpu="400m", mem="200Mi", priority=0)
        tr.events.append(_pod_ev(t, _stamp(pod, f"uid-fill-{i}")))
    t_gang = fill_end + 0.5
    for g in range(p["gangs"]):
        size = p["gang_size"]
        grp = PodGroup(metadata=ObjectMeta(name=f"gang-{g}"),
                       min_member=size, queue="default",
                       schedule_timeout_seconds=120.0)
        _stamp(grp, f"uid-gang-{g}")
        tr.events.append(_ev(t_gang + g * 0.3, "group",
                             {"group": to_wire(grp)}))
        for m in range(size):
            claim = ResourceClaim(
                metadata=ObjectMeta(name=f"claim-g{g}-m{m}"),
                spec=ResourceClaimSpec(device_requests=[
                    DeviceRequest(name="accel", device_class_name="tpu",
                                  count=1)]))
            _stamp(claim, f"uid-claim-g{g}-m{m}")
            tr.events.append(_ev(t_gang + g * 0.3 + 0.01, "obj", {
                "verb": "create_resource_claim", "obj": to_wire(claim)}))
            pod = _pod(f"gang-{g}-m{m}", cpu="500m", mem="200Mi",
                       priority=100)
            pod.metadata.labels[LABEL_POD_GROUP] = f"gang-{g}"
            pod.spec.resource_claims = [PodResourceClaim(
                name="accel", resource_claim_name=f"claim-g{g}-m{m}")]
            t = t_gang + g * 0.3 + 0.05 + 0.2 * rng.random()
            tr.events.append(_pod_ev(t, _stamp(pod, f"uid-gang{g}m{m}")))
    return _finish(tr)


def overload_stampede(p: dict, seed: int) -> Trace:
    """Overload storm: a mass best-effort burst plus a tenant quota
    slam land on top of a small priority workload. Every best-effort
    tenant dumps its full pod quota inside one tiny window while
    low-weight (``weight`` 0.1 — the brownout best-effort tier) DRR
    turn-taking meters them out; the priority tenant (weight 8) keeps
    cutting through. The SLO gates the PRIORITY pods only
    (``slo_uid_prefix``): best-effort pods are SUPPOSED to wait — their
    p99 is the shed, not the regression. Demand stays under node
    capacity and exactly at quota so the storm fully drains."""
    rng = random.Random(seed)
    tenants = {"prio": {"weight": 8.0}}
    for i in range(p["be_tenants"]):
        tenants[f"be-{i}"] = {
            "weight": 0.1,
            "quota": {"pods": str(p["pods_per_tenant"])}}
    tr = Trace(name=f"overload_stampede-s{seed}",
               generator="overload_stampede",
               seed=seed, params=dict(p),
               config={**REPLAY_CONFIG, "tenants": tenants,
                       "slo_uid_prefix": "uid-prio-"},
               slo={"time_to_bind_p99_ms": 2500.0})
    for i in range(p["nodes"]):
        tr.events.append(_ev(0.0, "node_up", {
            "node": to_wire(_stamp(_node(i), f"uid-node-{i}"))}))
    dur = float(p["duration"])
    burst_at = float(p["burst_at"])
    window = float(p["burst_window"])
    # the protected class: high-priority pods spread over the WHOLE
    # duration, so some land before, inside, and after the stampede
    for i in range(p["prio_pods"]):
        t = dur * (i + rng.random()) / p["prio_pods"]
        pod = _pod(f"prio-{i}", labels={LABEL_QUEUE: "prio"},
                   priority=100)
        tr.events.append(_pod_ev(t, _stamp(pod, f"uid-prio-{i}")))
    # the stampede: every best-effort tenant slams its full quota into
    # one window — a correlated burst of be_tenants × pods_per_tenant
    for ti in range(p["be_tenants"]):
        for j in range(p["pods_per_tenant"]):
            t = burst_at + window * rng.random()
            pod = _pod(f"be{ti}-p{j}", labels={LABEL_QUEUE: f"be-{ti}"})
            tr.events.append(_pod_ev(t, _stamp(pod, f"uid-be{ti}-p{j}")))
    return _finish(tr)


GENERATORS: dict[str, Regime] = {
    "diurnal_ramp": Regime(
        diurnal_ramp,
        defaults={"nodes": 24, "pods": 600, "duration": 12.0,
                  "peak_ratio": 6.0, "cycles": 2},
        bounds={"pods": (100, 900), "duration": (4.0, 20.0),
                "peak_ratio": (1.0, 20.0), "cycles": (1, 4)}),
    "sawtooth_churn": Regime(
        sawtooth_churn,
        defaults={"nodes": 24, "churn_frac": 0.25, "period": 4.0,
                  "duration": 12.0, "pods": 500},
        bounds={"churn_frac": (0.05, 0.45), "period": (1.0, 6.0),
                "pods": (100, 700), "duration": (6.0, 16.0)}),
    "zone_outage": Regime(
        zone_outage,
        defaults={"zones": 4, "nodes_per_zone": 6, "bg_pods": 300,
                  "pinned_pods": 60, "stampede_pods": 200,
                  "outage_start": 3.0, "outage_len": 4.0,
                  "duration": 12.0},
        bounds={"outage_len": (1.0, 9.0), "pinned_pods": (20, 120),
                "stampede_pods": (50, 400), "outage_start": (1.0, 4.0)}),
    "quota_storm": Regime(
        quota_storm,
        defaults={"tenants": 100, "pods_per_tenant": 8, "nodes": 24,
                  "window": 3.0},
        bounds={"tenants": (10, 150), "pods_per_tenant": (2, 12),
                "window": (0.5, 6.0)}),
    "gang_dra_crossfire": Regime(
        gang_dra_crossfire,
        defaults={"nodes": 8, "filler_pods": 280, "filler_window": 3.0,
                  "gangs": 6, "gang_size": 8},
        bounds={"filler_pods": (100, 330), "gangs": (2, 10),
                "gang_size": (2, 8), "filler_window": (1.0, 5.0)}),
    # fuzz bounds keep peak demand under capacity at the extremes:
    # 20 tenants × 40 pods + 80 priority = 880 < 24 nodes × 40
    "overload_stampede": Regime(
        overload_stampede,
        defaults={"nodes": 24, "be_tenants": 12, "pods_per_tenant": 30,
                  "prio_pods": 40, "burst_at": 2.0, "burst_window": 0.5,
                  "duration": 10.0},
        bounds={"be_tenants": (5, 20), "pods_per_tenant": (10, 40),
                "prio_pods": (20, 80), "burst_window": (0.1, 2.0),
                "burst_at": (1.0, 4.0)}),
}


def generate(name: str, params: dict | None = None, seed: int = 0) -> Trace:
    """Build a named regime's trace. Unknown names raise with the
    catalog so CLI typos fail helpfully."""
    reg = GENERATORS.get(name)
    if reg is None:
        raise KeyError(
            f"unknown regime {name!r}; have {sorted(GENERATORS)}")
    return reg.generate(params, seed)
