"""Adversarial workload fuzzer: search generator parameter space for
the trace that maximizes trace-time p99 time-to-bind (or per-placement
regret), and auto-file SLO-breaching traces as permanent regression
gates.

Search shape: seeded random sampling over each regime's declared
parameter bounds, then coordinate-descent refinement around the worst
cell found — perturb one parameter at a time toward whichever direction
worsens the objective, keep improvements, stop on the wall-clock
budget. Every candidate replays against the SAME jit shapes
(generators.REPLAY_CONFIG), so a whole search pays XLA compilation
once.

Filing: a candidate whose trace-time stats breach its regime's intent
SLO is written to ``tests/regression_traces/`` as git-diffable
JSON-lines. The filed trace keeps the violated ``slo`` (the evidence —
replaying it reproduces the breach) and gains a ``gate``: the enforced
ratchet bound, set to the observed value × headroom, which replays
GREEN today and trips only if the scheduler regresses past it. The
replay speed the verdict was judged at is recorded in ``meta`` and
reused by the regression runner, because compute latency does not
compress with speed even though engineered waits do.
"""

from __future__ import annotations

import os
import random
import time
from typing import Callable, Optional

from kubernetes_tpu.scenario.generators import GENERATORS
from kubernetes_tpu.scenario.replay import replay_trace
from kubernetes_tpu.scenario.trace import Trace, save_trace

# gate headroom: the ratchet bound a filed trace enforces afterwards.
# Generous on purpose — the gate exists to catch regressions, not to
# re-litigate the breach on a noisy CI box
GATE_FACTOR = 2.0
GATE_PAD_MS = 1000.0

GATED_METRICS = ("time_to_bind_p50_ms", "time_to_bind_p99_ms",
                 "time_to_bind_max_ms")


def _sample(rng: random.Random, bounds: dict, defaults: dict) -> dict:
    p = dict(defaults)
    for k, (lo, hi) in bounds.items():
        if isinstance(lo, int) and isinstance(hi, int):
            p[k] = rng.randint(lo, hi)
        else:
            p[k] = round(rng.uniform(float(lo), float(hi)), 3)
    return p


def _perturb(rng: random.Random, params: dict, bounds: dict,
             key: str, direction: int) -> dict:
    """One coordinate-descent move: push ``key`` a quarter-range step in
    ``direction``, clamped to bounds."""
    lo, hi = bounds[key]
    step = (float(hi) - float(lo)) * 0.25 * direction
    v = float(params[key]) + step
    v = min(max(v, float(lo)), float(hi))
    if isinstance(lo, int) and isinstance(hi, int):
        v = int(round(v))
    else:
        v = round(v, 3)
    out = dict(params)
    out[key] = v
    return out


def _score(report: dict, objective: str) -> float:
    if not report.get("completed"):
        # a wedged trace is the worst outcome there is — but it can't be
        # filed as a gate (it never produces a stable verdict), so rank
        # it high without letting it win over real completed tails
        return float(report["stats"]["time_to_bind_p99_ms"]) + 1.0
    if objective == "regret":
        return float(report.get("regret", {}).get("regret_p99", 0.0))
    return float(report["stats"]["time_to_bind_p99_ms"])


def _gate_from(stats: dict) -> dict:
    return {m: round(float(stats[m]) * GATE_FACTOR + GATE_PAD_MS, 2)
            for m in GATED_METRICS if m in stats}


def file_regression_trace(trace: Trace, report: dict, out_dir: str,
                          objective: str) -> str:
    """Re-stamp the losing trace with its ratchet gate + provenance and
    write it as JSON-lines under ``out_dir``."""
    trace.gate = _gate_from(report["stats"])
    trace.meta = {
        **trace.meta,
        "filed_by": "scenario.fuzz",
        "filed_speed": report["speed"],
        "objective": objective,
        "observed": dict(report["stats"]),
        "violated_slo": dict(report["slo"]["target"]),
        "breaches": report["slo"]["breaches"],
    }
    os.makedirs(out_dir, exist_ok=True)
    fname = f"{trace.generator}-s{trace.seed}.jsonl"
    path = os.path.join(out_dir, fname)
    save_trace(trace, path, fmt="jsonl")
    return path


def fuzz(regimes: Optional[list[str]] = None, budget_s: float = 120.0,
         seed: int = 0, speed: float = 3.0, objective: str = "p99",
         out_dir: Optional[str] = None, refine_rounds: int = 2,
         replay_timeout_s: float = 60.0,
         log: Callable[[str], None] = lambda s: None,
         config=None) -> dict:
    """Run the adversarial search; returns the summary report.

    Phase 1 (random): round-robin the regimes, sampling params inside
    their bounds, until ~60% of the budget is gone. Phase 2 (descent):
    around the worst cell, perturb one parameter at a time both ways
    and recurse on improvements until the budget runs out. Candidates
    that breach their regime SLO are filed to ``out_dir`` (set it to
    tests/regression_traces/ to arm the ratchet); only the WORST
    breaching candidate per regime is filed, so a long search doesn't
    dump dozens of near-duplicate traces.
    """
    names = list(regimes or GENERATORS)
    rng = random.Random(seed)
    t0 = time.time()
    candidates = []
    worst = None            # (score, trace, report)
    filed_best: dict[str, tuple] = {}   # regime -> (score, trace, report)
    if objective == "regret" and config is None:
        import tempfile

        from kubernetes_tpu.config.types import default_config
        config = default_config()
        config.trace_export_path = os.path.join(
            tempfile.mkdtemp(prefix="scenario-fuzz-"), "export.jsonl")
        config.trace_export_alts = True

    def run_candidate(regime: str, params: dict, cand_seed: int):
        nonlocal worst
        trace = GENERATORS[regime].generate(params, seed=cand_seed)
        try:
            report = replay_trace(trace, speed=speed,
                                  timeout_s=replay_timeout_s,
                                  config=config)
        except Exception as exc:  # noqa: BLE001 — a crashing candidate
            log(f"  {regime} seed={cand_seed} CRASHED: {exc!r}")
            return None           # is logged, not fatal to the search
        score = _score(report, objective)
        row = {"regime": regime, "seed": cand_seed, "params": params,
               "score": round(score, 2),
               "slo_ok": report["slo"]["ok"],
               "completed": report["completed"],
               "audit_ok": report["audit"]["ok"]}
        candidates.append(row)
        log(f"  {regime} seed={cand_seed} score={score:.0f} "
            f"slo_ok={report['slo']['ok']} params={params}")
        if worst is None or score > worst[0]:
            worst = (score, trace, report)
        if not report["slo"]["ok"] and report["completed"]:
            prev = filed_best.get(regime)
            if prev is None or score > prev[0]:
                filed_best[regime] = (score, trace, report)
        return score

    def remaining() -> float:
        return budget_s - (time.time() - t0)

    # phase 1: seeded random sweep, round-robin across regimes
    i = 0
    while remaining() > budget_s * 0.4 or not candidates:
        regime = names[i % len(names)]
        i += 1
        reg = GENERATORS[regime]
        params = _sample(rng, reg.bounds, reg.defaults)
        run_candidate(regime, params, cand_seed=rng.randrange(1 << 16))
        if remaining() <= 0:
            break

    # phase 2: coordinate descent around the worst cell
    rounds = 0
    while worst is not None and remaining() > 0 and rounds < refine_rounds:
        rounds += 1
        _, wtrace, _ = worst
        regime = wtrace.generator
        bounds = GENERATORS[regime].bounds
        base = dict(wtrace.params)
        improved = False
        for key in bounds:
            if remaining() <= 0:
                break
            for direction in (+1, -1):
                if remaining() <= 0:
                    break
                cand = _perturb(rng, base, bounds, key, direction)
                if cand == base:
                    continue
                before = worst[0]
                s = run_candidate(regime, cand, cand_seed=wtrace.seed)
                if s is not None and s > before:
                    improved = True
                    break     # re-center on the new worst cell
            if improved:
                break
        if not improved:
            break

    filed = []
    if out_dir:
        for regime, (_, trace, report) in sorted(filed_best.items()):
            filed.append(file_regression_trace(trace, report, out_dir,
                                               objective))
            log(f"  filed {filed[-1]}")
    return {
        "objective": objective,
        "speed": speed,
        "budget_s": budget_s,
        "elapsed_s": round(time.time() - t0, 1),
        "candidates": len(candidates),
        "rows": candidates,
        "worst": None if worst is None else {
            "score": round(worst[0], 2),
            "regime": worst[1].generator,
            "seed": worst[1].seed,
            "params": worst[1].params,
            "slo": worst[2]["slo"],
        },
        "filed": filed,
    }
