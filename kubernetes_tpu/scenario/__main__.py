"""CLI for the scenario engine.

    python -m kubernetes_tpu.scenario list
    python -m kubernetes_tpu.scenario generate zone_outage --seed 3 \
        --out /tmp/zo.jsonl [--param outage_len=8]
    python -m kubernetes_tpu.scenario replay /tmp/zo.jsonl --speed 3
    python -m kubernetes_tpu.scenario replay zone_outage --speed 3
    python -m kubernetes_tpu.scenario fuzz --budget 120 --seed 0 \
        --file-to tests/regression_traces
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from kubernetes_tpu.scenario.fuzz import fuzz
from kubernetes_tpu.scenario.generators import GENERATORS, generate
from kubernetes_tpu.scenario.replay import replay_trace
from kubernetes_tpu.scenario.trace import load_trace, save_trace


def _params(kvs: list[str]) -> dict:
    out = {}
    for kv in kvs or []:
        k, _, v = kv.partition("=")
        try:
            out[k] = json.loads(v)
        except ValueError:
            out[k] = v
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="kubernetes_tpu.scenario")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sub.add_parser("list", help="catalog the named regimes")

    g = sub.add_parser("generate", help="params+seed -> trace file")
    g.add_argument("regime", choices=sorted(GENERATORS))
    g.add_argument("--seed", type=int, default=0)
    g.add_argument("--out", required=True)
    g.add_argument("--param", action="append", default=[],
                   help="override, e.g. --param outage_len=8")

    r = sub.add_parser("replay", help="replay a trace file or regime")
    r.add_argument("trace", help="path to a trace, or a regime name")
    r.add_argument("--seed", type=int, default=0)
    r.add_argument("--speed", type=float, default=3.0)
    r.add_argument("--timeout", type=float, default=180.0)
    r.add_argument("--param", action="append", default=[])

    f = sub.add_parser("fuzz", help="adversarial parameter search")
    f.add_argument("--budget", type=float, default=120.0,
                   help="wall-clock seconds")
    f.add_argument("--seed", type=int, default=0)
    f.add_argument("--speed", type=float, default=3.0)
    f.add_argument("--objective", choices=("p99", "regret"),
                   default="p99")
    f.add_argument("--regime", action="append", default=[],
                   help="restrict to these regimes (default: all)")
    f.add_argument("--file-to", default=None,
                   help="directory to file SLO-breaching traces into")

    args = ap.parse_args(argv)
    if args.cmd == "list":
        for name in sorted(GENERATORS):
            reg = GENERATORS[name]
            print(f"{name}: defaults={reg.defaults} "
                  f"fuzz_bounds={reg.bounds}")
        return 0
    if args.cmd == "generate":
        tr = generate(args.regime, _params(args.param), seed=args.seed)
        save_trace(tr, args.out)
        print(f"{args.out}: {len(tr.events)} events, "
              f"{tr.duration():.1f} trace-s, counts={tr.counts()}")
        return 0
    if args.cmd == "replay":
        if os.path.exists(args.trace):
            tr = load_trace(args.trace)
        else:
            tr = generate(args.trace, _params(args.param),
                          seed=args.seed)
        rep = replay_trace(tr, speed=args.speed,
                           timeout_s=args.timeout)
        print(json.dumps(rep, indent=1, default=str))
        return 0 if rep["ok"] else 1
    if args.cmd == "fuzz":
        rep = fuzz(regimes=args.regime or None, budget_s=args.budget,
                   seed=args.seed, speed=args.speed,
                   objective=args.objective, out_dir=args.file_to,
                   log=lambda s: print(s, flush=True))
        print(json.dumps({k: v for k, v in rep.items() if k != "rows"},
                         indent=1, default=str))
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
