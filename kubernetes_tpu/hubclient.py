"""RemoteHub: the client-go analog — a Hub implementation over HTTP.

Speaks hubserver's wire: typed verbs via ``POST /call``, informers via
``GET /watch`` streams (one reflector thread per watch, LIST replay +
synced marker + live events). A Scheduler constructed with a RemoteHub
runs unmodified against a hub in another process/host — the same
swap the reference makes between fake clientsets and a real apiserver.

Server-side Conflict/NotFound round-trip as the hub's own exception
types, so optimistic-concurrency handling (bind conflicts, requeues)
behaves identically on both transports.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

from kubernetes_tpu.hub import Conflict, EventHandlers, NotFound
from kubernetes_tpu.hubserver import CALL_METHODS, WATCH_KINDS
from kubernetes_tpu.utils.wire import from_wire, to_wire

_ERRORS = {"Conflict": Conflict, "NotFound": NotFound,
           "ValueError": ValueError, "TypeError": TypeError}


class RemoteError(Exception):
    """Server-side failure with no local exception mapping."""


class _RemoteLeases:
    def __init__(self, call):
        self._call = call

    def get(self, name: str):
        return self._call("leases.get", name)

    def update(self, lease, expect_holder) -> bool:
        return self._call("leases.update", lease, expect_holder)


class RemoteHub:
    def __init__(self, base_url: str, timeout: float = 30.0):
        self._base = base_url.rstrip("/")
        self._timeout = timeout
        self._watchers: list = []          # open watch responses
        self._threads: list[threading.Thread] = []
        self._closed = threading.Event()
        self.leases = _RemoteLeases(self._call)

    # ------------- RPC -------------

    def _call(self, method: str, *args):
        body = json.dumps({"method": method,
                           "args": [to_wire(a) for a in args]}).encode()
        req = urllib.request.Request(
            self._base + "/call", data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as resp:
                payload = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            payload = json.loads(e.read())
            exc = _ERRORS.get(payload.get("error", ""))
            msg = payload.get("message", "")
            if exc is not None:
                raise exc(msg) from None
            raise RemoteError(f"{payload.get('error')}: {msg}") from None
        return from_wire(payload["result"])

    def __getattr__(self, name: str):
        if name in CALL_METHODS:
            def proxy(*args, _m=name):
                return self._call(_m, *args)

            proxy.__name__ = name
            # cache so repeated lookups skip __getattr__
            setattr(self, name, proxy)
            return proxy
        raise AttributeError(name)

    # ------------- watch (reflector threads) -------------

    def _watch(self, kind: str, h: EventHandlers, replay: bool) -> None:
        """One reflector: LIST(replay)+WATCH with resourceVersion dedup,
        reconnect-with-relist on stream failure (client-go's reflector
        discipline). ``state`` tracks uid -> (rv, obj) so

        * duplicate adds from the replay/live race are dropped by rv,
        * orphan deletes (object gone before we ever listed it) are
          dropped,
        * a reconnect's replay is DIFFED against state: rv-newer objects
          dispatch as updates, unknown ones as adds, and tracked objects
          absent from the relist dispatch as deletes (the events missed
          during the gap).

        When the caller asked replay=False (live-only consumers), the
        first connection's replay still runs but only SEEDS state without
        dispatching, so reconnects can't replay ancient history at it."""
        synced = threading.Event()
        state: dict[str, tuple[int, object]] = {}

        def dispatch(ev: dict, suppress: bool, live: set) -> None:
            etype = ev.get("type")
            if etype == "delete":
                old = from_wire(ev.get("old"))
                uid = old.metadata.uid
                if state.pop(uid, None) is not None and h.on_delete \
                        and not suppress:
                    h.on_delete(old)
                return
            new = from_wire(ev.get("new"))
            uid = new.metadata.uid
            rv = new.metadata.resource_version
            live.add(uid)
            prev = state.get(uid)
            if prev is not None and rv <= prev[0]:
                return                      # duplicate (replay/live race)
            state[uid] = (rv, new)
            if suppress:
                return
            if prev is None:
                if h.on_add:
                    h.on_add(new)
            elif h.on_update:
                h.on_update(prev[1], new)

        def connect():
            resp = urllib.request.urlopen(
                f"{self._base}/watch?kind={kind}&replay=1",
                timeout=self._timeout)
            self._watchers.append(resp)
            return resp

        def consume(resp, suppress_replay: bool) -> None:
            replaying = True
            live: set[str] = set()
            for raw in resp:
                if self._closed.is_set():
                    return
                line = raw.strip()
                if not line:
                    continue
                ev = json.loads(line)
                if ev.get("synced"):
                    # relist diff: anything tracked but absent from this
                    # replay was deleted while we weren't watching
                    for uid in [u for u in state if u not in live]:
                        _, obj = state.pop(uid)
                        if h.on_delete and not suppress_replay:
                            h.on_delete(obj)
                    replaying = False
                    synced.set()
                    continue
                if not ev:
                    continue                # keepalive
                dispatch(ev, suppress_replay and replaying, live)

        def run(first_resp) -> None:
            resp, suppress = first_resp, not replay
            while not self._closed.is_set():
                try:
                    consume(resp, suppress)
                except (OSError, ValueError, AttributeError):
                    # close() from another thread nulls the fp mid-read
                    # (AttributeError); a dying server surfaces OSError
                    pass
                finally:
                    synced.set()
                    try:
                        resp.close()
                    except OSError:
                        pass
                if self._closed.is_set():
                    return
                # reconnect + relist; replay is never suppressed again —
                # state absorbs it via rv dedup, the diff emits the gap
                self._closed.wait(0.2)
                suppress = False
                try:
                    resp = connect()
                except OSError:
                    continue

        resp0 = connect()
        t = threading.Thread(target=run, args=(resp0,), daemon=True,
                             name=f"reflector-{kind}")
        t.start()
        self._threads.append(t)
        # WaitForCacheSync: watch_X returns only after the LIST replay has
        # been fully dispatched, matching the in-process hub's synchronous
        # replay semantics the scheduler's constructor relies on
        synced.wait(timeout=self._timeout)

    def unwatch(self, h: EventHandlers) -> None:
        """In-process parity no-op: remote watches end with close()."""

    def close(self) -> None:
        self._closed.set()
        for resp in self._watchers:
            try:
                resp.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2)
        self._watchers.clear()
        self._threads.clear()


def _make_watch(kind: str):
    def watch(self: RemoteHub, h: EventHandlers, replay: bool = True):
        self._watch(kind, h, replay)

    watch.__name__ = f"watch_{kind}"
    return watch


for _kind in WATCH_KINDS:
    setattr(RemoteHub, f"watch_{_kind}", _make_watch(_kind))
