"""RemoteHub: the client-go analog — a Hub implementation over HTTP.

Speaks hubserver's wire: typed verbs via ``POST /call``, informers via
``GET /watch`` streams (one reflector thread per watch connection, LIST
replay + synced marker + live events). A Scheduler constructed with a
RemoteHub runs unmodified against a hub in another process/host — the
same swap the reference makes between fake clientsets and a real
apiserver.

Server-side Conflict/NotFound round-trip as the hub's own exception
types, so optimistic-concurrency handling (bind conflicts, requeues)
behaves identically on both transports.

Wire codec (fabric.codec): the client offers the compact binary codec
on every call and watch; the server confirms only on an exact registry-
fingerprint match, and the client pins whichever codec the first /call
answer arrived in. A ``CodecMismatch`` verdict (server restarted with a
different registry shape) re-pins JSON and retries — negotiation is a
per-connection property, never a correctness risk. ``wire_stats()``
counts messages and bytes per codec for the ``wire_codec_*`` metrics.

Resilience (client-go's retry/reflector discipline, SURVEY §5.3/§5.8):

* idempotent verbs (get/list/leases.get) retry transport failures and
  5xx-gateway responses through decorrelated-jitter backoff under a
  per-call deadline and a shared retry budget (no retry storms);
* non-idempotent verbs fail fast with ``Unavailable`` so the caller's
  own reconciliation (informer truth, requeue-with-backoff) owns the
  ambiguity of a write that may or may not have landed;
* watch reconnects back off instead of spinning, the initial connect
  survives a hub still binding its port, and stale stream handles are
  pruned instead of leaking;
* ``connected``/``resilience_stats()`` expose degraded state, retry and
  reconnect counts, and cumulative degraded seconds for metrics.

Watch-resume (the etcd revision discipline, kubernetes_tpu.storage):
every reflector tracks the newest journal revision it has seen (event
``rv`` fields + sync markers). A reconnect dials ``since_rv=<last>``
first — the hub replays only the missed journal suffix, so a stream cut
at Daemonset scale costs a handful of events, not a 15k-object relist
storm. Only when the server answers 410 (``RvTooOld``: the gap was
compacted) does the reflector fall back to the full relist, whose
replay is DIFFED against local state so missed deletes still surface.
``resilience_stats()`` counts both paths (``watch_resumes`` /
``watch_relists``) — per CONNECTION, not per kind: a multiplexed watch
(``watch_kinds``, the relay tree's downstream shape) carries many kinds
on one socket, and a cut of that socket is ONE resume, not one per
kind.
"""

from __future__ import annotations

import http.client
import json
import logging
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

from kubernetes_tpu.fabric import codec as binwire
from kubernetes_tpu.hub import (
    Conflict,
    EventHandlers,
    Fenced,
    NotFound,
    NotLeader,
    StaleRing,
    TooManyRequests,
    Unavailable,
)
from kubernetes_tpu.hubserver import (
    CALL_METHODS,
    FRAMES_CONTENT_TYPE,
    WATCH_KINDS,
    format_cursors,
)
from kubernetes_tpu.storage import JournalEvent
from kubernetes_tpu.utils.backoff import Backoff, RetryBudget
from kubernetes_tpu.utils.wire import from_wire, to_wire

_ERRORS = {"Conflict": Conflict, "NotFound": NotFound, "Fenced": Fenced,
           "ValueError": ValueError, "TypeError": TypeError,
           # typed redirects: NotLeader re-parses its leader hint from
           # the message; StaleRing sends the caller back to the ring
           "NotLeader": NotLeader, "StaleRing": StaleRing,
           # flow control: re-parses its retry_after hint the same way
           # (429s are handled before this map for idempotent verbs —
           # the entry covers writes surfacing the typed verdict)
           "TooManyRequests": TooManyRequests}

# safe to replay blindly: reads never mutate. The split covers dotted
# verbs too ("leases.get" -> "get"). The explicit extras are fabric
# verbs that are retry-safe without being reads: re-registering a
# shard/relay is idempotent, advancing the allocator floor is a max(),
# and a retried rv.next merely burns a revision (gaps in the global rv
# space are already the journal's contract).
IDEMPOTENT_METHODS = frozenset(
    m for m in CALL_METHODS
    if m.split(".")[-1].startswith(("get", "list"))) | frozenset({
        # a retried eviction wave skips already-gone victims, so replay
        # after an ambiguous transport failure is safe
        "delete_pods",
        "rv.next", "rv.advance_to", "rv.last", "leases.epoch_of",
        "fabric_register_shard", "fabric_register_relay",
        "fabric_register_router", "fabric_topology", "fabric_shards",
        "fabric_ring", "fabric_replica_status",
        # re-registering (or re-dropping) a scheduler replica is
        # idempotent like the other registries; fabric_set_sched_ring
        # is a CAS and deliberately NOT here (same as fabric_set_ring)
        "fabric_register_scheduler", "fabric_unregister_scheduler",
        "fabric_schedulers", "fabric_sched_ring",
    })

# a response from these statuses is the PATH failing, not the hub's
# verdict on the request (gateway/proxy 5xx — chaos injects 503)
_RETRYABLE_HTTP = (502, 503, 504)

_TRANSPORT_ERRORS = (OSError, http.client.HTTPException)

logger = logging.getLogger("kubernetes_tpu.hubclient")


class RemoteError(Exception):
    """Server-side failure with no local exception mapping."""


class _RemoteNamespace:
    """Dotted-verb proxy: ``client.leases.update(...)`` -> the wire's
    ``leases.update`` — one shape for every namespaced surface (leases,
    the fabric state shard's ``rv`` allocator)."""

    __slots__ = ("_call", "_prefix")

    def __init__(self, call, prefix: str):
        self._call = call
        self._prefix = prefix

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        method = f"{self._prefix}.{name}"

        def proxy(*args, _m=method):
            return self._call(_m, *args)

        proxy.__name__ = name
        return proxy


class _RemoteLeases(_RemoteNamespace):
    """The wire carries positional args only; ``update`` is pinned
    here because LeaderElector calls it with ``expect_holder=`` as a
    keyword."""

    def update(self, lease, expect_holder=None) -> bool:
        return self._call("leases.update", lease, expect_holder)


class RemoteHub:
    def __init__(self, base_url: str, timeout: float = 30.0,
                 retry_deadline: float = 8.0,
                 retry_base: float = 0.05, retry_cap: float = 1.0,
                 retry_budget: float = 20.0,
                 retry_refill_per_sec: float = 4.0,
                 codec: str | None = None,
                 identity: str | None = None):
        self._base = base_url.rstrip("/")
        self._timeout = timeout
        # the caller's component identity (scheduler/relay/...): rides
        # every /call (X-KTPU-Identity) and watch dial (identity=) so
        # flow control classifies the flow instead of guessing from
        # the verb; None = unattributed (best-effort level)
        self._identity = identity
        self._retry_deadline = retry_deadline
        self._retry_base = retry_base
        self._retry_cap = retry_cap
        self._budget = RetryBudget(budget=retry_budget,
                                   refill_per_sec=retry_refill_per_sec)
        self._watchers: list = []          # open watch responses
        self._threads: list[threading.Thread] = []
        self._closed = threading.Event()
        self._wlock = threading.Lock()     # guards _watchers
        # wire codec: None = negotiate (offer bin1, pin whatever the
        # first /call answer arrives in); "json" forces the legacy wire
        self._pin: str | None = \
            binwire.CODEC_JSON if codec == binwire.CODEC_JSON else None
        # degraded-state tracking (stats lock; hot path touches it only
        # on failure or on the first success after a failure)
        self._slock = threading.Lock()
        self._degraded_since: float | None = None
        self._degraded_accum = 0.0
        self._retries = 0
        self._throttled = 0          # 429 answers (calls + watch dials)
        self._throttle_retries = 0   # 429s retried with the server hint
        self._watch_reconnects = 0
        self._watch_resumes = 0    # reconnects served from the journal
        self._watch_relists = 0    # reconnects that fell back to LIST
        # per-codec message/byte accounting (wire_codec_* metrics)
        self._wire = {binwire.CODEC_JSON: {"msgs": 0, "bytes_sent": 0,
                                           "bytes_recv": 0},
                      binwire.CODEC_BINARY: {"msgs": 0, "bytes_sent": 0,
                                             "bytes_recv": 0}}
        # reflectors currently disconnected (watch health is tracked
        # apart from call health: RPCs can succeed while every stream is
        # down, and informer-confirm-dependent logic must see THAT)
        self._watch_down = 0
        self.leases = _RemoteLeases(self._call, "leases")
        # the fabric state shard's shared revision allocator (rv.next /
        # rv.last / rv.advance_to); harmless against a plain hub, which
        # simply doesn't serve the verbs
        self.rv = _RemoteNamespace(self._call, "rv")

    # ------------- degraded-state bookkeeping -------------

    def _mark_degraded(self) -> None:
        with self._slock:
            if self._degraded_since is None:
                self._degraded_since = time.monotonic()

    def _mark_connected(self) -> None:
        if self._degraded_since is None:   # benign race: cheap fast path
            return
        with self._slock:
            if self._degraded_since is not None:
                self._degraded_accum += time.monotonic() - \
                    self._degraded_since
                self._degraded_since = None

    def _count_wire(self, codec: str, sent: int = 0, recv: int = 0,
                    msgs: int = 1) -> None:
        with self._slock:
            w = self._wire[codec]
            w["msgs"] += msgs
            w["bytes_sent"] += sent
            w["bytes_recv"] += recv

    def _count_call(self, body_codec: str, sent: int,
                    resp_codec: str, recv: int) -> None:
        """Both halves of one RPC under ONE lock acquisition (the
        request and answer may ride different codecs mid-negotiation)."""
        with self._slock:
            w = self._wire[body_codec]
            w["msgs"] += 1
            w["bytes_sent"] += sent
            w = self._wire[resp_codec]
            w["msgs"] += 1
            w["bytes_recv"] += recv

    @property
    def connected(self) -> bool:
        return self._degraded_since is None

    @property
    def codec(self) -> str:
        """The pinned wire codec ("bin1"/"json"); "json" while still
        probing (the probe itself goes out on the JSON wire)."""
        return self._pin or binwire.CODEC_JSON

    @property
    def watches_healthy(self) -> bool:
        """False while any reflector stream is down — even if RPCs
        succeed, informer confirms cannot arrive through a dead watch."""
        with self._slock:
            return self._watch_down == 0

    def resilience_stats(self) -> dict:
        """Counters for the hub_client_* metrics."""
        with self._slock:
            degraded_s = self._degraded_accum
            if self._degraded_since is not None:
                degraded_s += time.monotonic() - self._degraded_since
            return {"retries": self._retries,
                    "throttled_429s": self._throttled,
                    "throttle_retries": self._throttle_retries,
                    "watch_reconnects": self._watch_reconnects,
                    "watch_resumes": self._watch_resumes,
                    "watch_relists": self._watch_relists,
                    "watches_down": self._watch_down,
                    "degraded_seconds": degraded_s,
                    "degraded": self._degraded_since is not None,
                    "codec": self._pin or "negotiating",
                    "wire": {c: dict(w) for c, w in self._wire.items()}}

    # ------------- RPC -------------

    def _call(self, method: str, *args):
        idempotent = method in IDEMPOTENT_METHODS
        bo = Backoff(self._retry_base, self._retry_cap)
        t_end = time.monotonic() + self._retry_deadline
        while True:
            pin = self._pin
            if pin == binwire.CODEC_BINARY:
                body = binwire.encode({"method": method,
                                       "args": list(args)})
                headers = {"Content-Type": "application/x-ktpu-bin",
                           binwire.WIRE_HEADER: binwire.offer()}
                body_codec = binwire.CODEC_BINARY
            else:
                body = json.dumps({
                    "method": method,
                    "args": [to_wire(a) for a in args]}).encode()
                headers = {"Content-Type": "application/json"}
                if pin is None:
                    # the probe: JSON body, "I can read bin1" offer —
                    # the answer's codec pins the connection
                    headers[binwire.WIRE_HEADER] = \
                        f"json;accept={binwire.CODEC_BINARY};" \
                        f"fp={binwire.registry_fingerprint()}"
                body_codec = binwire.CODEC_JSON
            if self._identity:
                headers["X-KTPU-Identity"] = self._identity
            req = urllib.request.Request(
                self._base + "/call", data=body, headers=headers)
            try:
                with urllib.request.urlopen(
                        req, timeout=self._timeout) as resp:
                    raw = resp.read()
                    resp_bin = resp.headers.get(
                        binwire.WIRE_HEADER, "").startswith(
                            binwire.CODEC_BINARY)
                self._mark_connected()
                self._count_call(body_codec, len(body),
                                 binwire.CODEC_BINARY if resp_bin
                                 else binwire.CODEC_JSON, len(raw))
                if self._pin is None:
                    # the server's answer codec IS the negotiation
                    # verdict (it confirms bin1 only on fingerprint
                    # match); pin it for every later call
                    self._pin = binwire.CODEC_BINARY if resp_bin \
                        else binwire.CODEC_JSON
                if resp_bin:
                    return binwire.decode(raw)["result"]
                return from_wire(json.loads(raw)["result"])
            except urllib.error.HTTPError as e:
                if e.code == 429:
                    # flow control shed us: the server is HEALTHY and
                    # answered with a hint — not degraded transport.
                    # Idempotent verbs retry with max(hint, jitter)
                    # inside the normal budget + deadline; writes
                    # surface the typed verdict (a non-idempotent verb
                    # is never replayed blindly, throttled or not).
                    self._mark_connected()
                    hint = 0.0
                    try:
                        hint = float(e.headers.get("Retry-After")
                                     or 0.0)
                    except (TypeError, ValueError):
                        hint = 0.0
                    try:
                        msg = json.loads(e.read()).get("message", "")
                    except (ValueError, OSError):
                        msg = ""
                    try:
                        e.close()
                    except OSError:
                        pass
                    with self._slock:
                        self._throttled += 1
                    exc = TooManyRequests(msg)
                    hint = hint or exc.retry_after
                    remaining = t_end - time.monotonic()
                    if not idempotent or remaining <= 0 \
                            or not self._budget.try_spend():
                        raise exc from None
                    with self._slock:
                        self._retries += 1
                        self._throttle_retries += 1
                    time.sleep(min(max(hint, bo.next()),
                                   max(remaining, 0.0)))
                    continue
                if e.code in _RETRYABLE_HTTP:
                    err = f"HTTP {e.code}"
                    try:
                        e.close()   # don't leak one socket per retry
                    except OSError:
                        pass
                else:
                    # the hub answered: transport is fine, the request
                    # has a verdict — map and raise it
                    self._mark_connected()
                    try:
                        payload = json.loads(e.read())
                    except (ValueError, OSError):
                        payload = {"error": f"HTTP {e.code}", "message": ""}
                    if payload.get("error") == "CodecMismatch" \
                            and pin != binwire.CODEC_JSON:
                        # the server's registry shape changed under us
                        # (restart with different code): re-pin JSON and
                        # retry — deterministic fix, not a fault
                        self._pin = binwire.CODEC_JSON
                        continue
                    exc = _ERRORS.get(payload.get("error", ""))
                    msg = payload.get("message", "")
                    if exc is not None:
                        raise exc(msg) from None
                    raise RemoteError(
                        f"{payload.get('error')}: {msg}") from None
            except _TRANSPORT_ERRORS as e:
                err = repr(e)
            self._mark_degraded()
            remaining = t_end - time.monotonic()
            if not idempotent or remaining <= 0 \
                    or not self._budget.try_spend():
                raise Unavailable(f"{method}: {err}") from None
            with self._slock:
                self._retries += 1
            time.sleep(min(bo.next(), max(remaining, 0.0)))

    def __getattr__(self, name: str):
        if name in CALL_METHODS:
            def proxy(*args, _m=name):
                return self._call(_m, *args)

            proxy.__name__ = name
            # cache so repeated lookups skip __getattr__
            setattr(self, name, proxy)
            return proxy
        raise AttributeError(name)

    # ------------- watch (reflector threads) -------------

    def watch_kinds(self, handlers: dict[str, EventHandlers],
                    replay: bool = True, since_rv: int | None = None,
                    cursors: dict[str, int] | None = None) -> None:
        """MULTIPLEXED watch: every kind in ``handlers`` rides ONE
        connection (the hubserver/relay ``kinds=`` wire), each event
        dispatched to its kind's handlers. One socket instead of one
        per kind is what lets 10k kubelet-analog clients hang off a
        relay without 10k×kinds upstream streams — and the
        resume/relist counters stay accurate because they count
        CONNECTIONS, not kinds.

        ``since_rv``/``cursors`` make the FIRST dial a resume instead
        of a LIST (a relay re-parenting onto a sibling carries its
        cursors over); a 410 on that first dial falls back to the
        relist path — whose replay is diffed, so continuity holds
        either way."""
        self._watch_multi(dict(handlers), replay, since_rv, cursors)

    def _watch(self, kind: str, h: EventHandlers, replay: bool) -> None:
        self._watch_multi({kind: h}, replay)

    def _watch_multi(self, handlers: dict[str, EventHandlers],
                     replay: bool, init_since: int | None = None,
                     init_cursors: dict[str, int] | None = None) -> None:
        """One reflector CONNECTION: LIST(replay)+WATCH with
        resourceVersion dedup, reconnect-with-RESUME on stream failure
        (client-go's reflector discipline over the hub's etcd-analog
        journal). Per kind, ``state`` tracks uid -> (rv, obj) so

        * duplicate adds from the replay/live race are dropped by rv,
        * orphan deletes (object gone before we ever listed it) are
          dropped,
        * a RELIST's replay is DIFFED against state: rv-newer objects
          dispatch as updates, unknown ones as adds, and tracked objects
          absent from the relist dispatch as deletes (the events missed
          during the gap).

        ``last_rv`` tracks the newest journal revision this connection
        has seen (event rv fields and sync markers; the revision space
        is global, so one cursor serves every kind on the stream).
        Reconnects dial ``since_rv=last_rv`` first: the hub replays
        only the missed journal suffix — no relist, no diff needed. A
        410 answer (RvTooOld: the gap was compacted) falls back to the
        full-relist path above. ``watch_resumes``/``watch_relists``
        count the split once per reconnect.

        When the caller asked replay=False (live-only consumers), the
        first connection's replay still runs but only SEEDS state without
        dispatching, so reconnects can't replay ancient history at it.

        Handlers with ``on_event`` set receive JournalEvents (rv
        included) instead of the typed trio — dedup and relist-diff
        still apply first; ``on_sync(rv, relisted)`` fires at each sync
        marker (the relay tree's continuity signal)."""
        kinds = sorted(handlers)
        mux = len(kinds) > 1
        synced = threading.Event()
        states: dict[str, dict[str, tuple[int, object]]] = \
            {k: {} for k in kinds}
        current: list = [None]   # this connection's live response handle
        last_rv = [init_since or 0]   # newest journal revision seen
        # per-SOURCE-SHARD resume cursors (the wire's "sh" tags + the
        # sync marker's "shards" map): through the fabric router the
        # stream is rv-ordered per shard but NOT across shards, so
        # resuming every shard at last_rv could skip a slower shard's
        # events forever; resuming each at ITS OWN cursor cannot.
        # Untagged streams (a single hub) leave this empty and resume
        # by last_rv exactly as before.
        shard_rvs: dict[str, int] = dict(init_cursors or {})

        def note_rv(rv) -> None:
            if rv and rv > last_rv[0]:
                last_rv[0] = rv

        def note_shard(sh, rv) -> None:
            if sh and rv and rv > shard_rvs.get(sh, 0):
                shard_rvs[sh] = rv

        def deliver(h: EventHandlers, etype: str, rv: int, kind: str,
                    old, new, trace=None, shard=None) -> None:
            if h.on_event is not None:
                h.on_event(JournalEvent(rv=rv, kind=kind, type=etype,
                                        old=old, new=new, trace=trace,
                                        shard=shard))
            elif etype == "delete":
                if h.on_delete:
                    h.on_delete(old)
            elif etype == "add":
                if h.on_add:
                    h.on_add(new)
            elif h.on_update:
                h.on_update(old, new)

        def dispatch(ev: dict, suppress: bool,
                     live: dict[str, set]) -> None:
            kind = ev.get("kind") or kinds[0]
            state = states.get(kind)
            if state is None:
                return                      # unknown kind on the stream
            h = handlers[kind]
            etype = ev.get("type")
            shard = ev.get("sh")
            # the commit's trace stamp: already a TraceContext on the
            # binary wire, a tagged dict on JSON; absent from a
            # pre-telemetry peer (hop data degrades, events never drop)
            trace = ev.get("trace")
            if isinstance(trace, dict):
                trace = from_wire(trace)
            if etype == "delete":
                old = from_wire(ev.get("old"))
                uid = old.metadata.uid
                if state.pop(uid, None) is not None and not suppress:
                    deliver(h, "delete", ev.get("rv") or 0, kind,
                            old, None, trace, shard)
                return
            new = from_wire(ev.get("new"))
            uid = new.metadata.uid
            rv = new.metadata.resource_version
            live[kind].add(uid)
            prev = state.get(uid)
            if prev is not None and rv <= prev[0]:
                return                      # duplicate (replay/live race)
            state[uid] = (rv, new)
            if suppress:
                return
            if prev is None:
                deliver(h, "add", rv, kind, None, new, trace, shard)
            else:
                deliver(h, "update", rv, kind, prev[1], new, trace,
                        shard)

        def connect(since_rv: int | None = None,
                    curs: dict[str, int] | None = None):
            kq = f"kinds={','.join(kinds)}" if mux else f"kind={kinds[0]}"
            if since_rv is not None:
                url = f"{self._base}/watch?{kq}&since_rv={since_rv}"
                if curs:
                    url += "&cursors=" + format_cursors(curs)
            else:
                url = f"{self._base}/watch?{kq}&replay=1"
            if self._pin != binwire.CODEC_JSON:
                url += f"&codec={binwire.CODEC_BINARY}" \
                       f"&fp={binwire.registry_fingerprint()}"
            if self._identity:
                url += "&identity=" + urllib.parse.quote(
                    self._identity, safe="")
            resp = urllib.request.urlopen(url, timeout=self._timeout)
            with self._wlock:
                # swap, don't leak: the previous connection's response
                # object is dead once we reconnect
                old = current[0]
                if old is not None and old in self._watchers:
                    self._watchers.remove(old)
                current[0] = resp
                self._watchers.append(resp)
            return resp

        def stream_events(resp):
            """Yield decoded event dicts in the stream's codec. Binary
            frames carry real objects (dispatch's from_wire passes them
            through); JSON lines carry tagged dicts. Wire accounting is
            batched (local counters, flushed every 64 events and at
            stream end): a per-event lock acquisition would contend the
            stats lock at relay-storm event rates."""
            ctype = resp.headers.get("Content-Type", "")
            is_bin = ctype.startswith(FRAMES_CONTENT_TYPE)
            codec_name = binwire.CODEC_BINARY if is_bin \
                else binwire.CODEC_JSON
            pend_msgs = pend_bytes = 0
            try:
                if is_bin:
                    while True:
                        payload = binwire.read_frame(resp)
                        if payload is None:
                            return
                        pend_msgs += 1
                        pend_bytes += len(payload) + 4
                        if pend_msgs >= 64:
                            self._count_wire(codec_name,
                                             recv=pend_bytes,
                                             msgs=pend_msgs)
                            pend_msgs = pend_bytes = 0
                        yield binwire.decode(payload)
                else:
                    for raw in resp:
                        line = raw.strip()
                        if not line:
                            continue
                        pend_msgs += 1
                        pend_bytes += len(raw)
                        if pend_msgs >= 64:
                            self._count_wire(codec_name,
                                             recv=pend_bytes,
                                             msgs=pend_msgs)
                            pend_msgs = pend_bytes = 0
                        yield json.loads(line)
            finally:
                if pend_msgs:
                    self._count_wire(codec_name, recv=pend_bytes,
                                     msgs=pend_msgs)

        def consume(resp, suppress_replay: bool,
                    progressed: list[bool], resumed: bool) -> None:
            # a resumed stream replays the JOURNAL SUFFIX, not a LIST:
            # its pre-sync events are ordinary incremental events (never
            # suppressed, never relist-diffed at the sync marker)
            in_replay = not resumed
            sync_seen = False
            live: dict[str, set] = {k: set() for k in kinds}
            gen = stream_events(resp)
            try:
                for ev in gen:
                    if self._closed.is_set():
                        return
                    if sync_seen and ev and not ev.get("synced"):
                        # a LIVE event arrived: the stream genuinely worked,
                        # so the next outage's backoff restarts from base.
                        # (Keying on any bytes would reset on every replay —
                        # a reconnect/relist storm the backoff exists to
                        # damp. consume() normally ENDS via an exception, so
                        # a return-based signal would never fire.)
                        progressed[0] = True
                    if ev.get("synced"):
                        note_rv(ev.get("rv"))
                        # the router/relay's per-shard sync map seeds
                        # the composite cursors: "complete through
                        # these per-shard revisions"
                        for sh, srv in (ev.get("shards") or {}).items():
                            note_shard(sh, srv)
                        if in_replay:
                            # relist diff: anything tracked but absent from
                            # this replay was deleted while we weren't
                            # watching
                            for kind in kinds:
                                state = states[kind]
                                seen = live[kind]
                                for uid in [u for u in state
                                            if u not in seen]:
                                    _, obj = state.pop(uid)
                                    if not suppress_replay:
                                        deliver(handlers[kind], "delete",
                                                ev.get("rv") or last_rv[0],
                                                kind, obj, None)
                        for kind in kinds:
                            h = handlers[kind]
                            if h.on_sync is not None:
                                h.on_sync(ev.get("rv") or last_rv[0],
                                          in_replay,
                                          ev.get("shards"))
                        in_replay = False
                        sync_seen = True
                        synced.set()
                        continue
                    if not ev:
                        continue                # keepalive
                    if not in_replay:
                        # the resume point advances ONLY along rv-ordered
                        # streams: live events, journal suffixes, and sync
                        # markers. LIST replay is insertion-ordered — a cut
                        # mid-replay could leave last_rv beyond objects never
                        # delivered, and a resume from there would skip them
                        # silently forever; leaving last_rv untouched makes
                        # that reconnect retry/relist instead. The same
                        # discipline governs the per-shard cursors.
                        note_rv(ev.get("rv"))
                        note_shard(ev.get("sh"), ev.get("rv"))
                    dispatch(ev, suppress_replay and in_replay, live)
            finally:
                # flush the batched wire counters DETERMINISTICALLY on
                # every exit — disconnect, EOF, close(), a dispatch
                # raise. Leaving the suspended generator to GC would
                # run its flushing finally "eventually" (refcount
                # timing), and a short stream's tail (< the 64-event
                # batch) would be missing from wire_codec_* until then
                gen.close()

        def run(first_resp, first_resumed: bool = False) -> None:
            resp, suppress = first_resp, not replay
            resumed = first_resumed
            bo = Backoff(self._retry_base, self._retry_cap)
            stream_ok = [True]

            def set_down(down: bool) -> None:
                # per-connection edge-triggered contribution to the
                # client-wide watch-health gauge (watches_healthy):
                # call health alone can't see a dead stream, and
                # informer-confirm-dependent logic needs to
                if down and stream_ok[0]:
                    stream_ok[0] = False
                    with self._slock:
                        self._watch_down += 1
                elif not down and not stream_ok[0]:
                    stream_ok[0] = True
                    with self._slock:
                        self._watch_down -= 1

            try:
                while not self._closed.is_set():
                    progressed = [False]
                    try:
                        consume(resp, suppress, progressed, resumed)
                    except (OSError, ValueError, AttributeError,
                            http.client.HTTPException):
                        # close() from another thread nulls the fp
                        # mid-read (AttributeError); a dying server
                        # surfaces OSError on the line reader but
                        # IncompleteRead (HTTPException) on the frame
                        # reader's exact-length read; a torn frame/line
                        # raises ValueError
                        pass
                    finally:
                        synced.set()
                        try:
                            resp.close()
                        except OSError:
                            pass
                    if self._closed.is_set():
                        return     # clean close() is not an outage
                    set_down(True)
                    if progressed[0]:
                        # the stream lived long enough to carry events:
                        # the next outage's backoff restarts from base
                        bo.reset()
                    self._mark_degraded()
                    # reconnect, preferring RESUME (since_rv=last seen
                    # revision: the hub replays only the missed journal
                    # suffix). A 410 means the gap was compacted — fall
                    # back to the relist, whose replay is never
                    # suppressed — state absorbs it via rv dedup, the
                    # diff emits the gap. The inner loop sleeps-then-
                    # dials until a connection holds, so consume() is
                    # never re-entered with a dead handle.
                    force_relist = False
                    hint = 0.0
                    while True:
                        if self._closed.wait(max(hint, bo.next())):
                            return             # close() during the sleep
                        hint = 0.0
                        if force_relist:
                            # stale per-shard cursors die with the
                            # relist; the diff covers the gap and the
                            # next sync marker re-seeds them
                            shard_rvs.clear()
                        since = None if force_relist or last_rv[0] <= 0 \
                            else last_rv[0]
                        try:
                            resp = connect(since, dict(shard_rvs)
                                           if since is not None
                                           and shard_rvs else None)
                        except urllib.error.HTTPError as e:
                            code = e.code
                            ra = e.headers.get("Retry-After") \
                                if e.headers else None
                            try:
                                e.close()      # no socket leak per retry
                            except OSError:
                                pass
                            if code == 410 and since is not None:
                                # journal compacted past our resume
                                # point: relist on the next dial
                                force_relist = True
                                continue
                            if code == 429:
                                # shed under watch-admission pressure:
                                # an honest throttle from a healthy
                                # server, not a verdict — redial after
                                # its Retry-After hint
                                try:
                                    hint = float(ra or 0.0)
                                except (TypeError, ValueError):
                                    hint = 0.0
                                with self._slock:
                                    self._throttled += 1
                                continue
                            if code in _RETRYABLE_HTTP:
                                continue       # gateway blip: redial
                            # a definitive server verdict (400 unknown
                            # kind, 404 misroute) cannot heal by
                            # retrying: stop this reflector instead of
                            # hammering the server
                            logger.error("watch %s rejected by server "
                                         "(HTTP %s); reflector stopping",
                                         ",".join(kinds), code)
                            return
                        except _TRANSPORT_ERRORS:
                            continue
                        resumed = since is not None
                        break
                    if self._closed.is_set():
                        # close() raced the reconnect: it already
                        # drained _watchers, so this handle is ours
                        try:
                            resp.close()
                        except OSError:
                            pass
                        return
                    suppress = False
                    set_down(False)
                    self._mark_connected()
                    # ONE reconnect = ONE resume-or-relist, however many
                    # kinds ride the connection (a relay-tree client
                    # multiplexes them all; counting per kind would
                    # overstate every cut by the kind count)
                    with self._slock:
                        self._watch_reconnects += 1
                        if resumed:
                            self._watch_resumes += 1
                        else:
                            self._watch_relists += 1
            finally:
                # a reflector exiting (close(), fatal server verdict)
                # must not pin the client-wide watch-health gauge down
                set_down(False)

        # guard the FIRST connect: scheduler startup must survive a hub
        # that is still binding its port (bounded retry, then Unavailable)
        bo = Backoff(self._retry_base, self._retry_cap)
        t_end = time.monotonic() + max(self._retry_deadline, self._timeout)
        # a caller-supplied resume point (relay re-parent) makes the
        # first dial a resume; a 410 falls back to the relist wire
        first_since = init_since if (init_since or init_cursors) \
            else None
        if first_since is None and init_cursors:
            first_since = max(init_cursors.values())
        first_resumed = False
        hint = 0.0
        while True:
            try:
                resp0 = connect(first_since,
                                dict(shard_rvs)
                                if first_since is not None and shard_rvs
                                else None)
                first_resumed = first_since is not None
                self._mark_connected()
                break
            except urllib.error.HTTPError as e:
                if e.code == 410 and first_since is not None:
                    # the resume point was compacted away: relist (the
                    # diffed replay preserves continuity for on_event
                    # consumers exactly like any mid-life 410)
                    first_since = None
                    shard_rvs.clear()
                    try:
                        e.close()
                    except OSError:
                        pass
                    continue
                if e.code == 429:
                    # the server shed this subscription under pressure:
                    # an answer from a healthy server (not degraded
                    # transport) — redial after its Retry-After hint
                    try:
                        hint = float(e.headers.get("Retry-After")
                                     or 0.0)
                    except (TypeError, ValueError):
                        hint = 0.0
                    with self._slock:
                        self._throttled += 1
                    err = RemoteError(
                        f"watch {','.join(kinds)}: HTTP 429")
                    try:
                        e.close()
                    except OSError:
                        pass
                elif e.code not in _RETRYABLE_HTTP:
                    # the server ANSWERED: surface its verdict instead
                    # of blind-retrying a doomed request to its deadline
                    raise RemoteError(
                        f"watch {','.join(kinds)}: HTTP {e.code}") \
                        from None
                else:
                    err = e
                    try:
                        e.close()   # don't leak one socket per retry
                    except OSError:
                        pass
                    self._mark_degraded()
            except _TRANSPORT_ERRORS as e:
                err = e
                self._mark_degraded()
            remaining = t_end - time.monotonic()
            if remaining <= 0 or self._closed.is_set():
                raise Unavailable(
                    f"watch {','.join(kinds)}: {err!r}") from None
            time.sleep(min(max(hint, bo.next()), max(remaining, 0.0)))
            hint = 0.0
        t = threading.Thread(target=run, args=(resp0, first_resumed),
                             daemon=True,
                             name=f"reflector-{'-'.join(kinds)}")
        t.start()
        self._threads.append(t)
        # WaitForCacheSync: watch_X returns only after the LIST replay has
        # been fully dispatched, matching the in-process hub's synchronous
        # replay semantics the scheduler's constructor relies on
        synced.wait(timeout=self._timeout)

    def unwatch(self, h: EventHandlers) -> None:
        """In-process parity no-op: remote watches end with close()."""

    def close(self) -> None:
        self._closed.set()
        with self._wlock:
            watchers, self._watchers = self._watchers, []
        for resp in watchers:
            try:
                resp.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2)
        self._threads.clear()


def _make_watch(kind: str):
    def watch(self: RemoteHub, h: EventHandlers, replay: bool = True):
        self._watch(kind, h, replay)

    watch.__name__ = f"watch_{kind}"
    return watch


for _kind in WATCH_KINDS:
    setattr(RemoteHub, f"watch_{_kind}", _make_watch(_kind))
