"""Serving endpoints: /metrics, /healthz, /configz, authz-gated /debug.

The slice of the reference's component HTTP surface the scheduler exposes
(cmd/kube-scheduler/app/server.go:252 newHealthEndpointsAndMetricsHandler:
healthz/livez/readyz + /metrics + /configz): a tiny threaded HTTP server
over the metrics Registry and the component config.

Debug endpoints (/debug/cache, /debug/queue, /debug/journal,
/debug/trace, /debug/pod) follow the reference's discipline for its
debugging handlers (server.go:248-255: installed only behind the authz
filter): they are DENIED unless the caller passed a ``debug_auth``
callback, which receives the request's Authorization header value and
returns True to admit. ``token_auth("secret")`` builds the common
bearer-token check.

Flight-recorder surface:
- ``/debug/trace[?n=32]`` — the last-N cycle traces from the always-on
  recorder ring plus per-phase percentiles (p50/p90/p99) and the
  host-tail share.
- ``/debug/pod?name=X[&namespace=ns]`` (or ``?uid=``) — one pod's
  lifecycle timeline (enqueue/pop/bind/park stamps) and its last
  unschedulable diagnosis (which device filter rejected how many nodes,
  which host plugin rejected).
- ``/debug/scorer`` — per-profile learned-scorer state (active
  checkpoint version/fingerprint, learn-loop generation + the regret
  summaries stamped by the promotion gate, reload and load-error
  counts).
"""

from __future__ import annotations

import json
import threading
from dataclasses import asdict, is_dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs


def token_auth(token: str) -> Callable[[str], bool]:
    """The usual debug_auth: admit ``Authorization: Bearer <token>``."""
    import hmac

    expect = f"Bearer {token}"

    def check(authorization: str) -> bool:
        return hmac.compare_digest(authorization or "", expect)

    return check


class ServingEndpoints:
    def __init__(self, scheduler, host: str = "127.0.0.1", port: int = 0,
                 debug_auth: Optional[Callable[[str], bool]] = None):
        self.scheduler = scheduler
        sched = scheduler

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # silence per-request stderr spam
                pass

            def _send(self, code: int, body: str,
                      ctype: str = "text/plain; charset=utf-8") -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _debug(self, path: str, query: dict) -> None:
                # server.go:248-255: debug handlers exist only behind
                # authorization — no callback, no endpoints (403, not
                # 404: the surface is real but the caller is not allowed)
                if debug_auth is None:
                    self._send(403, "debug endpoints disabled "
                                    "(no debug_auth configured)")
                    return
                if not debug_auth(self.headers.get("Authorization", "")):
                    self._send(401, "unauthorized")
                    return
                if path == "/debug/cache":
                    body = json.dumps(sched.cache.dump(), indent=2,
                                      default=str)
                elif path == "/debug/queue":
                    payload = {"pending": sched.queue.pending_counts(),
                               "stats": sched.stats}
                    jq = getattr(sched, "jobqueue", None)
                    if jq is not None and jq.active:
                        # per-tenant job queues + assembling gangs
                        payload["job_queue"] = jq.debug_state()
                    gang = getattr(sched, "_gang", None)
                    if gang is not None:
                        payload["gangs"] = gang.debug_state()
                    payload["waiting_pods"] = {
                        name: [wp.uid for wp in fw.waiting_pods.iterate()]
                        for name, fw in getattr(sched, "frameworks",
                                                {}).items()
                        if len(fw.waiting_pods)}
                    body = json.dumps(payload, indent=2, default=str)
                elif path == "/debug/journal":
                    js_fn = getattr(sched.hub, "get_journal_stats", None)
                    body = json.dumps(js_fn() if js_fn else {}, indent=2,
                                      default=str)
                elif path == "/debug/trace":
                    flight = getattr(sched, "flight", None)
                    if flight is None:
                        self._send(404, "no flight recorder")
                        return
                    try:
                        n = int(query.get("n", ["32"])[0])
                    except ValueError:
                        n = 32
                    prof = getattr(sched, "profiler", None)
                    body = json.dumps({
                        "enabled": flight.enabled,
                        "cycles": flight.last(n),
                        "phases": flight.phase_percentiles(),
                        "host_tail_share": round(
                            flight.host_tail_share(), 4),
                        # pipelined waves: device-occupancy distribution
                        # (per-cycle launch span / cycle wall)
                        "occupancy": flight.occupancy_stats(),
                        # the device-launch profiler rides the trace
                        # surface: compiles per bucket shape, recompile
                        # causes, resident HBM buffer bytes
                        "device": (prof.snapshot() if prof is not None
                                   else None),
                    }, indent=2, default=str)
                elif path == "/debug/scorer":
                    # learned-scorer state per profile: checkpoint
                    # path/version/fingerprint, learn-loop generation
                    # + promoted-meta regret view, reload + load-error
                    # counts (plugins/learned.py manager stats)
                    payload = {}
                    for name, pcfg in getattr(sched, "_profile_cfg",
                                              {}).items():
                        mgr = (pcfg or {}).get("learned")
                        payload[name] = (mgr.stats() if mgr is not None
                                         else {"enabled": False})
                    body = json.dumps(payload, indent=2, default=str)
                elif path == "/debug/fabric":
                    # control-plane fabric surface: the hub's shard map
                    # + per-shard journal state (ShardedHub), and the
                    # hub client's wire-codec accounting (RemoteHub).
                    # Relay topology/cursors live on each RelayServer's
                    # own token-gated /debug/fabric — relays are their
                    # own processes; the scheduler only sees its hub.
                    payload = {}
                    sm_fn = getattr(sched.hub, "shard_map", None)
                    if sm_fn is not None:
                        try:
                            payload["shard_map"] = sm_fn()
                        except Exception:  # noqa: BLE001 — hub down or
                            pass           # a pre-fabric peer
                    js_fn = getattr(sched.hub, "get_journal_stats",
                                    None)
                    if js_fn is not None:
                        try:
                            js = js_fn()
                        except Exception:  # noqa: BLE001 — hub down
                            js = {}
                        payload["shards"] = js.get("shards", {})
                        payload["journal_rv"] = js.get("rv")
                    rs_fn = getattr(sched.hub, "resilience_stats", None)
                    if rs_fn is not None:
                        s = rs_fn()
                        payload["wire"] = s.get("wire", {})
                        payload["codec"] = s.get("codec")
                    topo_fn = getattr(sched.hub, "fabric_topology",
                                      None)
                    if topo_fn is not None:
                        # replicated state core: who leads, each
                        # replica's term and log/commit indexes (served
                        # through the router's state forwarding; absent
                        # on pre-replica fabrics)
                        try:
                            topo = topo_fn()
                            replicas = topo.get("replicas")
                            if replicas:
                                payload["state_replicas"] = replicas
                            scheds = topo.get("schedulers")
                            if scheds:
                                # scale-out: the live scheduler-replica
                                # registry + slice-ring epoch
                                payload["scheduler_replicas"] = scheds
                                payload["sched_ring_epoch"] = \
                                    topo.get("sched_ring_epoch")
                        except Exception:  # noqa: BLE001 — quorum
                            pass           # mid-election / plain hub
                    sm = getattr(sched, "_slices", None)
                    if sm is not None:
                        # this replica's own slice view: which slots it
                        # drains, under which ring/fencing epochs, and
                        # how many peer-owned pods wait in the pen
                        payload["slices"] = {
                            "identity": sm.identity,
                            "owned_slots": sorted(sm.owned),
                            "ring_epoch": sm.ring_epoch,
                            "fence_epoch": sm.epoch,
                            "generation": sm.generation,
                            "rebalances": sm.rebalances,
                            "foreign_pending": len(
                                getattr(sched, "_foreign", {}))}
                    body = json.dumps(payload, indent=2, default=str)
                elif path == "/debug/fleet":
                    # fleet topology + health: the FleetView collector's
                    # summary (one row per fabric component endpoint,
                    # healthz verdicts + strict-parse scrape errors)
                    fleet = getattr(sched, "fleet", None)
                    if fleet is None:
                        self._send(404, "no fleet view attached")
                        return
                    payload = fleet.summary()
                    # this scheduler's own overload state rides the
                    # fleet view: brownout is exactly the fact an
                    # operator opens /debug/fleet to find
                    bs_fn = getattr(sched, "brownout_state", None)
                    if bs_fn is not None:
                        payload["scheduler_brownout"] = bs_fn()
                    body = json.dumps(payload, indent=2,
                                      default=str)
                elif path == "/debug/autopsy":
                    # incident black boxes: the bundle listing, or one
                    # parsed bundle (?name=). 404 without a store —
                    # capture is opt-in via config.autopsy_dir
                    store = getattr(sched, "autopsy", None)
                    if store is None:
                        self._send(404, "no autopsy store configured "
                                        "(set config.autopsy_dir)")
                        return
                    name = query.get("name", [""])[0]
                    if name:
                        try:
                            payload = store.load(name)
                        except (OSError, ValueError) as e:
                            self._send(404, f"bundle unreadable: {e}")
                            return
                    else:
                        wd = getattr(sched, "watchdog", None)
                        payload = {
                            "dir": store.directory,
                            "incidents": getattr(wd, "incidents", 0),
                            "bundles": store.list(),
                        }
                    body = json.dumps(payload, indent=2, default=str)
                elif path == "/debug/pod":
                    timelines = getattr(sched, "timelines", None)
                    if timelines is None:
                        self._send(404, "no pod timelines")
                        return
                    tl = timelines.get(
                        name=query.get("name", [""])[0],
                        uid=query.get("uid", [""])[0],
                        namespace=query.get("namespace",
                                            ["default"])[0])
                    if tl is None:
                        self._send(404, "pod not found (timelines keep "
                                        "the newest pods only)")
                        return
                    body = json.dumps(tl, indent=2, default=str)
                else:
                    self._send(404, "not found")
                    return
                self._send(200, body, "application/json")

            def do_GET(self):  # noqa: N802 (stdlib API)
                path, _, rawq = self.path.partition("?")
                if path == "/metrics":
                    self._send(200, sched.metrics.registry.render_text())
                elif path == "/metrics/fleet":
                    # the merged fleet exposition: every component's
                    # samples re-labeled with component/shard — one
                    # scrape target for the whole fabric
                    fleet = getattr(sched, "fleet", None)
                    if fleet is None:
                        self._send(404, "no fleet view attached")
                    else:
                        self._send(200, fleet.render_text())
                elif path == "/readyz":
                    # degraded (hub unreachable) = alive but NOT ready:
                    # load balancers should drain, probes should not kill
                    degraded_fn = getattr(sched, "hub_degraded", None)
                    if degraded_fn is not None and degraded_fn():
                        self._send(503, "degraded: hub unreachable")
                    else:
                        self._send(200, "ok")
                elif path in ("/healthz", "/livez"):
                    self._send(200, "ok")
                elif path == "/configz":
                    cfg = sched.config
                    body = json.dumps(
                        asdict(cfg) if is_dataclass(cfg) else str(cfg),
                        indent=2, default=str)
                    self._send(200, body, "application/json")
                elif path.startswith("/debug/"):
                    self._debug(path, parse_qs(rawq))
                else:
                    self._send(404, "not found")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="ktpu-serving")
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
