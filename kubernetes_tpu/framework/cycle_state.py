"""Per-scheduling-cycle shared state.

Equivalent of the reference's CycleState
(staging/src/k8s.io/kube-scheduler/framework/cycle_state.go:44 and
pkg/scheduler/framework/cycle_state.go): a typed KV store plugins share
within one cycle, plus the Filter/Score skip sets PreFilter/PreScore
populate. In the batched pipeline one CycleState exists per pod per batch.
"""

from __future__ import annotations

from typing import Any, Optional


class CycleState:
    __slots__ = ("_storage", "skip_filter_plugins", "skip_score_plugins",
                 "recorded_plugin_metrics")

    def __init__(self) -> None:
        self._storage: dict[str, Any] = {}
        self.skip_filter_plugins: set[str] = set()
        self.skip_score_plugins: set[str] = set()
        self.recorded_plugin_metrics = False

    def read(self, key: str) -> Optional[Any]:
        return self._storage.get(key)

    def write(self, key: str, value: Any) -> None:
        self._storage[key] = value

    def delete(self, key: str) -> None:
        self._storage.pop(key, None)

    def clone(self) -> "CycleState":
        c = CycleState()
        c._storage = dict(self._storage)
        c.skip_filter_plugins = set(self.skip_filter_plugins)
        c.skip_score_plugins = set(self.skip_score_plugins)
        return c
