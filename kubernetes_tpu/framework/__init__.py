from kubernetes_tpu.framework.interface import (  # noqa: F401
    ActionType,
    ClusterEvent,
    Code,
    EventResource,
    QueueingHint,
    Status,
)
from kubernetes_tpu.framework.cycle_state import CycleState  # noqa: F401
