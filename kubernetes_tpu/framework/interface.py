"""Scheduling framework extension points, statuses, and cluster events.

Host-side equivalent of the reference's plugin API
(/root/reference/pkg/scheduler/framework/interface.go:444-960) and event
model (framework/types.go:46-274). The major departure from the reference:
the hot Filter/Score path for the default plugin set is ONE fused device
program (models.pipeline.schedule_batch) rather than per-plugin virtual
calls — host plugins implement the same interfaces below and run around the
device launch (mixed host/device framework, SURVEY.md §7.0).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable, Optional

from kubernetes_tpu.api.objects import Node, Pod


class Code(enum.IntEnum):
    """Status codes (interface.go Code)."""

    SUCCESS = 0
    ERROR = 1
    UNSCHEDULABLE = 2
    UNSCHEDULABLE_AND_UNRESOLVABLE = 3
    WAIT = 4
    SKIP = 5
    PENDING = 6


@dataclass
class Status:
    """Result of running a plugin (interface.go Status)."""

    code: Code = Code.SUCCESS
    reasons: list[str] = field(default_factory=list)
    plugin: str = ""

    @classmethod
    def unschedulable(cls, *reasons: str, plugin: str = "",
                      resolvable: bool = True) -> "Status":
        code = (Code.UNSCHEDULABLE if resolvable
                else Code.UNSCHEDULABLE_AND_UNRESOLVABLE)
        return cls(code=code, reasons=list(reasons), plugin=plugin)

    @classmethod
    def error(cls, msg: str, plugin: str = "") -> "Status":
        return cls(code=Code.ERROR, reasons=[msg], plugin=plugin)

    @classmethod
    def skip(cls) -> "Status":
        return cls(code=Code.SKIP)

    def is_success(self) -> bool:
        return self.code == Code.SUCCESS

    def is_skip(self) -> bool:
        return self.code == Code.SKIP

    def is_rejected(self) -> bool:
        return self.code in (Code.UNSCHEDULABLE,
                             Code.UNSCHEDULABLE_AND_UNRESOLVABLE)

    def message(self) -> str:
        return "; ".join(self.reasons)


SUCCESS = Status()


class ActionType(enum.IntFlag):
    """What changed about a resource (framework/types.go:46-120)."""

    ADD = 1 << 0
    DELETE = 1 << 1
    UPDATE_NODE_ALLOCATABLE = 1 << 2
    UPDATE_NODE_LABEL = 1 << 3
    UPDATE_NODE_TAINT = 1 << 4
    UPDATE_NODE_CONDITION = 1 << 5
    UPDATE_NODE_ANNOTATION = 1 << 6
    UPDATE_POD_LABEL = 1 << 7
    UPDATE_POD_SCALE_DOWN = 1 << 8
    UPDATE_POD_TOLERATION = 1 << 9
    UPDATE_POD_SCHEDULING_GATES_ELIMINATED = 1 << 10
    UPDATE_POD_GENERATED_RESOURCE_CLAIM = 1 << 11

    UPDATE = (UPDATE_NODE_ALLOCATABLE | UPDATE_NODE_LABEL | UPDATE_NODE_TAINT
              | UPDATE_NODE_CONDITION | UPDATE_NODE_ANNOTATION
              | UPDATE_POD_LABEL | UPDATE_POD_SCALE_DOWN
              | UPDATE_POD_TOLERATION
              | UPDATE_POD_SCHEDULING_GATES_ELIMINATED
              | UPDATE_POD_GENERATED_RESOURCE_CLAIM)
    ALL = ADD | DELETE | UPDATE


class EventResource(str, enum.Enum):
    """Resource kinds events refer to (framework/types.go:121-180)."""

    POD = "Pod"
    ASSIGNED_POD = "AssignedPod"
    UNSCHEDULABLE_POD = "UnschedulablePod"
    NODE = "Node"
    PVC = "PersistentVolumeClaim"
    PV = "PersistentVolume"
    STORAGE_CLASS = "StorageClass"
    CSI_NODE = "CSINode"
    CSI_STORAGE_CAPACITY = "CSIStorageCapacity"
    RESOURCE_CLAIM = "ResourceClaim"
    RESOURCE_SLICE = "ResourceSlice"
    POD_GROUP = "PodGroup"
    WILDCARD = "*"


@dataclass(frozen=True)
class ClusterEvent:
    """A (resource, action) pair informers deliver (framework/types.go)."""

    resource: EventResource
    action_type: ActionType
    label: str = ""

    def match(self, other: "ClusterEvent") -> bool:
        res_ok = (self.resource == EventResource.WILDCARD
                  or self.resource == other.resource)
        return res_ok and bool(self.action_type & other.action_type)


EVENT_WILDCARD = ClusterEvent(EventResource.WILDCARD, ActionType.ALL, "*")


class QueueingHint(enum.IntEnum):
    """Can this event unstick a rejected pod? (framework/types.go:248)"""

    SKIP = 0
    QUEUE = 1


# QueueingHintFn(logger, pod, old_obj, new_obj) -> QueueingHint
QueueingHintFn = Callable[[Pod, Optional[object], Optional[object]],
                          QueueingHint]


@dataclass
class ClusterEventWithHint:
    event: ClusterEvent
    queueing_hint_fn: Optional[QueueingHintFn] = None


# --------------------------- plugin interfaces ---------------------------


class Plugin:
    """Base: every plugin has a unique name (interface.go:444)."""

    NAME = ""

    def name(self) -> str:
        return self.NAME or type(self).__name__


class PreEnqueuePlugin(Plugin):
    """Called before adding a pod to the activeQ (interface.go:453)."""

    def pre_enqueue(self, pod: Pod) -> Status:
        raise NotImplementedError


class QueueSortPlugin(Plugin):
    """Orders pods in the activeQ (interface.go:465)."""

    def less(self, a, b) -> bool:  # a, b: QueuedPodInfo
        raise NotImplementedError


class EnqueueExtensions(Plugin):
    """Which events may unstick pods this plugin rejected (interface.go:488)."""

    def events_to_register(self) -> list[ClusterEventWithHint]:
        raise NotImplementedError


class PreFilterPlugin(Plugin):
    """Per-cycle state build before Filter (interface.go:518)."""

    def pre_filter(self, state, pod: Pod, nodes) -> Status:
        raise NotImplementedError


class FilterPlugin(Plugin):
    """Per-node feasibility check (interface.go:546)."""

    def filter(self, state, pod: Pod, node_info) -> Status:
        raise NotImplementedError


class PostFilterPlugin(Plugin):
    """Runs when no node fit — preemption lives here (interface.go:567)."""

    def post_filter(self, state, pod: Pod, filtered_node_status) -> tuple:
        raise NotImplementedError


class PreScorePlugin(Plugin):
    def pre_score(self, state, pod: Pod, nodes) -> Status:
        raise NotImplementedError


class ScorePlugin(Plugin):
    """Per-node score in [0, 100] (interface.go:613)."""

    def score(self, state, pod: Pod, node_info) -> tuple[float, Status]:
        raise NotImplementedError

    def normalize_scores(self, state, pod: Pod, scores) -> Status:
        return SUCCESS


class ReservePlugin(Plugin):
    def reserve(self, state, pod: Pod, node_name: str) -> Status:
        raise NotImplementedError

    def unreserve(self, state, pod: Pod, node_name: str) -> None:
        pass


class PermitPlugin(Plugin):
    """allow / reject / wait-with-timeout (interface.go:666)."""

    def permit(self, state, pod: Pod, node_name: str
               ) -> tuple[Status, float]:
        raise NotImplementedError


class PreBindPlugin(Plugin):
    def pre_bind(self, state, pod: Pod, node_name: str) -> Status:
        raise NotImplementedError


class BindPlugin(Plugin):
    def bind(self, state, pod: Pod, node_name: str) -> Status:
        raise NotImplementedError


class PostBindPlugin(Plugin):
    def post_bind(self, state, pod: Pod, node_name: str) -> None:
        pass


