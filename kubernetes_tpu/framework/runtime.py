"""Framework runtime: resolves a profile into runnable extension points.

Equivalent of the reference's frameworkImpl
(/root/reference/pkg/scheduler/framework/runtime/framework.go:53,268):
instantiates plugins from the registry, expands the MultiPoint shorthand
with override semantics (expandMultiPointPlugins :523), resolves score
weights (scorePluginWeight :57), and exposes per-point runners.

The structural difference from the reference: RunFilterPlugins /
RunScorePlugins for the device plugin set are NOT virtual calls per
(plugin, node) — they are one fused launch of models.pipeline. The runtime
therefore exposes the launch configuration (enabled filter slots, the
ScoreWeights vector) instead, and runs only host plugins procedurally.
"""

from __future__ import annotations

from typing import Optional

from kubernetes_tpu.api.objects import Pod
from kubernetes_tpu.config.types import SchedulerProfile
from kubernetes_tpu.framework.cycle_state import CycleState
from kubernetes_tpu.framework.interface import (
    BindPlugin,
    ClusterEventWithHint,
    Code,
    FilterPlugin,
    PermitPlugin,
    PostBindPlugin,
    PostFilterPlugin,
    PreBindPlugin,
    PreEnqueuePlugin,
    PreFilterPlugin,
    PreScorePlugin,
    QueueSortPlugin,
    ReservePlugin,
    ScorePlugin,
    Status,
)
from kubernetes_tpu.models.pipeline import (
    FILTER_PLUGINS,
    SCORE_PLUGINS,
    ScoreWeights,
)
from kubernetes_tpu.plugins.registry import PluginDescriptor, in_tree_registry

import jax.numpy as jnp

# pipeline ScoreWeights field per SCORE_PLUGINS entry
_WEIGHT_FIELD = {
    "TaintToleration": "taint_toleration",
    "NodeAffinity": "node_affinity",
    "NodeResourcesFit": "resources_fit",
    "NodeResourcesBalancedAllocation": "balanced_allocation",
    "ImageLocality": "image_locality",
    "PodTopologySpread": "pod_topology_spread",
    "InterPodAffinity": "inter_pod_affinity",
    "LearnedScore": "learned",
}


def _score_weight(point: str, explicit: float, multipoint: float,
                  d: PluginDescriptor) -> float:
    if point != "score":
        return 0.0
    # scorePluginWeight: explicit > multipoint > default > 1
    return explicit or multipoint or d.default_weight or 1.0


def expand_point(profile, registry: dict[str, PluginDescriptor],
                 point: str) -> list[tuple[str, float]]:
    """Effective (name, weight) list at one extension point: MultiPoint
    expansion with specific-point overrides and disabled sets
    (runtime/framework.go:523 expandMultiPointPlugins). Module-level so
    config validation resolves points exactly the way the runtime will."""
    plugins = profile.plugins
    ps = getattr(plugins, point)
    mp = plugins.multi_point
    disabled = {p.name for p in ps.disabled}
    wipe = "*" in disabled
    mp_disabled = {p.name for p in mp.disabled}
    mp_wipe = "*" in mp_disabled
    explicit = {p.name: p for p in ps.enabled}
    out: list[tuple[str, float]] = []
    consumed: set[str] = set()
    for p in mp.enabled:
        d = registry.get(p.name)
        if d is None or point not in d.points:
            continue
        if mp_wipe or p.name in mp_disabled:
            continue
        if wipe or p.name in disabled:
            continue
        if p.name in explicit:
            # specific-point config overrides weight, keeps MP order
            out.append((p.name, _score_weight(point, explicit[p.name].weight,
                                              p.weight, d)))
            consumed.add(p.name)
        else:
            out.append((p.name, _score_weight(point, 0.0, p.weight, d)))
    for p in ps.enabled:
        if p.name in consumed:
            continue
        d = registry.get(p.name)
        if d is None or point not in d.points:
            continue
        out.append((p.name, _score_weight(point, p.weight, 0.0, d)))
    return out


class Framework:
    """One profile's resolved plugin configuration + host-plugin instances."""

    def __init__(self, profile: SchedulerProfile,
                 registry: Optional[dict[str, PluginDescriptor]] = None,
                 extra_args: Optional[dict] = None):
        self.profile = profile
        self.registry = dict(in_tree_registry() if registry is None
                             else registry)
        self._extra_args = extra_args or {}
        # point -> ordered list of (name, weight)
        self.points: dict[str, list[tuple[str, float]]] = {}
        for point in ("pre_enqueue", "queue_sort", "filter", "post_filter",
                      "score", "reserve", "permit", "pre_bind", "bind",
                      "post_bind"):
            self.points[point] = self._expand(point)
        # permit WAIT registry (waiting_pods_map.go); plugins reach running
        # waiting pods through the framework handle (interface.go:684)
        from kubernetes_tpu.framework.waiting import WaitingPodsMap

        self.waiting_pods = WaitingPodsMap()
        # flight-recorder seam: the scheduler installs a
        # (plugin_name, extension_point, secs) callback here to get
        # per-plugin timing (the reference's
        # plugin_execution_duration_seconds). None = no timing reads at
        # all. Called only from loop-thread runners (host filters/
        # scores/reserve) — binder-thread points stay uninstrumented.
        self.plugin_timer = None
        self._instances: dict[str, object] = {}
        for point, entries in self.points.items():
            for name, _ in entries:
                d = self.registry.get(name)
                if d is not None and d.factory is not None \
                        and name not in self._instances:
                    args = dict(profile.plugin_config.get(name, {}))
                    args.update(self._extra_args)
                    self._instances[name] = d.factory(args)

    # ------------- MultiPoint expansion (framework.go:523) -------------

    def _expand(self, point: str) -> list[tuple[str, float]]:
        return expand_point(self.profile, self.registry, point)

    # ------------- device launch configuration -------------

    def enabled_filters(self) -> tuple[bool, ...]:
        """Static per-slot enable flags for pipeline.FILTER_PLUGINS."""
        on = {name for name, _ in self.points["filter"]}
        return tuple(name in on for name in FILTER_PLUGINS)

    def fit_scoring(self):
        """(strategy, shape | None) from NodeResourcesFitArgs
        (apis/config types.go ScoringStrategy: LeastAllocated default,
        MostAllocated, RequestedToCapacityRatio with shape points
        {utilization 0..100, score 0..10})."""
        args = self.profile.plugin_config.get("NodeResourcesFit", {})
        ss = args.get("scoring_strategy") or {}
        strategy = ss.get("type", "LeastAllocated")
        shape = None
        pts = (ss.get("requested_to_capacity_ratio") or {}).get("shape")
        if strategy == "RequestedToCapacityRatio":
            if not pts:
                raise ValueError(
                    "NodeResourcesFit scoringStrategy "
                    "RequestedToCapacityRatio requires a non-empty "
                    "requested_to_capacity_ratio.shape")
            pts = sorted(pts, key=lambda p: p["utilization"])
            shape = (jnp.asarray([p["utilization"] / 100.0 for p in pts],
                                 jnp.float32),
                     jnp.asarray([p["score"] * 10.0 for p in pts],
                                 jnp.float32))
        return strategy, shape

    def score_weights(self) -> ScoreWeights:
        """Dynamic ScoreWeights vector from resolved config weights."""
        w = {name: weight for name, weight in self.points["score"]}
        fields = {}
        for plugin in SCORE_PLUGINS:
            fields[_WEIGHT_FIELD[plugin]] = jnp.float32(w.get(plugin, 0.0))
        return ScoreWeights(**fields)

    # ------------- host extension-point runners -------------

    def instance(self, name: str):
        return self._instances.get(name)

    def _iter(self, point: str, cls):
        """Instances at a point matching cls, cached: this runs per pod per
        extension point on the commit path, and the plugin sets are fixed
        after construction (the reference's frameworkImpl also resolves its
        per-point slices once, runtime/framework.go:268)."""
        cache = self.__dict__.setdefault("_iter_cache", {})
        key = (point, cls)
        out = cache.get(key)
        if out is None:
            out = cache[key] = tuple(
                inst for name, _ in self.points[point]
                if isinstance(inst := self._instances.get(name), cls))
        return out

    def has_host_filters(self) -> bool:
        """Any instantiated host FilterPlugin in the filter point? (device
        plugins are descriptors with no instance)."""
        for _pl in self._iter("filter", FilterPlugin):
            return True
        return False

    def host_gates(self):
        """Per-plugin fast relevance probes (``applies(pod)``). When every
        host filter declares one, the scheduler skips the whole host pass
        for pods none of them applies to — the default host set (volumes,
        device claims) costs plain workloads nothing. None = some plugin
        has no probe, so every pod must run the host pass."""
        gates = [getattr(pl, "applies", None)
                 for pl in self._iter("filter", FilterPlugin)]
        if any(g is None for g in gates):
            return None
        return gates

    def has_host_scores(self) -> bool:
        return any(isinstance(self._instances.get(name), ScorePlugin)
                   for name, _ in self.points["score"])

    def host_score_gates(self):
        """Per-plugin relevance probes for host ScorePlugins, mirroring
        host_gates(): when every host scorer declares ``applies(pod)``,
        a pod none of them applies to skips host scoring entirely —
        adding VolumeBinding's capacity Score must not re-route every
        plain pod through the per-node Python score loop. None = some
        scorer has no probe."""
        gates = [getattr(self._instances.get(name), "applies", None)
                 for name, _ in self.points["score"]
                 if isinstance(self._instances.get(name), ScorePlugin)]
        if any(g is None for g in gates):
            return None
        return gates

    def run_host_filters(self, state: CycleState, pod: Pod, node_infos
                         ) -> tuple[Optional[list[bool]], dict[str, int],
                                    Optional[Status]]:
        """Host PreFilter + Filter for one pod over the snapshot's nodes —
        the host half of the mixed framework (runtime/framework.go:877-922
        RunFilterPlugins, with the device plugin set already fused into the
        launch). Returns (per-node mask | None if every plugin skipped,
        reject counts by plugin name, early terminal status).

        An early status (a PreFilter rejecting outright) means the pod is
        unschedulable everywhere; the caller masks every node and attributes
        the failure to the returned plugin."""
        import time as _time

        plugins = self.__dict__.get("_host_filter_list")
        if plugins is None:
            plugins = self._host_filter_list = list(
                self._iter("filter", FilterPlugin))
        if not plugins:
            return None, {}, None
        timer = self.plugin_timer
        active = []
        for pl in plugins:
            if isinstance(pl, PreFilterPlugin):
                t0 = _time.perf_counter() if timer else 0.0
                s = pl.pre_filter(state, pod, node_infos)
                if timer is not None:
                    timer(pl.name(), "PreFilter",
                          _time.perf_counter() - t0)
                if s.is_skip():
                    continue
                if not s.is_success():
                    s.plugin = s.plugin or pl.name()
                    return None, {s.plugin: len(node_infos)}, s
            active.append(pl)
        if not active:
            return None, {}, None
        mask = [True] * len(node_infos)
        counts: dict[str, int] = {}
        if timer is None:
            for i, ni in enumerate(node_infos):
                for pl in active:
                    s = pl.filter(state, pod, ni)
                    if not s.is_success():
                        mask[i] = False
                        name = s.plugin or pl.name()
                        counts[name] = counts.get(name, 0) + 1
                        break       # first-fail attribution, like the device
            return mask, counts, None
        # timed variant: accumulate per-plugin across the node loop and
        # flush ONE observation per plugin (a per-(node, plugin) observe
        # would be histogram walks in the hot loop; the perf_counter
        # pair per call is noise next to the Python plugin call itself)
        acc = [0.0] * len(active)
        for i, ni in enumerate(node_infos):
            for j, pl in enumerate(active):
                t0 = _time.perf_counter()
                s = pl.filter(state, pod, ni)
                acc[j] += _time.perf_counter() - t0
                if not s.is_success():
                    mask[i] = False
                    name = s.plugin or pl.name()
                    counts[name] = counts.get(name, 0) + 1
                    break           # first-fail attribution, like the device
        for j, pl in enumerate(active):
            if acc[j] > 0.0:
                timer(pl.name(), "Filter", acc[j])
        return mask, counts, None

    def run_host_scores(self, state: CycleState, pod: Pod, node_infos
                        ) -> Optional[list[float]]:
        """Host PreScore + Score, weight-aggregated per node; None when no
        host ScorePlugin is configured (the common case — the default score
        set runs on device)."""
        entries = [(self._instances.get(name), weight)
                   for name, weight in self.points["score"]
                   if isinstance(self._instances.get(name), ScorePlugin)]
        # per-plugin relevance probe: a scorer that declares applies()
        # skips pods it cannot score (the per-node loop is Python)
        entries = [(pl, w) for pl, w in entries
                   if not hasattr(pl, "applies") or pl.applies(pod)]
        if not entries:
            return None
        import time as _time

        timer = self.plugin_timer
        total = [0.0] * len(node_infos)
        for pl, weight in entries:
            t0 = _time.perf_counter() if timer else 0.0
            if isinstance(pl, PreScorePlugin):
                s = pl.pre_score(state, pod, node_infos)
                if s.is_skip():
                    if timer is not None:
                        timer(pl.name(), "Score",
                              _time.perf_counter() - t0)
                    continue
            scores = []
            for ni in node_infos:
                val, s = pl.score(state, pod, ni)
                scores.append(val if s.is_success() else 0.0)
            pl.normalize_scores(state, pod, scores)
            if timer is not None:
                timer(pl.name(), "Score", _time.perf_counter() - t0)
            w = weight or 1.0
            for i, v in enumerate(scores):
                total[i] += w * v
        return total

    def run_pre_enqueue_plugins(self, pod: Pod) -> Status:
        """interface.go PreEnqueuePlugin; gate failures keep the pod in
        unschedulablePods (scheduling_queue.go:538 runPreEnqueuePlugins)."""
        for pl in self._iter("pre_enqueue", PreEnqueuePlugin):
            s = pl.pre_enqueue(pod)
            if not s.is_success():
                s.plugin = s.plugin or pl.name()
                return s
        return Status()

    @staticmethod
    def _priority_sort_less(a, b) -> bool:
        # fallback: PrioritySort semantics
        pa, pb = a.pod.priority(), b.pod.priority()
        if pa != pb:
            return pa > pb
        return a.timestamp < b.timestamp

    @property
    def queue_sort_less(self):
        """The resolved QueueSort comparator, bound once — the heap calls it
        O(pods log pods) times per drain, so no per-compare plugin walk."""
        fn = self.__dict__.get("_queue_sort_fn")
        if fn is None:
            fn = self._priority_sort_less
            for pl in self._iter("queue_sort", QueueSortPlugin):
                fn = pl.less
                break
            self._queue_sort_fn = fn
        return fn

    @property
    def queue_sort_key(self):
        """Per-item sort-key function when the resolved QueueSort carries
        the default PrioritySort semantics (the only in-tree sort), else
        None. Lets the activeQ heap compare precomputed tuples instead of
        calling a Python comparator per sift step."""
        from kubernetes_tpu.plugins.registry import PrioritySort

        fn = self.queue_sort_less
        if fn is Framework._priority_sort_less or \
                getattr(fn, "__func__", None) is PrioritySort.less:
            return lambda qp: (-qp.pod.priority(), qp.timestamp)
        return None

    def run_reserve_plugins(self, state: CycleState, pod: Pod,
                            node_name: str) -> Status:
        timer = self.plugin_timer
        if timer is None:
            for pl in self._iter("reserve", ReservePlugin):
                s = pl.reserve(state, pod, node_name)
                if not s.is_success():
                    return s
            return Status()
        import time as _time

        for pl in self._iter("reserve", ReservePlugin):
            t0 = _time.perf_counter()
            s = pl.reserve(state, pod, node_name)
            timer(pl.name(), "Reserve", _time.perf_counter() - t0)
            if not s.is_success():
                return s
        return Status()

    def run_unreserve_plugins(self, state: CycleState, pod: Pod,
                              node_name: str) -> None:
        for pl in self._iter("reserve", ReservePlugin):
            pl.unreserve(state, pod, node_name)

    def run_permit_plugins(self, state: CycleState, pod: Pod,
                           node_name: str
                           ) -> tuple[Status, dict[str, float]]:
        """RunPermitPlugins (runtime/framework.go:1480): a rejecting plugin
        fails the pod; WAIT verdicts aggregate into (WAIT status,
        {plugin: timeout}) — the scheduler parks the pod in the
        waitingPodsMap until allowed/rejected/timed out."""
        waits: dict[str, float] = {}
        for pl in self._iter("permit", PermitPlugin):
            s, timeout = pl.permit(state, pod, node_name)
            if s.is_skip():
                continue
            if s.code == Code.WAIT:
                waits[s.plugin or pl.name()] = timeout or 0.0
                continue
            if not s.is_success():
                s.plugin = s.plugin or pl.name()
                return s, {}
        if waits:
            return Status(code=Code.WAIT), waits
        return Status(), {}

    def run_pre_bind_plugins(self, state: CycleState, pod: Pod,
                             node_name: str) -> Status:
        for pl in self._iter("pre_bind", PreBindPlugin):
            s = pl.pre_bind(state, pod, node_name)
            if not s.is_success():
                return s
        return Status()

    def run_bind_plugins(self, state: CycleState, pod: Pod,
                         node_name: str) -> Status:
        for pl in self._iter("bind", BindPlugin):
            s = pl.bind(state, pod, node_name)
            if not s.is_skip():
                return s
        return Status.error("no bind plugin handled the pod")

    def run_post_bind_plugins(self, state: CycleState, pod: Pod,
                              node_name: str) -> None:
        for pl in self._iter("post_bind", PostBindPlugin):
            pl.post_bind(state, pod, node_name)

    def run_post_filter_plugins(self, state: CycleState, pod: Pod,
                                diagnosis) -> tuple[Optional[str], Status]:
        """Returns (nominated_node_name, status)."""
        for pl in self._iter("post_filter", PostFilterPlugin):
            result, s = pl.post_filter(state, pod, diagnosis)
            if s.is_success() or s.code.name == "ERROR":
                return result, s
        return None, Status.unschedulable("no postFilter plugin helped")

    # ------------- queueing hints (scheduler.go:428) -------------

    def events_to_register(self) -> dict[str, list[ClusterEventWithHint]]:
        """plugin name -> cluster events that may unstick its rejections."""
        out: dict[str, list[ClusterEventWithHint]] = {}
        seen: set[str] = set()
        for entries in self.points.values():
            for name, _ in entries:
                if name in seen:
                    continue
                seen.add(name)
                d = self.registry.get(name)
                if d is not None and d.events:
                    out[name] = list(d.events)
        return out
