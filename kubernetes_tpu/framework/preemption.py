"""Preemption: the generic Evaluator + the DefaultPreemption PostFilter.

Host orchestration mirrors /root/reference/pkg/scheduler/framework/
preemption/preemption.go (Evaluator.Preempt :232, findCandidates :307,
SelectCandidate/pickOneNodeForPreemption :395,:565, prepareCandidate :428)
and plugins/defaultpreemption/default_preemption.go (PostFilter :133,
SelectVictimsOnNode :219, PodEligibleToPreemptOthers :327,
GetOffsetAndNumCandidates :186) — with the per-node dry-run replaced by ONE
device sweep over victim prefixes (ops.preempt.preempt_sweep).

Victim ordering: pods on a node sort ascending by importance
(util.MoreImportantPod: priority, then start time) so the minimal feasible
prefix evicts the least-important pods first — the resource-space fixed
point of the reference's remove-all-then-reprieve loop.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

import numpy as np

from kubernetes_tpu.api.labels import label_selector_matches
from kubernetes_tpu.api.objects import Pod
from kubernetes_tpu.framework.interface import PostFilterPlugin, Status
from kubernetes_tpu.ops import features as F
from kubernetes_tpu.ops.preempt import preempt_sweep_jit
from kubernetes_tpu.utils.interner import NONE

MI = 1024 * 1024

# default_preemption.go:40-44 (DefaultPreemptionArgs defaults)
MIN_CANDIDATE_NODES_PERCENTAGE = 10
MIN_CANDIDATE_NODES_ABSOLUTE = 100


@dataclass
class Candidate:
    """One preemption candidate (candidate.go): a node + its victims."""

    node_name: str
    row: int
    victims: list[Pod]
    pdb_violations: int


class Evaluator:
    """Generic preemption evaluator over the device mirror."""

    def __init__(self, hub, get_mirror, get_caps, get_enabled_filters,
                 nominator, rng: random.Random | None = None):
        self.hub = hub
        # callables: the scheduler re-buckets the mirror/caps, and the
        # framework (which owns the filter config) is built after us
        self._get_mirror = get_mirror
        self._get_caps = get_caps
        self._get_enabled_filters = get_enabled_filters
        self.nominator = nominator
        self._rng = rng or random.Random(0)
        # request-row cache: a victim's packed resource row is immutable per
        # uid FOR A GIVEN MIRROR — a re-bucketed mirror changes res_cols and
        # ext-resource column order, so the cache is tied to the mirror
        # object and dropped when the scheduler rebuilds it
        self._res_rows: dict[str, np.ndarray] = {}
        self._res_rows_mirror: object = None

    # ---------------- eligibility (default_preemption.go:327) -------------

    def pod_eligible_to_preempt_others(self, pod: Pod) -> tuple[bool, str]:
        if pod.spec.preemption_policy == "Never":
            return False, "preemptionPolicy=Never"
        nom = pod.status.nominated_node_name
        if nom:
            # if the nominated node has a terminating lower-priority pod, the
            # previous preemption is still in flight: wait for it
            mirror = self._get_mirror()
            row = mirror.row_of(nom)
            if row >= 0:
                snap_pods = self._pods_on_node(nom)
                for p in snap_pods:
                    if (p.metadata.deletion_timestamp is not None
                            and p.priority() < pod.priority()):
                        return False, "previous victims still terminating"
        return True, ""

    # ---------------- candidate discovery ----------------

    def _pods_on_node(self, node_name: str) -> list[Pod]:
        info = self.cache_snapshot.get(node_name)
        return [pi.pod for pi in info.pods] if info is not None else []

    def find_candidates(self, pod: Pod, snapshot) -> list[Candidate]:
        """Device sweep + host assembly of (node, victims) candidates."""
        self.cache_snapshot = snapshot.node_info_map
        mirror = self._get_mirror()
        caps = self._get_caps()
        prio = pod.priority()

        # per-node victims ascending by importance (evict least-important
        # first): priority asc, then start time desc (younger first).
        # Nodes with no victims are skipped: the sweep only selects rows
        # with 1 <= kmin <= len(victims), and an empty row can never win.
        victims_by_row: dict[int, list] = {}
        k_max = 0
        for info in snapshot.node_info_list:
            vs = [pi for pi in info.pods if pi.pod.priority() < prio]
            if not vs:
                continue
            row = mirror.row_of(info.name)
            if row < 0:
                continue
            vs.sort(key=lambda pi: (pi.pod.priority(),
                                    -pi.pod.metadata.creation_timestamp))
            victims_by_row[row] = vs
            k_max = max(k_max, len(vs))
        if k_max == 0:
            return []
        k_cap = 1
        while k_cap < k_max:
            k_cap *= 2

        # cumulative freed request per victim prefix (vectorized: the per-
        # victim python accumulation was the preemption hot spot at 20k
        # victims — one np.cumsum per node + a uid-keyed res-row cache)
        n = caps.nodes
        r = caps.res_cols
        if self._res_rows_mirror is not mirror:
            self._res_rows.clear()
            self._res_rows_mirror = mirror
        res_rows = self._res_rows
        if len(res_rows) > 200_000:
            res_rows.clear()
        cumsum = np.zeros((n, k_cap + 1, r), np.float32)
        for row, vs in victims_by_row.items():
            rows_k = []
            for pi in vs:
                uid = pi.pod.metadata.uid
                rr = res_rows.get(uid)
                if rr is None:
                    rr = np.asarray(mirror._res_row(pi.request), np.float32)
                    res_rows[uid] = rr
                rows_k.append(rr)
            acc = np.cumsum(np.stack(rows_k), axis=0)          # [k, R]
            acc[:, F.COL_PODS] = np.arange(1, len(vs) + 1, dtype=np.float32)
            cumsum[row, 1: len(vs) + 1] = acc
            if len(vs) < k_cap:
                cumsum[row, len(vs) + 1:] = acc[-1]  # pad: no extra victims

        pblobs = mirror.pack_batch_blobs([pod], 1)
        cblobs = mirror.to_blobs()
        kmin = np.asarray(preempt_sweep_jit(
            cblobs, pblobs, mirror.well_known(), cumsum, caps,
            self._get_enabled_filters()))

        rows = [row for row, vs in victims_by_row.items()
                if kmin[row] != NONE and 1 <= kmin[row] <= len(vs)]
        if not rows:
            return []

        # candidate subset: random offset + bounded count (preemption.go:307
        # GetOffsetAndNumCandidates)
        num_nodes = len(snapshot.node_info_list)
        want = max(num_nodes * MIN_CANDIDATE_NODES_PERCENTAGE // 100,
                   MIN_CANDIDATE_NODES_ABSOLUTE)
        rows.sort()
        off = self._rng.randrange(len(rows))
        picked = [rows[(off + i) % len(rows)]
                  for i in range(min(want, len(rows)))]

        pdbs = self.hub.list_pdbs()
        out = []
        for row in picked:
            vs = victims_by_row[row][: int(kmin[row])]
            victims = [pi.pod for pi in vs]
            out.append(Candidate(
                node_name=mirror.name_of_row(row) or "",
                row=row, victims=victims,
                pdb_violations=self._pdb_violations(victims, pdbs)))
        return out

    @staticmethod
    def _pdb_violations(victims: list[Pod], pdbs) -> int:
        """How many VICTIMS violate some PDB's disruptionsAllowed — each pod
        counts at most once even if it matches several exhausted PDBs
        (preemption.go filterPodsWithPDBViolation classifies per pod); every
        eviction still draws down each matching PDB's budget."""
        budget = {pdb.metadata.uid: pdb.disruptions_allowed for pdb in pdbs}
        violations = 0
        for v in victims:
            matched = [pdb for pdb in pdbs
                       if pdb.metadata.namespace == v.metadata.namespace
                       and pdb.selector is not None
                       and label_selector_matches(pdb.selector,
                                                  v.metadata.labels)]
            if any(budget[pdb.metadata.uid] <= 0 for pdb in matched):
                violations += 1
            for pdb in matched:
                budget[pdb.metadata.uid] -= 1
        return violations

    # ---------------- selection (preemption.go:565 pickOneNode) -----------

    @staticmethod
    def select_candidate(candidates: list[Candidate]) -> Candidate | None:
        if not candidates:
            return None

        def key(c: Candidate):
            prios = [v.priority() for v in c.victims]
            high = max(prios) if prios else -(2 ** 31)
            # latest start of the highest-priority victim: prefer evicting
            # the youngest important pod
            starts = [v.metadata.creation_timestamp for v in c.victims
                      if v.priority() == high]
            latest = max(starts) if starts else 0.0
            return (c.pdb_violations, high, sum(prios), len(c.victims),
                    -latest, c.node_name)

        return min(candidates, key=key)

    # ---------------- execution (preemption.go:428 prepareCandidate) ------

    def prepare_candidate(self, candidate: Candidate, pod: Pod) -> None:
        for victim in candidate.victims:
            try:
                self.hub.delete_pod(victim.metadata.uid)
            except Exception:  # noqa: BLE001 — already gone is fine
                pass
        # lower-priority nominees on this node must re-evaluate: drop the
        # nomination AND clear the API status (the stale nominatedNodeName
        # would otherwise keep feeding the pipeline's own-reservation
        # add-back); the status update event re-activates them
        dropped = self.nominator.clear_for_node_below_priority(
            candidate.node_name, pod.priority())
        for nominee in dropped:
            self.hub.clear_nominated_node(nominee.metadata.uid)

    # ---------------- the whole PostFilter flow ----------------

    def preempt(self, pod: Pod, snapshot) -> tuple[str | None, Status]:
        self.cache_snapshot = snapshot.node_info_map
        ok, why = self.pod_eligible_to_preempt_others(pod)
        if not ok:
            return None, Status.unschedulable(
                f"not eligible for preemption: {why}",
                plugin="DefaultPreemption")
        candidates = self.find_candidates(pod, snapshot)
        best = self.select_candidate(candidates)
        if best is None:
            return None, Status.unschedulable(
                "no preemption candidates", plugin="DefaultPreemption")
        self.prepare_candidate(best, pod)
        self.nominator.add(pod, best.node_name)
        return best.node_name, Status()


class DefaultPreemption(PostFilterPlugin):
    """PostFilter plugin wrapper (default_preemption.go:133)."""

    NAME = "DefaultPreemption"

    def __init__(self, evaluator: Evaluator):
        self.evaluator = evaluator

    def name(self) -> str:
        return self.NAME

    def post_filter(self, state, pod: Pod, diagnosis
                    ) -> tuple[str | None, Status]:
        snapshot = diagnosis.get("snapshot") if diagnosis else None
        if snapshot is None:
            return None, Status.unschedulable("no snapshot in diagnosis",
                                              plugin=self.NAME)
        return self.evaluator.preempt(pod, snapshot)
